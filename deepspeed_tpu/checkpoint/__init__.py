from deepspeed_tpu.checkpoint import constants
from deepspeed_tpu.runtime.state_dict_factory import (
    MegatronSDLoader, SDLoaderBase, SDLoaderFactory)

__all__ = ["constants", "MegatronSDLoader", "SDLoaderBase",
           "SDLoaderFactory"]
