"""Symbolic constants for checkpoint dict keys
(ref: deepspeed/checkpoint/constants.py:1-25)."""

# optimizer checkpoint keys
OPTIMIZER_STATE_DICT = "optimizer_state_dict"
FP32_GROUPS = "fp32_groups"
FP32_FLAT_GROUPS = "fp32_flat_groups"
BASE_OPTIMIZER_STATE = "base_optimizer_state"
SINGLE_PARTITION_OF_FP32_GROUPS = "single_partition_of_fp32_groups"
GROUPS_PADDING = "groups_padding"
PARTITION_COUNT = "partition_count"
ZERO_STAGE = "zero_stage"
CLIP_GRAD = "clip_grad"

# module checkpoint keys
PARAM_SHAPES = "param_shapes"
BUFFER_NAMES = "buffer_names"
DS_VERSION = "ds_version"

# deepspeed_tpu checkpoint layout (runtime/checkpointing.py)
LATEST_FILE = "latest"
META_FILE = "ds_meta.json"
STATE_DIR = "state"
