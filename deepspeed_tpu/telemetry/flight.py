"""Flight recorder — black-box postmortem artifacts for serving
incidents.

A ``DegradedError``, watchdog trip, or breaker break used to leave its
evidence in live Python objects: whoever caught the exception could
inspect ``srv.stats`` and the tracer ring, and whoever didn't got
nothing. The flight recorder turns the incident into a self-contained,
versioned, CRC-stamped JSON artifact — tracer ring, metrics snapshot,
autoscaler decisions, fired faults, resolved flags, program cost
registry, cost-accounting state, and the jax/platform identity — that
``tools/postmortem.py`` can reconstruct a timeline and cost summary
from with zero access to the process that died.

Discipline mirrors the rest of the telemetry plane: ``DS_FLIGHT_RECORDER``
defaults off (DS013 — the off path is the bit-reference and swaps in
the constant-time :class:`NoopFlightRecorder`); when on, the recorder
is *always armed* — it costs nothing until an incident (the tracer
ring it dumps already exists), then one ``json.dump`` on the failure
path, which is already off the hot loop. Artifacts are bounded: at
most :attr:`FlightRecorder.MAX_ARTIFACTS` files are kept per
directory, oldest deleted first.
"""

import json
import os
import tempfile
import time
import zlib
from typing import Callable, Dict, List, Optional

__all__ = ["ARTIFACT_VERSION", "FlightRecorder", "NoopFlightRecorder",
           "NOOP_FLIGHT", "canonical_json", "verify_artifact",
           "load_artifact"]

#: bump when the body schema changes shape incompatibly;
#: tools/postmortem.py refuses versions it doesn't know
ARTIFACT_VERSION = 1


def canonical_json(body: Dict) -> str:
    """The canonical serialization the CRC is computed over: sorted
    keys, no whitespace. ``body`` must already be plain JSON data
    (the recorder normalizes through json before stamping, so the
    reader's recomputation is byte-identical)."""
    return json.dumps(body, sort_keys=True, separators=(",", ":"))


def _normalize(obj):
    """Force ``obj`` into plain JSON data (tuples -> lists, unknown
    objects -> repr strings) so the CRC survives a write/read cycle."""
    return json.loads(json.dumps(obj, default=repr))


class FlightRecorder:
    """Armed recorder bound to a set of section providers.

    ``sections`` maps section name -> zero-arg callable returning that
    section's plain data; providers are called only at :meth:`dump`
    time, and one failing provider degrades to an ``{"error": ...}``
    stub instead of losing the artifact (a postmortem writer must not
    itself crash the postmortem)."""

    enabled = True
    MAX_ARTIFACTS = 8

    def __init__(self, outdir: Optional[str] = None,
                 sections: Optional[Dict[str, Callable[[], object]]] = None,
                 label: str = "serving"):
        self.outdir = outdir or os.path.join(tempfile.gettempdir(),
                                             "ds_flight")
        self.label = label
        self.sections: Dict[str, Callable[[], object]] = dict(sections or {})
        self.dumps: List[str] = []        # paths written this process
        self._seq = 0

    def add_section(self, name: str, provider: Callable[[], object]) -> None:
        self.sections[name] = provider

    # .. the one real entry point ......................................

    def dump(self, reason: str, extra: Optional[Dict] = None) -> str:
        """Write one postmortem artifact; returns its path. Never
        raises on provider failure — the artifact records the error."""
        body: Dict = {
            "schema": ARTIFACT_VERSION,
            "label": self.label,
            "reason": str(reason),
            "wall_time": time.time(),
            "identity": _identity(),
        }
        for name, provider in self.sections.items():
            try:
                body[name] = provider()
            except Exception as e:          # provider must not kill dump
                body[name] = {"error": f"{type(e).__name__}: {e}"}
        if extra:
            body["extra"] = extra
        body = _normalize(body)
        artifact = {
            "version": ARTIFACT_VERSION,
            "crc32": zlib.crc32(canonical_json(body).encode("utf-8")),
            "body": body,
        }
        os.makedirs(self.outdir, exist_ok=True)
        self._seq += 1
        fname = (f"postmortem-{self.label}-{int(time.time() * 1000)}"
                 f"-{os.getpid()}-{self._seq}.json")
        path = os.path.join(self.outdir, fname)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(artifact, f, sort_keys=True)
        self.dumps.append(path)
        self._prune()
        return path

    def _prune(self) -> None:
        """Keep the artifact directory bounded: newest MAX_ARTIFACTS
        postmortems survive."""
        try:
            files = sorted(
                f for f in os.listdir(self.outdir)
                if f.startswith("postmortem-") and f.endswith(".json"))
            for stale in files[:-self.MAX_ARTIFACTS]:
                os.unlink(os.path.join(self.outdir, stale))
        except OSError:
            pass


class NoopFlightRecorder:
    """Off-mode twin: no directory, no sections, ``dump`` returns
    None — one attribute test on the failure path."""

    enabled = False
    outdir = None
    sections: Dict = {}
    dumps: List[str] = []

    def add_section(self, name, provider) -> None:
        pass

    def dump(self, reason: str, extra=None):
        return None


NOOP_FLIGHT = NoopFlightRecorder()


def _identity() -> Dict:
    """jax/platform identity, degrading gracefully when jax is absent
    (the postmortem reader never imports jax at all)."""
    import platform
    out: Dict = {"python": platform.python_version(),
                 "platform": platform.platform()}
    try:
        import jax
        out["jax"] = jax.__version__
        dev = jax.local_devices()[0]
        out["backend"] = dev.platform
        out["device_kind"] = dev.device_kind
        out["device_count"] = jax.local_device_count()
    except Exception as e:
        out["jax"] = f"unavailable: {type(e).__name__}"
    return out


# .. reader side (shared with tools/postmortem.py) ......................

def load_artifact(path: str) -> Dict:
    """Read + verify an artifact; returns the body. Raises ValueError
    on unknown version or CRC mismatch — a truncated or hand-edited
    postmortem must fail loudly, not analyze quietly."""
    with open(path, "r", encoding="utf-8") as f:
        artifact = json.load(f)
    verify_artifact(artifact)
    return artifact["body"]


def verify_artifact(artifact: Dict) -> None:
    if not isinstance(artifact, dict) or "body" not in artifact:
        raise ValueError("not a flight-recorder artifact (no body)")
    ver = artifact.get("version")
    if ver != ARTIFACT_VERSION:
        raise ValueError(f"unknown postmortem artifact version {ver!r} "
                         f"(reader knows {ARTIFACT_VERSION})")
    want = artifact.get("crc32")
    got = zlib.crc32(canonical_json(artifact["body"]).encode("utf-8"))
    if want != got:
        raise ValueError(f"postmortem CRC mismatch: stamped {want}, "
                         f"recomputed {got} — artifact corrupt")
