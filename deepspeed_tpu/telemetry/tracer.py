"""Request-lifecycle tracer — a host-side ring buffer of scheduler
events, exportable as a Chrome-trace / Perfetto JSON timeline.

Every request transition the scheduler makes lands here as one record:
``enqueue`` → ``admit`` (tagged with the prefix match and any COW) →
``prefill_chunk``* → ``prefill_done`` → ``first_token`` → ``evict`` /
re-``admit`` → ``finish`` (state done/timeout/shed), plus scheduler-
lane records (``step_phase`` breakdowns, ``watchdog``, ``degraded``)
and ``fault`` records streamed in from
:class:`deepspeed_tpu.utils.faults.FaultInjector` listeners — so a
seeded chaos run replays as a single ordered timeline
(docs/OBSERVABILITY.md has the schema, docs/ROBUSTNESS.md the chaos
cross-reference).

The buffer is a preallocated ring of fixed capacity: recording is one
tuple build + indexed store (no growth, no I/O, no device work), old
records are overwritten once the ring wraps (``dropped`` counts them),
and nothing is serialized until :meth:`export` — so the tracer can sit
inside the scheduler hot loop without breaking the DS001 sync-free
contract or the zero-recompile CompileWatch pin.

Export builds per-request lifecycle SPANS from the point records: a
``queued`` span per enqueue→admit interval, ``prefill`` per
admit→prefill_done, ``decode`` per prefill_done→(finish|evict); an
evicted request simply opens a new queued span, so a preempted
lifecycle shows up as repeated queued/prefill/decode triples on one
timeline row. Faults and scheduler phases ride along as instant/slice
events on the scheduler row (tid 0).
"""

import json
import time
from typing import Any, Callable, Dict, List, Optional

# record layout: (ts, etype, rid, step, slot, data-dict-or-None)
_TS, _ETYPE, _RID, _STEP, _SLOT, _DATA = range(6)

# lifecycle phases, in the order a healthy request traverses them
SPAN_QUEUED = "queued"
SPAN_PREFILL = "prefill"
SPAN_DECODE = "decode"


class RequestTracer:
    """Ring-buffered event recorder. ``event()`` is the only hot-path
    entry point; everything else is export-time."""

    enabled = True

    def __init__(self, capacity: int = 65536,
                 clock: Callable[[], float] = time.perf_counter):
        self.capacity = int(capacity)
        if self.capacity <= 0:
            raise ValueError("tracer capacity must be positive")
        self._clock = clock
        self._buf: List[Optional[tuple]] = [None] * self.capacity
        self._n = 0          # total records ever written

    # -- recording (hot path) ------------------------------------------
    def event(self, etype: str, rid: Any = None, step: int = -1,
              slot: int = -1, **data) -> None:
        self._buf[self._n % self.capacity] = (
            self._clock(), etype, rid, step, slot, data or None)
        self._n += 1

    # -- inspection ----------------------------------------------------
    @property
    def dropped(self) -> int:
        return max(0, self._n - self.capacity)

    def records(self) -> List[tuple]:
        """Surviving records, oldest first."""
        if self._n <= self.capacity:
            return [r for r in self._buf[:self._n]]
        head = self._n % self.capacity
        return self._buf[head:] + self._buf[:head]

    def events_of(self, rid: Any) -> List[tuple]:
        return [r for r in self.records() if r[_RID] == rid]

    def reset(self) -> None:
        self._buf = [None] * self.capacity
        self._n = 0

    # -- export --------------------------------------------------------
    def to_chrome_trace(self) -> Dict:
        """Chrome-trace/Perfetto JSON object. pid 1 is the serving
        process; tid 0 the scheduler lane (step phases, faults,
        watchdog); tids 1.. one lane per request in first-seen order.
        Request lifecycles become ``ph: "X"`` complete events; faults
        and terminal states become ``ph: "i"`` instants; sampled step
        occupancy becomes a ``ph: "C"`` counter track."""
        recs = self.records()
        events: List[Dict] = [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "deepspeed_tpu.serving"}},
            {"ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
             "args": {"name": "scheduler"}},
        ]
        if not recs:
            return {"traceEvents": events, "displayTimeUnit": "ms",
                    "dropped_events": 0}
        t0 = recs[0][_TS]

        def us(ts: float) -> float:
            return round((ts - t0) * 1e6, 3)

        tids: Dict[Any, int] = {}

        def tid_of(rid: Any) -> int:
            t = tids.get(rid)
            if t is None:
                t = tids[rid] = len(tids) + 1
                events.append({"ph": "M", "pid": 1, "tid": t,
                               "name": "thread_name",
                               "args": {"name": f"req {rid}"}})
            return t

        # open[rid] = (span_name, start_ts, start_args)
        open_span: Dict[Any, tuple] = {}

        def close(rid: Any, ts: float, extra: Optional[Dict] = None) -> None:
            sp = open_span.pop(rid, None)
            if sp is None:
                return
            name, start, args = sp
            a = {"rid": str(rid)}
            a.update(args or {})
            a.update(extra or {})
            events.append({"ph": "X", "pid": 1, "tid": tid_of(rid),
                           "cat": "request", "name": name,
                           "ts": us(start), "dur": us(ts) - us(start),
                           "args": a})

        for ts, etype, rid, step, slot, data in recs:
            data = data or {}
            if etype == "enqueue":
                close(rid, ts)           # defensive: rid reuse
                open_span[rid] = (SPAN_QUEUED, ts, {})
            elif etype == "admit":
                close(rid, ts)
                open_span[rid] = (SPAN_PREFILL, ts, {
                    "slot": slot,
                    "prefix_hit": bool(data.get("matched", 0)),
                    "matched_tokens": data.get("matched", 0)})
            elif etype == "prefill_done":
                close(rid, ts)
                open_span[rid] = (SPAN_DECODE, ts, {"slot": slot})
            elif etype == "evict":
                close(rid, ts, {"evicted": True})
                open_span[rid] = (SPAN_QUEUED, ts, {"requeued": True})
                events.append({"ph": "i", "pid": 1, "tid": tid_of(rid),
                               "cat": "request", "name": "evict",
                               "ts": us(ts), "s": "t",
                               "args": {"rid": str(rid), "slot": slot,
                                        "step": step}})
            elif etype == "finish":
                state = data.get("state", "done")
                # a request shed/timed out straight from the queue (or a
                # prefill-final-chunk finish) closes whatever span is open
                if rid not in open_span:
                    open_span[rid] = (SPAN_QUEUED, ts, {})
                close(rid, ts, {"state": state})
                events.append({"ph": "i", "pid": 1, "tid": tid_of(rid),
                               "cat": "request", "name": f"finish:{state}",
                               "ts": us(ts), "s": "t",
                               "args": {"rid": str(rid), "step": step,
                                        "generated":
                                            data.get("generated", 0)}})
            elif etype == "step_phase":
                # consecutive slices on the scheduler lane, one per phase
                start = ts - data.get("total_s", 0.0)
                for ph in ("admission", "prefill", "decode", "bookkeeping"):
                    d = data.get(f"{ph}_s")
                    if d is None:
                        continue
                    events.append({"ph": "X", "pid": 1, "tid": 0,
                                   "cat": "step", "name": ph,
                                   "ts": us(start), "dur": round(d * 1e6, 3),
                                   "args": {"step": step}})
                    start += d
                if "occupancy" in data:
                    events.append({"ph": "C", "pid": 1, "name": "occupancy",
                                   "ts": us(ts),
                                   "args": {"slots": data["occupancy"]}})
            elif etype == "fault":
                events.append({"ph": "i", "pid": 1, "tid": 0,
                               "cat": "fault",
                               "name": f"fault:{data.get('site')}:"
                                       f"{data.get('kind')}",
                               "ts": us(ts), "s": "g",
                               "args": {"site": data.get("site"),
                                        "kind": data.get("kind"),
                                        "visit": data.get("visit"),
                                        "step": step}})
            else:
                # first_token, prefill_chunk, cow, cache_evict_block,
                # watchdog, degraded, ... — instant on the owning lane
                tid = tid_of(rid) if rid is not None else 0
                a = {"step": step}
                if rid is not None:
                    a["rid"] = str(rid)
                a.update(data)
                events.append({"ph": "i", "pid": 1, "tid": tid,
                               "cat": "scheduler", "name": etype,
                               "ts": us(ts), "s": "t", "args": a})
        # whatever is still open at export time renders as in-flight
        last = recs[-1][_TS]
        for rid in list(open_span):
            close(rid, last, {"in_flight": True})
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "dropped_events": self.dropped}

    def export(self, path: str) -> str:
        """Write the Chrome-trace JSON to ``path`` (load it in Perfetto
        / chrome://tracing, or ``tools/trace_analyze.py serve <path>``)."""
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


class NoopTracer:
    """DS_TELEMETRY=off twin: every entry point is a constant-time
    no-op, so the scheduler's call sites need no branching."""

    enabled = False
    capacity = 0
    dropped = 0

    def event(self, etype, rid=None, step=-1, slot=-1, **data) -> None:
        pass

    def events_of(self, rid) -> List[tuple]:
        return []

    def records(self) -> List[tuple]:
        return []

    def reset(self) -> None:
        pass

    def to_chrome_trace(self) -> Dict:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "dropped_events": 0}

    def export(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path
