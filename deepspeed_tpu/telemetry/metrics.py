"""Metrics registry — counters, gauges, fixed-bucket histograms.

Serving-side analog of the reference's engine-owned monitor
(deepspeed/monitor/*): one registry instance owns every metric the
scheduler emits, and two exporters turn it into the formats the rest of
the stack consumes — Prometheus text exposition (``to_prometheus``) for
scrape endpoints, and ``to_scalars`` tuples for
:class:`deepspeed_tpu.utils.monitor.Monitor` so training and serving
share one scalar sink.

Design constraints (docs/OBSERVABILITY.md):

- **host-side only** — observing a value is a dict lookup plus an int
  add; nothing here touches jax, so the registry can sit inside the
  scheduler hot loop without violating the dslint DS001 contract;
- **fixed buckets** — histograms bucket at observe time into
  preallocated cumulative-friendly counts (no per-observation
  allocation, no unbounded reservoir), and percentiles are estimated by
  linear interpolation inside the owning bucket — the classic
  Prometheus ``histogram_quantile`` math, reproduced host-side so
  ``infer_bench`` rows do not need a scrape cycle;
- **unit-agnostic** — serving clocks are caller-supplied (step index in
  tests, ``perf_counter`` seconds in the bench), so the default bucket
  ladder spans both regimes log-spaced.
"""

from bisect import bisect_left
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# log-ish ladder covering sub-millisecond wall clocks AND integer step
# clocks: 1-2.5-5 decades from 100us to 250 units
DEFAULT_BUCKETS: Tuple[float, ...] = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0)

# even ladder over [0, 1] for ratio-valued histograms (speculative
# accept_rate, hit rates): 5%-wide buckets keep the p50/p95 of a rate
# meaningful where the timing ladder above would dump every sample
# into two buckets
RATE_BUCKETS: Tuple[float, ...] = tuple(
    round(0.05 * i, 2) for i in range(1, 21))

# ladder for sampling-temperature histograms: a 0.0 bucket isolates
# greedy traffic, then 0.1-wide steps over the practical (0, 2] range
# (anything hotter lands in +Inf — it is noise-temperature anyway)
TEMP_BUCKETS: Tuple[float, ...] = (0.0,) + tuple(
    round(0.1 * i, 1) for i in range(1, 21))


def _fmt(v) -> str:
    """Prometheus sample formatting: integral values render without the
    trailing ``.0`` so counter lines stay the conventional ``name 42``."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class Counter:
    """Monotonic counter. ``value`` stays an int while fed ints (the
    serving stats view compares against ints in tests)."""
    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0

    def inc(self, n=1) -> None:
        self.value += n


class Gauge:
    """Last-write-wins instantaneous value."""
    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def set(self, v) -> None:
        self.value = v


class Histogram:
    """Fixed-bucket histogram with Prometheus ``le`` (inclusive upper
    bound) semantics; the last bucket is the implicit ``+Inf`` overflow.
    ``percentile`` linearly interpolates inside the owning bucket and
    clamps the overflow bucket to the largest observed value, so an
    estimate never exceeds reality.

    Alongside the cumulative buckets the histogram keeps a bounded ring
    of the most recent ``(at, value)`` observations so controllers can
    ask for "p99 over the last N clock units" (``window_summary``)
    instead of the lifetime digest. The ring is host-side and O(1) per
    observe; it never feeds the Prometheus exposition, which stays
    cumulative-only."""
    __slots__ = ("name", "help", "uppers", "counts", "sum", "count",
                 "_vmax", "_ring", "_seq")

    #: default ring depth — enough for a few windows of serving traffic
    #: without unbounded growth (SLO windows are tens of observations)
    WINDOW_CAPACITY = 1024

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None,
                 window_capacity: Optional[int] = None):
        self.name = name
        self.help = help
        ups = tuple(sorted(float(b) for b in
                           (DEFAULT_BUCKETS if buckets is None else buckets)))
        if not ups:
            raise ValueError(f"histogram {name}: needs >= 1 finite bucket")
        self.uppers = ups
        self.counts = [0] * (len(ups) + 1)   # [+ overflow]
        self.sum = 0.0
        self.count = 0
        self._vmax = 0.0
        cap = self.WINDOW_CAPACITY if window_capacity is None \
            else int(window_capacity)
        self._ring: deque = deque(maxlen=max(cap, 1))
        self._seq = 0

    def observe(self, v, at: Optional[float] = None) -> None:
        """Record one observation. ``at`` is the caller's clock (step
        index or seconds); when omitted it defaults to the observation
        sequence number so windows degrade to "last N observations"."""
        v = float(v)
        self.counts[bisect_left(self.uppers, v)] += 1
        self.sum += v
        self.count += 1
        if v > self._vmax:
            self._vmax = v
        self._ring.append((self._seq if at is None else float(at), v))
        self._seq += 1

    def window_values(self, window: Optional[float] = None,
                      now: Optional[float] = None) -> List[float]:
        """Raw values from the ring with ``at >= now - window``; the
        whole ring when ``window`` is None. ``now`` defaults to the
        newest observation's clock, so a quiet histogram still reports
        its latest window instead of an empty one."""
        if not self._ring:
            return []
        if window is None:
            return [v for _, v in self._ring]
        if now is None:
            now = self._ring[-1][0]
        lo = now - float(window)
        return [v for at, v in self._ring if at >= lo]

    def window_summary(self, window: Optional[float] = None,
                       now: Optional[float] = None) -> Dict[str, float]:
        """Exact p50/p95/p99/mean over the recent-observation ring —
        same keys as ``summary`` but computed from raw windowed values
        (numpy-style linear interpolation) rather than bucket counts."""
        vals = sorted(self.window_values(window, now))
        if not vals:
            return {"p50": 0.0, "p95": 0.0, "p99": 0.0,
                    "mean": 0.0, "count": 0.0}

        def pct(q: float) -> float:
            rank = (q / 100.0) * (len(vals) - 1)
            lo = int(rank)
            hi = min(lo + 1, len(vals) - 1)
            return vals[lo] + (vals[hi] - vals[lo]) * (rank - lo)

        return {"p50": pct(50), "p95": pct(95), "p99": pct(99),
                "mean": sum(vals) / len(vals), "count": float(len(vals))}

    def percentile(self, q: float) -> float:
        """Estimate the q-th percentile (q in [0, 100]) from the bucket
        counts — same interpolation as PromQL histogram_quantile."""
        if self.count == 0:
            return 0.0
        target = (q / 100.0) * self.count
        cum = 0
        lo = 0.0
        for i, ub in enumerate(self.uppers):
            c = self.counts[i]
            if c and cum + c >= target:
                frac = min(max((target - cum) / c, 0.0), 1.0)
                return min(lo + (ub - lo) * frac, self._vmax)
            cum += c
            lo = ub
        return self._vmax      # lives in the overflow bucket

    def summary(self) -> Dict[str, float]:
        """p50/p95/p99 digest — the shape Monitor.write_scalars expands
        into ``tag/p50`` style sub-scalars."""
        return {"p50": self.percentile(50), "p95": self.percentile(95),
                "p99": self.percentile(99),
                "mean": self.sum / self.count if self.count else 0.0,
                "count": float(self.count)}


class MetricsRegistry:
    """Name -> metric map with get-or-create accessors (re-requesting a
    name returns the same instance, so serving phases and exporters
    never race on registration order)."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        c = self._counters.get(name)
        if c is None:
            c = self._counters[name] = Counter(name, help)
        return c

    def gauge(self, name: str, help: str = "") -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = Gauge(name, help)
        return g

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            h = self._histograms[name] = Histogram(name, help, buckets)
        return h

    def names(self) -> List[str]:
        return (list(self._counters) + list(self._gauges)
                + list(self._histograms))

    # -- exporters -----------------------------------------------------
    def to_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4): HELP/TYPE headers
        per family, cumulative ``_bucket{le=...}`` series + ``_sum`` /
        ``_count`` for histograms."""
        out: List[str] = []
        for c in self._counters.values():
            if c.help:
                out.append(f"# HELP {c.name} {c.help}")
            out.append(f"# TYPE {c.name} counter")
            out.append(f"{c.name} {_fmt(c.value)}")
        for g in self._gauges.values():
            if g.help:
                out.append(f"# HELP {g.name} {g.help}")
            out.append(f"# TYPE {g.name} gauge")
            out.append(f"{g.name} {_fmt(g.value)}")
        for h in self._histograms.values():
            if h.help:
                out.append(f"# HELP {h.name} {h.help}")
            out.append(f"# TYPE {h.name} histogram")
            cum = 0
            for i, ub in enumerate(h.uppers):
                cum += h.counts[i]
                out.append(f'{h.name}_bucket{{le="{_fmt(ub)}"}} {cum}')
            out.append(f'{h.name}_bucket{{le="+Inf"}} {h.count}')
            out.append(f"{h.name}_sum {_fmt(h.sum)}")
            out.append(f"{h.name}_count {h.count}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> Dict[str, Dict]:
        """Plain-data dump (bench rows, DegradedError attachments)."""
        return {
            "counters": {n: c.value for n, c in self._counters.items()},
            "gauges": {n: g.value for n, g in self._gauges.items()},
            "histograms": {n: h.summary()
                           for n, h in self._histograms.items()},
        }

    def to_scalars(self, step: int) -> List[Tuple[str, object, int]]:
        """``(tag, value, step)`` tuples for Monitor.write_scalars —
        histogram entries carry their summary dict, which the monitor
        expands into ``tag/p50`` etc."""
        out: List[Tuple[str, object, int]] = []
        for n, c in self._counters.items():
            out.append((n, c.value, step))
        for n, g in self._gauges.items():
            out.append((n, g.value, step))
        for n, h in self._histograms.items():
            out.append((n, h.summary(), step))
        return out


def merge_registries(regs: Iterable["MetricsRegistry"]) -> "MetricsRegistry":
    """Fold several per-replica registries into one fleet view.

    Counters and gauges sum (the serving gauges — occupancy, queue
    depth, blocks in use — are extensive quantities, so the fleet total
    is the meaningful aggregate); histograms require an identical
    bucket ladder and merge bucket-wise, with the recent-observation
    rings interleaved by clock so ``window_summary`` on the merged
    histogram sees the fleet's latest traffic. The inputs are left
    untouched — this is a snapshot-style fold, safe to call every
    controller tick."""
    out = MetricsRegistry()
    for reg in regs:
        for n, c in reg._counters.items():
            out.counter(n, c.help).inc(c.value)
        for n, g in reg._gauges.items():
            mg = out.gauge(n, g.help)
            mg.set(mg.value + g.value)
        for n, h in reg._histograms.items():
            mh = out.histogram(n, h.help, h.uppers)
            if mh.uppers != h.uppers:
                raise ValueError(
                    f"histogram {n}: bucket ladders differ across "
                    f"replicas — fleet merge needs identical ladders")
            for i, c in enumerate(h.counts):
                mh.counts[i] += c
            mh.sum += h.sum
            mh.count += h.count
            if h._vmax > mh._vmax:
                mh._vmax = h._vmax
            merged = sorted(list(mh._ring) + list(h._ring),
                            key=lambda p: p[0])
            mh._ring.clear()
            mh._ring.extend(merged[-mh._ring.maxlen:])
            mh._seq = max(mh._seq, h._seq)
    return out
