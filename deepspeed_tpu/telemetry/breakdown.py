"""Step-time breakdown — sampled per-phase attribution of scheduler
steps (admission / prefill / decode / bookkeeping).

The serving loop is deliberately sync-free: device dispatches are
asynchronous and dslint's DS001 forbids blocking host syncs in the hot
loop. Accurate phase attribution, however, NEEDS a device barrier —
otherwise prefill's dispatch cost books under decode and decode's under
next step's admission. This hook resolves the tension the same way
``utils/timer.py``'s SynchronizedWallClockTimer does: synchronize, then
read the wall clock — but only on SAMPLED steps (every
``sample_every``-th), so steady-state steps pay one modulo + branch and
the compile/parity contracts are untouched (the sync is
``block_until_ready`` on values the step already produced; it keys no
new programs).

Sampled laps land in the registry (``serving_step_<phase>_s``
histograms + ``serving_step_s`` total) and in the tracer as one
``step_phase`` record, which the Chrome-trace export renders as
consecutive slices on the scheduler lane.
"""

import time
from typing import Callable, Dict, Optional

from deepspeed_tpu.telemetry.metrics import MetricsRegistry

PHASES = ("admission", "prefill", "decode", "bookkeeping")

# wall-seconds ladder: scheduler phases run 10us..1s on CPU/TPU hosts
_PHASE_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
                  5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


class StepBreakdown:
    """Drive from the scheduler as::

        sampled = bd.begin(step_idx, sync=engine_sync)   # maybe sync+stamp
        ...admission work...;  bd.lap("admission")
        ...prefill work...;    bd.lap("prefill")
        ...decode work...;     bd.lap("decode")
        ...bookkeeping...;     bd.finish(occupancy=occ)  # lap + record

    On non-sampled steps every call is a single boolean check."""

    enabled = True

    def __init__(self, registry: MetricsRegistry, tracer,
                 sample_every: int = 16):
        self.sample_every = max(1, int(sample_every))
        self._tracer = tracer
        self._hists = {
            ph: registry.histogram(
                f"serving_step_{ph}_s",
                help=f"sampled wall seconds per step in the {ph} phase",
                buckets=_PHASE_BUCKETS)
            for ph in PHASES}
        self._total = registry.histogram(
            "serving_step_s", help="sampled total wall seconds per step",
            buckets=_PHASE_BUCKETS)
        self._sampling = False
        self._step = -1
        self._sync: Optional[Callable[[], None]] = None
        self._t0 = 0.0
        self._durs: Dict[str, float] = {}

    def begin(self, step: int, sync: Optional[Callable[[], None]] = None
              ) -> bool:
        """Arm the breakdown for ``step`` if it is a sampled one. The
        sync drains work queued by PREVIOUS steps so the first lap is
        not billed for their tail."""
        self._sampling = (step % self.sample_every == 0)
        if not self._sampling:
            return False
        self._step = step
        self._sync = sync
        self._durs = {}
        if sync is not None:
            sync()
        self._t0 = time.perf_counter()
        return True

    def lap(self, phase: str) -> None:
        """Close the current phase: sync (device work dispatched during
        the phase bills to it, not to the next) and stamp."""
        if not self._sampling:
            return
        if self._sync is not None:
            self._sync()
        t = time.perf_counter()
        self._durs[phase] = self._durs.get(phase, 0.0) + (t - self._t0)
        self._t0 = t

    def finish(self, occupancy: Optional[int] = None) -> None:
        """Final lap (everything since the decode lap is bookkeeping),
        then publish: histograms per phase + one tracer record."""
        if not self._sampling:
            return
        self.lap("bookkeeping")
        self._sampling = False
        total = sum(self._durs.values())
        for ph, d in self._durs.items():
            self._hists[ph].observe(d)
        self._total.observe(total)
        data = {f"{ph}_s": d for ph, d in self._durs.items()}
        data["total_s"] = total
        if occupancy is not None:
            data["occupancy"] = int(occupancy)
        self._tracer.event("step_phase", step=self._step, **data)


class NoopBreakdown:
    """DS_TELEMETRY=off twin: ``begin`` reports not-sampled and every
    other call is a constant-time no-op."""

    enabled = False
    sample_every = 0

    def begin(self, step, sync=None) -> bool:
        return False

    def lap(self, phase) -> None:
        pass

    def finish(self, occupancy=None) -> None:
        pass
