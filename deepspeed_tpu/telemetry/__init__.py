"""Serving telemetry — request-lifecycle tracing, a metrics registry
with Prometheus/Perfetto exporters, and a sampled step-time breakdown.

The reproduction's analog of the reference's engine-owned monitoring
(deepspeed/monitor/* + the flops profiler), at serving granularity: an
iteration-level scheduler is exactly the system where aggregate
counters hide what matters (per-request queue wait, TTFT, TPOT,
eviction/COW/retry timelines), so this package gives the
:class:`~deepspeed_tpu.inference.serving.ServingEngine` a first-class
observability plane — see docs/OBSERVABILITY.md for the metric catalog,
trace schema and overhead notes.

Three pieces, one facade:

- :class:`~deepspeed_tpu.telemetry.metrics.MetricsRegistry` — counters,
  gauges, fixed-bucket histograms; exports Prometheus text exposition
  and Monitor-compatible scalar tuples;
- :class:`~deepspeed_tpu.telemetry.tracer.RequestTracer` — ring-
  buffered host-side lifecycle events; exports Chrome-trace/Perfetto
  JSON (``tools/trace_analyze.py serve <file>`` reads it);
- :class:`~deepspeed_tpu.telemetry.breakdown.StepBreakdown` — sampled
  per-phase step timing under the ``utils/timer.py`` device-sync
  discipline.

Enablement mirrors the prefix-cache knob: explicit ``telemetry=`` on
``ServingEngine`` wins, else ``DS_TELEMETRY=on|off`` (default OFF — the
off path swaps in constant-time no-op twins, so the hot loop pays one
attribute access per call site and the compile/parity contracts are
byte-identical either way).
"""

import time
from typing import Optional

from deepspeed_tpu.utils.env import resolve_flag
from deepspeed_tpu.telemetry.breakdown import (NoopBreakdown, PHASES,
                                               StepBreakdown)
from deepspeed_tpu.telemetry.metrics import (Counter, DEFAULT_BUCKETS,
                                             Gauge, Histogram,
                                             MetricsRegistry,
                                             RATE_BUCKETS, TEMP_BUCKETS,
                                             merge_registries)
from deepspeed_tpu.telemetry.tracer import NoopTracer, RequestTracer
from deepspeed_tpu.telemetry.costs import (CostAccountant,
                                           NOOP_COSTS,
                                           NoopCostAccountant,
                                           ProgramCostRegistry,
                                           device_peak_flops,
                                           model_flops_per_token)
from deepspeed_tpu.telemetry.flight import (FlightRecorder, NOOP_FLIGHT,
                                            NoopFlightRecorder,
                                            load_artifact)

__all__ = ["Telemetry", "NoopTelemetry", "NOOP", "resolve_telemetry",
           "MetricsRegistry", "Counter", "Gauge", "Histogram",
           "RequestTracer", "NoopTracer", "StepBreakdown",
           "NoopBreakdown", "PHASES", "DEFAULT_BUCKETS", "RATE_BUCKETS",
           "TEMP_BUCKETS", "merge_registries",
           "CostAccountant", "NoopCostAccountant", "NOOP_COSTS",
           "ProgramCostRegistry", "device_peak_flops",
           "model_flops_per_token",
           "FlightRecorder", "NoopFlightRecorder", "NOOP_FLIGHT",
           "load_artifact"]


def resolve_telemetry(flag: Optional[bool] = None) -> bool:
    """Explicit flag wins; else the ``DS_TELEMETRY`` env knob; default
    off (the no-op plane is the bit-reference)."""
    return resolve_flag("DS_TELEMETRY", flag)


class Telemetry:
    """Live bundle: one registry + one tracer + one breakdown, shared
    by everything a single :class:`ServingEngine` emits. Pass an
    instance to several engines to aggregate, or one per engine to
    keep timelines separate."""

    enabled = True

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 trace_capacity: int = 65536, sample_every: int = 16,
                 clock=time.perf_counter):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.tracer = RequestTracer(capacity=trace_capacity, clock=clock)
        self.breakdown = StepBreakdown(self.registry, self.tracer,
                                       sample_every=sample_every)

    # convenience exporters -------------------------------------------
    def to_prometheus(self) -> str:
        return self.registry.to_prometheus()

    def export_trace(self, path: str) -> str:
        return self.tracer.export(path)

    def to_scalars(self, step: int):
        return self.registry.to_scalars(step)


class NoopTelemetry:
    """Off-mode bundle: no registry (the engine keeps a private one for
    the stats view), no recording, no sampling."""

    enabled = False
    registry = None

    def __init__(self):
        self.tracer = NoopTracer()
        self.breakdown = NoopBreakdown()

    def to_prometheus(self) -> str:
        return ""

    def export_trace(self, path: str) -> str:
        return self.tracer.export(path)

    def to_scalars(self, step: int):
        return []


NOOP = NoopTelemetry()
