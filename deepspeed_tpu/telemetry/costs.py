"""Cost-accounting plane: analytic cost model, XLA program cost
registry, and per-dispatch attribution.

The reproduction's serving-side answer to the reference's
``deepspeed/profiling/`` flops profiler: the telemetry plane (metrics/
tracer/breakdown) can say how *long* a request took, this module says
what it *cost* — FLOPs, HBM bytes, and KV block-seconds — per program,
per request, and per tenant. Three pieces:

- **analytic model** — integer FLOPs/bytes formulas derived from the
  one source of truth in ``models/gpt.py`` (``num_params``,
  ``kv_bytes_per_token``); the training-side flops profiler
  (``profiling/flops_profiler``) imports its per-token constants from
  here so the two sides can never disagree;
- :class:`ProgramCostRegistry` — walks the shared
  ``utils/jit_registry.py`` engine program catalog and records, per
  compiled twin, XLA's own ``cost_analysis()``/``memory_analysis()``
  numbers when a lowered executable is available, falling back to the
  analytic formulas at a reference shape when XLA declines (so the
  registry is always populated, CPU included);
- :class:`CostAccountant` — exact integer per-dispatch charges rolled
  into global ``serving_flops_total``/``serving_hbm_bytes_total``/
  ``serving_kv_block_seconds`` counters AND per-request footprints,
  with tenant rollup keyed by ``adapter_id``. Charges are computed
  per live slot and summed into the globals from the *same* integers,
  so conservation (sum of footprints == global counters, per dispatch
  class) holds exactly by construction.

Everything here is host-side arithmetic on python ints — no jax calls
on the charge path, no device sync, zero new compiled programs
(``CompileWatch(0)`` holds with the plane on).
"""

import json
from typing import Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.utils.jit_registry import (DISPATCH_CLASSES,
                                              dispatch_class,
                                              engine_programs)

__all__ = ["PEAK_FLOPS", "device_peak_flops", "matmul_params",
           "model_flops_per_token", "attn_flops", "infer_flops",
           "infer_hbm_bytes", "weight_bytes", "split_even",
           "new_footprint", "merge_footprints", "ProgramCostRegistry",
           "CostAccountant", "NoopCostAccountant", "NOOP_COSTS"]

# dense peak flops per chip (bf16 MXU throughput) by device_kind
# prefix — the roofline denominator for MFU estimates. Extend as new
# generations appear in jax's device_kind strings. (Moved here from
# profiling/flops_profiler so serving and training share one table.)
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def device_peak_flops(device=None) -> Optional[float]:
    """Peak dense FLOP/s for ``device`` (default: first local device),
    longest-prefix matched against :data:`PEAK_FLOPS`; None when the
    platform is unknown (CPU, new TPU generations)."""
    if device is None:
        import jax
        devices = jax.local_devices()
        if not devices:
            return None
        device = devices[0]
    kind = getattr(device, "device_kind", "") or ""
    best = None
    best_len = -1
    for prefix, peak in PEAK_FLOPS.items():
        if kind.startswith(prefix) and len(prefix) > best_len:
            best, best_len = peak, len(prefix)
    return best


# --------------------------------------------------------------------------
# analytic model — integer formulas over models/gpt.py's param counts
# --------------------------------------------------------------------------

def matmul_params(cfg, include_head: bool = True) -> int:
    """Parameters that participate in a matmul per token — ``num_params``
    minus the wte lookup, with the logit projection counted when
    ``include_head`` (for tied embeddings the d*V head matmul is real
    compute even though the weight is shared with wte). The same N the
    training-side ``train_flops_per_token`` uses, so fwd = 2N and
    fwd+bwd = 6N agree."""
    from deepspeed_tpu.models.gpt import num_params
    n = num_params(cfg) - cfg.vocab_size * cfg.d_model
    if include_head and cfg.tie_embeddings:
        n += cfg.d_model * cfg.vocab_size
    return int(n)


def model_flops_per_token(cfg, include_head: bool = True) -> int:
    """Forward matmul FLOPs per token, attention excluded: 2 FLOPs per
    matmul parameter. One third of the training-side ``6N``."""
    return 2 * matmul_params(cfg, include_head)


def attn_flops(cfg, n_tokens: int, start_pos: int) -> int:
    """Forward attention-score FLOPs for ``n_tokens`` consecutive
    tokens starting at absolute position ``start_pos``: the token at
    position p attends over p+1 keys, QK^T and PV are each
    ``2 * d_model`` FLOPs per (query, key) pair per layer — the
    inference-shape refinement of the training formula's
    ``12 * L * d * s`` (which is 3x fwd at full context)."""
    n, s = int(n_tokens), int(start_pos)
    ctx_sum = n * s + (n * (n + 1)) // 2     # sum of (s + i + 1)
    return 4 * cfg.n_layers * cfg.d_model * ctx_sum


def infer_flops(cfg, n_tokens: int, start_pos: int,
                include_head: bool = True) -> int:
    """Total forward FLOPs to process ``n_tokens`` new tokens of one
    sequence whose cache already holds ``start_pos`` tokens — linear
    (weight matmul) plus causal attention. Exact integer."""
    return (int(n_tokens) * model_flops_per_token(cfg, include_head)
            + attn_flops(cfg, n_tokens, start_pos))


def weight_bytes(cfg, param_itemsize: int = 2) -> int:
    """Bytes of model weights one dispatch streams from HBM (every
    program reads the full parameter set once per dispatch)."""
    from deepspeed_tpu.models.gpt import num_params
    return int(num_params(cfg)) * int(param_itemsize)


def infer_hbm_bytes(cfg, n_tokens: int, start_pos: int,
                    kv_bytes_tok: int, param_itemsize: int = 2,
                    include_weights: bool = True) -> int:
    """Analytic HBM traffic for one sequence's share of a dispatch:
    KV-cache reads (each new token streams the cache up to its
    position) plus KV writes for the new tokens, plus optionally one
    full weight read (callers split the weight read across the live
    slots of a batched dispatch — see :func:`split_even`)."""
    n, s = int(n_tokens), int(start_pos)
    ctx_sum = n * s + (n * (n + 1)) // 2
    kv = int(kv_bytes_tok) * (ctx_sum + n)    # reads + writes
    return kv + (weight_bytes(cfg, param_itemsize) if include_weights
                 else 0)


def split_even(total: int, n: int) -> List[int]:
    """Split integer ``total`` into ``n`` integer shares that sum to
    ``total`` exactly — ``total // n`` each, remainder distributed one
    unit at a time to the first ``total % n`` shares. The primitive
    that keeps per-request attribution conservative to the FLOP."""
    if n <= 0:
        return []
    q, r = divmod(int(total), n)
    return [q + 1 if i < r else q for i in range(n)]


# --------------------------------------------------------------------------
# per-request footprint
# --------------------------------------------------------------------------

def new_footprint() -> Dict:
    """Empty per-request cost footprint: per dispatch class a
    (dispatches, flops, hbm_bytes) triple, plus KV block-seconds
    integrated at horizon boundaries. Plain data — it rides request
    snapshots across router drains unchanged."""
    fp = {cls: {"dispatches": 0, "flops": 0, "hbm_bytes": 0}
          for cls in DISPATCH_CLASSES}
    fp["block_seconds"] = 0
    return fp


def merge_footprints(fps: Sequence[Dict]) -> Dict:
    """Sum footprints (tenant/fleet rollup)."""
    out = new_footprint()
    for fp in fps:
        if not fp:
            continue
        for cls in DISPATCH_CLASSES:
            for k in ("dispatches", "flops", "hbm_bytes"):
                out[cls][k] += fp.get(cls, {}).get(k, 0)
        out["block_seconds"] += fp.get("block_seconds", 0)
    return out


def footprint_totals(fp: Dict) -> Dict[str, int]:
    """Collapse a footprint to its cross-class totals."""
    return {
        "flops": sum(fp[c]["flops"] for c in DISPATCH_CLASSES),
        "hbm_bytes": sum(fp[c]["hbm_bytes"] for c in DISPATCH_CLASSES),
        "dispatches": sum(fp[c]["dispatches"] for c in DISPATCH_CLASSES),
        "block_seconds": fp["block_seconds"],
    }


# --------------------------------------------------------------------------
# program cost registry
# --------------------------------------------------------------------------

class ProgramCostRegistry:
    """Static per-program cost card for every serving executable in the
    shared ``utils/jit_registry.py`` catalog.

    :meth:`populate` walks ``engine_programs()`` against a live engine:
    when the caller supplies compiled executables (or asks for an AOT
    probe) each entry records XLA's own ``cost_analysis()`` FLOPs /
    bytes-accessed and ``memory_analysis()`` peak/argument/output
    bytes; when XLA declines — the CPU backend reports neither — the
    entry falls back to the analytic formulas above at a reference
    shape, so the registry is populated either way. Entries are plain
    dicts; ``to_json()`` is the flight-recorder section."""

    def __init__(self):
        self.entries: Dict[str, Dict] = {}

    # .. population .....................................................

    def populate(self, engine, cache=None, compiled=None) -> None:
        """Fill one entry per registered twin present on ``engine``.

        ``compiled`` optionally maps program id -> an object exposing
        ``cost_analysis()``/``memory_analysis()`` (an AOT
        ``jfn.lower(...).compile()`` result); entries without one get
        the analytic fallback. ``cache`` (a PagedKVCache) refines the
        KV byte constants; without it the fp32/bf16 defaults from the
        config dtype are used."""
        from deepspeed_tpu.models.gpt import kv_bytes_per_token
        cfg = engine.cfg
        try:
            import numpy as _np
            param_itemsize = int(_np.dtype(engine.dtype).itemsize)
        except Exception:
            param_itemsize = 2
        if cache is not None:
            kv_tok = int(cache.bytes_per_token)
        else:
            kv_tok = int(kv_bytes_per_token(cfg, engine.dtype))
        block = int(getattr(cache, "block_size", 16) or 16)
        block_bytes = kv_tok * block
        ref_ctx = max(1, int(cfg.max_seq_len) // 2)

        for pid, attr, cls in engine_programs():
            if getattr(engine, attr, None) is None:
                continue
            entry = {"program": pid, "attr": attr,
                     "dispatch_class": cls, "source": "analytic"}
            entry.update(self._analytic(cfg, cls, kv_tok, block_bytes,
                                        param_itemsize, ref_ctx))
            exe = (compiled or {}).get(pid)
            if exe is not None:
                xla = probe_compiled(exe)
                if xla:
                    entry["source"] = "xla"
                    entry.update(xla)
            self.entries[pid] = entry

    @staticmethod
    def _analytic(cfg, cls: str, kv_tok: int, block_bytes: int,
                  param_itemsize: int, ref_ctx: int) -> Dict:
        """Reference-shape cost card: one token (prefill/decode/verify)
        at half the model's max context, one block (cow/spill)."""
        if cls in ("prefill", "decode", "verify"):
            return {
                "flops": infer_flops(cfg, 1, ref_ctx),
                "bytes_accessed": infer_hbm_bytes(
                    cfg, 1, ref_ctx, kv_tok, param_itemsize),
                "flops_per_token": model_flops_per_token(cfg),
                "attn_flops_per_ctx_token": 4 * cfg.n_layers * cfg.d_model,
                "kv_bytes_per_token": kv_tok,
                "weight_bytes": weight_bytes(cfg, param_itemsize),
                "ref_context": ref_ctx,
            }
        # cow copies a block (read + write); spill moves one block one
        # way across the host interconnect
        moved = 2 * block_bytes if cls == "cow" else block_bytes
        return {"flops": 0, "bytes_accessed": moved,
                "block_bytes": block_bytes}

    # .. views ..........................................................

    def __len__(self) -> int:
        return len(self.entries)

    def get(self, pid: str) -> Optional[Dict]:
        return self.entries.get(pid)

    def export_gauges(self, registry) -> None:
        """Mirror each entry's headline numbers as gauges on a metrics
        registry (``program_flops_<pid>`` / ``program_hbm_bytes_<pid>``
        — declared as wildcard families in the telemetry schema)."""
        for pid, e in sorted(self.entries.items()):
            registry.gauge(f"program_flops_{pid}").set(e.get("flops", 0))
            registry.gauge(f"program_hbm_bytes_{pid}").set(
                e.get("bytes_accessed", 0))

    def to_json(self) -> Dict:
        return {"programs": {pid: dict(e)
                             for pid, e in sorted(self.entries.items())}}

    def dumps(self) -> str:
        return json.dumps(self.to_json(), sort_keys=True)


def probe_compiled(compiled) -> Dict:
    """Extract XLA's cost/memory analysis from a compiled executable,
    tolerating every historical shape of the API (dict, list-of-dict,
    absent, raising). Returns {} when XLA declines — the caller keeps
    its analytic numbers."""
    out: Dict = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if cost:
            if "flops" in cost:
                out["flops"] = int(cost["flops"])
            if "bytes accessed" in cost:
                out["bytes_accessed"] = int(cost["bytes accessed"])
    except (AttributeError, TypeError, ValueError, KeyError,
            IndexError, RuntimeError):
        pass        # XLA declined; the caller keeps analytic numbers
    try:
        mem = compiled.memory_analysis()
        for attr, key in (("temp_size_in_bytes", "peak_bytes"),
                          ("argument_size_in_bytes", "argument_bytes"),
                          ("output_size_in_bytes", "output_bytes")):
            v = getattr(mem, attr, None)
            if v is not None:
                out[key] = int(v)
    except (AttributeError, TypeError, ValueError, RuntimeError):
        pass        # memory analysis is backend-optional
    return out


# --------------------------------------------------------------------------
# per-dispatch accountant
# --------------------------------------------------------------------------

class CostAccountant:
    """Exact integer attribution of dispatch costs.

    One instance per :class:`ServingEngine`. Every charge computes the
    cost per live slot (each slot's own token count and cache context),
    adds the integers to that request's footprint AND the same integers
    to the global per-class totals — so the conservation invariant

        sum(per-request footprints) + system footprint == globals

    holds exactly per dispatch class, with no float rounding and no
    remainder leakage (:func:`split_even` handles shared costs such as
    the per-dispatch weight read). Costs with no owning request (spill
    of refcount-zero blocks) land in ``self.system``. When a metrics
    registry is supplied the cross-class totals also feed the
    ``serving_flops_total``/``serving_hbm_bytes_total``/
    ``serving_kv_block_seconds`` counters."""

    enabled = True

    def __init__(self, cfg, kv_bytes_tok: int, block_bytes: int,
                 param_itemsize: int = 2, registry=None):
        self.cfg = cfg
        self.kv_bytes_tok = int(kv_bytes_tok)
        self.block_bytes = int(block_bytes)
        self.param_itemsize = int(param_itemsize)
        self._weight_bytes = weight_bytes(cfg, param_itemsize)
        self._flops_tok = model_flops_per_token(cfg)
        self.totals = {cls: {"dispatches": 0, "flops": 0, "hbm_bytes": 0}
                       for cls in DISPATCH_CLASSES}
        self.block_seconds_total = 0
        self.system = new_footprint()
        self.tenants: Dict[str, Dict] = {}
        self._c_flops = self._c_bytes = self._c_blocks = None
        if registry is not None:
            self._c_flops = registry.counter(
                "serving_flops_total",
                "analytic model FLOPs dispatched, all classes")
            self._c_bytes = registry.counter(
                "serving_hbm_bytes_total",
                "analytic HBM bytes moved, all classes")
            self._c_blocks = registry.counter(
                "serving_kv_block_seconds",
                "KV block residency integrated at horizon boundaries "
                "(scheduler-clock units)")

    # .. internals ......................................................

    def _tenant(self, req) -> Dict:
        key = getattr(req, "adapter_id", None) or "base"
        t = self.tenants.get(key)
        if t is None:
            t = self.tenants[key] = new_footprint()
        return t

    def _add(self, cls: str, req, flops: int, nbytes: int,
             dispatches: int = 0) -> None:
        for fp in ((req.cost if req is not None else self.system),
                   self.totals):
            slot = fp[cls]
            slot["flops"] += flops
            slot["hbm_bytes"] += nbytes
            slot["dispatches"] += dispatches
        if req is not None:
            t = self._tenant(req)[cls]
            t["flops"] += flops
            t["hbm_bytes"] += nbytes
            t["dispatches"] += dispatches
        else:
            # system charges roll up under a reserved tenant
            t = self.tenants.setdefault("system", new_footprint())[cls]
            t["flops"] += flops
            t["hbm_bytes"] += nbytes
            t["dispatches"] += dispatches
        if self._c_flops is not None:
            self._c_flops.inc(flops)
            self._c_bytes.inc(nbytes)

    # .. charge API (serving hot loop — host ints only) .................

    def charge_prefill(self, req, n_tokens: int, start_pos: int) -> None:
        """One prefill-chunk dispatch: single slot owns the whole cost,
        weight read included."""
        flops = infer_flops(self.cfg, n_tokens, start_pos)
        nbytes = infer_hbm_bytes(self.cfg, n_tokens, start_pos,
                                 self.kv_bytes_tok, self.param_itemsize)
        self._add("prefill", req, flops, nbytes, dispatches=1)

    def charge_batched(self, cls: str, items) -> None:
        """One batched dispatch (decode/horizon/verify): ``items`` is a
        sequence of ``(req, n_tokens, start_pos)`` per live slot. Each
        slot is charged its own KV/attention cost; the single weight
        read is split exactly across the live slots."""
        items = list(items)
        if not items:
            return
        shares = split_even(self._weight_bytes, len(items))
        for (req, n, s), wshare in zip(items, shares):
            flops = infer_flops(self.cfg, n, s)
            nbytes = infer_hbm_bytes(self.cfg, n, s, self.kv_bytes_tok,
                                     self.param_itemsize,
                                     include_weights=False) + wshare
            self._add(cls, req, flops, nbytes, dispatches=1)

    def charge_cow(self, req, n_blocks: int) -> None:
        """Copy-on-write block copies triggered by ``req``: read+write
        per block, no FLOPs."""
        if n_blocks <= 0:
            return
        self._add("cow", req, 0, 2 * self.block_bytes * int(n_blocks),
                  dispatches=int(n_blocks))

    def charge_spill(self, n_blocks: int, req=None,
                     restore: bool = False) -> None:
        """Host-tier block transfers (spill or restore): one-way block
        bytes each. Refcount-zero spills have no owner and land in the
        system footprint."""
        if n_blocks <= 0:
            return
        self._add("spill", req, 0, self.block_bytes * int(n_blocks),
                  dispatches=int(n_blocks))

    def charge_block_seconds(self, req, blocks: int, ticks: int) -> None:
        """KV residency integrated at a horizon boundary: ``blocks``
        held for ``ticks`` scheduler-clock units."""
        bs = int(blocks) * int(ticks)
        if bs <= 0:
            return
        req.cost["block_seconds"] += bs
        self._tenant(req)["block_seconds"] += bs
        self.block_seconds_total += bs
        if self._c_blocks is not None:
            self._c_blocks.inc(bs)

    # .. views ..........................................................

    def snapshot(self) -> Dict:
        """Plain-data dump for flight recorder / bench rows."""
        return {
            "totals": {cls: dict(v) for cls, v in self.totals.items()},
            "flops_total": sum(v["flops"] for v in self.totals.values()),
            "hbm_bytes_total": sum(v["hbm_bytes"]
                                   for v in self.totals.values()),
            "block_seconds_total": self.block_seconds_total,
            "system": {cls: dict(self.system[cls])
                       for cls in DISPATCH_CLASSES}
            | {"block_seconds": self.system["block_seconds"]},
            "tenants": {k: merge_footprints([v])
                        for k, v in sorted(self.tenants.items())},
        }


class NoopCostAccountant:
    """Off-mode twin: every charge is a constant-time no-op, so the
    accounting-off hot loop is bit-identical to pre-plane behavior."""

    enabled = False
    totals: Dict = {}
    tenants: Dict = {}
    block_seconds_total = 0

    def charge_prefill(self, req, n_tokens, start_pos):
        pass

    def charge_batched(self, cls, items):
        pass

    def charge_cow(self, req, n_blocks):
        pass

    def charge_spill(self, n_blocks, req=None, restore=False):
        pass

    def charge_block_seconds(self, req, blocks, ticks):
        pass

    def snapshot(self) -> Dict:
        return {}


NOOP_COSTS = NoopCostAccountant()
