"""deepspeed_tpu — a TPU-native large-model training & inference framework.

Public API mirrors the reference's surface (ref: deepspeed/__init__.py:50
initialize, :204 add_config_arguments, :220 init_inference) re-designed for
JAX/XLA: models are loss functions over parameter pytrees, parallelism is a
device mesh, and ZeRO stages are sharding specs.
"""

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

from deepspeed_tpu.version import __version__
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.parallel import mesh as _mesh_lib
from deepspeed_tpu.utils.logging import logger, log_dist


def _infer_world_size(mesh=None, config_dict=None) -> int:
    import jax
    if mesh is not None:
        return _mesh_lib.dp_world_size(mesh)
    n = len(jax.devices())
    if config_dict:
        mc = (config_dict.get("mesh") or {})
        fixed = (mc.get("tensor_parallel_size", 1) *
                 mc.get("pipeline_parallel_size", 1) *
                 mc.get("sequence_parallel_size", 1))
        return max(1, n // fixed)
    return n


def initialize(args=None,
               model: Optional[Callable] = None,
               optimizer=None,
               model_parameters: Optional[Any] = None,
               training_data=None,
               lr_scheduler=None,
               mesh=None,
               partition_rules: Optional[Sequence] = None,
               config: Optional[Union[str, Dict]] = None,
               config_params: Optional[Union[str, Dict]] = None,
               has_aux: bool = False,
               collate_fn=None):
    """Initialize the training engine (ref: deepspeed/__init__.py:50).

    Parameters
    ----------
    model : callable(params, batch, rng) -> loss | (loss, aux)
        The loss function. (The torch reference takes an nn.Module; the
        jax-native contract is a pure function + a parameter pytree.)
        ``deepspeed_tpu.models`` provides ready models exposing this.
    model_parameters : the fp32 parameter pytree.
    config : path to a JSON config or a dict (same schema as the reference).
    mesh : optional prebuilt jax.sharding.Mesh.
    partition_rules : optional tensor-parallel PartitionRules.

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)`` for
    tuple-compatibility with the reference; optimizer/lr_scheduler are the
    engine-owned objects.
    """
    config = config if config is not None else config_params
    assert config is not None, "deepspeed_tpu.initialize requires a config"
    assert model is not None, "deepspeed_tpu.initialize requires a loss function"
    assert model_parameters is not None, "model_parameters (param pytree) required"

    # LayeredModel -> parameter-streaming engine (the analog of the
    # reference's PipelineModule dispatch at deepspeed/__init__.py:118-142;
    # here the layered form enables the ZeRO-Infinity param tier,
    # ref: runtime/zero/partitioned_param_swapper.py). Single-chip by
    # design: the whole point is capacity beyond one chip's HBM.
    from deepspeed_tpu.runtime.zero.param_offload import (
        InfinityParamEngine, LayeredModel)
    if isinstance(model, LayeredModel):
        if optimizer is not None or mesh is not None or partition_rules:
            raise ValueError(
                "LayeredModel (param-streaming) engine owns its host "
                "optimizer and runs single-chip — optimizer/mesh/"
                "partition_rules are not supported; configure the "
                "optimizer via the JSON config instead")
        from deepspeed_tpu.runtime.lr_schedules import get_lr_schedule
        ds_config = DeepSpeedConfig(config, world_size=1)
        base_lr = (ds_config.optimizer.params or {}).get("lr", 1e-3)
        sched = lr_scheduler if callable(lr_scheduler) else get_lr_schedule(
            ds_config.scheduler.type, ds_config.scheduler.params,
            base_lr=base_lr)
        engine = InfinityParamEngine(model, model_parameters, ds_config,
                                     lr_schedule=sched)
        dataloader = None
        if training_data is not None:
            from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader
            dataloader = DeepSpeedDataLoader(
                training_data, batch_size=ds_config.train_batch_size,
                collate_fn=collate_fn)
        return engine, None, dataloader, sched

    config_dict = config if isinstance(config, dict) else None
    world_size = _infer_world_size(mesh, config_dict)
    ds_config = DeepSpeedConfig(config, world_size=world_size)

    engine = DeepSpeedEngine(
        loss_fn=model,
        params=model_parameters,
        config=ds_config,
        mesh=mesh,
        partition_rules=partition_rules,
        optimizer=optimizer,
        lr_schedule=lr_scheduler if callable(lr_scheduler) else None,
        has_aux=has_aux)

    dataloader = None
    if training_data is not None:
        from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader
        dataloader = DeepSpeedDataLoader(
            training_data,
            batch_size=ds_config.train_batch_size,
            collate_fn=collate_fn)

    return engine, engine.optimizer, dataloader, engine.lr_schedule


def init_inference(model=None, **kwargs):
    """Inference engine entry (ref: deepspeed/__init__.py:220)."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    return InferenceEngine(model, **kwargs)


# activation checkpointing API, importable as deepspeed_tpu.checkpointing
# (ref: deepspeed.checkpointing re-export in deepspeed/__init__.py)
from deepspeed_tpu.runtime.activation_checkpointing import (  # noqa: E402
    checkpointing)


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config CLI args
    (ref: deepspeed/__init__.py:153-204)."""
    group = parser.add_argument_group("DeepSpeed-TPU",
                                      "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag to wire configs)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the DeepSpeed-TPU json configuration file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse_suppress())
    return parser


def argparse_suppress():
    import argparse
    return argparse.SUPPRESS


# zero namespace (ref: deepspeed.zero.Init re-export, deepspeed/__init__.py)
from deepspeed_tpu.runtime import zero  # noqa: E402
