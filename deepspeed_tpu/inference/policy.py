"""Injection policies: foreign checkpoints -> fused TPU layout.

Capability analog of the reference's policy registry
(ref: deepspeed/module_inject/replace_policy.py — HFBertLayerPolicy :49,
HFGPTNEOLayerPolicy :112, HFGPTJLayerPolicy :157, MegatronLayerPolicy :202,
HFGPT2LayerPolicy; applied by replace_transformer_layer
module_inject/replace_module.py:123). Instead of swapping nn.Modules
in-place, a policy converts a source model's weights into the framework's
stacked-layer GPT pytree, after which the fused JAX/Pallas blocks and TP
partition rules apply unchanged.
"""

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.gpt import GPTConfig
from deepspeed_tpu.utils.logging import logger

_POLICIES = {}


def register_policy(name: str):
    def deco(cls):
        _POLICIES[name] = cls
        return cls
    return deco


def resolve_model(model) -> Tuple[GPTConfig, Dict]:
    """Dispatch a user-passed model object/name to a policy."""
    for policy in _POLICIES.values():
        if policy.matches(model):
            return policy.convert(model)
    raise ValueError(
        f"no inference policy matches {type(model)}; known: "
        f"{list(_POLICIES)}")


@register_policy("hf_gpt2")
class HFGPT2Policy:
    """HuggingFace GPT-2 (torch) -> fused GPT layout
    (ref: HFGPT2LayerPolicy in replace_policy.py)."""

    @staticmethod
    def matches(model) -> bool:
        return type(model).__name__ in ("GPT2LMHeadModel", "GPT2Model")

    @staticmethod
    def convert(model) -> Tuple[GPTConfig, Dict]:
        import jax.numpy as jnp
        hf_cfg = model.config
        cfg = GPTConfig(
            vocab_size=hf_cfg.vocab_size,
            n_layers=hf_cfg.n_layer,
            n_heads=hf_cfg.n_head,
            d_model=hf_cfg.n_embd,
            max_seq_len=hf_cfg.n_positions,
            tie_embeddings=True)
        sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
        pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""

        def stack(fmt):
            return jnp.asarray(np.stack(
                [sd[pre + fmt.format(i)] for i in range(cfg.n_layers)]))

        params = {
            "wte": {"embedding": jnp.asarray(sd[pre + "wte.weight"])},
            "wpe": {"embedding": jnp.asarray(sd[pre + "wpe.weight"])},
            "block": {
                "ln1": {"scale": stack("h.{}.ln_1.weight"),
                        "bias": stack("h.{}.ln_1.bias")},
                # HF GPT-2 uses Conv1D: weight already [in, out]
                "qkv": {"kernel": stack("h.{}.attn.c_attn.weight"),
                        "bias": stack("h.{}.attn.c_attn.bias")},
                "attn_out": {"kernel": stack("h.{}.attn.c_proj.weight"),
                             "bias": stack("h.{}.attn.c_proj.bias")},
                "ln2": {"scale": stack("h.{}.ln_2.weight"),
                        "bias": stack("h.{}.ln_2.bias")},
                "mlp_in": {"kernel": stack("h.{}.mlp.c_fc.weight"),
                           "bias": stack("h.{}.mlp.c_fc.bias")},
                "mlp_out": {"kernel": stack("h.{}.mlp.c_proj.weight"),
                            "bias": stack("h.{}.mlp.c_proj.bias")},
            },
            "ln_f": {"scale": jnp.asarray(sd[pre + "ln_f.weight"]),
                     "bias": jnp.asarray(sd[pre + "ln_f.bias"])},
        }
        logger.info(f"injected HF GPT-2: {cfg.n_layers}L/{cfg.d_model}d")
        return cfg, params


@register_policy("hf_gpt_neo")
class HFGPTNeoPolicy:
    """HuggingFace GPT-Neo -> fused GPT layout
    (ref: HFGPTNEOLayerPolicy, replace_policy.py:112). GPT-Neo uses
    separate unbiased q/k/v projections and UNSCALED attention."""

    @staticmethod
    def matches(model) -> bool:
        return type(model).__name__ in ("GPTNeoForCausalLM", "GPTNeoModel")

    @staticmethod
    def convert(model) -> Tuple[GPTConfig, Dict]:
        import jax.numpy as jnp
        hf_cfg = model.config
        if any(t == "local" for t in getattr(hf_cfg, "attention_layers", [])):
            logger.warning(
                "GPT-Neo local (windowed) attention layers are converted as "
                "global attention; outputs will differ on those layers")
        cfg = GPTConfig(
            vocab_size=hf_cfg.vocab_size,
            n_layers=hf_cfg.num_layers,
            n_heads=hf_cfg.num_heads,
            d_model=hf_cfg.hidden_size,
            max_seq_len=hf_cfg.max_position_embeddings,
            tie_embeddings=True,
            attn_scale=1.0)   # GPT-Neo does not scale attention logits
        sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
        pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
        d = cfg.d_model

        def lin(fmt):
            """nn.Linear [out, in] -> [in, out], stacked over layers."""
            return np.stack([sd[pre + fmt.format(i)].T
                             for i in range(cfg.n_layers)])

        def vec(fmt):
            return np.stack([sd[pre + fmt.format(i)]
                             for i in range(cfg.n_layers)])

        qkv = np.concatenate(
            [lin("h.{}.attn.attention.q_proj.weight"),
             lin("h.{}.attn.attention.k_proj.weight"),
             lin("h.{}.attn.attention.v_proj.weight")], axis=-1)
        params = {
            "wte": {"embedding": jnp.asarray(sd[pre + "wte.weight"])},
            "wpe": {"embedding": jnp.asarray(sd[pre + "wpe.weight"])},
            "block": {
                "ln1": {"scale": jnp.asarray(vec("h.{}.ln_1.weight")),
                        "bias": jnp.asarray(vec("h.{}.ln_1.bias"))},
                "qkv": {"kernel": jnp.asarray(qkv),
                        "bias": jnp.zeros((cfg.n_layers, 3 * d), jnp.float32)},
                "attn_out": {
                    "kernel": jnp.asarray(
                        lin("h.{}.attn.attention.out_proj.weight")),
                    "bias": jnp.asarray(
                        vec("h.{}.attn.attention.out_proj.bias"))},
                "ln2": {"scale": jnp.asarray(vec("h.{}.ln_2.weight")),
                        "bias": jnp.asarray(vec("h.{}.ln_2.bias"))},
                "mlp_in": {"kernel": jnp.asarray(lin("h.{}.mlp.c_fc.weight")),
                           "bias": jnp.asarray(vec("h.{}.mlp.c_fc.bias"))},
                "mlp_out": {"kernel": jnp.asarray(lin("h.{}.mlp.c_proj.weight")),
                            "bias": jnp.asarray(vec("h.{}.mlp.c_proj.bias"))},
            },
            "ln_f": {"scale": jnp.asarray(sd[pre + "ln_f.weight"]),
                     "bias": jnp.asarray(sd[pre + "ln_f.bias"])},
        }
        logger.info(f"injected HF GPT-Neo: {cfg.n_layers}L/{cfg.d_model}d")
        return cfg, params


@register_policy("hf_gptj")
class HFGPTJPolicy:
    """HuggingFace GPT-J -> fused GPT layout
    (ref: HFGPTJLayerPolicy, replace_policy.py:157). GPT-J: rotary
    positions, parallel attn/MLP residual, no learned positions, untied
    biased lm_head."""

    @staticmethod
    def matches(model) -> bool:
        return type(model).__name__ in ("GPTJForCausalLM", "GPTJModel")

    @staticmethod
    def convert(model) -> Tuple[GPTConfig, Dict]:
        import jax.numpy as jnp
        hf_cfg = model.config
        cfg = GPTConfig(
            vocab_size=hf_cfg.vocab_size,
            n_layers=hf_cfg.n_layer,
            n_heads=hf_cfg.n_head,
            d_model=hf_cfg.n_embd,
            max_seq_len=hf_cfg.n_positions,
            tie_embeddings=False,
            rotary_dim=hf_cfg.rotary_dim,
            parallel_residual=True,
            use_wpe=False)
        sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
        pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
        d = cfg.d_model
        L = cfg.n_layers

        def lin(fmt):
            return np.stack([sd[pre + fmt.format(i)].T for i in range(L)])

        def vec(fmt):
            return np.stack([sd[pre + fmt.format(i)] for i in range(L)])

        qkv = np.concatenate([lin("h.{}.attn.q_proj.weight"),
                              lin("h.{}.attn.k_proj.weight"),
                              lin("h.{}.attn.v_proj.weight")], axis=-1)
        params = {
            "wte": {"embedding": jnp.asarray(sd[pre + "wte.weight"])},
            "block": {
                # ln_1 feeds both branches; ln2 is unused under
                # parallel_residual but kept as identity for layout parity
                "ln1": {"scale": jnp.asarray(vec("h.{}.ln_1.weight")),
                        "bias": jnp.asarray(vec("h.{}.ln_1.bias"))},
                "qkv": {"kernel": jnp.asarray(qkv),
                        "bias": jnp.zeros((L, 3 * d), jnp.float32)},
                "attn_out": {"kernel": jnp.asarray(lin("h.{}.attn.out_proj.weight")),
                             "bias": jnp.zeros((L, d), jnp.float32)},
                "ln2": {"scale": jnp.ones((L, d), jnp.float32),
                        "bias": jnp.zeros((L, d), jnp.float32)},
                "mlp_in": {"kernel": jnp.asarray(lin("h.{}.mlp.fc_in.weight")),
                           "bias": jnp.asarray(vec("h.{}.mlp.fc_in.bias"))},
                "mlp_out": {"kernel": jnp.asarray(lin("h.{}.mlp.fc_out.weight")),
                            "bias": jnp.asarray(vec("h.{}.mlp.fc_out.bias"))},
            },
            "ln_f": {"scale": jnp.asarray(sd[pre + "ln_f.weight"]),
                     "bias": jnp.asarray(sd[pre + "ln_f.bias"])},
            "lm_head": {"kernel": jnp.asarray(sd["lm_head.weight"].T),
                        "bias": jnp.asarray(sd["lm_head.bias"])},
        }
        logger.info(f"injected HF GPT-J: {cfg.n_layers}L/{cfg.d_model}d "
                    f"rotary_dim={cfg.rotary_dim}")
        return cfg, params


@register_policy("hf_bert")
class HFBertPolicy:
    """HuggingFace BERT -> fused encoder layout (models/bert.py)
    (ref: HFBertLayerPolicy, replace_policy.py:49). Post-LN:
    ln1 = attention.output.LayerNorm, ln2 = output.LayerNorm."""

    @staticmethod
    def matches(model) -> bool:
        return type(model).__name__ in ("BertModel", "BertForMaskedLM",
                                        "BertForPreTraining")

    @staticmethod
    def convert(model):
        import jax.numpy as jnp
        from deepspeed_tpu.models.bert import BertConfig
        hf_cfg = model.config
        cfg = BertConfig(
            vocab_size=hf_cfg.vocab_size,
            n_layers=hf_cfg.num_hidden_layers,
            n_heads=hf_cfg.num_attention_heads,
            d_model=hf_cfg.hidden_size,
            max_seq_len=hf_cfg.max_position_embeddings,
            type_vocab_size=hf_cfg.type_vocab_size,
            layer_norm_eps=hf_cfg.layer_norm_eps,
            pre_layer_norm=False)
        sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
        pre = "bert." if any(k.startswith("bert.") for k in sd) else ""
        L, d = cfg.n_layers, cfg.d_model
        enc = pre + "encoder.layer.{}."

        def lin(fmt):
            return np.stack([sd[(enc + fmt).format(i)].T for i in range(L)])

        def vec(fmt):
            return np.stack([sd[(enc + fmt).format(i)] for i in range(L)])

        qkv_k = np.concatenate([lin("attention.self.query.weight"),
                                lin("attention.self.key.weight"),
                                lin("attention.self.value.weight")], axis=-1)
        qkv_b = np.concatenate([vec("attention.self.query.bias"),
                                vec("attention.self.key.bias"),
                                vec("attention.self.value.bias")], axis=-1)
        emb = pre + "embeddings."
        params = {
            "embeddings": {
                "word": jnp.asarray(sd[emb + "word_embeddings.weight"]),
                "position": jnp.asarray(sd[emb + "position_embeddings.weight"]),
                "token_type": jnp.asarray(
                    sd[emb + "token_type_embeddings.weight"]),
                "ln": {"scale": jnp.asarray(sd[emb + "LayerNorm.weight"]),
                       "bias": jnp.asarray(sd[emb + "LayerNorm.bias"])},
            },
            "block": {
                "qkv": {"kernel": jnp.asarray(qkv_k),
                        "bias": jnp.asarray(qkv_b)},
                "attn_out": {
                    "kernel": jnp.asarray(lin("attention.output.dense.weight")),
                    "bias": jnp.asarray(vec("attention.output.dense.bias"))},
                "ln1": {"scale": jnp.asarray(
                            vec("attention.output.LayerNorm.weight")),
                        "bias": jnp.asarray(
                            vec("attention.output.LayerNorm.bias"))},
                "mlp_in": {"kernel": jnp.asarray(lin("intermediate.dense.weight")),
                           "bias": jnp.asarray(vec("intermediate.dense.bias"))},
                "mlp_out": {"kernel": jnp.asarray(lin("output.dense.weight")),
                            "bias": jnp.asarray(vec("output.dense.bias"))},
                "ln2": {"scale": jnp.asarray(vec("output.LayerNorm.weight")),
                        "bias": jnp.asarray(vec("output.LayerNorm.bias"))},
            },
        }
        # optional heads
        if pre + "pooler.dense.weight" in sd:
            params["pooler"] = {
                "kernel": jnp.asarray(sd[pre + "pooler.dense.weight"].T),
                "bias": jnp.asarray(sd[pre + "pooler.dense.bias"])}
        if "cls.predictions.transform.dense.weight" in sd:
            params["mlm"] = {
                "kernel": jnp.asarray(
                    sd["cls.predictions.transform.dense.weight"].T),
                "bias": jnp.asarray(sd["cls.predictions.transform.dense.bias"]),
                "ln": {"scale": jnp.asarray(
                           sd["cls.predictions.transform.LayerNorm.weight"]),
                       "bias": jnp.asarray(
                           sd["cls.predictions.transform.LayerNorm.bias"])},
                "decoder_bias": jnp.asarray(sd["cls.predictions.bias"]),
            }
        logger.info(f"injected HF BERT: {cfg.n_layers}L/{cfg.d_model}d post-LN")
        return cfg, params


@register_policy("hf_distilbert")
class HFDistilBertPolicy:
    """HuggingFace DistilBERT -> fused encoder layout
    (ref: HFDistilBertLayerPolicy in replace_policy.py). Post-LN like
    BERT; no token-type embeddings (a 1-row zero table keeps the fused
    encoder's segment lookup a no-op) and separate q/k/v projections."""

    @staticmethod
    def matches(model) -> bool:
        return type(model).__name__ in ("DistilBertModel",
                                        "DistilBertForMaskedLM")

    @staticmethod
    def convert(model):
        import jax.numpy as jnp
        from deepspeed_tpu.models.bert import BertConfig
        hf_cfg = model.config
        cfg = BertConfig(
            vocab_size=hf_cfg.vocab_size,
            n_layers=hf_cfg.n_layers,
            n_heads=hf_cfg.n_heads,
            d_model=hf_cfg.dim,
            max_seq_len=hf_cfg.max_position_embeddings,
            type_vocab_size=1,
            layer_norm_eps=1e-12,
            pre_layer_norm=False)
        sd = {k: v.detach().cpu().numpy()
              for k, v in model.state_dict().items()}
        pre = "distilbert." if any(k.startswith("distilbert.")
                                   for k in sd) else ""
        L, d = cfg.n_layers, cfg.d_model
        lay = pre + "transformer.layer.{}."

        def lin(fmt):
            return np.stack([sd[(lay + fmt).format(i)].T for i in range(L)])

        def vec(fmt):
            return np.stack([sd[(lay + fmt).format(i)] for i in range(L)])

        qkv_k = np.concatenate([lin("attention.q_lin.weight"),
                                lin("attention.k_lin.weight"),
                                lin("attention.v_lin.weight")], axis=-1)
        qkv_b = np.concatenate([vec("attention.q_lin.bias"),
                                vec("attention.k_lin.bias"),
                                vec("attention.v_lin.bias")], axis=-1)
        emb = pre + "embeddings."
        params = {
            "embeddings": {
                "word": jnp.asarray(sd[emb + "word_embeddings.weight"]),
                "position": jnp.asarray(
                    sd[emb + "position_embeddings.weight"]),
                "token_type": jnp.zeros((1, d), jnp.float32),
                "ln": {"scale": jnp.asarray(sd[emb + "LayerNorm.weight"]),
                       "bias": jnp.asarray(sd[emb + "LayerNorm.bias"])},
            },
            "block": {
                "qkv": {"kernel": jnp.asarray(qkv_k),
                        "bias": jnp.asarray(qkv_b)},
                "attn_out": {
                    "kernel": jnp.asarray(lin("attention.out_lin.weight")),
                    "bias": jnp.asarray(vec("attention.out_lin.bias"))},
                "ln1": {"scale": jnp.asarray(vec("sa_layer_norm.weight")),
                        "bias": jnp.asarray(vec("sa_layer_norm.bias"))},
                "mlp_in": {"kernel": jnp.asarray(lin("ffn.lin1.weight")),
                           "bias": jnp.asarray(vec("ffn.lin1.bias"))},
                "mlp_out": {"kernel": jnp.asarray(lin("ffn.lin2.weight")),
                            "bias": jnp.asarray(vec("ffn.lin2.bias"))},
                "ln2": {"scale": jnp.asarray(
                            vec("output_layer_norm.weight")),
                        "bias": jnp.asarray(vec("output_layer_norm.bias"))},
            },
        }
        logger.info(
            f"injected HF DistilBERT: {cfg.n_layers}L/{cfg.d_model}d post-LN")
        return cfg, params


@register_policy("megatron_sd")
class MegatronPolicy:
    """Megatron-LM GPT-2 state_dict -> fused GPT layout
    (ref: MegatronLayerPolicy, replace_policy.py:202; TP-resharding of
    these checkpoints lives in runtime/state_dict_factory.py). Accepts a
    raw (already TP-merged) Megatron state dict — torch Linear layout
    ([out, in] weights, transposed here) with the fused
    query_key_value projection stored q|k|v-contiguous (the "version 0"
    layout; interleaved megatron_v2 dicts should first pass through
    MegatronSDLoader.sanity-reorder)."""

    @staticmethod
    def matches(model) -> bool:
        if not isinstance(model, dict):
            return False
        return any("attention.query_key_value.weight" in k for k in model)

    @staticmethod
    def convert(model):
        import jax.numpy as jnp
        meta = dict(model.get("config", {})) if isinstance(
            model.get("config", None), dict) else {}
        sd = {k: (v.detach().cpu().numpy() if hasattr(v, "detach")
                  else np.asarray(v))
              for k, v in model.items() if k != "config"}
        # locate the layer prefix, e.g. "language_model.transformer.layers."
        probe = next(k for k in sd
                     if "attention.query_key_value.weight" in k)
        pre = probe.split("layers.")[0] + "layers."
        import re as _re
        L = 1 + max(int(_re.search(r"layers\.(\d+)\.", k).group(1))
                    for k in sd if pre in k)
        d = sd[probe].shape[1]
        emb_key = next(k for k in sd if "word_embeddings.weight" in k)
        pos_key = next(k for k in sd if "position_embeddings.weight" in k)
        n_heads = int(meta.get("n_heads", 0))
        if not n_heads:
            # Megatron's standard head_dim is 64; pass {"config":
            # {"n_heads": N}} in the dict to override
            assert d % 64 == 0, (
                f"cannot infer n_heads for d_model={d}; supply "
                "sd['config'] = {'n_heads': ...}")
            n_heads = d // 64
            logger.warning(
                f"Megatron policy: n_heads not given, assuming "
                f"head_dim=64 -> {n_heads} heads")

        def lin(fmt):
            return np.stack([sd[(pre + fmt).format(i)].T for i in range(L)])

        def vec(fmt):
            return np.stack([sd[(pre + fmt).format(i)] for i in range(L)])

        cfg = GPTConfig(
            vocab_size=sd[emb_key].shape[0], n_layers=L, n_heads=n_heads,
            d_model=d, max_seq_len=sd[pos_key].shape[0],
            tie_embeddings=True)
        params = {
            "wte": {"embedding": jnp.asarray(sd[emb_key])},
            "wpe": {"embedding": jnp.asarray(sd[pos_key])},
            "block": {
                "ln1": {"scale": vec("{}.input_layernorm.weight"),
                        "bias": vec("{}.input_layernorm.bias")},
                "qkv": {"kernel": lin("{}.attention.query_key_value.weight"),
                        "bias": vec("{}.attention.query_key_value.bias")},
                "attn_out": {"kernel": lin("{}.attention.dense.weight"),
                             "bias": vec("{}.attention.dense.bias")},
                "ln2": {"scale": vec("{}.post_attention_layernorm.weight"),
                        "bias": vec("{}.post_attention_layernorm.bias")},
                "mlp_in": {"kernel": lin("{}.mlp.dense_h_to_4h.weight"),
                           "bias": vec("{}.mlp.dense_h_to_4h.bias")},
                "mlp_out": {"kernel": lin("{}.mlp.dense_4h_to_h.weight"),
                            "bias": vec("{}.mlp.dense_4h_to_h.bias")},
            },
        }
        lnf_w = next((k for k in sd if "final_layernorm.weight" in k), None)
        if lnf_w is not None:
            params["ln_f"] = {
                "scale": jnp.asarray(sd[lnf_w]),
                "bias": jnp.asarray(sd[lnf_w.replace("weight", "bias")])}
        else:
            params["ln_f"] = {"scale": jnp.ones((d,), np.float32),
                              "bias": jnp.zeros((d,), np.float32)}
        params["block"] = {
            kk: {k2: jnp.asarray(v2) for k2, v2 in vv.items()}
            for kk, vv in params["block"].items()}
        logger.info(f"injected Megatron GPT: {L}L/{d}d heads={n_heads}")
        return cfg, params


@register_policy("gpt_tuple")
class NativePolicy:
    """Our own (config, params) tuples — GPT (incl. MoE-GPT) or BERT."""

    @staticmethod
    def matches(model) -> bool:
        if not (isinstance(model, tuple) and len(model) == 2):
            return False
        from deepspeed_tpu.models.bert import BertConfig
        return isinstance(model[0], (GPTConfig, BertConfig))

    @staticmethod
    def convert(model):
        return model


def revert_transformer_layer(*a, **k):  # pragma: no cover
    """The reference's reverse op (replace_module.py:732) is meaningless
    here: conversion is out-of-place; the source model is untouched."""
    raise NotImplementedError(
        "conversion is out-of-place; the original model object is unchanged")


def _hf_llama_readers(sd, L, Dh):
    """Shared readers for HF llama-layout state dicts (used by the llama
    and mixtral policies): 'model.'-prefix detection, stacked [L, in,
    out] linears with the optional split-half -> interleaved rotary
    channel permutation (2p <- p, 2p+1 <- p + Dh/2), and stacked norm
    scales."""
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    half = Dh // 2

    def perm_heads(w, H):
        w = w.reshape(H, Dh, -1)
        out = np.empty_like(w)
        out[:, 0::2] = w[:, :half]
        out[:, 1::2] = w[:, half:]
        return out.reshape(H * Dh, -1)

    def lin(fmt, perm_h=None):
        import jax.numpy as jnp
        mats = []
        for i in range(L):
            w = sd[pre + fmt.format(i)]
            if perm_h:
                w = perm_heads(w, perm_h)
            mats.append(w.T)
        return jnp.asarray(np.stack(mats))

    def vec(fmt):
        import jax.numpy as jnp
        return jnp.asarray(np.stack([sd[pre + fmt.format(i)]
                                     for i in range(L)]))

    return pre, lin, vec


def _hf_llama_attn_params(sd, pre, lin, vec, cfg):
    """The llama-layout pieces shared by the llama and mixtral policies:
    fused qkv (with the rotary channel permutation), attention output,
    norms, embeddings and head. The caller adds its FFN (dense swiglu or
    sparse MoE) under block."""
    import jax.numpy as jnp
    qkv = jnp.concatenate(
        [lin("layers.{}.self_attn.q_proj.weight", cfg.n_heads),
         lin("layers.{}.self_attn.k_proj.weight", cfg.kv_heads),
         lin("layers.{}.self_attn.v_proj.weight")], axis=-1)
    block = {
        "ln1": {"scale": vec("layers.{}.input_layernorm.weight")},
        "qkv": {"kernel": qkv},
        "attn_out": {"kernel": lin("layers.{}.self_attn.o_proj.weight")},
        "ln2": {"scale": vec("layers.{}.post_attention_layernorm.weight")},
    }
    top = {
        "wte": {"embedding": jnp.asarray(sd[pre + "embed_tokens.weight"])},
        "ln_f": {"scale": jnp.asarray(sd[pre + "norm.weight"])},
        "lm_head": {"kernel": jnp.asarray(sd["lm_head.weight"].T)},
    }
    return block, top


@register_policy("hf_llama")
class HFLlamaPolicy:
    """HuggingFace llama-family decoder (Llama/Mistral layout) -> native
    rmsnorm/swiglu dialect (capability analog of the reference's
    per-architecture injection policies, module_inject/replace_policy.py).

    HF stores q/k projections in the split-half rotary convention
    (rotate_half: channel p pairs with p + Dh/2); the native rotary is
    interleaved (GPT-J style: 2p pairs with 2p+1), so q/k output
    channels are permuted per head — interleaved 2p <- HF p,
    2p+1 <- HF p + Dh/2 — after which the two conventions compute
    identical attention."""

    @staticmethod
    def matches(model) -> bool:
        # headless LlamaModel is excluded: llama ties nothing, so there
        # is no lm_head to synthesize from (unlike HFGPT2Policy's tied
        # fallback)
        return type(model).__name__ in ("LlamaForCausalLM",
                                        "MistralForCausalLM")

    @staticmethod
    def convert(model) -> Tuple[GPTConfig, Dict]:
        import jax.numpy as jnp
        hf = model.config
        Dh = hf.hidden_size // hf.num_attention_heads
        n_kv = getattr(hf, "num_key_value_heads", hf.num_attention_heads)
        cfg = GPTConfig(
            vocab_size=hf.vocab_size,
            n_layers=hf.num_hidden_layers,
            n_heads=hf.num_attention_heads,
            n_kv_heads=n_kv if n_kv != hf.num_attention_heads else None,
            d_model=hf.hidden_size,
            d_ff=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            norm="rmsnorm", norm_eps=hf.rms_norm_eps,
            activation="swiglu", use_bias=False, use_wpe=False,
            tie_embeddings=False, rotary_dim=Dh,
            rope_theta=getattr(hf, "rope_theta", 10000.0),
            attn_window=getattr(hf, "sliding_window", None))
        sd = {k: v.detach().cpu().numpy()
              for k, v in model.state_dict().items()}
        L = cfg.n_layers
        pre, lin, vec = _hf_llama_readers(sd, L, Dh)
        block, top = _hf_llama_attn_params(sd, pre, lin, vec, cfg)
        block.update({
            "mlp_gate": {"kernel": lin("layers.{}.mlp.gate_proj.weight")},
            "mlp_in": {"kernel": lin("layers.{}.mlp.up_proj.weight")},
            "mlp_out": {"kernel": lin("layers.{}.mlp.down_proj.weight")},
        })
        params = {"block": block, **top}
        logger.info(f"injected HF llama: {cfg.n_layers}L/{cfg.d_model}d "
                    f"kv_heads={cfg.kv_heads} theta={cfg.rope_theta}")
        return cfg, params


@register_policy("hf_mixtral")
class HFMixtralPolicy:
    """HuggingFace Mixtral (llama attention + top-k sparse MoE FFN) ->
    native MoE decode path (capability analog of the reference's MoE
    inference modules, ops/transformer/inference/moe_inference.py).

    Router parity: Mixtral weighs experts by softmax over the top-k
    router logits; the eval path takes the full softmax and
    renormalizes the k selected probabilities — mathematically the
    same weights. Token dropping is disabled at eval (engine._ffn runs
    a dense no-drop expert mix; GShard capacity exists for training
    efficiency, not eval semantics). q/k rotary channels get the same
    split-half -> interleaved permutation as HFLlamaPolicy."""

    @staticmethod
    def matches(model) -> bool:
        return type(model).__name__ == "MixtralForCausalLM"

    @staticmethod
    def convert(model) -> Tuple[GPTConfig, Dict]:
        import jax.numpy as jnp
        from deepspeed_tpu.models.moe_gpt import MoEGPTConfig
        hf = model.config
        Dh = hf.hidden_size // hf.num_attention_heads
        n_kv = getattr(hf, "num_key_value_heads", hf.num_attention_heads)
        E = hf.num_local_experts
        if hf.num_experts_per_tok > 2:
            raise ValueError(
                f"Mixtral checkpoint routes top-{hf.num_experts_per_tok} "
                f"but the gating layer supports top-1/top-2 only")
        cfg = MoEGPTConfig(
            vocab_size=hf.vocab_size,
            n_layers=hf.num_hidden_layers,
            n_heads=hf.num_attention_heads,
            n_kv_heads=n_kv if n_kv != hf.num_attention_heads else None,
            d_model=hf.hidden_size,
            d_ff=hf.intermediate_size,
            max_seq_len=hf.max_position_embeddings,
            norm="rmsnorm", norm_eps=hf.rms_norm_eps,
            activation="swiglu", use_bias=False, use_wpe=False,
            tie_embeddings=False, rotary_dim=Dh,
            rope_theta=getattr(hf, "rope_theta", 10000.0),
            attn_window=getattr(hf, "sliding_window", None),
            num_experts=E, moe_k=hf.num_experts_per_tok,
            # Mixtral semantics: softmax over the selected top-k (1.0 at
            # k=1), and validation must never drop a token
            gate_weighting="topk_softmax",
            eval_capacity_factor=float(E))
        sd = {k: v.detach().cpu().numpy()
              for k, v in model.state_dict().items()}
        L = cfg.n_layers
        pre, lin, vec = _hf_llama_readers(sd, L, Dh)

        def experts(w_name):
            # [L, E, out, in] -> transpose to [L, E, in, out]
            return jnp.asarray(np.stack(
                [np.stack([sd[pre + f"layers.{i}.block_sparse_moe."
                                    f"experts.{e}.{w_name}.weight"].T
                           for e in range(E)]) for i in range(L)]))

        block, top = _hf_llama_attn_params(sd, pre, lin, vec, cfg)
        block["moe"] = {
            "gate": {"wg": lin("layers.{}.block_sparse_moe.gate.weight")},
            "experts": {
                "wi": {"kernel": experts("w3")},   # up
                "wg": {"kernel": experts("w1")},   # gate
                "wo": {"kernel": experts("w2")},   # down
            },
        }
        params = {"block": block, **top}
        logger.info(f"injected HF Mixtral: {cfg.n_layers}L/{cfg.d_model}d "
                    f"E={E} k={cfg.moe_k}")
        return cfg, params
