"""Injection policies: foreign checkpoints -> fused TPU layout.

Capability analog of the reference's policy registry
(ref: deepspeed/module_inject/replace_policy.py — HFBertLayerPolicy :49,
HFGPTNEOLayerPolicy :112, HFGPTJLayerPolicy :157, MegatronLayerPolicy :202,
HFGPT2LayerPolicy; applied by replace_transformer_layer
module_inject/replace_module.py:123). Instead of swapping nn.Modules
in-place, a policy converts a source model's weights into the framework's
stacked-layer GPT pytree, after which the fused JAX/Pallas blocks and TP
partition rules apply unchanged.
"""

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.gpt import GPTConfig
from deepspeed_tpu.utils.logging import logger

_POLICIES = {}


def register_policy(name: str):
    def deco(cls):
        _POLICIES[name] = cls
        return cls
    return deco


def resolve_model(model) -> Tuple[GPTConfig, Dict]:
    """Dispatch a user-passed model object/name to a policy."""
    for policy in _POLICIES.values():
        if policy.matches(model):
            return policy.convert(model)
    raise ValueError(
        f"no inference policy matches {type(model)}; known: "
        f"{list(_POLICIES)}")


@register_policy("hf_gpt2")
class HFGPT2Policy:
    """HuggingFace GPT-2 (torch) -> fused GPT layout
    (ref: HFGPT2LayerPolicy in replace_policy.py)."""

    @staticmethod
    def matches(model) -> bool:
        return type(model).__name__ in ("GPT2LMHeadModel", "GPT2Model")

    @staticmethod
    def convert(model) -> Tuple[GPTConfig, Dict]:
        import jax.numpy as jnp
        hf_cfg = model.config
        cfg = GPTConfig(
            vocab_size=hf_cfg.vocab_size,
            n_layers=hf_cfg.n_layer,
            n_heads=hf_cfg.n_head,
            d_model=hf_cfg.n_embd,
            max_seq_len=hf_cfg.n_positions,
            tie_embeddings=True)
        sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
        pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""

        def stack(fmt):
            return jnp.asarray(np.stack(
                [sd[pre + fmt.format(i)] for i in range(cfg.n_layers)]))

        params = {
            "wte": {"embedding": jnp.asarray(sd[pre + "wte.weight"])},
            "wpe": {"embedding": jnp.asarray(sd[pre + "wpe.weight"])},
            "block": {
                "ln1": {"scale": stack("h.{}.ln_1.weight"),
                        "bias": stack("h.{}.ln_1.bias")},
                # HF GPT-2 uses Conv1D: weight already [in, out]
                "qkv": {"kernel": stack("h.{}.attn.c_attn.weight"),
                        "bias": stack("h.{}.attn.c_attn.bias")},
                "attn_out": {"kernel": stack("h.{}.attn.c_proj.weight"),
                             "bias": stack("h.{}.attn.c_proj.bias")},
                "ln2": {"scale": stack("h.{}.ln_2.weight"),
                        "bias": stack("h.{}.ln_2.bias")},
                "mlp_in": {"kernel": stack("h.{}.mlp.c_fc.weight"),
                           "bias": stack("h.{}.mlp.c_fc.bias")},
                "mlp_out": {"kernel": stack("h.{}.mlp.c_proj.weight"),
                            "bias": stack("h.{}.mlp.c_proj.bias")},
            },
            "ln_f": {"scale": jnp.asarray(sd[pre + "ln_f.weight"]),
                     "bias": jnp.asarray(sd[pre + "ln_f.bias"])},
        }
        logger.info(f"injected HF GPT-2: {cfg.n_layers}L/{cfg.d_model}d")
        return cfg, params


@register_policy("gpt_tuple")
class NativePolicy:
    """Our own (GPTConfig, params) tuples."""

    @staticmethod
    def matches(model) -> bool:
        return (isinstance(model, tuple) and len(model) == 2 and
                isinstance(model[0], GPTConfig))

    @staticmethod
    def convert(model):
        return model


def revert_transformer_layer(*a, **k):  # pragma: no cover
    """The reference's reverse op (replace_module.py:732) is meaningless
    here: conversion is out-of-place; the source model is untouched."""
    raise NotImplementedError(
        "conversion is out-of-place; the original model object is unchanged")
