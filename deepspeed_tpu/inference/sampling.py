"""Per-request sampling subsystem for the continuous-batching slots.

Every per-request knob — temperature, top_k, top_p, seed,
repetition_penalty — lives as a **slot-indexed device array** (data,
not jit statics), so the two-program steady-state compile contract
(docs/SERVING.md) holds with arbitrarily mixed greedy/sampled batches:
the fused sampler below is traced INTO the prefill/decode slot
programs, and a request's knobs only change the values flowing through
the one compiled program, never its signature.

Three layers share this module:

- **Fused device sampler** (:func:`sample_tokens`): temperature scale →
  top-k mask → top-p nucleus mask → seeded categorical, vectorized over
  slots. temperature=0 lanes take the argmax lane and are BIT-IDENTICAL
  to the greedy serving output (the sampled machinery is where()-masked
  out of their result, not merely "close").
- **Per-slot key chain**: the categorical for the token at generation
  index ``i`` of a request seeded ``s`` uses
  ``fold_in(PRNGKey(s), i)`` — the fold happens on device inside the
  compiled program. Because the key is a pure function of
  ``(seed, tokens generated so far)`` there is no sequential RNG state
  to lose: eviction/requeue (which re-prefills prompt + partial output)
  and a router drain onto a survivor resume the chain exactly, and
  ``snapshot_entry``/``from_snapshot`` round-trip it by carrying the
  sampling params (docs/SAMPLING.md).
- **Host fp64 Leviathan primitives** (:func:`fp64_dist`,
  :func:`inverse_cdf`, :func:`accept_prob`, :func:`residual_dist`,
  :func:`spec_verify_tokens`): ONE implementation of the rejection-
  sampling accept/resample math (Leviathan et al. 2023 / Chen et al.
  2023) shared by the static speculative path
  (inference/speculative.py) and the serving spec-decode verify
  (serving.ServingEngine._spec_decode_step).
"""

import math
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# mask value for excluded tokens — matches engine._sample so the
# truncated distributions agree bitwise where both paths apply a mask
NEG_INF = -1e30

_U64 = (1 << 64) - 1


# ---------------------------------------------------------------------
# request-facing parameter bundle
# ---------------------------------------------------------------------
@dataclass(frozen=True)
class SamplingParams:
    """Resolved per-request sampling knobs (docs/SAMPLING.md).

    temperature=0 means greedy — and then every other knob is inert by
    contract (the greedy lane must stay bit-identical to the pre-
    sampling serving output, so no penalty/mask may perturb it)."""
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    repetition_penalty: float = 1.0

    def validate(self) -> "SamplingParams":
        if self.temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, "
                             f"got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0 (0 = off), "
                             f"got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1] (1 = off), "
                             f"got {self.top_p}")
        if self.repetition_penalty <= 0.0:
            raise ValueError(f"repetition_penalty must be > 0, "
                             f"got {self.repetition_penalty}")
        return self

    @property
    def sampled(self) -> bool:
        return self.temperature > 0.0


def resolve_params(req, default_temperature: float = 0.0,
                   default_top_k: int = 0,
                   default_seed: int = 0) -> SamplingParams:
    """Per-request knobs win; engine-wide ctor defaults fill the gaps
    (a request field of None means "engine default")."""
    def pick(v, d):
        return d if v is None else v
    return SamplingParams(
        temperature=float(pick(getattr(req, "temperature", None),
                               default_temperature)),
        top_k=int(pick(getattr(req, "top_k", None), default_top_k)),
        top_p=float(pick(getattr(req, "top_p", None), 1.0)),
        seed=int(pick(getattr(req, "seed", None), default_seed)),
        repetition_penalty=float(pick(
            getattr(req, "repetition_penalty", None), 1.0)),
    ).validate()


def base_key(seed: int) -> np.ndarray:
    """[2] uint32 threefry key for a request seed — the root of the
    per-request key chain (host mirror; folds happen on device)."""
    return np.asarray(jax.random.PRNGKey(int(seed) & _U64), np.uint32)


def candidate_seed(seed: int, index: int) -> int:
    """Derived seed for candidate ``index`` of an n>1 request —
    SeedSequence-mixed so adjacent user seeds don't collide with
    adjacent candidate indices."""
    if index == 0:
        return int(seed)
    return int(np.random.SeedSequence([int(seed) & _U64, int(index)])
               .generate_state(1)[0])


# ---------------------------------------------------------------------
# fused slot-vectorized sampler (traced into the slot programs)
# ---------------------------------------------------------------------
def sample_tokens(logits, keys, positions, temps, top_ks, top_ps,
                  rep_pens, seen):
    """Sample one token per slot from last-position ``logits`` [B, V].

    All knob arguments are slot-indexed arrays (DATA to jit, never
    statics): keys [B, 2] uint32 per-request base keys; positions [B]
    int32 tokens-generated-so-far (the key-chain counter); temps/
    top_ps/rep_pens [B] float32; top_ks [B] int32; seen [B, V] bool
    (tokens the repetition penalty applies to). Returns
    ``(tokens [B] int32, logprobs [B] float32)`` where the logprob is
    the chosen token's log-probability under the final (masked,
    renormalized) sampling distribution — or under plain
    softmax(logits) for greedy lanes.

    temperature<=0 lanes return ``argmax(logits.astype(f32))`` exactly
    (the greedy bit-identity contract); the sampled machinery below is
    masked out of their lane with where(), so its arithmetic can never
    perturb a greedy result. The whole sampled pipeline sits behind a
    ``lax.cond`` on "any lane sampled" — still ONE compiled program
    (both branches live in the same executable), but an all-greedy
    batch skips the mask/argsort/threefry work at RUNTIME, so greedy
    serving keeps its pre-sampling dispatch latency.
    """
    logits = logits.astype(jnp.float32)
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lane = temps > 0.0
    glps = jax.nn.log_softmax(logits, axis=-1)
    greedy_lp = jnp.take_along_axis(glps, greedy[:, None], axis=-1)[:, 0]

    def _sampled(_):
        # repetition penalty (CTRL-style): push seen tokens toward
        # "less likely" on the sampled lanes only
        pen = rep_pens[:, None]
        z = jnp.where(seen,
                      jnp.where(logits > 0, logits / pen, logits * pen),
                      logits)
        z = z / jnp.where(lane, temps, 1.0)[:, None]

        # one descending argsort serves both truncations, and the keep
        # mask is scattered back through it — no fp comparisons across
        # differently-ordered softmax reductions
        order = jnp.argsort(-z, axis=-1)
        z_sorted = jnp.take_along_axis(z, order, axis=-1)
        rank = jnp.arange(V, dtype=jnp.int32)[None, :]
        k = top_ks[:, None]
        keep = (k <= 0) | (rank < k)
        probs_sorted = jax.nn.softmax(jnp.where(keep, z_sorted, NEG_INF),
                                      axis=-1)
        csum = jnp.cumsum(probs_sorted, axis=-1)
        # nucleus: keep ranks whose EXCLUSIVE prefix mass is still
        # under top_p (the most-probable token always survives)
        tp = jnp.where(top_ps >= 1.0, jnp.inf, top_ps)[:, None]
        keep = keep & ((csum - probs_sorted) < tp)
        keep = keep.at[:, 0].set(True)
        inv = jnp.argsort(order, axis=-1)
        keep = jnp.take_along_axis(keep, inv, axis=-1)
        z = jnp.where(keep, z, NEG_INF)

        lane_keys = jax.vmap(jax.random.fold_in)(keys, positions)
        drawn = jax.vmap(jax.random.categorical)(lane_keys, z)
        slps = jax.nn.log_softmax(z, axis=-1)
        drawn_lp = jnp.take_along_axis(slps, drawn[:, None],
                                       axis=-1)[:, 0]
        return drawn.astype(jnp.int32), drawn_lp

    drawn, drawn_lp = jax.lax.cond(
        jnp.any(lane), _sampled, lambda _: (greedy, greedy_lp), None)
    tokens = jnp.where(lane, drawn, greedy)
    logprobs = jnp.where(lane, drawn_lp, greedy_lp)
    return tokens, logprobs


# ---------------------------------------------------------------------
# host-side slot state: the numpy mirrors the serving scheduler feeds
# to the fused sampler every step
# ---------------------------------------------------------------------
class SlotSamplerState:
    """Slot-indexed host mirrors of the sampling arrays.

    The scheduler owns one instance; rows are (re)written at admission
    and cleared at release. ``lanes()`` packages them as the
    ``sample_state`` tuple the engine wrappers thread into the compiled
    slot programs."""

    def __init__(self, num_slots: int, vocab_size: int):
        self.num_slots = num_slots
        self.vocab_size = vocab_size
        self.keys = np.zeros((num_slots, 2), np.uint32)
        self.temps = np.zeros(num_slots, np.float32)
        self.top_ks = np.zeros(num_slots, np.int32)
        self.top_ps = np.ones(num_slots, np.float32)
        self.rep_pens = np.ones(num_slots, np.float32)
        self.seen = np.zeros((num_slots, vocab_size), bool)
        # device mirror of the per-slot knobs, rebuilt lazily after a
        # mutation: the decode hot path re-uploads only the [B]
        # gen_counts each step instead of all seven arrays (the rest
        # change at admission/release cadence, not step cadence)
        self._device_lanes = None

    def admit(self, slot: int, params: SamplingParams,
              tokens: Optional[Sequence[int]] = None) -> None:
        self.keys[slot] = base_key(params.seed)
        self.temps[slot] = params.temperature
        self.top_ks[slot] = params.top_k
        self.top_ps[slot] = params.top_p
        self.rep_pens[slot] = params.repetition_penalty
        self.seen[slot] = False
        if tokens is not None and params.repetition_penalty != 1.0:
            self.seen[slot, np.asarray(tokens, np.int64) % self.vocab_size] \
                = True
        self._device_lanes = None

    def release(self, slot: int) -> None:
        self.keys[slot] = 0
        self.temps[slot] = 0.0
        self.top_ks[slot] = 0
        self.top_ps[slot] = 1.0
        self.rep_pens[slot] = 1.0
        self.seen[slot] = False
        self._device_lanes = None

    def observe(self, slot: int, token: int) -> None:
        if self.rep_pens[slot] != 1.0:
            self.seen[slot, int(token) % self.vocab_size] = True
            self._device_lanes = None

    def lanes(self, gen_counts) -> Tuple:
        """The slot-batched ``sample_state`` tuple: gen_counts [B] is
        each slot's tokens-generated-so-far (the key-chain counter)."""
        if self._device_lanes is None:
            self._device_lanes = (
                jnp.asarray(self.keys, jnp.uint32),
                jnp.asarray(self.temps, jnp.float32),
                jnp.asarray(self.top_ks, jnp.int32),
                jnp.asarray(self.top_ps, jnp.float32),
                jnp.asarray(self.rep_pens, jnp.float32),
                jnp.asarray(self.seen, bool))
        keys, temps, top_ks, top_ps, pens, seen = self._device_lanes
        return (keys, np.asarray(gen_counts, np.int32), temps,
                top_ks, top_ps, pens, seen)

    def lane(self, slot: int, gen_count: int) -> Tuple:
        """Single-slot ``sample_state`` (the prefill-emit path)."""
        return (self.keys[slot], np.int32(gen_count), self.temps[slot],
                self.top_ks[slot], self.top_ps[slot], self.rep_pens[slot],
                self.seen[slot])


def greedy_state(batch: int, vocab_size: int) -> Tuple:
    """All-greedy ``sample_state`` for legacy callers that only want
    logits back (every lane takes the argmax path)."""
    return (np.zeros((batch, 2), np.uint32), np.zeros(batch, np.int32),
            np.zeros(batch, np.float32), np.zeros(batch, np.int32),
            np.ones(batch, np.float32), np.ones(batch, np.float32),
            np.zeros((batch, vocab_size), bool))


# ---------------------------------------------------------------------
# shared fp64 Leviathan primitives (host side)
# ---------------------------------------------------------------------
def fp64_dist(logits, temperature: float, top_k: int = 0,
              top_p: float = 1.0) -> np.ndarray:
    """[..., V] logits -> fp64 probabilities at ``temperature``
    (optionally top_k/top_p-truncated). The temperature/top_k
    arithmetic is bit-for-bit the historical speculative.py ``dist``
    (the static-path parity pin in tests/test_speculative.py depends
    on that)."""
    z = np.asarray(logits, np.float64) / temperature
    if top_k > 0:
        k_eff = min(top_k, z.shape[-1])   # match generate()'s clamp
        kth = np.sort(z, axis=-1)[..., -k_eff, None]
        z = np.where(z < kth, -np.inf, z)
    z = z - z.max(-1, keepdims=True)
    e = np.exp(z)
    p = e / e.sum(-1, keepdims=True)
    if top_p < 1.0:
        p = nucleus_truncate(p, top_p)
    return p


def nucleus_truncate(p: np.ndarray, top_p: float) -> np.ndarray:
    """Zero everything outside the smallest probability-sorted prefix
    with cumulative mass >= top_p, then renormalize (rank-based cut,
    like the fused sampler: the most-probable token always survives)."""
    order = np.argsort(-p, axis=-1, kind="stable")
    p_sorted = np.take_along_axis(p, order, axis=-1)
    csum = np.cumsum(p_sorted, axis=-1)
    keep_sorted = (csum - p_sorted) < top_p
    keep_sorted[..., 0] = True
    keep = np.take_along_axis(keep_sorted, np.argsort(order, axis=-1),
                              axis=-1)
    out = np.where(keep, p, 0.0)
    return out / out.sum(-1, keepdims=True)


def inverse_cdf(p, u):
    """Inverse-CDF draw from probabilities ``p`` [..., V] with uniform
    ``u`` (scalar or [...]): index of the first cumsum bin above ``u``,
    clamped (fp rounding can leave cumsum[-1] < 1 and u above it)."""
    c = np.cumsum(np.asarray(p, np.float64), axis=-1)
    u = np.asarray(u, np.float64)
    while u.ndim < c.ndim:
        u = u[..., None]
    return np.minimum((u > c).sum(-1), c.shape[-1] - 1)


def accept_prob(px, qx):
    """Leviathan acceptance probability min(1, p(x)/q(x)) for the draft
    token x (elementwise over rows)."""
    return np.minimum(1.0, px / np.maximum(qx, 1e-300))


def residual_dist(p, q) -> np.ndarray:
    """Post-rejection resample distribution norm(max(0, p - q)) for one
    row, falling back to ``p`` when the residual has no mass (p == q)."""
    res = np.maximum(0.0, np.asarray(p, np.float64)
                     - np.asarray(q, np.float64))
    tot = res.sum()
    return res / tot if tot > 0 else np.asarray(p, np.float64)


def point_mass_residual(p: np.ndarray, x: int) -> np.ndarray:
    """residual_dist against a point mass at ``x`` — the deterministic-
    drafter case (serving's n-gram/greedy drafters propose one token
    with q(x) = 1): max(0, p - delta_x) is just p with x zeroed."""
    res = np.asarray(p, np.float64).copy()
    res[x] = 0.0
    tot = res.sum()
    return res / tot if tot > 0 else np.asarray(p, np.float64)


def position_uniforms(seed: int, pos: int, n: int = 2) -> np.ndarray:
    """Counter-based uniforms for deciding the token at generation
    index ``pos`` of a request seeded ``seed`` (Philox keyed by
    (seed, pos)). No sequential state: a verify chunk always starts at
    a committed token boundary, so evict/requeue and router drain
    replay the identical draws for every position they re-decide."""
    bits = np.random.Philox(key=[np.uint64(int(seed) & _U64),
                                 np.uint64(int(pos) & _U64)])
    return np.random.Generator(bits).random(n)


def spec_verify_tokens(p_rows, proposal, seed: int, pos0: int):
    """Leviathan verify of one slot's draft chunk against the target's
    verify distributions (the serving `_spec_decode_step` sampled lane).

    p_rows: [k+1, V] fp64 target distributions — row j is the
    distribution for the token at generation index ``pos0 + j``.
    proposal: [k] draft tokens from a DETERMINISTIC drafter (q is a
    point mass at the proposed token, so the acceptance probability
    min(1, p(x)/q(x)) reduces to p(x)). Returns
    ``(tokens, logprobs, n_accepted)``: the accepted prefix plus ONE
    correction token (residual-resampled at the first rejection) or
    bonus token (drawn from p at the position past the chunk).
    Logprobs are log p(token) under the target distribution at each
    position. Distribution-lossless: the emitted marginal equals
    sampling the target alone (docs/SAMPLING.md)."""
    toks, lps = [], []
    k = len(proposal)
    for j in range(k):
        x = int(proposal[j])
        u = position_uniforms(seed, pos0 + j, 2)
        px = float(p_rows[j][x])
        if u[0] < px:             # accept_prob(px, q=1) == px
            toks.append(x)
            lps.append(math.log(max(px, 1e-300)))
            continue
        res = point_mass_residual(p_rows[j], x)
        t = int(inverse_cdf(res, u[1]))
        toks.append(t)
        lps.append(math.log(max(float(p_rows[j][t]), 1e-300)))
        return toks, lps, j
    u = position_uniforms(seed, pos0 + k, 2)
    t = int(inverse_cdf(p_rows[k], u[0]))
    toks.append(t)
    lps.append(math.log(max(float(p_rows[k][t]), 1e-300)))
    return toks, lps, k
