"""Speculative decoding: a small draft model proposes, the target
verifies a whole chunk in ONE step.

Beyond the reference's inference stack (its generation is one forward
per token, ref: deepspeed/inference/engine.py:355); on TPU the economics
are ideal: the target's chunk-verify step is a [gamma+1]-token matmul —
MXU-friendly where single-token decode is HBM-bound — so accepted
tokens cost ~1/(accepted+1) target steps.

Greedy contract (temperature=0): the emitted sequence is EXACTLY what
target.generate would emit alone — speculation changes latency, never
output. Sampled contract (temperature>0): Leviathan et al. rejection
sampling — accept draft token x with min(1, p(x)/q(x)), resample
rejections from norm(max(0, p-q)) — whose OUTPUT DISTRIBUTION equals
sampling the target alone (verified against the exact two-step
marginal in tests/test_speculative.py). The fp64 accept/resample
primitives live in inference/sampling.py and are SHARED with the
continuous-batching serving verify, so static and slot speculation run
one Leviathan implementation.

The chunk-verify step is the engine's ``_extend`` program
(inference/engine.py ``_extend_fn`` / ``_block_extend``): the decode
block generalized from 1 to G query tokens — queries attend the cache
plus the causal prefix of their own chunk. The same block math drives
the PAGED serving verify (``_verify_slots_fn`` / ``_block_verify_paged``
behind ``ServingEngine(spec_decode=True)``, docs/SPECULATIVE.md), so
this static path and continuous-batching speculation share one
implementation. Cache slots past a partial acceptance hold stale K/V,
which is safe by construction: the next round REWRITES those positions
before any query reads them (position-addressed writes happen before
attention in the same step).
"""

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.sampling import (accept_prob, fp64_dist,
                                              inverse_cdf, residual_dist)


def generate_speculative(target, draft, tokens, max_new_tokens: int = 32,
                         gamma: int = 4, temperature: float = 0.0,
                         top_k: int = 0, seed: int = 0,
                         return_stats: bool = False):
    """Speculative generation (see module docstring).

    target/draft: InferenceEngine instances over the SAME vocabulary
    (the draft is typically a much smaller model). tokens: [B, S] int32
    prompt (no padding mask support in this path). Returns [B, S+N]
    tokens, plus an acceptance-stats dict when return_stats is set.

    temperature=0 (default): greedy — the output EXACTLY equals
    target.generate(..., temperature=0). temperature>0: lossless
    sampled speculation (Leviathan et al. rejection scheme) — draft
    token x is accepted with prob min(1, p(x)/q(x)); a rejection
    resamples from norm(max(0, p-q)); a full acceptance samples the
    bonus from p. The OUTPUT DISTRIBUTION equals sampling the target
    alone (the sample path differs from target.generate's rng stream,
    so sequences aren't bitwise-comparable — the distribution is).
    top_k truncates BOTH p and q to their top-k before the accept/
    resample math, matching generate(top_k=...)'s truncated target
    process (any proposal q is admissible for unbiasedness; the
    truncated q keeps the support aligned).
    """
    assert target.cfg.vocab_size == draft.cfg.vocab_size, \
        "speculative decoding needs a shared vocabulary"
    tokens = np.asarray(tokens, np.int32)
    B, S = tokens.shape
    assert S + max_new_tokens + gamma + 1 <= min(target.max_seq_len,
                                                 draft.max_seq_len), \
        "prompt + new tokens (+ a gamma-sized verify margin) must fit " \
        "both engines' caches"
    sampled = temperature > 0.0
    rng = np.random.default_rng(seed)

    def dist(logits):
        """[.., V] logits -> fp64 probabilities at `temperature`
        (optionally top_k-truncated, matching generate()'s sampler) —
        the shared Leviathan primitive (inference/sampling.py)."""
        return fp64_dist(logits, temperature, top_k=top_k)

    V = target.cfg.vocab_size

    def draw(p):
        """Sample one token per row from [B, V] probabilities."""
        return inverse_cdf(p, rng.random((p.shape[0], 1))).astype(np.int32)

    def draw1(p):
        """One sample from a [V] probability vector."""
        return int(inverse_cdf(p, rng.random()))

    t_logits, t_cache = target._prefill(target.params, jnp.asarray(tokens))
    d_logits, d_cache = draft._prefill(draft.params, jnp.asarray(tokens))
    # the engine's compiled chunk-verify program (cache donated; jit
    # retraces per distinct chunk width and caches across calls)
    extend_t = target._extend

    out = [tokens]
    # first target token comes straight from the prefill logits
    first = np.asarray(t_logits[:, -1].astype(jnp.float32))
    cur = draw(dist(first)) if sampled else first.argmax(-1).astype(np.int32)
    n_emitted = 1
    n_rounds = 0
    n_accepted_total = 0
    pos = S                       # next unwritten cache index, both caches

    while n_emitted <= max_new_tokens:
        g = int(min(gamma, max_new_tokens - n_emitted + 1))
        if g == 0:
            break
        # ---- draft proposes g tokens autoregressively (the engine's
        # own compiled, cache-donating decode step) ----
        proposal = np.zeros((B, g), np.int32)
        q_dists = (np.zeros((g, B, V), np.float64) if sampled else None)
        d_tok = cur
        for i in range(g):
            dl, d_cache = draft._decode(draft.params, d_cache,
                                        jnp.asarray(d_tok[:, None]),
                                        jnp.asarray(pos + i, jnp.int32))
            if sampled:
                q_dists[i] = dist(np.asarray(dl[:, -1].astype(jnp.float32)))  # dslint: disable=DS001 — draft dists feed host-side sampling each round by design
                d_tok = draw(q_dists[i])
            else:
                # ids only cross the host boundary on the greedy path
                d_tok = np.asarray(  # dslint: disable=DS001 — proposal ids steer the next draft step on host
                    jnp.argmax(dl[:, -1].astype(jnp.float32), -1),
                ).astype(np.int32)
            proposal[:, i] = d_tok
        # ---- target verifies [cur, d_1..d_g] — g+1 tokens, ONE step;
        # a fully-agreeing round emits g+1 tokens (bonus included) ----
        chunk = np.concatenate([cur[:, None], proposal], axis=1)
        tl, t_cache = extend_t(target.params, t_cache, jnp.asarray(chunk),
                               jnp.asarray(pos, jnp.int32))
        if sampled:
            p_dists = dist(np.asarray(tl.astype(jnp.float32)))  # dslint: disable=DS001 — [B,g+1,V]; Leviathan accept/reject is host control flow
            # Leviathan acceptance per row: accept draft token i with
            # prob min(1, p_i(x)/q_i(x))
            rows = np.arange(B)
            accept = np.ones((B, g), bool)
            for i in range(g):
                px = p_dists[rows, i, proposal[:, i]]
                qx = q_dists[i][rows, proposal[:, i]]
                accept[:, i] = rng.random(B) < accept_prob(px, qx)
            first_bad = np.argmin(
                np.concatenate([accept, np.zeros((B, 1), bool)], axis=1),
                axis=1)
            # batch lockstep: stop at the earliest rejection. Cutting a
            # row's acceptance early stays unbiased — its continuation
            # is then a fresh sample from p at that position
            n_acc = int(first_bad.min())
            nxt = np.zeros(B, np.int32)
            for b in range(B):
                if n_acc == g:
                    # full acceptance everywhere: bonus token from the
                    # target's next-position distribution
                    nxt[b] = draw1(p_dists[b, g])
                elif first_bad[b] == n_acc:
                    # a genuine rejection at this position: resample
                    # from the residual norm(max(0, p - q))
                    nxt[b] = draw1(residual_dist(p_dists[b, n_acc],
                                                 q_dists[n_acc][b]))
                else:
                    # this row ACCEPTED the draft token at the lockstep
                    # cut — it must be emitted as-is (a fresh sample
                    # from p here would mix alpha*p with the residual
                    # and bias the marginal away from p)
                    nxt[b] = proposal[b, n_acc]
            cur_next = nxt
        else:
            # ids only cross the host boundary on the greedy path
            greedy = np.asarray(  # dslint: disable=DS001 — acceptance count is host control flow
                jnp.argmax(tl.astype(jnp.float32), -1)).astype(np.int32)
            # greedy[:, j] = target's token AFTER chunk prefix of length
            # j+1. accepted = #leading draft tokens agreeing with the
            # target; the batch takes the row minimum so all rows stay
            # in lockstep (a conservative, correct choice; per-row
            # bookkeeping would need ragged caches)
            agree = greedy[:, :-1] == proposal
            # first disagreement per row (the appended False column
            # makes argmin return g when a row accepted everything)
            first_bad = np.argmin(
                np.concatenate([agree, np.zeros((B, 1), bool)], axis=1),
                axis=1)
            n_acc = int(first_bad.min())
            cur_next = greedy[:, n_acc]   # correction (or bonus) token
        emit = [cur[:, None]]
        for i in range(n_acc):
            emit.append(proposal[:, i][:, None])
        out.append(np.concatenate(emit, axis=1))
        cur = cur_next
        n_emitted += n_acc + 1
        pos += n_acc + 1
        n_rounds += 1
        n_accepted_total += n_acc
        if n_acc == g:
            # fully-accepted round: the draft proposed d_g but never
            # CONSUMED it, so its K/V slot (pos-1) would be a hole that
            # poisons every later draft proposal — ingest it now
            # (logits discarded; output correctness never depends on
            # the draft, but acceptance rates do)
            _, d_cache = draft._decode(
                draft.params, d_cache, proposal[:, g - 1][:, None],
                jnp.asarray(pos - 1, jnp.int32))
        # rewind both caches logically: stale K/V beyond pos get
        # rewritten before the next read (see module docstring); the
        # DRAFT cache must also hold K/V for the accepted chunk — it
        # does: the draft wrote positions pos-..; mismatched slots are
        # overwritten next round
    result = np.concatenate(out + [cur[:, None]], axis=1)
    result = result[:, :S + max_new_tokens]
    if return_stats:
        return result, {"rounds": n_rounds,
                        "accepted_per_round": (n_accepted_total /
                                               max(1, n_rounds)),
                        "target_steps": n_rounds + 1,
                        "tokens": int(result.shape[1] - S)}
    return result
