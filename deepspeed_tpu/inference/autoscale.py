"""SLO autoscaler: the policy loop that makes the telemetry plane
drive the fleet.

The serving stack has every actuator (``ReplicaRouter.add_replica`` /
``retire_replica`` / the ``shed_batch`` admission gate) and every
sensor (the per-replica metric registries with TTFT/TPOT/queue-wait
histograms, health gauges); this module is the closed loop between
them — the reproduction's analog of the reference's monitor +
elasticity layers (deepspeed/monitor/*, elastic training), in the
shape modern continuous-batching servers use it: SLO-driven admission
and replica scaling.

:class:`SLOController` is a pure host-side policy object the router
ticks once per :meth:`~deepspeed_tpu.inference.router.ReplicaRouter.
step`. Every ``eval_every`` ticks it reads the **windowed** fleet view
(``Histogram.window_summary`` over the recent-observation rings,
merged across every registry in the fleet — "p99 TTFT over the last
``window`` clock units", not lifetime) plus the live load
(queue depth + occupied slots), and decides ONE of:

- ``scale_up`` — windowed p99 TTFT over ``ttft_slo`` (or, with
  ``tpot_slo`` set, windowed p99 TPOT over it) and the fleet is
  below ``max_replicas``: spawn a replica via the router's
  ``replica_factory``. Replicas sharing one ``InferenceEngine`` share
  its compiled programs, so scale-up compiles nothing
  (tests/test_autoscale.py pins this with ``CompileWatch(0)``).
  In a disaggregated fleet (any ``prefill``-role replica) the two
  pools scale INDEPENDENTLY: TTFT pressure adds a ``prefill``
  replica (first tokens are late because prefills queue), TPOT or
  queue pressure adds a ``decode`` replica (streams are stalling);
  a role-less fleet adds ``mixed`` replicas exactly as before.
- ``tighten`` — over SLO but the fleet cannot (or need not) grow:
  close the ``shed_batch`` admission gate so ``priority="batch"``
  traffic sheds at the front door and interactive traffic keeps the
  headroom. ``relax`` re-opens the gate once windowed p99 falls below
  ``relax_ratio * ttft_slo``.
- ``retire`` — the fleet has been completely idle (zero queued, zero
  occupied) for ``idle_to_retire`` consecutive clock units and is
  above ``min_replicas``: drain-and-retire the highest-index active
  replica through the router's snapshot path. Role-aware: the victim
  is never the last decode-capable replica, and the router settles
  any in-flight KV migrations first (docs/ROBUSTNESS.md).
- ``noop`` — everything inside the envelope.

Decisions are rate-limited by ``cooldown`` (clock units between
fleet-shape changes) so one slow window cannot fan out into a replica
storm. Every evaluation — including no-ops — lands in the Perfetto
trace as an ``autoscale`` instant carrying the triggering metrics
(windowed p99/count, queue depth, occupancy, active replica count),
and bumps the ``autoscale_*`` registry metrics, so a run is fully
reconstructable offline (``tools/trace_analyze.py fleet``).

The controller is all host-side control flow: it launches no device
work and allocates no device memory (dslint DS001 holds trivially),
and it is deterministic — decisions are a pure function of the
router's metric state, so a seeded load replay reproduces the exact
decision timeline. Default OFF: a router constructed without
``autoscale=`` is bit-identical to the fixed-fleet shape
(docs/OBSERVABILITY.md).
"""

from typing import Dict, List, Optional

from deepspeed_tpu.telemetry.metrics import Histogram
from deepspeed_tpu.utils.logging import logger

# decision kinds, in the order the policy considers them
SCALE_UP, RETIRE, TIGHTEN, RELAX, NOOP = (
    "scale_up", "retire", "tighten", "relax", "noop")

_DECISION_COUNTERS = (
    ("decisions", "controller evaluations (all decision kinds)"),
    ("scale_ups", "scale-up decisions taken"),
    ("retires", "retire decisions taken"),
    ("tightens", "admission-tighten decisions taken"),
    ("relaxes", "admission-relax decisions taken"),
    ("noops", "evaluations that changed nothing"),
)


class SLOController:
    """Windowed-SLO policy for :class:`ReplicaRouter` (module docstring
    has the control law).

    All times are in the router's scheduler clock units — step indices
    in tests, seconds under ``wall_clock=True`` — matching the units
    the TTFT histograms observe in.

    - ``ttft_slo``: the p99 TTFT budget; windowed p99 above it is the
      scale-up / tighten trigger.
    - ``tpot_slo``: optional p99 time-per-output-token budget read off
      the fleet's ``serving_tpot`` histograms; pressure here scales
      the DECODE pool in a disaggregated fleet. None = TTFT/queue
      policy only (the pre-disaggregation bit-reference).
    - ``window``: how far back the windowed percentile looks.
    - ``eval_every``: ticks between evaluations (the hook itself is a
      counter increment on the off-ticks).
    - ``min_replicas`` / ``max_replicas``: fleet-size envelope; only
      non-broken, non-retired replicas count.
    - ``cooldown``: minimum clock distance between fleet-shape changes
      (scale-ups and retires share it).
    - ``idle_to_retire``: consecutive idle clock units before a
      scale-down.
    - ``relax_ratio``: hysteresis — the admission gate re-opens only
      once windowed p99 drops below ``relax_ratio * ttft_slo``.
    - ``min_samples``: windowed observations required before the p99
      is trusted (a 1-sample "p99" is noise).
    - ``queue_high``: optional LEADING indicator — mean queued
      requests per active replica above this also counts as SLO
      pressure. TTFT is a lagging signal (a spike's damage is already
      in the queue before the first late token is observed); queue
      depth lets the controller act while the backlog is still
      building. None = pure windowed-TTFT policy.
    """

    def __init__(self, *, ttft_slo: float, tpot_slo: Optional[float] = None,
                 window: float = 32.0,
                 eval_every: int = 4, min_replicas: int = 1,
                 max_replicas: int = 4, cooldown: float = 16.0,
                 idle_to_retire: float = 32.0, relax_ratio: float = 0.5,
                 min_samples: int = 4, queue_high: Optional[float] = None):
        if ttft_slo <= 0:
            raise ValueError("ttft_slo must be positive")
        if tpot_slo is not None and tpot_slo <= 0:
            raise ValueError("tpot_slo must be positive when set")
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        self.ttft_slo = float(ttft_slo)
        self.tpot_slo = None if tpot_slo is None else float(tpot_slo)
        self.window = float(window)
        self.eval_every = max(1, int(eval_every))
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.cooldown = float(cooldown)
        self.idle_to_retire = float(idle_to_retire)
        self.relax_ratio = float(relax_ratio)
        self.min_samples = max(1, int(min_samples))
        self.queue_high = None if queue_high is None else float(queue_high)
        self.decisions: List[Dict] = []      # host-side decision log
        self._ticks = 0
        self._last_resize: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._stat = None                    # lazily bound to a router

    # -- policy --------------------------------------------------------
    def on_step(self, router, now: float) -> Optional[str]:
        """Router hook: one tick. Returns the decision kind on
        evaluation ticks, None otherwise."""
        self._ticks += 1
        if self._ticks % self.eval_every:
            return None
        return self._evaluate(router, now)

    def _evaluate(self, router, now: float) -> str:
        self._bind(router)
        win = self._window_view(router, now)
        tpot_win = (self._window_view(router, now, metric="serving_tpot")
                    if self.tpot_slo is not None else None)
        active = [rep for rep in router.replicas
                  if rep.health not in ("broken", "retired")]
        qdepth = sum(len(rep.srv.queue) for rep in active)
        load = qdepth + sum(
            sum(1 for s in rep.srv.slots if s is not None)
            for rep in active)
        # idle bookkeeping: a completely quiet fleet starts (or
        # continues) the idle clock; any work resets it
        if load == 0:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None
        idle_for = 0.0 if self._idle_since is None \
            else max(0.0, now - self._idle_since)

        p99, count = win["p99"], win["count"]
        pressure = (self.queue_high is not None and active
                    and qdepth / len(active) > self.queue_high)
        ttft_over = count >= self.min_samples and p99 > self.ttft_slo
        tpot_over = (tpot_win is not None
                     and tpot_win["count"] >= self.min_samples
                     and tpot_win["p99"] > self.tpot_slo)
        over = ttft_over or tpot_over or pressure
        cooled = (self._last_resize is None
                  or now - self._last_resize >= self.cooldown)
        # disaggregated fleets scale their two pools independently:
        # TTFT pressure means prefills are queueing (add prefill),
        # TPOT or queue pressure means decode streams are stalling
        # (add decode); a role-less fleet keeps adding mixed replicas
        disagg = any(rep.role == "prefill" for rep in router.replicas)
        grow_role = "mixed"
        if disagg:
            grow_role = ("prefill"
                         if ttft_over and not (tpot_over or pressure)
                         else "decode")

        action = NOOP
        if over and len(active) < self.max_replicas and cooled \
                and router.replica_factory is not None:
            idx = router.add_replica(
                now=now, role=grow_role,
                reason=f"p99 ttft {p99:.3g} (slo "
                       f"{self.ttft_slo:.3g}), queue {qdepth}")
            self._last_resize = now
            action = SCALE_UP
            detail = {"replica": idx, "role": grow_role}
        elif over and not router.shed_batch:
            router.shed_batch = True
            action = TIGHTEN
            detail = {}
        elif router.shed_batch \
                and (count < self.min_samples
                     or p99 <= self.relax_ratio * self.ttft_slo) \
                and not tpot_over:
            # the window shows no pressure (below the hysteresis floor)
            # or no evidence at all (spike cleared, ring drained past
            # the window) — re-open the gate
            router.shed_batch = False
            action = RELAX
            detail = {}
        elif (not over and len(active) > self.min_replicas and cooled
              and idle_for >= self.idle_to_retire):
            # role-aware victim: never the last decode-capable replica
            # (the router would refuse; a fleet of only prefill
            # replicas cannot finish a single request)
            decode_capable = [r for r in active if r.role != "prefill"]
            cands = [r for r in active
                     if r.role == "prefill" or len(decode_capable) > 1]
            if cands:
                victim = max(rep.idx for rep in cands)
                router.retire_replica(victim, now=now,
                                      reason="sustained idle")
                self._last_resize = now
                self._idle_since = now   # restart the idle clock
                action = RETIRE
                detail = {"replica": victim}
            else:
                detail = {}
        else:
            detail = {}

        decision = {
            "at": now, "action": action,
            "p99_ttft": p99, "window_count": count,
            "window": self.window, "ttft_slo": self.ttft_slo,
            "load": load, "queue_depth": qdepth,
            "queue_pressure": bool(pressure), "idle_for": idle_for,
            "active_replicas": len(active),
            "shed_batch": router.shed_batch,
        }
        if tpot_win is not None:
            decision["p99_tpot"] = tpot_win["p99"]
            decision["tpot_window_count"] = tpot_win["count"]
            decision["tpot_slo"] = self.tpot_slo
        decision.update(detail)
        self.decisions.append(decision)
        self._stat["decisions"].inc()
        key = {SCALE_UP: "scale_ups", RETIRE: "retires",
               TIGHTEN: "tightens", RELAX: "relaxes", NOOP: "noops"}
        self._stat[key[action]].inc()
        self._g_target.set(len([rep for rep in router.replicas
                                if rep.health not in ("broken",
                                                      "retired")]))
        self._g_tight.set(1 if router.shed_batch else 0)
        # the decision AND its triggering metrics land in the trace —
        # the reconstructability contract trace_analyze fleet reads
        router.telemetry.tracer.event("autoscale", step=router._clock,
                                      **decision)
        if action != NOOP:
            logger.info(f"autoscale: {action} "
                        f"(p99_ttft={p99:.4g} slo={self.ttft_slo:.4g} "
                        f"load={load} active={decision['active_replicas']})")
        return action

    # -- plumbing ------------------------------------------------------
    def _bind(self, router) -> None:
        """Lazily register the ``autoscale_*`` metrics on the router's
        registry (the controller cannot do it at construction: it does
        not know its router yet)."""
        if self._stat is not None:
            return
        self._stat = {}
        for key, help_ in _DECISION_COUNTERS:
            self._stat[key] = router.metrics.counter(
                f"autoscale_{key}", help_)
        self._g_target = router.metrics.gauge(
            "autoscale_target_replicas",
            "active (non-broken, non-retired) replicas after the last "
            "controller decision")
        self._g_tight = router.metrics.gauge(
            "autoscale_admission_tight",
            "1 while the shed_batch admission gate is closed")

    def _window_view(self, router, now: float,
                     metric: str = "serving_ttft") -> Dict[str, float]:
        """Fleet-windowed latency digest for ``metric`` (TTFT by
        default, TPOT for the decode-pool signal): interleave the
        recent-observation rings of every matching histogram in the
        fleet into one scratch histogram and summarize the window
        ending at ``now``. Count 0 when telemetry is off fleet-wide."""
        scratch = Histogram(f"fleet_{metric}_window")
        pairs = []
        for reg in router.fleet_registries():
            h = reg._histograms.get(metric)
            if h is not None:
                pairs.extend(h._ring)
        pairs.sort(key=lambda p: p[0])
        scratch._ring.extend(pairs[-scratch._ring.maxlen:])
        return scratch.window_summary(window=self.window, now=now)
