"""Block-paged KV-cache: fixed-size blocks + per-request block tables.

The static engine preallocates a ``[L, B, S_max, Hkv, Dh]`` cache, so one
long request holds ``S_max`` slots for every row and the whole batch's
memory is ``B * S_max`` tokens regardless of what is actually in flight
(the reproduction of the reference's global Context workspace, ref:
ops/transformer/inference/transformer_inference.py:113 softmax_context).
This module is the PagedAttention answer (Kwon et al., SOSP '23): K/V
live in a pool of fixed-size blocks ``[L, N_blocks, block, Hkv, Dh]``,
each serving slot owns an ordered list of block ids (its block table),
and a free-list allocator hands blocks out on demand — cache memory
scales with tokens in flight, fragmentation is bounded by one partial
block per request, and a finished request's blocks return to the pool
immediately.

**Shared-prefix caching** (``prefix_cache=True`` / ``DS_PREFIX_CACHE=on``,
vLLM automatic prefix caching + SGLang RadixAttention): blocks carry
REFCOUNTS, and a host-side radix index (:mod:`.prefix_index`) maps full
block-sized token chunks to the pool blocks already holding their K/V.
Admission matches a new prompt's longest cached prefix and maps those
blocks into the slot's table read-only (refcount++), charging the free
list only for the uncached suffix; a divergence *inside* a block is
handled by copy-on-write (device-copy the partially-matching block into
a fresh one, overwrite from the divergence point). A finished request's
indexed blocks stay resident at refcount 0 — evictable — and block
reclaim becomes LRU over those instead of whole-request preemption.
``prefix_cache=False`` (the default) is bit-identical to the pre-prefix
allocator and stays the behavioral reference.

Host-side bookkeeping (tables, lengths, refcounts, the free list, the
radix index) is plain numpy — it changes every scheduler iteration and
must never trigger a recompile; the device arrays (``k``/``v`` pools)
thread functionally through the engine's donated ``prefill_into_slot``
/ ``decode_slots`` programs, and the only device work this module ever
issues is the one COW block copy (a single compiled program, warmed at
serving startup).

Block id 0 is RESERVED as the trash block: the slot programs route
writes for masked-out lanes (chunk padding, inactive slots) there, so
the compiled scatter needs no branch.

**Host-DRAM tier** (``host_tier=True`` / ``DS_KV_HOST_TIER=on``,
docs/KV_TIERING.md): refcount-zero INDEXED blocks can spill to a
:class:`~deepspeed_tpu.inference.host_tier.HostBlockPool` instead of
dying at eviction — the reproduction of the reference's ZeRO-Infinity
``swap_tensor`` offload re-aimed at inference. A low-watermark spill
daemon (:meth:`PagedKVCache.spill_tick`, driven once per serving step,
never on the admission critical path) gathers up to ``transfer_blocks``
LRU spill candidates with ONE fixed-width compiled gather and harvests
the bytes to host on the NEXT tick (double-buffered: the device→host
copy overlaps a full decode step). A prefix match that lands on
host-tier links restores them block-by-block through a fixed-width
compiled scatter, drawing restore targets from the FREE LIST only.
Both programs are warmed at :meth:`PagedKVCache.warm_host_tier`, so the
steady state compiles ZERO new programs. Every failure rung degrades,
never corrupts: a CRC-bad host block discards its whole chain
(cold-miss re-prefill), a failed spill leaves the block device-resident
behind exponential backoff, and an exhausted host budget falls back to
plain eviction — exactly the tier-off behavior.
"""

import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.env import resolve_flag
from deepspeed_tpu.inference.host_tier import (
    HostBlockPool, HostCorruption, resolve_host_tier)
from deepspeed_tpu.inference.prefix_index import PrefixIndex, PrefixMatch
from deepspeed_tpu.models import gpt as gpt_lib
from deepspeed_tpu.models.gpt import GPTConfig
from deepspeed_tpu.ops.quantizer import resolve_kv_quant


class CacheExhausted(Exception):
    """The free list cannot cover an allocation — the scheduler's cue to
    evict-and-requeue instead of OOMing the device."""


def resolve_prefix_cache(flag: Optional[bool] = None) -> bool:
    """Resolve the shared-prefix cache switch.

    Explicit argument wins, else the ``DS_PREFIX_CACHE`` env var
    (``on``/``off``, also ``1``/``0``/``true``/``false``), else OFF —
    the refcount-free allocator is the behavioral bit-reference."""
    return resolve_flag("DS_PREFIX_CACHE", flag)


def _cow_copy_fn(k_pool, v_pool, src, dst):
    """Copy ONE pool block (every layer) ``src`` -> ``dst``: the device
    half of copy-on-write. Pools are donated so the copy is in-place in
    HBM; ``src``/``dst`` are traced scalars, so every (src, dst) pair
    reuses one compiled program."""
    return (k_pool.at[:, dst].set(k_pool[:, src]),
            v_pool.at[:, dst].set(v_pool[:, src]))


_default_cow = jax.jit(_cow_copy_fn, donate_argnums=(0, 1))


def _cow_copy_fn_q(k_pool, v_pool, k_scale, v_scale, src, dst):
    """Quantized-pool COW: the block's per-(block, kv_head) scales travel
    with its int8 payload — a shared block and its copy dequantize to the
    same values."""
    return (k_pool.at[:, dst].set(k_pool[:, src]),
            v_pool.at[:, dst].set(v_pool[:, src]),
            k_scale.at[:, dst].set(k_scale[:, src]),
            v_scale.at[:, dst].set(v_scale[:, src]))


_default_cow_q = jax.jit(_cow_copy_fn_q, donate_argnums=(0, 1, 2, 3))


def _gather_blocks_fn(k_pool, v_pool, ids):
    """Pull ``len(ids)`` blocks out of the pools (device side of a
    spill). ``ids`` is a FIXED-width traced vector — every spill batch
    reuses one compiled program, short batches pad with the trash block
    (its lanes are gathered and then simply not stored). Pools are NOT
    donated: the gathered copy rides out asynchronously while the pools
    keep serving decode."""
    return k_pool[:, ids], v_pool[:, ids]


_default_gather = jax.jit(_gather_blocks_fn)


def _gather_blocks_fn_q(k_pool, v_pool, k_scale, v_scale, ids):
    """Quantized-pool spill gather: the int8 payload travels WITH its
    fp32 per-(block, kv_head) scale sidecars, so a restored block
    dequantizes to exactly what was spilled."""
    return (k_pool[:, ids], v_pool[:, ids],
            k_scale[:, ids], v_scale[:, ids])


_default_gather_q = jax.jit(_gather_blocks_fn_q)


def _scatter_block_fn(k_pool, v_pool, k_blk, v_blk, dst):
    """Write ONE restored block back into the pools (device side of a
    host→device restore). ``dst`` is a traced scalar — one compiled
    program for every restore. Pools are donated: the write is in-place
    in HBM, mirroring the COW copy."""
    return (k_pool.at[:, dst].set(k_blk),
            v_pool.at[:, dst].set(v_blk))


_default_scatter = jax.jit(_scatter_block_fn, donate_argnums=(0, 1))


def _scatter_block_fn_q(k_pool, v_pool, k_scale, v_scale,
                        k_blk, v_blk, ks_blk, vs_blk, dst):
    """Quantized-pool restore scatter: payload and scale sidecars land
    together."""
    return (k_pool.at[:, dst].set(k_blk),
            v_pool.at[:, dst].set(v_blk),
            k_scale.at[:, dst].set(ks_blk),
            v_scale.at[:, dst].set(vs_blk))


_default_scatter_q = jax.jit(_scatter_block_fn_q,
                             donate_argnums=(0, 1, 2, 3))


class PagedKVCache:
    """Pool + allocator + per-slot block tables (+ optional prefix index).

    num_blocks is the HBM-budget watermark made concrete: either passed
    directly or derived from ``hbm_budget_bytes`` via the per-token cache
    cost (models.gpt.kv_bytes_per_token). ``watermark`` free blocks are
    held back at admission time so every active slot can always grow into
    its next decode block without immediate eviction.

    With ``prefix_cache=True`` every mapped block carries a refcount
    (shared prefix blocks count once per slot mapping them); a block is
    in exactly ONE of three states: on the free list, held (refcount >
    0), or cached (indexed, refcount 0, reclaimable in LRU order).
    ``copy_fn(k, v, src, dst) -> (k, v)`` performs the COW block copy —
    the serving engine wires the engine's donated program in; standalone
    caches fall back to a module-level jitted copy.

    With ``kv_quant="int8"`` (or ``DS_KV_QUANT=int8``) the pools store
    int8 with fp32 per-(block, kv_head) scales in parallel ``k_scale`` /
    ``v_scale`` pools ``[L, N_blocks, Hkv]``; ``copy_fn`` then takes and
    returns the scale pools too (``(k, v, ks, vs, src, dst) -> 4-tuple``)
    so scales travel with blocks on COW. ``"off"`` (default) keeps the
    fp pools byte-identical to the unquantized cache — the bit-reference.

    With ``host_tier=True`` (or ``DS_KV_HOST_TIER=on``) refcount-zero
    indexed blocks spill to host DRAM under HBM pressure instead of
    being evicted outright, and a prefix match on a spilled chain
    restores the bytes instead of re-prefilling (module docstring;
    docs/KV_TIERING.md). The tier requires the prefix cache — only
    indexed blocks are worth keeping on ANY tier — so with
    ``prefix_cache=False`` the flag is inert and the device-only
    allocator stays the bit-reference. ``gather_fn`` / ``scatter_fn``
    override the transfer programs (the serving engine wires the
    engine's jitted, correctly-sharded ones in); standalone caches fall
    back to module-level jitted defaults.
    """

    def __init__(self, cfg: GPTConfig, *, num_slots: int,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 dtype=jnp.bfloat16, max_seq_len: Optional[int] = None,
                 watermark: Optional[int] = None, faults=None,
                 prefix_cache: bool = False,
                 copy_fn: Optional[Callable] = None,
                 tracer=None,
                 kv_quant: Optional[str] = None,
                 host_tier: Optional[bool] = None,
                 host_budget_bytes: Optional[int] = None,
                 transfer_blocks: int = 4,
                 spill_watermark: Optional[int] = None,
                 gather_fn: Optional[Callable] = None,
                 scatter_fn: Optional[Callable] = None):
        self.cfg = cfg
        # telemetry hook (telemetry/tracer.RequestTracer): COW copies
        # and index-block reclaims land in the serving timeline; None
        # (standalone caches, telemetry off) records nothing
        self.tracer = tracer
        # fault-injection hook (utils/faults.FaultInjector): the
        # ``cache.allocate`` / ``cache.ensure`` sites can fire a
        # synthetic CacheExhausted so the scheduler's eviction path runs
        # under test without actually shrinking the pool;
        # ``cache.match`` degrades a prefix lookup to a miss and
        # ``cache.cow`` fails the copy-on-write before any bookkeeping
        self.faults = faults
        self.block_size = int(block_size)
        self.num_slots = int(num_slots)
        self.blocks_per_slot, self.tokens_per_slot = gpt_lib.decode_geometry(
            cfg, self.block_size, max_seq_len)
        self.dtype = jnp.dtype(dtype)
        # KV quantization: int8 pools + fp32 per-(block, kv_head) scale
        # pools ("off" keeps the fp pools bit-identical to before)
        self.kv_quant = resolve_kv_quant(kv_quant)
        self.quantized = self.kv_quant == "int8"
        L, Hkv, Dh = cfg.n_layers, cfg.kv_heads, cfg.head_dim
        self.pool_dtype = jnp.dtype(jnp.int8) if self.quantized \
            else self.dtype
        self.bytes_per_token = gpt_lib.kv_bytes_per_token(
            cfg, self.pool_dtype)
        # scale overhead: 2 pools (K and V) × L layers × Hkv heads × fp32
        # per block — amortized it is 2*L*Hkv*4/block_size bytes/token
        self.scale_bytes_per_block = (2 * L * Hkv * 4) if self.quantized \
            else 0
        if num_blocks is None:
            if hbm_budget_bytes:
                per_block = (self.bytes_per_token * self.block_size
                             + self.scale_bytes_per_block)
                num_blocks = int(hbm_budget_bytes // per_block)
            else:
                # default pool: the static reservation's worth of blocks
                # (num_slots full sequences) — usage accounting then shows
                # how far actual tokens-in-flight undercut it. Counted in
                # blocks, not bytes: under kv_quant the scale sidecar must
                # not shave the pool below its own slots' capacity
                num_blocks = self.num_slots * self.blocks_per_slot
        # +1: block 0 is the reserved trash block, never allocated
        self.num_blocks = int(num_blocks) + 1
        if self.num_blocks < 2:
            raise ValueError(
                f"HBM budget covers {self.num_blocks - 1} blocks; the "
                f"pool needs at least 1 allocatable block")
        self.k = jnp.zeros((L, self.num_blocks, self.block_size, Hkv, Dh),
                           self.pool_dtype)
        self.v = jnp.zeros_like(self.k)
        if self.quantized:
            self.k_scale = jnp.zeros((L, self.num_blocks, Hkv),
                                     jnp.float32)
            self.v_scale = jnp.zeros_like(self.k_scale)
        else:
            self.k_scale = None
            self.v_scale = None
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._owned: List[List[int]] = [[] for _ in range(num_slots)]
        self._refcount = np.zeros((self.num_blocks,), np.int32)
        self.tables = np.zeros((num_slots, self.blocks_per_slot), np.int32)
        self.lengths = np.zeros((num_slots,), np.int32)
        self.active = np.zeros((num_slots,), bool)
        self.watermark = num_slots if watermark is None else int(watermark)
        self.prefix_cache = bool(prefix_cache)
        self.index: Optional[PrefixIndex] = \
            PrefixIndex(self.block_size) if self.prefix_cache else None
        self.copy_fn = copy_fn
        # host-DRAM second tier (docs/KV_TIERING.md): gated on the
        # prefix index because only INDEXED blocks spill — a block no
        # future request can match is dead weight on any tier. With the
        # index absent the knob is inert (bit-reference either way).
        self.host_tier = resolve_host_tier(host_tier) and \
            self.index is not None
        self.host_pool: Optional[HostBlockPool] = \
            HostBlockPool(host_budget_bytes) if self.host_tier else None
        self.gather_fn = gather_fn
        self.scatter_fn = scatter_fn
        self.transfer_blocks = max(1, int(transfer_blocks))
        # spill trigger: one transfer batch ABOVE the admission
        # watermark by default, so spilling starts before admission
        # control begins holding requests back
        self.spill_watermark = (self.watermark + self.transfer_blocks) \
            if spill_watermark is None else int(spill_watermark)
        # blocks whose bytes are mid-flight (queued gather not yet
        # harvested): excluded from EVERY reclaim/eviction predicate and
        # from free-list returns until the harvest settles them
        self._in_transfer: set = set()
        # replica-to-replica migration landings (docs/ROBUSTNESS.md):
        # rid -> (block ids, prefix length). Parked blocks are neither
        # free nor owned nor indexed — invisible to every reclaim path —
        # until the request's admission adopts them or a drain/fallback
        # drops them back onto the free list
        self._parked: Dict = {}
        self._pending_spill = None   # (ids, gathered device arrays)
        self._spill_cooldown = 0     # ticks until the next spill attempt
        self._spill_backoff = 1      # cooldown applied on the next failure
        self._restore_ms: List[float] = []
        self.peak_used_blocks = 0
        self.peak_tokens_in_flight = 0
        # prefix-cache counters (mirrored into serving stats / bench rows)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_saved = 0
        self.cow_copies = 0
        self.cache_block_evictions = 0
        # host-tier counters
        self.host_spills = 0
        self.host_restores = 0
        self.host_restore_failures = 0
        self.host_spill_aborts = 0
        self.host_budget_refusals = 0
        # migration counters (landings adopted / chains dropped)
        self.parked_adopted = 0
        self.parked_aborts = 0

    # -- accounting ----------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        """Blocks not on the free list — held by slots OR resident in
        the prefix cache (both occupy HBM)."""
        return (self.num_blocks - 1) - len(self._free)

    @property
    def held_blocks(self) -> int:
        """Blocks mapped into at least one slot table (refcount > 0)."""
        return int((self._refcount > 0).sum())

    @property
    def shared_blocks(self) -> int:
        """Blocks mapped by MORE than one slot — the sharing win."""
        return int((self._refcount > 1).sum())

    def _reclaimable(self, bid: int) -> bool:
        """The ONE reclaim-eligibility predicate: refcount zero AND not
        mid-transfer. Every eviction/availability path must use it — a
        block whose bytes are in flight to host must not be handed out
        (the harvest would scatter stale truth over a live block)."""
        return self._refcount[bid] == 0 and bid not in self._in_transfer

    @property
    def cached_blocks(self) -> int:
        """Indexed blocks no slot holds: resident, reclaimable (LRU)."""
        if self.index is None:
            return 0
        return self.index.evictable_count(self._reclaimable)

    @property
    def host_blocks(self) -> int:
        """Blocks resident on the host tier (spilled, restorable)."""
        return len(self.host_pool) if self.host_pool is not None else 0

    @property
    def host_bytes(self) -> int:
        """Host-DRAM bytes the spilled blocks occupy."""
        return self.host_pool.bytes_used if self.host_pool is not None \
            else 0

    @property
    def tokens_in_flight(self) -> int:
        return int(self.lengths.sum())

    def stats(self) -> Dict[str, float]:
        """Allocator state for bench rows and operators: block counts by
        state, internal fragmentation of slot tables (tail-block waste:
        allocated-but-unwritten token positions over allocated capacity),
        and the prefix-cache counters."""
        cap_tokens = sum(len(o) for o in self._owned) * self.block_size
        frag = (1.0 - self.tokens_in_flight / cap_tokens) if cap_tokens \
            else 0.0
        return {
            "num_blocks": self.num_blocks - 1,
            "free_blocks": self.free_blocks,
            "used_blocks": self.used_blocks,
            "held_blocks": self.held_blocks,
            "shared_blocks": self.shared_blocks,
            "cached_blocks": self.cached_blocks,
            "fragmentation": round(float(frag), 4),
            "tokens_in_flight": self.tokens_in_flight,
            "peak_used_blocks": self.peak_used_blocks,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_tokens_saved": self.prefix_tokens_saved,
            "cow_copies": self.cow_copies,
            "cache_block_evictions": self.cache_block_evictions,
            "host_blocks": self.host_blocks,
            "host_bytes": self.host_bytes,
            "host_spills": self.host_spills,
            "host_restores": self.host_restores,
            "host_restore_failures": self.host_restore_failures,
            "host_spill_aborts": self.host_spill_aborts,
            "host_budget_refusals": self.host_budget_refusals,
            "parked_blocks": sum(len(b) for b, _ in self._parked.values()),
            "parked_adopted": self.parked_adopted,
            "parked_aborts": self.parked_aborts,
        }

    def used_block_bytes(self) -> int:
        """Bytes actually held by allocated blocks — what the bench's
        'paged peak HBM' row reports (scales with tokens in flight,
        block-quantized). Includes the per-block scale overhead when the
        pool is int8."""
        return self.used_blocks * (self.block_size * self.bytes_per_token
                                   + self.scale_bytes_per_block)

    def static_equivalent_bytes(self, batch: int,
                                max_seq_len: Optional[int] = None) -> int:
        """What the static [B, S_max] cache would reserve for the same
        traffic — the comparison row."""
        s = max_seq_len or self.cfg.max_seq_len
        return batch * s * self.bytes_per_token

    def blocks_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def at_capacity(self, slot: int) -> bool:
        """True when the slot's cache has consumed its whole block
        budget: the next decode write would CLAMP into the last live
        block (inference/engine.py masks it to the trash block), so the
        scheduler must finish the request before the kernel runs."""
        return int(self.lengths[slot]) >= self.tokens_per_slot

    # -- admission control ---------------------------------------------
    def _peek_match(self, tokens) -> PrefixMatch:
        """LRU-neutral prefix lookup (admission precheck)."""
        if self.index is None or tokens is None or len(tokens) < 2:
            return PrefixMatch()
        return self.index.match(tokens, max_tokens=len(tokens) - 1,
                                touch=False)

    def blocks_needed(self, n_tokens: int, tokens=None) -> int:
        """Fresh blocks an allocation would draw from the pool after
        prefix sharing (a COW divergence still needs its fresh copy).
        Only DEVICE-tier matched links are free; a host-tier hit costs
        one fresh block too — its restore target."""
        m = self._peek_match(tokens)
        dev = m.tiers.count("device") if m.tiers else len(m.block_ids)
        return self.blocks_for(n_tokens) - dev

    def available_blocks(self, tokens=None) -> int:
        """Free blocks plus LRU-reclaimable cached blocks, EXCLUDING any
        block a match on ``tokens`` would map (a chain block at refcount
        0 cannot both be shared into the slot and reclaimed for it).
        Host-tier links never pin: their keys live in a separate
        namespace and their restore targets are charged by
        :meth:`blocks_needed`."""
        n = len(self._free)
        if self.index is not None:
            m = self._peek_match(tokens)
            if m.tiers:
                pinned = {b for b, t in zip(m.block_ids, m.tiers)
                          if t == "device"}
            else:
                pinned = set(m.block_ids)
            if m.cow_src is not None:
                pinned.add(m.cow_src)
            n += self.index.evictable_count(
                lambda b: self._reclaimable(b) and b not in pinned)
        return n

    def can_admit(self, n_tokens: int, tokens=None,
                  watermark: Optional[int] = None) -> bool:
        """Admission-control check: fresh blocks for the (uncached part
        of the) prompt available AND the watermark reserve stays intact
        so live slots can keep growing. Shared prefix blocks are free —
        admission charges only the uncached suffix."""
        wm = self.watermark if watermark is None else int(watermark)
        return self.available_blocks(tokens) >= \
            self.blocks_needed(n_tokens, tokens) + wm

    # -- allocator -----------------------------------------------------
    def allocate(self, slot: int, n_tokens: int, tokens=None) -> int:
        """Reserve blocks covering ``n_tokens`` for a fresh slot.

        With the prefix cache on and the prompt's ``tokens`` given, the
        longest cached prefix is mapped in read-only (shared blocks,
        refcount++) and only the uncached suffix draws fresh blocks; a
        mid-block divergence copy-on-writes the partially-matching block.
        Returns the number of prefix tokens already resident — the
        slot's ``lengths`` starts there and prefill begins at that
        offset (0 on a miss / with the cache off)."""
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.num_slots})")
        if self.active[slot] or self._owned[slot]:
            raise ValueError(f"slot {slot} is already allocated; free() "
                             f"it before re-allocating")
        self._maybe_inject("cache.allocate", slot)
        need_total = self.blocks_for(n_tokens)
        if need_total > self.blocks_per_slot:
            raise ValueError(
                f"{n_tokens} tokens need {need_total} blocks > per-slot "
                f"table width {self.blocks_per_slot}")
        m = self._match_for_allocate(tokens)
        # every fault site above fired and every validation ran; from
        # here the bookkeeping must be atomic (claim -> check -> commit,
        # with rollback on the one remaining failure: pool shortage)
        pinned = list(m.block_ids)
        if m.cow_src is not None:
            pinned.append(m.cow_src)
        for bid in pinned:
            self._refcount[bid] += 1      # claim: un-reclaimable below
        fresh_need = need_total - len(m.block_ids)
        avail = len(self._free)
        if self.index is not None:
            avail += self.index.evictable_count(self._reclaimable)
        if fresh_need > avail:
            for bid in pinned:
                self._refcount[bid] -= 1  # rollback the claim
            raise CacheExhausted(
                f"need {fresh_need} fresh blocks "
                f"({need_total} total, {len(m.block_ids)} shared), "
                f"{avail} available")
        ids = [self._pop_free() for _ in range(fresh_need)]
        if m.cow_src is not None:
            # the divergent/partial block: device-copy into the first
            # fresh block (table position len(chain)); the suffix
            # prefill overwrites it from the divergence point on
            self._cow(m.cow_src, ids[0])
            self._refcount[m.cow_src] -= 1   # pin released post-copy
        for bid in ids:
            self._refcount[bid] = 1
        all_ids = m.block_ids + ids
        self._owned[slot] = list(all_ids)
        self.tables[slot, :] = 0
        self.tables[slot, :len(all_ids)] = all_ids
        self.lengths[slot] = m.matched
        self.active[slot] = True
        if self.index is not None and tokens is not None:
            if m.matched > 0:
                self.prefix_hits += 1
                self.prefix_tokens_saved += m.matched
            else:
                self.prefix_misses += 1
        self._mark()
        return m.matched

    def _match_for_allocate(self, tokens) -> PrefixMatch:
        """The real (LRU-touching) prefix match, with its fault sites:
        ``cache.match`` degrades the lookup to a miss, ``cache.cow``
        fails the copy-on-write — both BEFORE any bookkeeping mutates,
        so an injected failure leaves the allocator untouched."""
        if self.index is None or tokens is None or len(tokens) < 2:
            return PrefixMatch()
        f = self._fire("cache.match")
        if f is not None and f.kind == "cache_exhausted":
            return PrefixMatch()          # degraded: serve as a cold miss
        m = self.index.match(tokens, max_tokens=len(tokens) - 1)
        if "host" in m.tiers:
            m = self._restore_match(m)
        if m.cow_src is not None:
            f = self._fire("cache.cow")
            if f is not None and f.kind == "cache_exhausted":
                raise CacheExhausted(
                    "injected copy-on-write failure at cache.cow "
                    f"({self.free_blocks} blocks actually free)")
        return m

    def _restore_match(self, m: PrefixMatch) -> PrefixMatch:
        """Bring a matched chain's host-tier links back on device, in
        prefix order. Each restore costs one FREE-LIST block (restores
        never reclaim — the admission path must stay cheap and must not
        cannibalize the very cache it is hitting). The first link that
        cannot restore — free list dry, injected ``cache.restore``
        fault, CRC corruption — TRUNCATES the match there: the already-
        restored prefix is kept, the tail degrades to a cold-miss
        re-prefill. Always correct tokens, merely slower."""
        for i, tier in enumerate(m.tiers):
            if tier == "device":
                continue
            ok = False
            if self._free:
                f = self._fire("cache.restore")
                if f is not None and f.kind == "cache_exhausted":
                    # injected transfer failure: the host entry SURVIVES
                    # (a later match retries it); this match degrades
                    self.host_restore_failures += 1
                else:
                    f = self._fire("cache.host_corrupt")
                    if f is not None and f.kind == "cache_exhausted":
                        # flip a real byte so the REAL CRC machinery,
                        # not a shortcut, drives the degrade path
                        self.host_pool.corrupt(m.block_ids[i])
                    ok = self._dispatch_restore(m.block_ids[i], i, m)
            if not ok:
                return self._truncate_match(m, i)
        return m

    def _dispatch_restore(self, key: int, i: int, m: PrefixMatch) -> bool:
        """One host→device block restore: CRC-verified fetch, H2D copy,
        fixed-shape scatter into a free block, index flip to device.
        Returns False on corruption (after discarding the poisoned
        subtree — every descendant's prefix runs through the bad
        chunk). Mutates ``m`` in place on success."""
        t0 = time.perf_counter()
        try:
            payload = self.host_pool.get(key)
        except HostCorruption:
            dev, hosts = self.index.remove_subtree(key)
            for hk in hosts:
                self.host_pool.discard(hk)
            for bid in dev:
                # device descendants at refcount 0 go straight back to
                # the free list (they were index-resident, so they are
                # not on it); held or mid-transfer blocks are settled by
                # their release / harvest instead
                if self._refcount[bid] == 0 and \
                        bid not in self._in_transfer:
                    self._free.append(bid)
            self.host_restore_failures += 1
            if self.tracer is not None:
                self.tracer.event("cache_restore_corrupt", key=int(key),
                                  dropped_host=len(hosts),
                                  dropped_device=len(dev))
            return False
        bid = self._free.pop()
        self._run_scatter(payload, bid)
        self.index.to_device(key, bid)
        self.host_pool.discard(key)
        m.block_ids[i] = bid
        m.tiers[i] = "device"
        self.host_restores += 1
        ms = (time.perf_counter() - t0) * 1000.0
        self._restore_ms.append(ms)
        if self.tracer is not None:
            self.tracer.event("cache_restore", block=bid, key=int(key),
                              ms=round(ms, 3))
        return True

    def _truncate_match(self, m: PrefixMatch, i: int) -> PrefixMatch:
        """Degrade: keep the usable device prefix ``[0, i)``, drop the
        rest. The COW candidate hangs off the FULL chain's tail, so a
        truncated match cannot carry it."""
        return PrefixMatch(block_ids=m.block_ids[:i], tiers=m.tiers[:i],
                           matched=i * self.block_size)

    def ensure_capacity(self, slot: int, n_tokens: int) -> None:
        """Grow the slot's table until it covers ``n_tokens`` (append).
        When the free list is dry, reclaim least-recently-used cached
        blocks (refcount 0) from the prefix index first — request
        preemption is the scheduler's LAST resort, not the first."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        self._maybe_inject("cache.ensure", slot)
        need = self.blocks_for(n_tokens)
        if need > self.blocks_per_slot:
            raise ValueError(
                f"{n_tokens} tokens exceed the per-slot capacity "
                f"{self.tokens_per_slot}")
        while len(self._owned[slot]) < need:
            bid = self._pop_free()
            self._refcount[bid] = 1
            self.tables[slot, len(self._owned[slot])] = bid
            self._owned[slot].append(bid)
        self._mark()

    def advance(self, slot: int, n_tokens: int) -> None:
        """Record ``n_tokens`` newly written to the slot's cache."""
        new_len = int(self.lengths[slot]) + int(n_tokens)
        assert new_len <= len(self._owned[slot]) * self.block_size, \
            (slot, new_len, len(self._owned[slot]))
        self.lengths[slot] = new_len
        self.peak_tokens_in_flight = max(self.peak_tokens_in_flight,
                                         self.tokens_in_flight)

    def capacity_tokens(self, slot: int) -> int:
        """Token positions the slot's allocated blocks cover — the cap
        on how far a speculative chunk may advance before rollback."""
        return len(self._owned[slot]) * self.block_size

    def horizon_budget(self, slot: int, n_tokens: int) -> int:
        """Opportunistic capacity grant for a fused multi-step decode
        (docs/MULTISTEP.md): try to grow the slot's table to cover
        ``n_tokens`` total positions, but — unlike :meth:`ensure_capacity`
        — treat a dry pool as a smaller horizon, not a failure. Returns
        the TOTAL token positions actually granted; the scheduler caps
        the slot's in-program emission budget there, so horizon tokens
        beyond the guaranteed first never trigger eviction (the plain
        one-token preamble already secured that one). The in-scan write
        path needs no rollback: a lane frozen at its budget stops
        advancing its length, so no write ever lands past the grant."""
        want = min(int(n_tokens), self.tokens_per_slot)
        if want > self.capacity_tokens(slot):
            try:
                self.ensure_capacity(slot, want)
            except CacheExhausted:
                pass
        return min(self.capacity_tokens(slot), self.tokens_per_slot)

    def rollback(self, slot: int, n_tokens: int) -> None:
        """Shrink the slot's logical length to ``n_tokens`` and RELEASE
        any owned tail block the shorter length no longer covers — the
        speculative-decode rollback contract: a rejected draft chunk
        that straddled a block edge must not leave the now-unused tail
        block referenced in the block table (it would silently pin a
        pool block per reject until the request finished). Stale K/V
        inside the kept partial block is safe: the next chunk rewrites
        those positions before any query attends them.

        Hardening: only a non-negative length within the currently
        allocated capacity is a legal rollback target (growing is
        ``advance``'s job), and only blocks this slot OWNS are released
        — shared prefix blocks sit below the prompt boundary, which a
        rollback can never cross (``n_tokens`` >= the pre-chunk length
        >= the prompt length)."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        n_tokens = int(n_tokens)
        if not 0 <= n_tokens <= self.capacity_tokens(slot):
            raise ValueError(
                f"rollback target {n_tokens} outside the allocated "
                f"capacity [0, {self.capacity_tokens(slot)}] of slot "
                f"{slot}")
        keep = self.blocks_for(n_tokens)
        while len(self._owned[slot]) > keep:
            bid = self._owned[slot].pop()
            self.tables[slot, len(self._owned[slot])] = 0
            self._release(bid)
        self.lengths[slot] = n_tokens

    def free(self, slot: int) -> None:
        """Release the slot's references. Idempotent: freeing an already-
        free slot is a no-op (retry/requeue paths may race a finish).
        A block whose refcount drops to 0 returns to the free list —
        unless the prefix index holds it, in which case it stays
        resident as reclaimable cache."""
        if not self.active[slot] and not self._owned[slot]:
            self.tables[slot, :] = 0
            self.lengths[slot] = 0
            return
        for bid in reversed(self._owned[slot]):
            self._release(bid)
        self._owned[slot] = []
        self.tables[slot, :] = 0
        self.lengths[slot] = 0
        self.active[slot] = False

    def register_prefix(self, slot: int, tokens) -> int:
        """Publish the slot's FULL prompt blocks into the prefix index
        (called once the prompt is completely prefilled, so every full
        block's K/V is final — full blocks are never written again).
        Chunks already cached keep their existing block; the slot's
        duplicate stays private. Returns newly registered blocks."""
        if self.index is None or tokens is None:
            return 0
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        n_full = min(len(tokens) // self.block_size,
                     len(self._owned[slot]))
        if int(self.lengths[slot]) < n_full * self.block_size:
            raise ValueError(
                f"slot {slot} holds {int(self.lengths[slot])} tokens; "
                f"cannot register {n_full} full blocks before they are "
                f"written")
        return self.index.insert(
            np.asarray(tokens, np.int32), self._owned[slot][:n_full],
            # a re-registered chunk that had spilled flips back to
            # device on the slot's fresh copy; its host bytes are
            # redundant the moment the flip lands
            on_host_displaced=(self.host_pool.discard
                               if self.host_pool is not None else None))

    def warm_cow(self) -> None:
        """Compile the COW copy program up front (trash-block self-copy)
        so the first real divergence — possibly inside a CompileWatch-
        guarded steady state — hits a warm cache."""
        if self.prefix_cache:
            self._run_cow(np.int32(0), np.int32(0))

    def warm_host_tier(self) -> None:
        """Compile the spill gather and restore scatter up front on
        trash-block lanes, so every steady-state transfer hits a warm
        cache — the CompileWatch(0) contract (docs/KV_TIERING.md)."""
        if not self.host_tier:
            return
        ids = np.zeros((self.transfer_blocks,), np.int32)
        arrs = self._run_gather(ids)
        payload = tuple(np.asarray(a[:, 0])
                        for a in jax.device_get(arrs))
        self._run_scatter(payload, 0)

    # -- host-tier spill daemon ----------------------------------------
    def spill_tick(self) -> int:
        """One spill-daemon tick — the serving loop drives this once per
        step, OFF the admission critical path. Harvests the previous
        tick's in-flight gather (one batched D2H pull, overlapped with
        the decode step that ran in between — the double buffer), then,
        under free-list pressure, dispatches the next fixed-width gather
        over the LRU spill candidates. Returns blocks landed on host
        this tick."""
        if not self.host_tier:
            return 0
        landed = self._harvest_spill()
        if self._pending_spill is not None:
            return landed
        if self._spill_cooldown > 0:
            self._spill_cooldown -= 1
            return landed
        if len(self._free) >= self.spill_watermark:
            return landed
        f = self._fire("cache.spill")
        if f is not None and f.kind == "cache_exhausted":
            # injected transfer failure: the candidates stay device-
            # resident; exponential backoff before the retry
            self._note_spill_failure()
            return landed
        ids = self.index.spill_candidates(self._reclaimable,
                                          self.transfer_blocks)
        if not ids:
            return landed
        padded = np.zeros((self.transfer_blocks,), np.int32)
        padded[:len(ids)] = ids       # short batches pad with trash lanes
        arrs = self._run_gather(padded)
        self._in_transfer.update(ids)
        self._pending_spill = (list(ids), arrs)
        if self.tracer is not None:
            self.tracer.event("cache_spill", blocks=[int(b) for b in ids])
        return landed

    def _harvest_spill(self) -> int:
        """Settle the in-flight gather: ONE batched device→host pull for
        the whole buffer, then per block either commit (store on host,
        flip the index tag, free the device block) or abort (the block
        was re-claimed or unindexed while its bytes flew — the device
        copy stays authoritative)."""
        if self._pending_spill is None:
            return 0
        ids, arrs = self._pending_spill
        self._pending_spill = None
        host = jax.device_get(arrs)
        landed = 0
        for i, bid in enumerate(ids):
            self._in_transfer.discard(bid)
            if self._refcount[bid] != 0 or bid not in self.index:
                self.host_spill_aborts += 1
                if self._refcount[bid] == 0 and bid not in self.index:
                    # unindexed mid-flight (corruption cleanup / release
                    # of a displaced chain): _release deferred to us, so
                    # this is the block's single return to the free list
                    self._free.append(bid)
                continue
            payload = tuple(np.asarray(a[:, i]) for a in host)
            key = self.host_pool.put(payload)
            if key is None:
                # budget refusal is policy, not failure: the block stays
                # device-resident and plain eviction remains its fate
                self.host_budget_refusals += 1
                self._note_spill_failure()
                continue
            self.index.to_host(bid, key)
            self._free.append(bid)
            self.host_spills += 1
            landed += 1
        if landed:
            self._spill_backoff = 1
        return landed

    def _note_spill_failure(self) -> None:
        """Exponential-backoff cooldown (in daemon ticks, capped): a
        failing transfer path must not be hammered every step."""
        self._spill_cooldown = self._spill_backoff
        self._spill_backoff = min(self._spill_backoff * 2, 64)

    def abort_transfers(self) -> int:
        """Abort every in-flight spill synchronously — the drain/retire
        contract: a replica must settle its transfer state BEFORE
        ``pending_snapshot(release=True)`` hands its requests away. The
        un-harvested gather is dropped (the candidates simply stay
        device-resident; JAX discards the orphaned computation) and the
        in-transfer set is settled so every block is releasable. Returns
        how many spills were aborted."""
        aborted = 0
        if self._pending_spill is not None:
            ids, _ = self._pending_spill
            self._pending_spill = None
            aborted = len(ids)
            self.host_spill_aborts += aborted
        for bid in sorted(self._in_transfer):
            self._in_transfer.discard(bid)
            if self._refcount[bid] == 0 and not (
                    self.index is not None and bid in self.index):
                self._free.append(bid)
        return aborted

    # -- replica-to-replica KV migration (docs/ROBUSTNESS.md) ----------
    # The disaggregated prefill/decode fleet generalizes the host tier's
    # CRC-verified transfer path into a replica→replica channel: the
    # SOURCE cache gathers a finished prefill's whole chain through host
    # DRAM (per-array CRC32 at put time), the DESTINATION lands the
    # blocks free-list-only as a PARKED chain its admission later
    # adopts. Every failure rung — budget refusal, CRC mismatch, dry
    # free list, a replica dying mid-flight — degrades to a cold
    # re-prefill on the decode side, never a wrong token.

    def warm_migration(self) -> None:
        """Compile the transfer gather/scatter up front on trash-block
        lanes (same programs :meth:`warm_host_tier` warms, but the
        migration channel needs them with the host tier OFF too), so a
        role'd fleet's steady state compiles nothing — CompileWatch(0)."""
        ids = np.zeros((self.transfer_blocks,), np.int32)
        arrs = self._run_gather(ids)
        payload = tuple(np.asarray(a[:, 0]) for a in jax.device_get(arrs))
        self._run_scatter(payload, 0)

    def migrate_gather(self, slot: int, pool: HostBlockPool) -> Dict:
        """Source half of a migration: pull the slot's owned chain
        through host DRAM in ``transfer_blocks``-wide batches (the same
        fixed-width gather the spill daemon uses — short batches pad
        with trash lanes) and :meth:`HostBlockPool.put` each block, so
        every array carries a CRC32 tag the landing verifies. Returns
        ``{"keys", "length", "n_blocks"}`` — the migration's
        ``kv_handle``. On ANY failure (budget refusal raises
        :class:`CacheExhausted`) the already-stored keys are discarded
        and the slot is left untouched: the source still owns its
        blocks, so the caller can fall back to a cold re-prefill."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not active")
        chain = list(self._owned[slot])
        keys: List[int] = []
        try:
            for start in range(0, len(chain), self.transfer_blocks):
                ids = chain[start:start + self.transfer_blocks]
                padded = np.zeros((self.transfer_blocks,), np.int32)
                padded[:len(ids)] = ids
                host = jax.device_get(self._run_gather(padded))
                for i in range(len(ids)):
                    payload = tuple(np.asarray(a[:, i]) for a in host)
                    key = pool.put(payload)
                    if key is None:
                        raise CacheExhausted(
                            f"migration host budget refused block "
                            f"{len(keys) + 1}/{len(chain)}")
                    keys.append(key)
        except Exception:
            for k in keys:
                pool.discard(k)
            raise
        return {"keys": keys, "length": int(self.lengths[slot]),
                "n_blocks": len(chain)}

    def land_parked(self, rid, keys: List[int], pool: HostBlockPool,
                    length: int) -> int:
        """Destination half: CRC-verified fetch of each migrated block
        and a free-list-ONLY scatter into this pool (landings never
        evict — the decode side's cache must not be cannibalized by an
        incoming migration; a dry free list raises
        :class:`CacheExhausted` and the request re-prefills cold). The
        landed chain parks under ``rid`` until :meth:`adopt_parked`. A
        mid-landing failure (corruption, dry list) returns every landed
        block to the free list and re-raises — the host entries stay
        the caller's to discard."""
        if rid in self._parked:
            raise ValueError(f"request {rid!r} already has a parked chain")
        landed: List[int] = []
        try:
            for key in keys:
                payload = pool.get(key)      # CRC32 -> HostCorruption
                if not self._free:
                    raise CacheExhausted(
                        f"migration landing needs a free block "
                        f"({len(landed)}/{len(keys)} landed)")
                bid = self._free.pop()
                self._run_scatter(payload, bid)
                landed.append(bid)
        except Exception:
            self._free.extend(reversed(landed))
            raise
        self._parked[rid] = (landed, int(length))
        self._mark()
        return len(landed)

    def has_parked(self, rid) -> bool:
        """True when a migrated chain is parked for ``rid``."""
        return rid in self._parked

    def adopt_parked(self, slot: int, rid) -> int:
        """Install the parked chain as ``slot``'s owned blocks — the
        migration analog of :meth:`allocate`'s prefix hit: the slot
        starts with ``length`` tokens already resident (refcount 1,
        private — migrated blocks are never shared) and prefill resumes
        at that offset, covering only the already-emitted tail tokens.
        Returns the resident prefix length."""
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.num_slots})")
        if self.active[slot] or self._owned[slot]:
            raise ValueError(f"slot {slot} is already allocated; free() "
                             f"it before adopting a parked chain")
        bids, length = self._parked.pop(rid)
        for bid in bids:
            self._refcount[bid] = 1
        self._owned[slot] = list(bids)
        self.tables[slot, :] = 0
        self.tables[slot, :len(bids)] = bids
        self.lengths[slot] = length
        self.active[slot] = True
        self.parked_adopted += 1
        self._mark()
        return length

    def drop_parked(self, rid) -> int:
        """Return a parked chain's blocks to the free list (idempotent
        — fallback and drain paths may both try). Returns blocks freed."""
        entry = self._parked.pop(rid, None)
        if entry is None:
            return 0
        bids, _ = entry
        self._free.extend(reversed(bids))
        self.parked_aborts += 1
        return len(bids)

    def abort_parked(self) -> int:
        """Drop every parked chain — the drain/retire contract, same
        discipline as :meth:`abort_transfers`: a replica settles its
        migration landings BEFORE ``pending_snapshot(release=True)``
        hands its requests away (each dropped chain's request re-
        prefills cold on a survivor). Returns chains dropped."""
        rids = list(self._parked)
        for rid in rids:
            self.drop_parked(rid)
        return len(rids)

    def drain_restore_ms(self) -> List[float]:
        """Hand the per-restore wall-clock samples (ms) to the caller
        (the serving engine feeds its ``kv_host_restore_ms`` histogram
        on the sampled cadence) and reset the buffer."""
        out = self._restore_ms
        self._restore_ms = []
        return out

    def _run_gather(self, ids: np.ndarray):
        """Dispatch the (quant-aware) fixed-width spill gather."""
        if self.quantized:
            fn = self.gather_fn if self.gather_fn is not None \
                else _default_gather_q
            return fn(self.k, self.v, self.k_scale, self.v_scale, ids)
        fn = self.gather_fn if self.gather_fn is not None \
            else _default_gather
        return fn(self.k, self.v, ids)

    def _run_scatter(self, payload: tuple, bid: int) -> None:
        """Dispatch the (quant-aware) restore scatter, rebinding pools
        from its donated outputs."""
        dev_arrays = tuple(jax.device_put(a) for a in payload)
        if self.quantized:
            fn = self.scatter_fn if self.scatter_fn is not None \
                else _default_scatter_q
            (self.k, self.v, self.k_scale, self.v_scale) = fn(
                self.k, self.v, self.k_scale, self.v_scale,
                *dev_arrays, np.int32(bid))
        else:
            fn = self.scatter_fn if self.scatter_fn is not None \
                else _default_scatter
            self.k, self.v = fn(self.k, self.v, *dev_arrays,
                                np.int32(bid))

    # -- internals -----------------------------------------------------
    def _run_cow(self, src, dst) -> None:
        """Dispatch the (quant-aware) COW copy program, rebinding pools
        (and scale pools when quantized) from its donated outputs."""
        if self.quantized:
            fn = self.copy_fn if self.copy_fn is not None \
                else _default_cow_q
            (self.k, self.v, self.k_scale, self.v_scale) = fn(
                self.k, self.v, self.k_scale, self.v_scale, src, dst)
        else:
            fn = self.copy_fn if self.copy_fn is not None else _default_cow
            self.k, self.v = fn(self.k, self.v, src, dst)

    def _cow(self, src: int, dst: int) -> None:
        self._run_cow(np.int32(src), np.int32(dst))
        self.cow_copies += 1
        if self.tracer is not None:
            self.tracer.event("cow", src=src, dst=dst)

    def _pop_free(self) -> int:
        """Next usable block: the free list, else the LRU refcount-zero
        cached block (unregistered from the index). Raises
        :class:`CacheExhausted` when neither can supply one."""
        if self._free:
            return self._free.pop()
        if self.index is not None:
            bid = self.index.pop_evictable(self._reclaimable)
            if bid is not None:
                self.cache_block_evictions += 1
                if self.tracer is not None:
                    self.tracer.event("cache_evict_block", block=bid)
                return bid
        raise CacheExhausted("free list empty and no reclaimable "
                             "cached blocks")

    def _release(self, bid: int) -> None:
        """Drop one reference with hardening: a foreign or already-free
        block id is a bookkeeping bug and raises instead of silently
        corrupting the pool (load-bearing once blocks are shared)."""
        if not 0 < bid < self.num_blocks:
            raise ValueError(f"foreign block id {bid} (pool has blocks "
                             f"1..{self.num_blocks - 1}; 0 is the trash "
                             f"block)")
        if self._refcount[bid] <= 0:
            raise ValueError(f"double free of block {bid}")
        self._refcount[bid] -= 1
        # a mid-transfer block is never returned here even when it drops
        # unindexed — the harvest's abort path is its single freer (two
        # freers would race into a double free-list entry)
        if self._refcount[bid] == 0 and bid not in self._in_transfer \
                and not (self.index is not None and bid in self.index):
            self._free.append(bid)

    def _mark(self):
        self.peak_used_blocks = max(self.peak_used_blocks, self.used_blocks)

    def _fire(self, site: str):
        if self.faults is None:
            return None
        return self.faults.fire(site)

    def _maybe_inject(self, site: str, slot: int) -> None:
        f = self._fire(site)
        if f is not None and f.kind == "cache_exhausted":
            raise CacheExhausted(
                f"injected cache exhaustion at {site} (slot {slot}, "
                f"{self.free_blocks} blocks actually free)")
