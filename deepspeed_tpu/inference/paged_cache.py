"""Block-paged KV-cache: fixed-size blocks + per-request block tables.

The static engine preallocates a ``[L, B, S_max, Hkv, Dh]`` cache, so one
long request holds ``S_max`` slots for every row and the whole batch's
memory is ``B * S_max`` tokens regardless of what is actually in flight
(the reproduction of the reference's global Context workspace, ref:
ops/transformer/inference/transformer_inference.py:113 softmax_context).
This module is the PagedAttention answer (Kwon et al., SOSP '23): K/V
live in a pool of fixed-size blocks ``[L, N_blocks, block, Hkv, Dh]``,
each serving slot owns an ordered list of block ids (its block table),
and a free-list allocator hands blocks out on demand — cache memory
scales with tokens in flight, fragmentation is bounded by one partial
block per request, and a finished request's blocks return to the pool
immediately.

Host-side bookkeeping (tables, lengths, the free list) is plain numpy —
it changes every scheduler iteration and must never trigger a recompile;
the device arrays (``k``/``v`` pools) thread functionally through the
engine's donated ``prefill_into_slot`` / ``decode_slots`` programs.

Block id 0 is RESERVED as the trash block: the slot programs route
writes for masked-out lanes (chunk padding, inactive slots) there, so
the compiled scatter needs no branch.
"""

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models import gpt as gpt_lib
from deepspeed_tpu.models.gpt import GPTConfig


class CacheExhausted(Exception):
    """The free list cannot cover an allocation — the scheduler's cue to
    evict-and-requeue instead of OOMing the device."""


class PagedKVCache:
    """Pool + allocator + per-slot block tables.

    num_blocks is the HBM-budget watermark made concrete: either passed
    directly or derived from ``hbm_budget_bytes`` via the per-token cache
    cost (models.gpt.kv_bytes_per_token). ``watermark`` free blocks are
    held back at admission time so every active slot can always grow into
    its next decode block without immediate eviction.
    """

    def __init__(self, cfg: GPTConfig, *, num_slots: int,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 dtype=jnp.bfloat16, max_seq_len: Optional[int] = None,
                 watermark: Optional[int] = None, faults=None):
        self.cfg = cfg
        # fault-injection hook (utils/faults.FaultInjector): the
        # ``cache.allocate`` / ``cache.ensure`` sites can fire a
        # synthetic CacheExhausted so the scheduler's eviction path runs
        # under test without actually shrinking the pool
        self.faults = faults
        self.block_size = int(block_size)
        self.num_slots = int(num_slots)
        self.blocks_per_slot, self.tokens_per_slot = gpt_lib.decode_geometry(
            cfg, self.block_size, max_seq_len)
        self.dtype = jnp.dtype(dtype)
        self.bytes_per_token = gpt_lib.kv_bytes_per_token(cfg, dtype)
        if num_blocks is None:
            if not hbm_budget_bytes:
                # default pool: the static reservation's worth of blocks
                # (num_slots full sequences) — usage accounting then shows
                # how far actual tokens-in-flight undercut it
                hbm_budget_bytes = (self.num_slots * self.tokens_per_slot
                                    * self.bytes_per_token)
            per_block = self.bytes_per_token * self.block_size
            num_blocks = int(hbm_budget_bytes // per_block)
        # +1: block 0 is the reserved trash block, never allocated
        self.num_blocks = int(num_blocks) + 1
        if self.num_blocks < 2:
            raise ValueError(
                f"HBM budget covers {self.num_blocks - 1} blocks; the "
                f"pool needs at least 1 allocatable block")
        L, Hkv, Dh = cfg.n_layers, cfg.kv_heads, cfg.head_dim
        self.k = jnp.zeros((L, self.num_blocks, self.block_size, Hkv, Dh),
                           dtype)
        self.v = jnp.zeros_like(self.k)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._owned: List[List[int]] = [[] for _ in range(num_slots)]
        self.tables = np.zeros((num_slots, self.blocks_per_slot), np.int32)
        self.lengths = np.zeros((num_slots,), np.int32)
        self.active = np.zeros((num_slots,), bool)
        self.watermark = num_slots if watermark is None else int(watermark)
        self.peak_used_blocks = 0
        self.peak_tokens_in_flight = 0

    # -- accounting ----------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    @property
    def tokens_in_flight(self) -> int:
        return int(self.lengths.sum())

    def used_block_bytes(self) -> int:
        """Bytes actually held by allocated blocks — what the bench's
        'paged peak HBM' row reports (scales with tokens in flight,
        block-quantized)."""
        return self.used_blocks * self.block_size * self.bytes_per_token

    def static_equivalent_bytes(self, batch: int,
                                max_seq_len: Optional[int] = None) -> int:
        """What the static [B, S_max] cache would reserve for the same
        traffic — the comparison row."""
        s = max_seq_len or self.cfg.max_seq_len
        return batch * s * self.bytes_per_token

    def blocks_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def at_capacity(self, slot: int) -> bool:
        """True when the slot's cache has consumed its whole block
        budget: the next decode write would CLAMP into the last live
        block (inference/engine.py masks it to the trash block), so the
        scheduler must finish the request before the kernel runs."""
        return int(self.lengths[slot]) >= self.tokens_per_slot

    def can_admit(self, n_tokens: int) -> bool:
        """Admission-control check: prompt blocks available AND the
        watermark reserve stays intact so live slots can keep growing."""
        return self.free_blocks >= self.blocks_for(n_tokens) + self.watermark

    # -- allocator -----------------------------------------------------
    def allocate(self, slot: int, n_tokens: int) -> None:
        """Reserve blocks covering ``n_tokens`` for a fresh slot."""
        assert not self.active[slot] and not self._owned[slot], slot
        self._maybe_inject("cache.allocate", slot)
        need = self.blocks_for(n_tokens)
        if need > self.blocks_per_slot:
            raise ValueError(
                f"{n_tokens} tokens need {need} blocks > per-slot table "
                f"width {self.blocks_per_slot}")
        if need > self.free_blocks:
            raise CacheExhausted(
                f"need {need} blocks, {self.free_blocks} free")
        ids = [self._free.pop() for _ in range(need)]
        self._owned[slot] = ids
        self.tables[slot, :] = 0
        self.tables[slot, :need] = ids
        self.lengths[slot] = 0
        self.active[slot] = True
        self._mark()

    def ensure_capacity(self, slot: int, n_tokens: int) -> None:
        """Grow the slot's table until it covers ``n_tokens`` (append)."""
        assert self.active[slot], slot
        self._maybe_inject("cache.ensure", slot)
        need = self.blocks_for(n_tokens)
        if need > self.blocks_per_slot:
            raise ValueError(
                f"{n_tokens} tokens exceed the per-slot capacity "
                f"{self.tokens_per_slot}")
        while len(self._owned[slot]) < need:
            if not self._free:
                raise CacheExhausted(
                    f"slot {slot} needs a block for token "
                    f"{n_tokens}; free list empty")
            bid = self._free.pop()
            self.tables[slot, len(self._owned[slot])] = bid
            self._owned[slot].append(bid)
        self._mark()

    def advance(self, slot: int, n_tokens: int) -> None:
        """Record ``n_tokens`` newly written to the slot's cache."""
        new_len = int(self.lengths[slot]) + int(n_tokens)
        assert new_len <= len(self._owned[slot]) * self.block_size, \
            (slot, new_len, len(self._owned[slot]))
        self.lengths[slot] = new_len
        self.peak_tokens_in_flight = max(self.peak_tokens_in_flight,
                                         self.tokens_in_flight)

    def free(self, slot: int) -> None:
        """Return every block the slot owns to the free list."""
        self._free.extend(reversed(self._owned[slot]))
        self._owned[slot] = []
        self.tables[slot, :] = 0
        self.lengths[slot] = 0
        self.active[slot] = False

    def _mark(self):
        self.peak_used_blocks = max(self.peak_used_blocks, self.used_blocks)

    def _maybe_inject(self, site: str, slot: int) -> None:
        if self.faults is None:
            return
        f = self.faults.fire(site)
        if f is not None and f.kind == "cache_exhausted":
            raise CacheExhausted(
                f"injected cache exhaustion at {site} (slot {slot}, "
                f"{self.free_blocks} blocks actually free)")
