"""Host-DRAM second tier for the paged KV-cache — spilled block storage.

HBM pressure used to give the prefix cache exactly one answer: evict
the LRU refcount-zero block and lose its K/V (re-prefill on the next
hit). This module adds the middle rung of the degradation ladder
(docs/KV_TIERING.md): a :class:`HostBlockPool` keeps evict-candidate
blocks in host DRAM — the reproduction of the reference's
ZeRO-Infinity ``swap_tensor`` host-offload capability (PAPER.md layer
5) re-aimed at inference serving — so a later radix hit on a spilled
chain RESTORES the bytes instead of recomputing them.

The pool is deliberately dumb: a dict of contiguous numpy copies under
a byte budget. All tiering POLICY (what spills, when, what a failed
restore degrades to) lives in :mod:`.paged_cache`; all transfer
mechanics (the fixed-width gather/scatter programs, double buffering)
live there too. What this module owns is DURABILITY: every stored
array carries a CRC32 integrity tag computed at put time and verified
at get time, so a corrupted host buffer (bit rot, a stray write, an
injected ``cache.host_corrupt`` fault) surfaces as
:class:`HostCorruption` — the cache discards the poisoned chain and
re-prefills, and NEVER serves wrong K/V as if it were cached truth.

Budget exhaustion is not an error: :meth:`HostBlockPool.put` returns
None and the caller leaves the block device-resident, where plain LRU
eviction — exactly the tier-off behavior — remains the backstop.

The pool has a second consumer beyond spill/restore: the router's
replica-to-replica KV migration (``router._migrate`` +
``paged_cache.migrate_gather``/``land_parked``) stages a finished
prefill's blocks here on the way from a prefill replica's pool to a
decode replica's — the same CRC32-at-put / verify-at-get contract
guarantees a corrupted hand-off degrades to a cold re-prefill instead
of wrong K/V (docs/ROBUSTNESS.md migration ladder).
"""

import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.env import resolve_flag


class HostCorruption(Exception):
    """A host-tier block failed its CRC32 integrity check at restore
    time — the cache's cue to discard the chain and degrade to a
    cold-miss re-prefill (wrong K/V must never reach attention)."""


def resolve_host_tier(flag: Optional[bool] = None) -> bool:
    """Resolve the host-DRAM KV tier switch.

    Explicit argument wins, else the ``DS_KV_HOST_TIER`` env var
    (``on``/``off``, also ``1``/``0``/``true``/``false``), else OFF —
    the single-tier (device-only) cache is the behavioral
    bit-reference."""
    return resolve_flag("DS_KV_HOST_TIER", flag)


def resolve_host_budget(budget_bytes: Optional[int] = None) -> int:
    """Host-tier byte budget: explicit argument wins, else
    ``DS_KV_HOST_BUDGET_MB`` (default 256 MiB — host DRAM is cheap but
    not free, and an unbounded pool would hide leaks)."""
    if budget_bytes is not None:
        return int(budget_bytes)
    return int(resolve_flag("DS_KV_HOST_BUDGET_MB") * (1 << 20))


class HostBlockPool:
    """CRC-tagged host-DRAM storage for spilled KV blocks.

    One entry holds one pool block's payload as a tuple of contiguous
    numpy arrays — ``(k_blk, v_blk)`` of shape ``[L, bs, Hkv, Dh]``,
    plus the ``(k_scale, v_scale)`` fp32 sidecars ``[L, Hkv]`` when the
    device pool is int8 (the tier composes with ``DS_KV_QUANT=int8`` by
    spilling quantized bytes AND their scales, so a restored block
    dequantizes to exactly what was spilled). Keys are monotonically
    increasing ints minted by :meth:`put`; a key is never reused, so a
    stale reference can only miss, not alias."""

    def __init__(self, budget_bytes: Optional[int] = None):
        self.budget_bytes = resolve_host_budget(budget_bytes)
        # key -> (arrays, crcs, nbytes)
        self._entries: Dict[int, Tuple[tuple, tuple, int]] = {}
        self._next_key = 0
        self.bytes_used = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: int) -> bool:
        return int(key) in self._entries

    def put(self, arrays: tuple) -> Optional[int]:
        """Store one block's arrays; returns its key, or None when the
        byte budget cannot cover it (the caller's cue to fall back to
        plain device-side eviction — budget exhaustion is a policy
        outcome, not an error)."""
        # ALWAYS copy: ascontiguousarray aliases an already-contiguous
        # input, and a caller-mutated alias would fail its own CRC
        copies = tuple(np.array(a, order="C", copy=True) for a in arrays)
        nbytes = sum(int(c.nbytes) for c in copies)
        if self.bytes_used + nbytes > self.budget_bytes:
            return None
        crcs = tuple(zlib.crc32(c.tobytes()) for c in copies)
        key = self._next_key
        self._next_key += 1
        self._entries[key] = (copies, crcs, nbytes)
        self.bytes_used += nbytes
        return key

    def get(self, key: int) -> tuple:
        """Fetch a block's arrays, verifying every CRC32 tag. Raises
        :class:`HostCorruption` on a mismatch (the entry is NOT
        discarded here — the cache owns the chain-level cleanup) and
        KeyError on a key that was never stored or already discarded."""
        arrays, crcs, _ = self._entries[int(key)]
        for i, (a, crc) in enumerate(zip(arrays, crcs)):
            if zlib.crc32(np.ascontiguousarray(a).tobytes()) != crc:
                raise HostCorruption(
                    f"host block {key} array {i} failed its CRC32 check "
                    f"(stored 0x{crc:08x})")
        return arrays

    def discard(self, key: int) -> None:
        """Drop an entry (idempotent — restore and subtree-removal
        paths may both try to clean the same key)."""
        entry = self._entries.pop(int(key), None)
        if entry is not None:
            self.bytes_used -= entry[2]

    def corrupt(self, key: int) -> None:
        """Flip one byte of a stored block IN PLACE — the chaos/test
        helper behind the real (non-injected) CRC-mismatch path."""
        arrays, _, _ = self._entries[int(key)]
        flat = arrays[0].reshape(-1).view(np.uint8)
        flat[0] ^= 0xFF
