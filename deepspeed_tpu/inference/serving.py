"""Continuous-batching serving scheduler over the paged KV-cache.

The static engine runs ONE fixed batch to completion: every row pays for
the slowest request, and a new arrival waits for the whole batch to
drain. This scheduler implements iteration-level (continuous) batching
as in Orca (Yu et al., OSDI '22): a fixed set of decode SLOTS, and on
every iteration

1. **expiry** — requests past their ``deadline`` retire with
   ``state="timeout"`` (partial tokens kept) instead of squatting a
   slot or queue position;
2. **admission** — queued requests claim free slots if the paged cache
   can cover their prompt while keeping the watermark reserve;
3. **prefill** — newly admitted requests prefill their prompt into
   their slot in fixed-width CHUNKS (one chunk per iteration per slot),
   so a long prompt never stalls the running decode batch for more than
   one chunk's latency;
4. **decode** — all decoding slots advance one token through the single
   compiled ``decode_slots`` program, each at its own position.

On cache exhaustion mid-decode the scheduler EVICTS the most recently
admitted request instead of OOMing: its blocks return to the pool and
the request requeues (front of the queue) with prompt+generated as its
new prompt — recompute-on-resume reproduces the exact pre-eviction
state, so greedy outputs are untouched (vLLM's recompute preemption).
``max_evictions`` caps how often one request may be preempted: a
request at the cap is PINNED (never chosen as a victim again), so an
eviction storm cannot livelock requeued work — the oldest pinned
request always runs to completion.

Graceful degradation (the chaos contract, tests/test_chaos.py):

- **bounded queue + load shedding** — with ``max_queue`` set, a submit
  into a full queue retires the NEWEST request with ``state="shed"``
  (reject-newest keeps already-accepted work's latency predictable);
  ``stats["backpressure"]`` exposes queue fullness in [0, 1] for
  upstream admission control;
- **retry with backoff** — transient device errors
  (:class:`~deepspeed_tpu.utils.faults.TransientDeviceError`) around the
  two slot programs retry up to ``max_retries`` times with exponential
  backoff and deterministic (seeded) jitter; faults fire BEFORE
  dispatch, so the donated pools are still valid on every retry;
- **step watchdog** — with ``step_time_budget_s`` set, ``watchdog_grace``
  consecutive over-budget decode dispatches raise a structured
  :class:`DegradedError` carrying every finished result and a snapshot
  of in-flight work (nothing is thrown away), instead of hanging;
- **fault injection** — the engine consults the ambient
  :mod:`deepspeed_tpu.utils.faults` injector (or one passed as
  ``faults=``) at the ``serving.decode`` / ``serving.prefill`` sites;
  the paged cache exposes ``cache.allocate`` / ``cache.ensure``.

Shared-prefix caching (``prefix_cache=True`` / ``DS_PREFIX_CACHE=on``,
docs/PREFIX_CACHE.md): admission asks the cache to match the request's
longest cached prefix — shared blocks map into the slot read-only and
PREFILL STARTS AT THE MATCHED BOUNDARY (``_progress`` begins at the
matched token count, so a fully-cached system prompt costs zero prefill
chunks beyond its uncached tail). When the prompt finishes prefilling,
its full blocks are published to the index for the next request.
``stats["prefix_hits"]`` / ``stats["prefix_tokens_saved"]`` count the
win; ``_finish``/``_preempt`` release REFERENCES, not blocks — a block
another slot still maps, or one the index keeps as reusable cache,
stays resident. Warm-vs-cold token parity is exact: the prefill program
is chunk-boundary invariant (fixed-width chunks, gather over the full
table, causal mask), so starting at a nonzero offset over shared blocks
reproduces the cold logits bit-for-bit (tests/test_prefix_cache.py).

Speculative decoding (``spec_decode=True`` / ``DS_SPEC_DECODE=on``,
docs/SPECULATIVE.md): each decode iteration a DRAFTER (prompt-lookup
n-grams by default — no second model) proposes ``spec_k`` tokens per
live slot; one compiled verify program (``engine.verify_slots``) scores
all ``spec_k + 1`` chunk positions per slot against the paged cache,
and each slot independently accepts its longest draft prefix agreeing
with the target's own greedy argmax, emitting ``accepted + 1`` tokens
(the ``+1`` is the target's correction — the classic draft-verify
free token). The first reject rolls the slot's cache back
(``cache.rollback``): lengths shrink past the rejected suffix and tail
blocks only that suffix touched return to the pool; stale K/V inside
kept blocks is overwritten by the next chunk before any query attends
it. A temperature=0 slot accepts its longest prefix agreeing with the
target's own greedy argmax, which makes its spec-on output BIT-
IDENTICAL to spec-off greedy serving (tests/test_spec_serving.py pins
this across eviction/requeue and prefix-cache hits); a sampled slot
runs per-position rejection-sampling verify (Leviathan/Chen), which is
DISTRIBUTION-lossless against plain sampled decode (docs/SAMPLING.md).
Speculation only changes how many steps the tokens take. An injected
draft/verify fault degrades that step to the plain one-token path
(``stats["spec_fallbacks"]``) — chaos turns speculation off, never
output wrong.

The steady state is two compiled programs (prefill chunk, slot decode —
with speculation on, the ``spec_k + 1``-position verify program REPLACES
slot decode) regardless of arrival pattern; all scheduling state is
host numpy. None
of the robustness paths (deadlines, shedding, backoff, expiry) touch
device shapes, so the compile-count contract is unchanged — pinned by
``test_serving_compile_count_contract`` and its chaos twin. The prefix
cache adds ONE more program (the copy-on-write block copy), compiled
eagerly at construction via ``cache.warm_cow()`` so steady state stays
recompile-free with the cache on.

Telemetry (``telemetry=True`` / ``DS_TELEMETRY=on``,
docs/OBSERVABILITY.md): every lifecycle transition (enqueue, admit with
prefix-hit tags, prefill chunks, evict/requeue, finish/timeout/shed),
injected faults and a sampled per-phase step-time breakdown stream into
a :class:`~deepspeed_tpu.telemetry.Telemetry` bundle — ring-buffered
host-side records plus a metrics registry with Prometheus and
Chrome-trace/Perfetto exporters. ``stats`` is now a READ-ONLY mapping
view over registry counters (same keys, same values as the old dict);
the scheduler deadline clock is a private field, so mutating a metric
can never move a deadline. Default off: the off path swaps in no-op
twins and is token-bit-identical to on (tests/test_telemetry.py).

Per-request sampling (docs/SAMPLING.md): every ``ServeRequest`` may
carry its own temperature/top_k/top_p/seed/repetition_penalty plus
``stop`` sequences, ``logprobs``, and ``n`` candidates. The knobs ride
as slot-indexed DEVICE ARRAYS into the fused sampler that is traced
inside the prefill/decode slot programs (inference/sampling.py) —
data, not jit statics — so arbitrarily mixed greedy/sampled batches
keep the two-program compile contract, and greedy slots in a mixed
batch stay bit-identical to an all-greedy run. The per-token key is
``fold_in(PRNGKey(seed), tokens_generated)``, a pure function of
request state, so eviction/requeue and router drain resume a sampled
stream bit-exactly (spec-decode sampled verify is the one documented
exception: distribution-lossless, deterministic per run history, not
bit-stable across a mid-stream resume).

Greedy parity contract (tested): for any arrival pattern, every
temperature=0 request's output is token-for-token identical to a solo
``InferenceEngine.generate`` run of its prompt.
"""

import json
import math
import time
from collections import deque
from collections.abc import Mapping
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference import sampling
from deepspeed_tpu.inference.adapters import (AdapterLoadError, AdapterPool,
                                              resolve_lora_serve)
from deepspeed_tpu.inference.host_tier import resolve_host_tier
from deepspeed_tpu.inference.paged_cache import (CacheExhausted,
                                                 PagedKVCache,
                                                 resolve_prefix_cache)
from deepspeed_tpu.inference.spec_decode import (make_draft,
                                                 resolve_spec_decode,
                                                 resolve_spec_k)
from deepspeed_tpu.ops.quantizer import resolve_kv_quant
from deepspeed_tpu.telemetry import (NOOP, MetricsRegistry, NoopTelemetry,
                                     RATE_BUCKETS, TEMP_BUCKETS, Telemetry,
                                     resolve_telemetry)
from deepspeed_tpu.telemetry.costs import (CostAccountant, NOOP_COSTS,
                                           ProgramCostRegistry)
from deepspeed_tpu.telemetry.costs import new_footprint as _new_footprint
from deepspeed_tpu.telemetry.flight import FlightRecorder, NOOP_FLIGHT
from deepspeed_tpu.utils import faults as faults_lib
from deepspeed_tpu.utils.env import (flag_names, resolve_decode_horizon,
                                     resolve_flag)
from deepspeed_tpu.utils.faults import TransientDeviceError
from deepspeed_tpu.utils.logging import logger

TERMINAL_STATES = ("done", "timeout", "shed", "error")

# in-program stop-sequence modeling caps for the fused multi-step decode
# (docs/MULTISTEP.md): a stop longer than HORIZON_STOP_WIDTH tokens, or
# past the first HORIZON_MAX_STOPS sequences, is left unmodeled — its
# lane free-runs inside the horizon and the authoritative host-side
# check truncates the stream at the true hit, so tokens stay exact;
# only the early-freeze optimization is lost for that request
HORIZON_STOP_WIDTH = 8
HORIZON_MAX_STOPS = 4

# the stats contract: same keys (and order) as the pre-telemetry dict,
# now backed by registry metrics ("c" counter / "g" gauge) and exposed
# through the read-only _StatsView
_STAT_FIELDS = (
    ("steps", "c", "scheduler iterations"),
    ("occupancy_sum", "c", "sum of per-step decode occupancy"),
    ("peak_occupancy", "g", "max decode occupancy seen"),
    ("evictions", "c", "preemptions (recompute-on-resume requeues)"),
    ("admitted", "c", "requests admitted to a slot"),
    ("completed", "c", "requests finished with state=done"),
    ("prefill_chunks", "c", "prefill chunk dispatches"),
    ("decode_steps", "c", "batched decode dispatches"),
    ("timeouts", "c", "requests retired at their deadline"),
    ("shed", "c", "requests rejected by the bounded queue"),
    ("retries", "c", "transient-device-error retries"),
    ("evict_capped", "c", "evictions refused by the storm guard"),
    ("watchdog_trips", "c", "over-budget decode dispatches"),
    ("backpressure", "g", "queue fullness in [0, 1]"),
    ("prefix_hits", "c", "admissions that matched a cached prefix"),
    ("prefix_tokens_saved", "c", "prompt tokens served from shared blocks"),
    ("spec_steps", "c", "speculative verify dispatches"),
    ("spec_slot_steps", "c", "per-slot verify participations"),
    ("spec_proposed", "c", "draft tokens offered for verification"),
    ("spec_accepted", "c", "draft tokens accepted by the target"),
    ("spec_emitted", "c", "tokens emitted by speculative steps"),
    ("spec_fallbacks", "c", "spec steps degraded to plain decode"),
    ("horizon_fallbacks", "c", "horizon dispatches degraded to "
                               "single-step decode"),
    ("sampled_tokens", "c", "tokens emitted by sampled (temperature>0) lanes"),
    ("stop_hits", "c", "requests finished by a stop sequence"),
    ("spec_k_capped", "c", "verify participations depth-capped by low "
                           "acceptance"),
    # multi-tenant LoRA serving (inference/adapters.py): pool-residency
    # traffic counters, incremented via the pool's stat hooks so there
    # is one source of truth
    ("adapter_hits", "c", "adapter acquisitions served pool-resident"),
    ("adapter_loads", "c", "adapter loads into the device pool"),
    ("adapter_evictions", "c", "refcount-zero adapters evicted (LRU)"),
    ("adapter_load_errors", "c", "requests retired state=error by a "
                                 "failed adapter load"),
    # host-tier mirrors (gauges set from the cache's own counters each
    # step, so the serving stats contract exposes them without a second
    # source of truth)
    ("host_blocks", "g", "KV blocks resident on the host-DRAM tier"),
    ("host_bytes", "g", "host-DRAM bytes held by spilled KV blocks"),
    ("host_spills", "g", "blocks spilled device->host (total)"),
    ("host_restores", "g", "blocks restored host->device (total)"),
    ("host_restore_failures", "g", "restores degraded to re-prefill "
                                   "(faults, corruption, dry free list)"),
)


class _StatsView(Mapping):
    """Read-only mapping over the registry-backed serving counters:
    the old ``stats`` dict's keys and values, minus mutability — writes
    go through the registry (``srv.metrics``), never through the view,
    so external code cannot skew the scheduler's bookkeeping."""

    def __init__(self, metrics: Dict[str, Any]):
        self._metrics = metrics

    def __getitem__(self, key):
        return self._metrics[key].value

    def __iter__(self):
        return iter(self._metrics)

    def __len__(self):
        return len(self._metrics)

    def __repr__(self):
        return repr(dict(self))


# ServeRequest fields dslint DS018 must NOT require to round-trip
# through snapshot_entry/from_snapshot — each is either derived on
# resubmit or meaningless on a fresh replica. Adding a field to
# ServeRequest without serializing it OR listing it here (with a
# reason) is a lint error: that is exactly how adapter_id, seed chains
# and cost footprints were silently lost before they were retrofitted.
SNAPSHOT_EPHEMERAL = frozenset({
    "n",                # expansion happens at submit; candidates snapshot
                        # individually, so a resumed request is always n=1
    "state",            # serialized for postmortems, but a resumed request
                        # must re-enter the scheduler as "queued"
    "token_times",      # scheduler-clock latency stamps; a fresh replica's
                        # clock makes them incomparable
    "submitted_at",     # ditto — resubmission re-stamps it
    "first_token_at",   # ditto
    "finished_at",      # pending requests by definition never finished
    "_admit_seq",       # admission order on the dead replica; the resuming
                        # scheduler assigns its own
    "_work",            # rebuilt from prompt + out at re-prefill
})


@dataclass
class ServeRequest:
    """One generation request. ``out`` accumulates generated token ids;
    ``token_times`` the scheduler-clock stamp of each emitted token (the
    bench derives per-token latency percentiles from these).
    ``deadline`` is an absolute scheduler-clock instant (same clock as
    ``submit``/``step``'s ``now``): once reached the request retires
    with ``state="timeout"``, keeping whatever it generated.

    Per-request sampling knobs (docs/SAMPLING.md): ``temperature`` /
    ``top_k`` / ``top_p`` / ``seed`` / ``repetition_penalty`` default to
    None = "use the engine-wide ctor default" — an explicit value wins.
    ``stop`` is a list of token-id sequences: generation finishes as
    soon as ``out`` ends with any of them (the matched stop tokens are
    KEPT in ``out``, so the resume/drain contract sees the true emitted
    stream). ``logprobs=True`` records each emitted token's
    log-probability under its sampling distribution in
    ``out_logprobs``. ``n>1`` expands at submit into ``n`` independent
    candidates (rids ``rid#1``..``rid#n-1`` plus the original) whose
    seeds derive from this request's seed via
    :func:`sampling.candidate_seed`.

    ``priority`` is an advisory class tag (``"interactive"`` /
    ``"batch"``; None = untagged) the engine itself ignores — the
    router's SLO controller sheds ``batch`` traffic first when
    admission tightens (docs/OBSERVABILITY.md)."""
    rid: Any
    prompt: np.ndarray
    max_new_tokens: int = 32
    eos_id: Optional[int] = None
    deadline: Optional[float] = None
    priority: Optional[str] = None
    # multi-tenant LoRA serving: which registered adapter decodes this
    # request (None = the base model; requires lora_serve on the
    # engine). An unloadable adapter retires the request with
    # state="error" — never wrong tokens (docs/ADAPTERS.md)
    adapter_id: Optional[str] = None
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: Optional[int] = None
    repetition_penalty: Optional[float] = None
    stop: Optional[List[Sequence[int]]] = None
    logprobs: bool = False
    n: int = 1
    out: List[int] = field(default_factory=list)
    out_logprobs: List[float] = field(default_factory=list)
    state: str = "queued"      # queued | prefill | decode | handoff |
    #                            done | timeout | shed | error — handoff
    #                            = finished prefill parked on a
    #                            prefill-only replica awaiting migration
    token_times: List[float] = field(default_factory=list)
    submitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    evictions: int = 0
    # per-request cost footprint (telemetry/costs.py): FLOPs/HBM bytes/
    # dispatch counts per class + KV block-seconds. Plain data; rides
    # pending_snapshot() across drains so attribution survives a
    # replica death. Populated only while cost accounting is on.
    cost: Dict = field(default_factory=_new_footprint)
    _admit_seq: int = -1             # eviction picks the youngest
    _work: Optional[np.ndarray] = None   # prompt (+generated, on resume)

    @property
    def tokens(self) -> np.ndarray:
        """prompt + generated, the generate()-shaped result row."""
        return np.concatenate(
            [self.prompt, np.asarray(self.out, np.int32)])

    @classmethod
    def from_snapshot(cls, entry: Dict) -> "ServeRequest":
        """Rebuild a resumable request from a ``pending_snapshot()``
        entry — the cold-resume half of the drain contract: submitting
        the rebuilt request to a FRESH engine re-prefills prompt +
        already-emitted tokens, and decode continues from the exact
        pre-failure position. Greedy output is token-identical to an
        undisturbed run; a sampled request resumes its key chain exactly
        (the per-token key is a pure function of (seed, tokens emitted
        so far), so carrying seed + out IS the chain state —
        docs/SAMPLING.md). ``n`` is pinned to 1: candidate expansion
        already happened at the original submit."""
        return cls(
            rid=entry["rid"],
            prompt=np.asarray(entry["prompt"], np.int32),
            max_new_tokens=int(entry["max_new_tokens"]),
            eos_id=entry.get("eos_id"),
            deadline=entry.get("deadline"),
            priority=entry.get("priority"),
            adapter_id=entry.get("adapter_id"),
            temperature=entry.get("temperature"),
            top_k=entry.get("top_k"),
            top_p=entry.get("top_p"),
            seed=entry.get("seed"),
            repetition_penalty=entry.get("repetition_penalty"),
            stop=[list(s) for s in entry["stop"]]
            if entry.get("stop") else None,
            logprobs=bool(entry.get("logprobs", False)),
            n=1,
            out=[int(t) for t in entry.get("out", ())],
            out_logprobs=[float(x)
                          for x in entry.get("out_logprobs", ())],
            evictions=int(entry.get("evictions", 0)),
            cost=(dict(entry["cost"]) if entry.get("cost")
                  else _new_footprint()))


class DegradedError(RuntimeError):
    """The engine cannot meet its contract (hung step, non-drain) but
    the work it DID finish is intact: ``results`` maps rid ->
    prompt+generated for every retired request, ``finished`` holds the
    request objects, ``pending`` is a host-side snapshot of in-flight
    work (rid/state/tokens-generated/evictions), ``stats`` the engine
    counters at raise time. The scheduler state stays consistent — a
    caller may resubmit ``pending`` work or keep stepping."""

    def __init__(self, message: str, results: Optional[Dict] = None,
                 finished: Optional[List[ServeRequest]] = None,
                 pending: Optional[List[Dict]] = None,
                 stats: Optional[Dict] = None):
        super().__init__(message)
        self.results = results or {}
        self.finished = finished or []
        self.pending = pending or []
        self.stats = stats or {}


def snapshot_entry(req: ServeRequest, **extra) -> Dict:
    """One ``pending_snapshot()`` entry for ``req``: the resume-
    sufficient host-side view :meth:`ServeRequest.from_snapshot`
    round-trips, plus whatever position tags (``slot``/``queue_pos``)
    the caller adds. Token lists are copied — mutating the live request
    afterwards cannot skew an already-raised DegradedError."""
    entry = {"rid": req.rid, "state": req.state,
             "generated": len(req.out),
             "evictions": req.evictions,
             "prompt": [int(t) for t in req.prompt],
             "out": [int(t) for t in req.out],
             "max_new_tokens": req.max_new_tokens,
             "eos_id": req.eos_id,
             "deadline": req.deadline,
             "priority": req.priority,
             # a drained/resumed request re-attaches (or re-loads) its
             # adapter at the survivor's admission (docs/ADAPTERS.md)
             "adapter_id": req.adapter_id,
             # sampling state: the per-token key is a pure function of
             # (seed, len(out)), so these fields ARE the key-chain state
             # a drain/resume needs (docs/SAMPLING.md)
             "temperature": req.temperature,
             "top_k": req.top_k,
             "top_p": req.top_p,
             "seed": req.seed,
             "repetition_penalty": req.repetition_penalty,
             "stop": [[int(t) for t in s] for s in req.stop]
             if req.stop else None,
             "logprobs": req.logprobs,
             "out_logprobs": [float(x) for x in req.out_logprobs],
             # cost footprint rides the snapshot so a drained request
             # keeps its accrued attribution on the survivor replica
             "cost": json.loads(json.dumps(req.cost))}
    entry.update(extra)
    return entry


class ServingEngine:
    """Continuous-batching front end for an ``InferenceEngine``.

    ``num_blocks``/``hbm_budget_bytes`` bound the paged cache (the HBM
    watermark); ``num_slots`` bounds the decode batch; ``prefill_chunk``
    bounds how much prompt work one iteration may do (decode latency
    stays O(chunk) under long-prompt arrivals).

    Robustness knobs (all default to the pre-chaos behavior):

    - ``max_queue``: queue bound; a submit beyond it sheds the newcomer
      (``state="shed"``). None = unbounded.
    - ``max_evictions``: per-request preemption cap; at the cap a
      request is pinned against further eviction (storm guard).
    - ``step_time_budget_s`` / ``watchdog_grace``: decode-dispatch time
      budget; ``watchdog_grace`` consecutive over-budget steps raise
      :class:`DegradedError` with partial results. None disables.
    - ``max_retries`` / ``retry_backoff_s``: transient-device-error
      retry count and initial backoff (doubled per attempt, plus
      deterministic jitter from the fault injector's seeded rng).
    - ``faults``: a :class:`~deepspeed_tpu.utils.faults.FaultInjector`;
      defaults to the ambient one (env ``DS_FAULTS`` or installed).
    - ``prefix_cache``: shared-prefix KV reuse across requests
      (refcounted block sharing + radix index + copy-on-write). None
      defers to ``DS_PREFIX_CACHE`` (default off — the private-blocks
      allocator stays the bit-reference).
    - ``telemetry``: lifecycle tracing + metrics registry + step-time
      breakdown (docs/OBSERVABILITY.md). True/False forces it, a
      :class:`~deepspeed_tpu.telemetry.Telemetry` instance is used
      as-is (share one across engines to aggregate), None defers to
      ``DS_TELEMETRY`` (default off — no-op plane, zero overhead).
    - ``spec_decode`` / ``spec_k`` / ``spec_draft``: speculative decode
      inside the batch (docs/SPECULATIVE.md) — each step a drafter
      proposes ``spec_k`` tokens per slot and ONE verify program scores
      all ``spec_k + 1`` positions; the accepted prefix advances the
      slot, the first reject rolls the cache back. temperature=0 slots
      accept by greedy-target agreement (bit-identical to spec-off
      greedy serving); sampled slots accept by rejection sampling
      (distribution-lossless, docs/SAMPLING.md).
      ``spec_decode`` None defers to ``DS_SPEC_DECODE`` (default off —
      plain one-token decode stays the bit-reference); ``spec_k`` None
      to ``DS_SPEC_K`` (default 4); ``spec_draft`` takes ``"ngram"``
      (prompt-lookup, default), a draft ``InferenceEngine``, or any
      ``propose(context, k)`` object.
    - ``spec_accept_floor`` / ``spec_adapt_warmup``: adaptive
      speculation depth — after ``spec_adapt_warmup`` verify
      participations, a slot whose acceptance EWMA is under the floor
      verifies only ONE draft token per step until its rate recovers
      (the verify program's static width never changes; floor<=0
      disables the cap).
    - ``temperature`` / ``top_k`` / ``seed``: engine-wide DEFAULTS for
      requests that leave their own sampling fields at None; a
      request's explicit knobs always win (docs/SAMPLING.md).
    - ``kv_quant``: int8 paged KV-cache blocks with per-block scales
      (docs/KV_QUANT.md) — ~2x decode slots at the same cache HBM.
      ``"int8"``/``"off"``; None defers to ``DS_KV_QUANT`` (default
      off — the unquantized pool stays the bit-reference; int8 is
      held to a documented greedy-match tolerance, not bit equality).
    - ``host_tier`` / ``host_budget_bytes``: host-DRAM second tier for
      refcount-zero cached prefix blocks (docs/KV_TIERING.md) — a
      low-watermark spill daemon rides each step's decode dispatch and
      a prefix hit on spilled links restores instead of re-prefilling.
      Requires ``prefix_cache``; restores/spills degrade to cold-miss
      re-prefill / plain eviction on any failure (CRC corruption,
      injected faults, budget exhaustion). None defers to
      ``DS_KV_HOST_TIER`` / ``DS_KV_HOST_BUDGET_MB`` (default off —
      the device-only cache stays the bit-reference).
      ``spill_watermark`` pins the free-list level below which the
      daemon spills (None = cache watermark + transfer batch).
    """

    def __init__(self, engine, *, num_slots: int = 4, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 prefill_chunk: int = 64, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0,
                 decode_impl: Optional[str] = None,
                 prefix_cache: Optional[bool] = None,
                 max_queue: Optional[int] = None,
                 max_evictions: int = 8,
                 step_time_budget_s: Optional[float] = None,
                 watchdog_grace: int = 2,
                 max_retries: int = 3, retry_backoff_s: float = 0.02,
                 faults: Optional[faults_lib.FaultInjector] = None,
                 telemetry=None,
                 spec_decode: Optional[bool] = None,
                 spec_k: Optional[int] = None,
                 spec_draft=None,
                 spec_accept_floor: float = 0.125,
                 spec_adapt_warmup: int = 4,
                 kv_quant: Optional[str] = None,
                 host_tier: Optional[bool] = None,
                 host_budget_bytes: Optional[int] = None,
                 spill_watermark: Optional[int] = None,
                 lora_serve: Optional[bool] = None,
                 lora_pool_mb: Optional[float] = None,
                 lora_pool_blocks: Optional[int] = None,
                 lora_max_rank: Optional[int] = None,
                 lora_rank_block: Optional[int] = None,
                 decode_horizon: Optional[int] = None,
                 cost_accounting: Optional[bool] = None,
                 flight_recorder: Optional[bool] = None,
                 flight_dir: Optional[str] = None,
                 prefill_only: bool = False):
        if engine.is_encoder:
            raise ValueError("serving needs a causal decoder engine")
        self.engine = engine
        if isinstance(telemetry, (Telemetry, NoopTelemetry)):
            self.telemetry = telemetry
        elif resolve_telemetry(telemetry):
            self.telemetry = Telemetry()
        else:
            self.telemetry = NOOP
        # decode attention path ("pallas" flash-decode through the block
        # table | "gather" dense reference); defaults to the engine's
        # resolved choice so env/platform selection applies uniformly.
        # Pinned for the run: impl is a static jit arg, so ONE impl keeps
        # steady state at two compiled programs.
        if decode_impl is None:
            self.decode_impl = engine.decode_impl
        else:
            from deepspeed_tpu.ops.attention.paged import resolve_decode_impl
            self.decode_impl = resolve_decode_impl(decode_impl)
        self.faults = faults if faults is not None else faults_lib.active()
        self.prefix_cache = resolve_prefix_cache(prefix_cache)
        # int8 KV-cache pools with per-block scales (DS_KV_QUANT=int8):
        # resolved once here, pinned for the run — the quantized slot
        # programs are separate executables, so a run uses EITHER the fp
        # set or the int8 set, never both
        self.kv_quant = resolve_kv_quant(kv_quant)
        self._quant = self.kv_quant == "int8"
        # multi-tenant LoRA serving (inference/adapters.py): resolved
        # once here, pinned for the run — the lora program twins are
        # separate executables, so a run uses EITHER the base set or
        # the lora set, never both (docs/ADAPTERS.md)
        self.lora_serve = resolve_lora_serve(lora_serve)
        cow = getattr(engine, "cow_blocks_q" if self._quant
                      else "cow_blocks", None)
        # host-tier transfer programs: like COW, the engine's jitted
        # (and correctly-sharded) gather/scatter are wired in when
        # present; the quantized pair moves the scale sidecars too
        gather = getattr(engine, "gather_blocks_q" if self._quant
                         else "gather_blocks", None)
        scatter = getattr(engine, "scatter_block_q" if self._quant
                          else "scatter_block", None)
        self.cache = PagedKVCache(
            engine.cfg, num_slots=num_slots, block_size=block_size,
            num_blocks=num_blocks, hbm_budget_bytes=hbm_budget_bytes,
            dtype=engine.dtype, max_seq_len=engine.max_seq_len,
            faults=self.faults, prefix_cache=self.prefix_cache,
            copy_fn=cow, kv_quant=self.kv_quant,
            host_tier=resolve_host_tier(host_tier),
            host_budget_bytes=host_budget_bytes,
            spill_watermark=spill_watermark,
            gather_fn=gather, scatter_fn=scatter,
            tracer=self.telemetry.tracer
            if self.telemetry.enabled else None)
        # the EFFECTIVE switch: the cache gates the tier on the prefix
        # index existing (only indexed blocks ever spill)
        self.host_tier = self.cache.host_tier
        mesh = getattr(engine, "mesh", None)
        if mesh is not None:
            # place the fresh pools exactly where the jitted programs
            # will put them (replicated over the engine mesh): a first
            # prefill call with differently-placed pools keys a second,
            # single-use executable — one whole wasted XLA compile at
            # cold start (caught by test_serving_compile_count_contract)
            from jax.sharding import NamedSharding, PartitionSpec
            pool_sh = NamedSharding(mesh, PartitionSpec())
            self.cache.k = jax.device_put(self.cache.k, pool_sh)
            self.cache.v = jax.device_put(self.cache.v, pool_sh)
            if self._quant:
                self.cache.k_scale = jax.device_put(self.cache.k_scale,
                                                    pool_sh)
                self.cache.v_scale = jax.device_put(self.cache.v_scale,
                                                    pool_sh)
        # compile the COW copy program now (after pool placement, so the
        # warmed executable matches steady-state shardings): the first
        # mid-block divergence must not add a compile inside the
        # CompileWatch-pinned steady state
        self.cache.warm_cow()
        # same contract for the host-tier transfer programs: the first
        # spill/restore must not compile inside the pinned steady state
        self.cache.warm_host_tier()
        # disaggregated prefill role (docs/ROBUSTNESS.md): a prefill-only
        # replica runs chunked prefill, emits the FIRST token (TTFT is
        # stamped where the prefill ran), then parks the request in
        # state="handoff" for the router to migrate its KV to a decode
        # replica — it never runs a decode step for it. Plain flag, no
        # program change: the decode executables stay compiled/warm, so
        # flipping a replica's role never recompiles.
        self.prefill_only = bool(prefill_only)
        self.num_slots = num_slots
        self.prefill_chunk = int(prefill_chunk)
        self.temperature = temperature
        self.top_k = top_k
        self.max_queue = max_queue
        self.max_evictions = int(max_evictions)
        self.step_time_budget_s = step_time_budget_s
        self.watchdog_grace = max(1, int(watchdog_grace))
        self.max_retries = int(max_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        # speculative decode: drafter + chunk length resolved once at
        # construction (spec_k is baked into the verify program's static
        # G = spec_k + 1 token dimension, so it cannot change per step)
        self.spec_decode = resolve_spec_decode(spec_decode)
        self.spec_k = resolve_spec_k(spec_k)
        self.draft = make_draft(spec_draft) if self.spec_decode else None
        # adaptive speculation depth: a slot whose acceptance EWMA sinks
        # under ``spec_accept_floor`` (after ``spec_adapt_warmup``
        # verify participations) caps its accepted prefix at 1 draft
        # token, so adversarial low-accept traffic stops paying verify
        # rollbacks for depth it never uses (floor<=0 disables)
        self.spec_accept_floor = float(spec_accept_floor)
        self.spec_adapt_warmup = int(spec_adapt_warmup)
        self._accept_ewma = np.ones(num_slots, np.float64)
        self._spec_obs = np.zeros(num_slots, np.int64)
        # fused multi-step decode horizon (docs/MULTISTEP.md): N decode
        # iterations per dispatch, resolved once and pinned — N is a
        # static dimension of the horizon programs, so one run compiles
        # exactly one horizon family (N=1 keeps the single-step
        # bit-reference program and never compiles the family at all).
        # With spec_decode on, the verify chunk is already the
        # multi-token step and takes precedence
        self.decode_horizon = resolve_decode_horizon(decode_horizon)
        # horizon-aware scheduler clock: the deadline clock ticks once
        # per EMITTED token (not per step), so step-clock deadlines and
        # ttft/tpot keep their one-token-per-tick meaning at N > 1.
        # _horizon_ticks = ticks the last decode phase consumed;
        # last_step_span exposes it to external step-unit drivers
        # (tools/load_gen.drive); token_time_unit is the per-token stamp
        # spacing such a driver announces (0.0 = wall-clock caller: all
        # of a horizon's tokens stamp at dispatch time)
        self._horizon_ticks = 1
        self._token_tick = 0.0
        self.last_step_span = 1.0
        self.token_time_unit = 0.0
        # wall seconds spent inside device dispatch/harvest calls — the
        # bench's host/device ms-per-token split (tools/infer_bench.py)
        self.device_time_s = 0.0
        # per-request sampling: engine-wide ctor knobs are DEFAULTS a
        # request's own fields override (sampling.resolve_params); the
        # resolved knobs live as slot-indexed arrays the fused sampler
        # reads as data, so greedy/sampled mixes share one program
        self.seed = int(seed)
        self.sampler = sampling.SlotSamplerState(num_slots,
                                                 engine.cfg.vocab_size)
        self._slot_params: List[Optional[sampling.SamplingParams]] = \
            [None] * num_slots
        self.queue: deque = deque()
        self.slots: List[Optional[ServeRequest]] = [None] * num_slots
        self.finished: List[ServeRequest] = []
        self._progress = np.zeros((num_slots,), np.int64)  # prefilled toks
        self._admit_counter = 0
        self._over_budget = 0            # consecutive watchdog strikes
        self._watchdog_msg: Optional[str] = None
        # the deadline clock is its OWN monotone counter (one tick per
        # step): stats["steps"] used to double as it, which let a stats
        # mutation skew every relative deadline — now stats are a
        # read-only view and the clock is private
        self._step_clock = 0
        # stats route through a metrics registry (the telemetry one
        # when enabled, else a private one — the counters must stay
        # live either way since they ARE the public stats contract)
        self.metrics = (self.telemetry.registry if self.telemetry.enabled
                        else MetricsRegistry())
        self._stat = {}
        for key, kind, help_ in _STAT_FIELDS:
            make = (self.metrics.counter if kind == "c"
                    else self.metrics.gauge)
            self._stat[key] = make(f"serving_{key}", help_)
        self.stats = _StatsView(self._stat)
        if self.telemetry.enabled:
            reg = self.metrics
            self._h_ttft = reg.histogram(
                "serving_ttft", "time to first token (scheduler clock "
                "units: seconds under wall_clock, steps otherwise)")
            self._h_tpot = reg.histogram(
                "serving_tpot",
                "per-output-token latency (scheduler clock units)")
            self._h_qwait = reg.histogram(
                "serving_queue_wait",
                "enqueue-to-admit wait (scheduler clock units)")
            self._h_occ = reg.histogram(
                "serving_batch_occupancy", "decoding slots per step",
                buckets=tuple(float(i) for i in range(num_slots + 1)))
            self._g_held = reg.gauge(
                "serving_hbm_blocks_held", "pool blocks with refcount > 0")
            self._g_cached = reg.gauge(
                "serving_hbm_blocks_cached",
                "refcount-0 blocks kept by the prefix index")
            self._g_free = reg.gauge(
                "serving_hbm_blocks_free", "free-list blocks")
            self._g_hit_rate = reg.gauge(
                "serving_prefix_hit_rate", "prefix hits / admissions")
            self._h_accept = reg.histogram(
                "serving_spec_accept_rate",
                "per-verify-step draft acceptance rate",
                buckets=RATE_BUCKETS)
            self._h_tps = reg.histogram(
                "serving_spec_tokens_per_step",
                "tokens emitted per live slot per verify step",
                buckets=tuple(float(i)
                              for i in range(1, self.spec_k + 2)))
            # multi-step decode plane (docs/MULTISTEP.md): realized
            # per-slot horizon utilization + the run's configured N
            self._h_horizon = reg.histogram(
                "serving_horizon_tokens",
                "tokens emitted per slot per fused multi-step decode "
                "dispatch",
                buckets=tuple(float(i)
                              for i in range(1, self.decode_horizon + 2))) \
                if self.decode_horizon > 1 else None
            self._g_horizon = reg.gauge(
                "decode_horizon",
                "fused decode iterations per dispatch (static per run; "
                "1 = single-step bit-reference)")
            self._g_horizon.set(float(self.decode_horizon))
            self._h_temp = reg.histogram(
                "serving_request_temperature",
                "resolved per-request sampling temperature at admission "
                "(0 = greedy)",
                buckets=TEMP_BUCKETS)
            # KV-pool shape of THIS run (static per run, gauges so the
            # Prometheus text path exports them next to the block
            # gauges): bytes/token includes the amortized per-block
            # scale overhead under int8
            self._g_kv_bpt = reg.gauge(
                "kv_cache_bytes_per_token",
                "KV pool bytes per cached token (all layers, K+V, "
                "including per-block scale overhead when quantized)")
            self._g_kv_bpt.set(
                self.cache.bytes_per_token
                + self.cache.scale_bytes_per_block / self.cache.block_size)
            self._g_kv_dtype = reg.gauge(
                "kv_pool_dtype", "KV pool element width in bits "
                "(8 = int8 quantized, 16 = bf16, 32 = f32)")
            self._g_kv_dtype.set(self.cache.pool_dtype.itemsize * 8)
            self._h_kv_err = reg.histogram(
                "serving_kv_quant_error",
                "sampled upper bound on the max-abs KV dequantization "
                "error (half the hottest block's quantization step)",
                buckets=(1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
                         1e-2, 2.5e-2, 5e-2, 1e-1, 2.5e-1)) \
                if self._quant else None
            # host-tier plane (docs/KV_TIERING.md): DRAM footprint gauge
            # plus per-restore latency histogram — restores sit on the
            # admission path, so their tail IS the warm-hit TTFT tax
            self._g_host_bytes = reg.gauge(
                "kv_host_tier_bytes",
                "host-DRAM bytes held by spilled KV blocks") \
                if self.host_tier else None
            self._h_host_restore = reg.histogram(
                "kv_host_restore_ms",
                "per-block host->device restore wall time (CRC verify "
                "+ H2D copy + scatter dispatch, ms)",
                buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                         25.0, 50.0, 100.0)) \
                if self.host_tier else None
            # adapter plane (docs/ADAPTERS.md): pool residency + size,
            # refreshed by the pool's stat hooks below
            self._g_lora_active = reg.gauge(
                "lora_active_adapters",
                "LoRA adapters resident in the device pool") \
                if self.lora_serve else None
            self._g_lora_pool = reg.gauge(
                "lora_pool_bytes",
                "device bytes reserved by the paged adapter pool") \
                if self.lora_serve else None

            def _on_fault(site: str, kind: str, visit: int) -> None:
                # injected faults land in the SAME timeline as the
                # request lifecycle, stamped with the scheduler step at
                # fire time — a chaos run replays as one trace
                self.telemetry.tracer.event(
                    "fault", step=self._step_clock,
                    site=site, kind=kind, visit=visit)

            self._fault_listener = _on_fault
            self.faults.add_listener(self._fault_listener)
        else:
            self._h_ttft = self._h_tpot = self._h_qwait = self._h_occ = None
            self._h_accept = self._h_tps = self._h_temp = None
            self._h_horizon = self._g_horizon = None
            self._h_kv_err = None
            self._g_host_bytes = self._h_host_restore = None
            self._g_lora_active = self._g_lora_pool = None
            self._fault_listener = None
        # the paged adapter pool + per-slot adapter-table rows: row j
        # holds the block ids the compiled programs gather slot j's
        # adapter factors through (all zeros = base-only: trash block 0
        # gathers exact zeros, keeping base-only slots bit-identical to
        # the pre-subsystem stream)
        if self.lora_serve:
            self.adapters = AdapterPool(
                engine, pool_mb=lora_pool_mb, pool_blocks=lora_pool_blocks,
                max_rank=lora_max_rank, rank_block=lora_rank_block,
                faults=self.faults,
                tracer=(self.telemetry.tracer if self.telemetry.enabled
                        else None),
                hooks={"on_hit": self._stat["adapter_hits"].inc,
                       "on_load": self._on_adapter_load,
                       "on_evict": self._on_adapter_evict})
            self._slot_arows = np.zeros(
                (num_slots, self.adapters.blocks_per_adapter), np.int32)
            if self._g_lora_pool is not None:
                self._g_lora_pool.set(self.adapters.pool_bytes)
        else:
            self.adapters = None
            self._slot_arows = None
        # cost-accounting plane (telemetry/costs.py, docs/OBSERVABILITY
        # .md): exact integer FLOPs/HBM-bytes/block-seconds attribution
        # per dispatch class, request and tenant. DS_TELEMETRY=on
        # implies it; DS_COST_ACCOUNTING=on enables it standalone.
        # Charges are host-int arithmetic only — no device work, no new
        # programs, and the off path is the usual constant no-op twin
        if self.telemetry.enabled \
                or resolve_flag("DS_COST_ACCOUNTING", cost_accounting):
            kv_tok = int(self.cache.bytes_per_token)
            block_bytes = (kv_tok * self.cache.block_size
                           + int(self.cache.scale_bytes_per_block))
            try:
                param_itemsize = int(np.dtype(engine.dtype).itemsize)
            except TypeError:
                param_itemsize = 2
            self.costs = CostAccountant(
                engine.cfg, kv_tok, block_bytes, param_itemsize,
                registry=self.metrics)
            self.cost_registry = ProgramCostRegistry()
            self.cost_registry.populate(engine, cache=self.cache)
            if self.telemetry.enabled:
                self.cost_registry.export_gauges(self.metrics)
        else:
            self.costs = NOOP_COSTS
            self.cost_registry = None
        # flight recorder (telemetry/flight.py): armed when
        # DS_FLIGHT_RECORDER=on — a DegradedError writes a versioned,
        # CRC-stamped postmortem artifact tools/postmortem.py can
        # analyze with zero live objects
        if resolve_flag("DS_FLIGHT_RECORDER", flight_recorder):
            self.flight = FlightRecorder(
                outdir=flight_dir or (resolve_flag("DS_FLIGHT_DIR")
                                      or None),
                sections=self._flight_sections(), label="serving")
        else:
            self.flight = NOOP_FLIGHT

    def _flight_sections(self) -> Dict:
        """Postmortem section providers — called only at dump time."""
        return {
            "tracer": lambda: [list(r)
                               for r in self.telemetry.tracer.records()],
            "metrics": lambda: self.metrics.snapshot(),
            "windows": lambda: {n: h.window_summary()
                                for n, h in
                                self.metrics._histograms.items()},
            "stats": lambda: dict(self.stats),
            "faults": lambda: [list(f) for f in self.faults.fired],
            "flags": lambda: {n: resolve_flag(n) for n in flag_names()},
            "programs": lambda: (self.cost_registry.to_json()
                                 if self.cost_registry else {}),
            "costs": lambda: self.costs.snapshot(),
            "requests": self._flight_requests,
        }

    def _flight_requests(self) -> List[Dict]:
        """Per-request postmortem rows: every finished request plus the
        in-flight set, each with its lifecycle state and cost
        footprint."""
        rows = []
        for req in self.finished:
            rows.append({"rid": req.rid, "state": req.state,
                         "generated": len(req.out),
                         "adapter_id": req.adapter_id,
                         "evictions": req.evictions,
                         "cost": req.cost})
        for slot, req in enumerate(self.slots):
            if req is not None:
                rows.append({"rid": req.rid, "state": req.state,
                             "slot": slot, "generated": len(req.out),
                             "adapter_id": req.adapter_id,
                             "evictions": req.evictions,
                             "cost": req.cost})
        for pos, req in enumerate(self.queue):
            rows.append({"rid": req.rid, "state": req.state,
                         "queue_pos": pos, "generated": len(req.out),
                         "adapter_id": req.adapter_id,
                         "evictions": req.evictions,
                         "cost": req.cost})
        return rows

    def _on_adapter_load(self) -> None:
        self._stat["adapter_loads"].inc()
        if self._g_lora_active is not None:
            self._g_lora_active.set(self.adapters.active_adapters)

    def _on_adapter_evict(self) -> None:
        self._stat["adapter_evictions"].inc()
        if self._g_lora_active is not None:
            self._g_lora_active.set(self.adapters.active_adapters)

    def register_adapter(self, adapter_id: str, source) -> None:
        """Stage a ``runtime/lora.py`` adapter export for serving under
        ``adapter_id`` (requires ``lora_serve``); device residency is
        deferred to the first admission that names it."""
        if self.adapters is None:
            raise ValueError("register_adapter requires lora_serve=True "
                             "(DS_LORA_SERVE=on)")
        self.adapters.register(adapter_id, source)

    def _lora_args(self, slot: Optional[int] = None):
        """The engine's ``lora=`` operand for the whole batch (or one
        prefill slot). None when the subsystem is off — the base
        programs stay the only ones ever traced."""
        if self.adapters is None:
            return None
        rows = self._slot_arows if slot is None else self._slot_arows[slot]
        return self.adapters.lora_args(rows)

    # -- API -----------------------------------------------------------
    def submit(self, req: ServeRequest, now: float = 0.0) -> bool:
        """Enqueue ``req``. Returns False when the bounded queue is full
        and the request was shed instead (``state="shed"``, recorded in
        ``finished`` so the caller sees exactly one terminal state per
        request). Malformed requests still raise ValueError."""
        total = len(req.prompt) + req.max_new_tokens
        if total > self.engine.max_seq_len:
            raise ValueError(
                f"request {req.rid}: prompt {len(req.prompt)} + "
                f"max_new {req.max_new_tokens} exceeds max_seq_len "
                f"{self.engine.max_seq_len}")
        if self.cache.blocks_for(total) > self.cache.num_blocks - 1:
            raise ValueError(
                f"request {req.rid} needs more blocks than the whole pool")
        # fail fast on malformed sampling knobs (resolve_params
        # validates the resolved bundle) — and resolve once here so the
        # n>1 expansion below derives candidate seeds from the SAME
        # seed admission will use
        params = sampling.resolve_params(req, self.temperature,
                                         self.top_k, self.seed)
        if req.n < 1:
            raise ValueError(f"request {req.rid}: n must be >= 1, "
                             f"got {req.n}")
        if req.n > 1:
            # expand into n independent candidates: the original keeps
            # its rid as candidate 0, clones get rid#i and a
            # SeedSequence-derived seed. n is pinned back to 1 on every
            # piece so a drain/resume resubmit never re-expands.
            n, req.n = req.n, 1
            ok = self.submit(req, now=now)
            for i in range(1, n):
                clone = ServeRequest(
                    rid=f"{req.rid}#{i}", prompt=req.prompt,
                    max_new_tokens=req.max_new_tokens, eos_id=req.eos_id,
                    deadline=req.deadline, adapter_id=req.adapter_id,
                    temperature=req.temperature,
                    top_k=req.top_k, top_p=req.top_p,
                    seed=sampling.candidate_seed(params.seed, i),
                    repetition_penalty=req.repetition_penalty,
                    stop=req.stop, logprobs=req.logprobs, n=1)
                ok = self.submit(clone, now=now) and ok
            return ok
        req.submitted_at = now
        # resume-aware working prompt: a request rebuilt from a
        # pending snapshot (out non-empty) re-prefills prompt+partial —
        # the same recompute-on-resume contract _preempt uses — so a
        # drained request continues token-identically on a fresh engine
        req._work = np.asarray(req.tokens if req.out else req.prompt,
                               np.int32)
        self.telemetry.tracer.event("enqueue", rid=req.rid,
                                    step=self._step_clock,
                                    queue_len=len(self.queue))
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            # reject-newest: accepted work keeps its latency budget; the
            # newcomer gets an immediate, explicit answer instead of an
            # unbounded queue wait
            req.state = "shed"
            req.finished_at = now
            self.finished.append(req)
            self._stat["shed"].inc()
            self.telemetry.tracer.event("finish", rid=req.rid,
                                        step=self._step_clock,
                                        state="shed", generated=0)
            self._update_backpressure()
            logger.warning(f"serving: shed request {req.rid} "
                           f"(queue full at {self.max_queue})")
            return False
        self.queue.append(req)
        self._update_backpressure()
        return True

    @property
    def busy(self) -> bool:
        return bool(self.queue) or any(s is not None for s in self.slots)

    def step(self, now: Optional[float] = None) -> int:
        """One scheduler iteration: expire, admit, prefill chunks,
        decode. Returns the number of decoding slots this iteration
        (the occupancy sample). Raises :class:`DegradedError` when the
        step watchdog trips (state stays consistent — every token
        produced so far, including this step's, is recorded)."""
        if now is None:
            now = float(self._step_clock)
            # internal step-clock mode: one tick per emitted token, so
            # a horizon's tokens stamp at now, now+1, ... exactly as
            # the N=1 loop would have stamped them
            self._token_tick = 1.0
        else:
            self._token_tick = float(self.token_time_unit)
        self._horizon_ticks = 1
        bd = self.telemetry.breakdown
        sampled = bd.begin(self._step_clock, sync=self._sync_devices)
        self._expire(now)
        self._admit(now)
        bd.lap("admission")
        self._prefill_step(now)
        bd.lap("prefill")
        occ = self._decode_step(now)
        self._spill_step()
        bd.lap("decode")
        # the deadline clock advances one tick per emitted token: a
        # horizon-N decode that produced p tokens consumed p ticks, so
        # relative deadlines keep their token-count meaning at N > 1
        # (N=1 keeps _horizon_ticks at 1 — bit-identical clocking)
        self._step_clock += self._horizon_ticks
        self.last_step_span = float(self._horizon_ticks)
        if self.costs.enabled:
            # KV residency integrates at horizon boundaries: every slot
            # holder is billed its block count x the ticks this step
            # consumed (scheduler-clock units; seconds under wall_clock)
            for i, r in enumerate(self.slots):
                if r is not None:
                    self.costs.charge_block_seconds(
                        r, self.cache.blocks_for(int(self.cache.lengths[i])),
                        self._horizon_ticks)
        self._stat["steps"].inc()
        self._stat["occupancy_sum"].inc(occ)
        peak = self._stat["peak_occupancy"]
        peak.set(max(peak.value, occ))
        self._update_backpressure()
        if self._h_occ is not None:
            self._h_occ.observe(occ)
            if sampled:
                self._sample_gauges()
        bd.finish(occupancy=occ)
        if self._watchdog_msg is not None:
            msg, self._watchdog_msg = self._watchdog_msg, None
            self._over_budget = 0
            self.telemetry.tracer.event("degraded", step=self._step_clock,
                                        message=msg)
            raise self._degraded(msg)
        return occ

    def run(self, requests=None, max_steps: int = 1_000_000,
            wall_clock: bool = False) -> Dict[Any, np.ndarray]:
        """Drain: submit ``requests`` (if given) and step until idle.
        Returns {rid: prompt+generated} for every retired request (the
        terminal state lives on the request object). Submissions are
        stamped with the SAME clock the step loop uses, so
        ``submitted_at``-based latency percentiles are meaningful under
        ``wall_clock=True``. A non-drain raises :class:`DegradedError`
        with everything finished so far attached instead of discarding
        it."""
        for r in (requests or []):
            self.submit(r, now=time.perf_counter() if wall_clock else 0.0)
        steps = 0
        while self.busy:
            self.step(time.perf_counter() if wall_clock else None)
            steps += 1
            if steps > max_steps:
                raise self._degraded(
                    f"serving did not drain in {max_steps} steps "
                    f"(queue {len(self.queue)})")
        return {r.rid: r.tokens for r in self.finished}

    def pending_snapshot(self, release: bool = False) -> List[Dict]:
        """Host-side view of in-flight work (attached to
        :class:`DegradedError`): one entry per slot/queue request.

        Entries carry everything :meth:`ServeRequest.from_snapshot`
        needs to round-trip into a *fresh* engine (prompt, emitted
        tokens, budget, eos, deadline) — host-side copies, decoupled
        from the live request objects. The default is NON-destructive:
        the engine keeps its slots/queue, so a watchdog-degraded caller
        may simply keep stepping. ``release=True`` is the declared-dead
        path (the router's drain): every slot's blocks — including
        prefix-cache pins — go back to the pool and the queue empties,
        so the snapshot is the only remaining owner of the work."""
        snap = []
        for slot, r in enumerate(self.slots):
            if r is not None:
                snap.append(snapshot_entry(r, slot=slot))
        for pos, r in enumerate(self.queue):
            snap.append(snapshot_entry(r, queue_pos=pos))
        if release:
            # drain/retire contract (docs/KV_TIERING.md): in-flight
            # spills settle BEFORE any slot releases — a mid-transfer
            # block must be releasable like any other, and the snapshot
            # path must never race a harvest. Parked migration landings
            # settle with the same discipline (docs/ROBUSTNESS.md):
            # their requests re-prefill cold on a survivor
            self.cache.abort_transfers()
            self.cache.abort_parked()
            for slot, r in enumerate(self.slots):
                if r is not None:
                    self._release_adapter(slot, r)
                    self.cache.free(slot)
                    self.slots[slot] = None
                    self.sampler.release(slot)
                    self._slot_params[slot] = None
            self.queue.clear()
            self._update_backpressure()
        return snap

    # -- disaggregated prefill/decode handoff (docs/ROBUSTNESS.md) -----
    def ready_handoffs(self) -> List:
        """Finished prefills parked for migration: ``(slot, req)`` for
        every slot in ``state="handoff"`` (``prefill_only`` replicas
        only — a mixed/decode replica never parks). The router harvests
        these each step and drives the KV migration."""
        return [(slot, r) for slot, r in enumerate(self.slots)
                if r is not None and r.state == "handoff"]

    def release_handoff(self, rid) -> bool:
        """Free the handoff slot for ``rid`` after the router has taken
        ownership (migrated the KV, or fallen back to a cold resume on
        the decode side): blocks back to the pool, slot reopened. The
        request is NOT retired here — its one terminal state lands on
        the destination replica. Returns False when ``rid`` holds no
        handoff slot (it timed out or was already released — the
        caller's snapshot path owns it then)."""
        for slot, r in enumerate(self.slots):
            if r is not None and r.rid == rid and r.state == "handoff":
                self._release_adapter(slot, r)
                self.cache.free(slot)
                self.slots[slot] = None
                self.sampler.release(slot)
                self._slot_params[slot] = None
                return True
        return False

    # -- phases ----------------------------------------------------------
    def _expire(self, now: float) -> None:
        """Retire every request whose deadline has passed — slot holders
        free their blocks immediately (no zombie slot squatting), queued
        requests never claim one."""
        for slot, req in enumerate(self.slots):
            if req is not None and req.deadline is not None \
                    and now >= req.deadline:
                logger.warning(
                    f"serving: request {req.rid} passed its deadline "
                    f"({req.deadline}) with {len(req.out)} of "
                    f"{req.max_new_tokens} tokens; timing out")
                self._finish(slot, req, now, state="timeout")
        if not self.queue:
            return
        keep = deque()
        for req in self.queue:
            if req.deadline is not None and now >= req.deadline:
                req.state = "timeout"
                req.finished_at = now
                # a migrated-in request expiring while queued must
                # return its parked landing, or the blocks leak
                self.cache.drop_parked(req.rid)
                self.finished.append(req)
                self._stat["timeouts"].inc()
                self.telemetry.tracer.event(
                    "finish", rid=req.rid, step=self._step_clock,
                    state="timeout", generated=len(req.out))
            else:
                keep.append(req)
        self.queue = keep

    def _unqueue(self, req: ServeRequest) -> None:
        """Remove ``req`` from the queue by IDENTITY (dataclass ``==``
        is unusable on array-carrying requests, and a parked request
        admitted out of line is not the head)."""
        for i, r in enumerate(self.queue):
            if r is req:
                del self.queue[i]
                return

    def _admit(self, now: float = 0.0) -> None:
        # FIFO head-of-line: no queue jumping, so a preempted-and-
        # requeued request (appendleft) resumes before newer arrivals
        while self.queue:
            slot = next((i for i, s in enumerate(self.slots) if s is None),
                        None)
            if slot is None:
                break
            req = self.queue[0]
            occupied = any(s is not None for s in self.slots)
            # idle engine: skip the watermark so a lone request that
            # fits the pool always makes progress (no livelock); the
            # admission charge covers only the uncached suffix when the
            # prefix cache can share blocks. Adapter-carrying requests
            # bypass prefix sharing entirely: the index keys blocks by
            # TOKENS only, but their K/V was computed under some
            # adapter's weights — a cross-tenant hit would serve
            # another adapter's activations (docs/ADAPTERS.md)
            tok_key = None if req.adapter_id is not None else req._work
            # migrated-in request (docs/ROBUSTNESS.md): the router
            # already landed its KV chain as a parked chain — adoption
            # needs no fresh blocks, so admission control is skipped
            parked = self.cache.has_parked(req.rid)
            if not parked:
                ok = self.cache.can_admit(len(req._work), tokens=tok_key,
                                          watermark=None if occupied
                                          else 0)
                if not ok:
                    # strict head-of-line would deadlock a disagg
                    # decode replica: the blocks a cold head request
                    # waits for can be HELD by parked migrated-in
                    # chains queued BEHIND it, and those only free by
                    # being served. Adoption consumes no fresh blocks,
                    # so a parked request may jump a blocked head —
                    # the one break from FIFO, taken only when FIFO
                    # cannot make progress (docs/ROBUSTNESS.md).
                    req = next((r for r in list(self.queue)[1:]
                                if self.cache.has_parked(r.rid)), None)
                    if req is None:
                        break
                    parked = True
                    tok_key = (None if req.adapter_id is not None
                               else req._work)
            cow0 = self.cache.cow_copies
            res0 = self.cache.host_restores
            try:
                if parked:
                    # the prompt's K/V is already resident: prefill
                    # covers only the emitted tail tokens (the same
                    # recompute window a prefix hit leaves), so decode
                    # resumes without re-prefilling the prompt
                    matched = self.cache.adopt_parked(slot, req.rid)
                    try:
                        # the migrated chain covers exactly the prompt;
                        # grow it to cover the emitted tail before the
                        # tail prefill writes there
                        self.cache.ensure_capacity(slot, len(req._work))
                    except CacheExhausted:
                        # cannot grow: degrade to a cold re-prefill —
                        # free the landing and retry the request as a
                        # normal admission (never a wrong token)
                        self.cache.free(slot)
                        break
                else:
                    matched = self.cache.allocate(slot, len(req._work),
                                                  tokens=tok_key)
            except CacheExhausted:
                # an injected (or racing) exhaustion at admission: the
                # request stays at the queue head and retries next step
                break
            if self.costs.enabled:
                # COW copies and host-tier restores the allocation
                # triggered are this request's bytes
                self.costs.charge_cow(req, self.cache.cow_copies - cow0)
                self.costs.charge_spill(self.cache.host_restores - res0,
                                        req=req, restore=True)
            arow = None
            if req.adapter_id is not None:
                try:
                    if self.adapters is None:
                        raise AdapterLoadError(
                            f"request {req.rid} names adapter "
                            f"{req.adapter_id!r} but lora_serve is off")
                    arow = self.adapters.acquire(req.adapter_id)
                except (AdapterLoadError, TransientDeviceError) as e:
                    # structured degradation (docs/ADAPTERS.md): the
                    # request retires with state="error" — the batch
                    # keeps serving, and a slot NEVER decodes with base
                    # (or stale) weights in place of its named adapter
                    self.cache.free(slot)
                    self._unqueue(req)
                    req.state = "error"
                    req.finished_at = now
                    self.finished.append(req)
                    self._stat["adapter_load_errors"].inc()
                    logger.warning(
                        f"serving: adapter {req.adapter_id!r} failed to "
                        f"load for request {req.rid} ({e}); retiring "
                        f"state=error")
                    self.telemetry.tracer.event(
                        "finish", rid=req.rid, step=self._step_clock,
                        state="error", generated=len(req.out))
                    continue
            self._unqueue(req)
            self.slots[slot] = req
            if arow is not None:
                self._slot_arows[slot] = arow
            # prefill resumes at the matched boundary — the shared
            # blocks' K/V is already resident, so those tokens are
            # never recomputed
            self._progress[slot] = matched
            if matched > 0 and not parked:
                self._stat["prefix_hits"].inc()
                self._stat["prefix_tokens_saved"].inc(matched)
            req.state = "prefill"
            req._admit_seq = self._admit_counter
            self._admit_counter += 1
            # sampling lanes for this slot: resolved knobs become the
            # slot-indexed arrays the fused sampler reads; the seen mask
            # seeds from prompt+generated (req._work), so a
            # repetition-penalized request resumes with the identical
            # penalty state after eviction or drain
            params = sampling.resolve_params(req, self.temperature,
                                             self.top_k, self.seed)
            self._slot_params[slot] = params
            self.sampler.admit(slot, params, req._work)
            self._accept_ewma[slot] = 1.0
            self._spec_obs[slot] = 0
            if self._h_temp is not None:
                self._h_temp.observe(params.temperature)
            self._stat["admitted"].inc()
            if self._h_qwait is not None and req.submitted_at is not None:
                self._h_qwait.observe(max(0.0, now - req.submitted_at),
                                      at=now)
            self.telemetry.tracer.event(
                "admit", rid=req.rid, step=self._step_clock, slot=slot,
                matched=int(matched), evictions=req.evictions)

    def _prefill_step(self, now: float) -> None:
        for slot, req in enumerate(self.slots):
            if req is None or req.state != "prefill":
                continue
            done = int(self._progress[slot])
            n = min(self.prefill_chunk, len(req._work) - done)
            chunk = np.zeros((self.prefill_chunk,), np.int32)
            chunk[:n] = req._work[done:done + n]
            # the slot's sampling lane rides every chunk (data, not a
            # signature change); only the FINAL chunk's sample is kept
            lane = self.sampler.lane(slot, len(req.out))
            lora = self._lora_args(slot)
            if self._quant:
                (logits, tok, lp, self.cache.k, self.cache.v,
                 self.cache.k_scale, self.cache.v_scale) = self._device_call(
                    "serving.prefill",
                    lambda *a: self.engine.prefill_into_slot(
                        *a, sample_state=lane, lora=lora),
                    self.cache.k, self.cache.v, self.cache.tables[slot],
                    chunk, done, n, self.cache.k_scale,
                    self.cache.v_scale, now=now)
            else:
                (logits, tok, lp, self.cache.k,
                 self.cache.v) = self._device_call(
                    "serving.prefill",
                    lambda *a: self.engine.prefill_into_slot(
                        *a, sample_state=lane, lora=lora),
                    self.cache.k, self.cache.v, self.cache.tables[slot],
                    chunk, done, n, now=now)
            self.cache.advance(slot, n)
            self._progress[slot] = done + n
            self._stat["prefill_chunks"].inc()
            # one prefill-chunk dispatch: n new tokens over `done`
            # cached context, whole cost owned by this slot's request
            self.costs.charge_prefill(req, n, done)
            self.telemetry.tracer.event(
                "prefill_chunk", rid=req.rid, step=self._step_clock,
                slot=slot, start=done, n=n)
            if self._progress[slot] == len(req._work):
                # prompt fully resident: publish its full blocks to the
                # prefix index (before _emit, which may free the slot)
                # so the NEXT request sharing this prefix skips them —
                # unless this slot decoded under an adapter: its K/V
                # carries that adapter's weights and must never be
                # served to another tenant (docs/ADAPTERS.md)
                if req.adapter_id is None:
                    self.cache.register_prefix(slot, req._work)
                self.telemetry.tracer.event(
                    "prefill_done", rid=req.rid, step=self._step_clock,
                    slot=slot)
                # final chunk: its last-position logits yielded the next
                # token inside the program (== generate()'s prefill
                # sample on the greedy lane; on resume, the recomputed
                # position is exactly the pre-eviction one, and the
                # sampled lane's key fold_in(key, len(out)) replays the
                # identical draw)
                self._emit_sampled(
                    slot, req,
                    int(np.asarray(tok)[0]),  # dslint: disable=DS001 — final chunk only: ONE pull per prefill completion (the prefill-emitted token), not per-chunk work
                    float(np.asarray(lp)[0]),  # dslint: disable=DS001 — same single completion-time pull
                    now)
                if req.state not in TERMINAL_STATES:
                    # prefill-only role: park the finished prefill for
                    # the router's KV migration instead of decoding it
                    req.state = "handoff" if self.prefill_only \
                        else "decode"

    def _decode_step(self, now: float) -> int:
        # every decoding slot needs room for ONE more token; exhaustion
        # evicts the youngest request rather than OOMing the pool
        for slot, req in enumerate(self.slots):
            if req is None or req.state != "decode":
                continue
            if self.cache.at_capacity(slot):
                # block budget exhausted: the kernel's next cache write
                # would clamp into the slot's LAST LIVE block — finish
                # (truncate) the request before it reaches the kernel.
                # Eviction is no escape: the resume prompt is just as
                # long, so a preempted slot would requeue forever.
                logger.warning(
                    f"serving: request {req.rid} hit the per-slot block "
                    f"budget ({self.cache.tokens_per_slot} tokens) in "
                    f"slot {slot}; finishing with {len(req.out)} of "
                    f"{req.max_new_tokens} tokens")
                self._finish(slot, req, now)
                continue
            cow0 = self.cache.cow_copies
            while True:
                try:
                    self.cache.ensure_capacity(
                        slot, int(self.cache.lengths[slot]) + 1)
                    break
                except CacheExhausted:
                    if self._evict_one(exclude=slot):
                        continue
                    # nobody else is evictable: preempt this very
                    # request — unless the storm guard has pinned it,
                    # in which case truncate rather than livelock
                    if req.evictions < self.max_evictions:
                        self._preempt(slot)
                    else:
                        self._stat["evict_capped"].inc()
                        logger.warning(
                            f"serving: request {req.rid} is eviction-"
                            f"pinned ({req.evictions} preemptions) and "
                            f"the pool cannot grow; finishing with "
                            f"{len(req.out)} of {req.max_new_tokens} "
                            f"tokens")
                        self._finish(slot, req, now)
                    break
            if self.costs.enabled:
                # mid-decode divergence copies are this request's bytes
                self.costs.charge_cow(req, self.cache.cow_copies - cow0)
        live = [i for i, r in enumerate(self.slots)
                if r is not None and r.state == "decode"]
        if not live:
            return 0
        if self.spec_decode:
            occ = self._spec_decode_step(live, now)
            if occ is not None:
                return occ
            # draft/verify faulted before dispatch: degrade THIS step to
            # the plain one-token path below (forward progress over
            # speed; the donated pools are intact, the live list is
            # unchanged — no slot was advanced or emitted into)
        elif self.decode_horizon > 1:
            occ = self._horizon_decode_step(live, now)
            if occ is not None:
                return occ
            # horizon faulted before dispatch: degrade THIS step to the
            # plain single-step path below — same contract as spec
            # (pools intact, no slot state moved, never a dropped token)
        tokens = np.zeros((self.num_slots,), np.int32)
        active = np.zeros((self.num_slots,), bool)
        gen_counts = np.zeros((self.num_slots,), np.int32)
        for i in live:
            tokens[i] = self.slots[i].out[-1]
            active[i] = True
            gen_counts[i] = len(self.slots[i].out)
        lanes = self.sampler.lanes(gen_counts)
        budget = self.step_time_budget_s
        t0 = time.perf_counter() if budget is not None else 0.0
        lora = self._lora_args()
        if self._quant:
            (logits, toks, lps, self.cache.k, self.cache.v,
             self.cache.k_scale, self.cache.v_scale) = self._device_call(
                "serving.decode",
                lambda *a: self.engine.decode_slots(
                    *a, sample_state=lanes, lora=lora),
                self.cache.k, self.cache.v, self.cache.tables,
                self.cache.lengths, tokens, active, self.decode_impl,
                self.cache.k_scale, self.cache.v_scale, now=now)
        else:
            (logits, toks, lps, self.cache.k,
             self.cache.v) = self._device_call(
                "serving.decode",
                lambda *a: self.engine.decode_slots(
                    *a, sample_state=lanes, lora=lora),
                self.cache.k, self.cache.v, self.cache.tables,
                self.cache.lengths, tokens, active, self.decode_impl,
                now=now)
        if budget is not None:
            self._watchdog_note(time.perf_counter() - t0)
        self._stat["decode_steps"].inc()
        if self.costs.enabled:
            # one batched dispatch: each live slot decoded 1 token over
            # its own cached context; the weight read splits exactly
            self.costs.charge_batched(
                "decode", [(self.slots[i], 1, int(self.cache.lengths[i]))
                           for i in live])
        # one host transfer covers every slot's token + logprob (the
        # sampler already ran inside the compiled decode program)
        t_dev = time.perf_counter()
        toks = np.asarray(toks)
        lps = np.asarray(lps)
        self.device_time_s += time.perf_counter() - t_dev
        for i in live:
            self.cache.advance(i, 1)
            self._emit_sampled(
                i, self.slots[i], int(toks[i]),
                float(lps[i]), now)  # dslint: disable=DS001 — lps is host numpy already (the single batched pull above)
        return len(live)

    def _horizon_decode_step(self, live: List[int],
                             now: float) -> Optional[int]:
        """One fused multi-step decode over the decoding slots: up to
        ``decode_horizon`` iterations of the decode body in ONE compiled
        dispatch (engine.decode_horizon, docs/MULTISTEP.md), with each
        slot's emission budget and eos/stop predicates freezing finished
        lanes in-program. Admission, eviction, deadline and watchdog
        checks stay at this horizon boundary; the harvest replays each
        slot's produced tokens through the exact N=1 emission
        bookkeeping, so token streams — including mid-horizon stops and
        evict/requeue resumes — are bit-identical to single-step
        serving. Returns the occupancy, or None to degrade this step to
        the plain one-token path (an injected ``serving.horizon`` fault
        fires BEFORE any capacity or slot state moves — degraded
        horizons lose speed, never tokens).

        Capacity is opportunistic, mirroring the speculative path: the
        horizon wants N tokens of room, but a slot that cannot grow
        (pool pressure, per-slot budget) just runs a shorter horizon —
        eviction is never triggered FOR horizon tokens, only for the
        one committed token the plain preamble already guaranteed.
        Deadlined slots cap their budget at the worst-case token-tick
        overshoot, so no token is ever stamped past a deadline the N=1
        loop would have enforced."""
        N = self.decode_horizon
        try:
            self.faults.fire("serving.horizon")
        except TransientDeviceError:
            self._stat["horizon_fallbacks"].inc()
            logger.warning("serving: horizon fault; degrading this step "
                           "to single-step decode")
            return None
        tokens = np.zeros((self.num_slots,), np.int32)
        active = np.zeros((self.num_slots,), bool)
        gen_counts = np.zeros((self.num_slots,), np.int32)
        budgets = np.zeros((self.num_slots,), np.int32)
        eos_ids = np.full((self.num_slots,), -1, np.int32)
        stop_ids = np.zeros((self.num_slots, HORIZON_MAX_STOPS,
                             HORIZON_STOP_WIDTH), np.int32)
        stop_lens = np.zeros((self.num_slots, HORIZON_MAX_STOPS), np.int32)
        tail = np.full((self.num_slots, HORIZON_STOP_WIDTH), -1, np.int32)
        tick = self._token_tick
        for i in live:
            req = self.slots[i]
            tokens[i] = req.out[-1]
            active[i] = True
            gen_counts[i] = len(req.out)
            length = int(self.cache.lengths[i])
            granted = self.cache.horizon_budget(
                i, min(length + N, self.cache.tokens_per_slot))
            # b >= 1 always: the plain preamble secured one token of
            # room, an emitted-out request would already have finished,
            # and _expire retired anything past its deadline
            b = min(N, granted - length,
                    req.max_new_tokens - len(req.out))
            if req.deadline is not None and tick > 0.0:
                b = min(b, max(1, int(math.ceil(
                    (req.deadline - now) / tick))))
            budgets[i] = max(1, b)
            if req.eos_id is not None:
                eos_ids[i] = int(req.eos_id)
            if req.stop:
                row = 0
                for s in req.stop:
                    ls = len(s)
                    if 0 < ls <= HORIZON_STOP_WIDTH \
                            and row < HORIZON_MAX_STOPS:
                        stop_ids[i, row, HORIZON_STOP_WIDTH - ls:] = \
                            [int(t) for t in s]
                        stop_lens[i, row] = ls
                        row += 1
                w = min(len(req.out), HORIZON_STOP_WIDTH)
                if w:
                    tail[i, HORIZON_STOP_WIDTH - w:] = req.out[-w:]
        lanes = self.sampler.lanes(gen_counts)
        budget = self.step_time_budget_s
        t0 = time.perf_counter() if budget is not None else 0.0
        lora = self._lora_args()
        if self._quant:
            (toks, lps, produced, done, self.cache.k, self.cache.v,
             self.cache.k_scale, self.cache.v_scale) = self._device_call(
                "serving.decode",
                lambda *a: self.engine.decode_horizon(
                    *a, sample_state=lanes, lora=lora),
                self.cache.k, self.cache.v, self.cache.tables,
                self.cache.lengths, tokens, active, N, budgets, eos_ids,
                stop_ids, stop_lens, tail, self.decode_impl,
                self.cache.k_scale, self.cache.v_scale, now=now)
        else:
            (toks, lps, produced, done, self.cache.k,
             self.cache.v) = self._device_call(
                "serving.decode",
                lambda *a: self.engine.decode_horizon(
                    *a, sample_state=lanes, lora=lora),
                self.cache.k, self.cache.v, self.cache.tables,
                self.cache.lengths, tokens, active, N, budgets, eos_ids,
                stop_ids, stop_lens, tail, self.decode_impl, now=now)
        if budget is not None:
            self._watchdog_note(time.perf_counter() - t0,
                                scale=int(budgets[live].max()))
        self._stat["decode_steps"].inc()
        # ONE batched host transfer harvests the whole horizon: [N, B]
        # tokens + logprobs and the per-slot produced counts
        t_dev = time.perf_counter()
        toks = np.asarray(toks)
        lps = np.asarray(lps)
        produced = np.asarray(produced)
        self.device_time_s += time.perf_counter() - t_dev
        if self.costs.enabled:
            # one fused dispatch: each live slot produced its own token
            # count over its own pre-advance context
            self.costs.charge_batched(
                "decode",
                [(self.slots[i], int(produced[i]),
                  int(self.cache.lengths[i])) for i in live])
        ticks = 1
        prod_by_slot = {}
        for i in live:
            req = self.slots[i]
            p = int(produced[i])
            prod_by_slot[i] = p
            # one advance covers the whole horizon (p <= the granted
            # capacity by the budget construction above); a mid-harvest
            # finish below frees the slot, releasing any surplus writes
            self.cache.advance(i, p)
            if self._h_horizon is not None:
                self._h_horizon.observe(p)
            for j in range(p):
                self._emit_sampled(
                    i, req, int(toks[j, i]), float(lps[j, i]),  # dslint: disable=DS001 — toks/lps are host numpy already (the single batched pull above)
                    now + j * tick)
                if req.state in TERMINAL_STATES:
                    # an unmodeled stop matched host-side before the
                    # budget ran out: the surplus in-program tokens die
                    # with the freed slot, streams stay exact
                    break
            ticks = max(ticks, p)
        self._horizon_ticks = ticks
        self.telemetry.tracer.event(
            "horizon_step", step=self._step_clock, n=N,
            produced=prod_by_slot)
        return len(live)

    def _spec_decode_step(self, live: List[int], now: float) -> Optional[int]:
        """One speculative iteration over the decoding slots: draft
        ``spec_k`` tokens per slot, verify all ``spec_k + 1`` positions
        in ONE program, accept each slot's draft prefix, emit accepted
        tokens plus the target's correction, roll the cache back past
        the first reject. A temperature=0 slot accepts by greedy-target
        agreement (bit-identical to spec-off greedy serving); a sampled
        slot runs per-position rejection sampling (Leviathan/Chen:
        accept the draft token x with prob min(1, p(x)/q(x)) — q is a
        point mass for the deterministic drafters, so that is p(x) —
        and resamples a rejection from the residual norm(max(0, p-q))),
        which is distribution-lossless against plain sampled decode
        (docs/SAMPLING.md). Returns the occupancy, or None to degrade
        this step to the plain one-token path (an injected draft/verify
        fault — both fire BEFORE dispatch, so no slot state has moved).

        Capacity is opportunistic: the chunk wants ``spec_k + 1`` tokens
        of room, but a slot that cannot grow (pool pressure, per-slot
        budget) just speculates shallower this step — eviction is never
        triggered FOR draft tokens, only for the one committed token the
        plain preamble already guaranteed. Adaptive depth rides the same
        cap: a slot whose acceptance EWMA fell under
        ``spec_accept_floor`` verifies only 1 draft token until its rate
        recovers (the chunk stays ``spec_k + 1`` wide — the static
        verify program never changes — the unverified suffix is simply
        rolled back like any rejection)."""
        G = self.spec_k + 1
        try:
            self.faults.fire("serving.spec_draft")
            proposals = {
                i: np.asarray(  # dslint: disable=DS001 — drafter output is host numpy (prompt-lookup never touches the device); this normalizes dtype/shape, no sync
                    self.draft.propose(self.slots[i].tokens, self.spec_k),
                    np.int32).ravel()
                for i in live}
        except TransientDeviceError:
            self._stat["spec_fallbacks"].inc()
            logger.warning("serving: draft fault; degrading this step "
                           "to plain decode")
            return None
        caps = {}
        for i in live:
            length = int(self.cache.lengths[i])
            want = min(length + G, self.cache.tokens_per_slot)
            if want > self.cache.capacity_tokens(i):
                cow0 = self.cache.cow_copies
                try:
                    self.cache.ensure_capacity(i, want)
                except CacheExhausted:
                    pass      # speculate into whatever room exists
                if self.costs.enabled:
                    self.costs.charge_cow(
                        self.slots[i], self.cache.cow_copies - cow0)
            caps[i] = min(self.cache.capacity_tokens(i),
                          self.cache.tokens_per_slot) - length
        tokens = np.zeros((self.num_slots, G), np.int32)
        active = np.zeros((self.num_slots,), bool)
        for i in live:
            tokens[i, 0] = self.slots[i].out[-1]   # the pending token
            tokens[i, 1:] = proposals[i][:self.spec_k]
            active[i] = True
        budget = self.step_time_budget_s
        t0 = time.perf_counter() if budget is not None else 0.0
        try:
            # no retry wrapper: a verify fault degrades to the plain
            # path (which retries) instead of re-speculating — the fault
            # fires before dispatch, so the donated pools are intact
            lora = self._lora_args()
            if self._quant:
                (logits, self.cache.k, self.cache.v, self.cache.k_scale,
                 self.cache.v_scale) = self.engine.verify_slots(
                    self.cache.k, self.cache.v, self.cache.tables,
                    self.cache.lengths, tokens, active, self.decode_impl,
                    self.cache.k_scale, self.cache.v_scale, lora=lora)
            else:
                logits, self.cache.k, self.cache.v = \
                    self.engine.verify_slots(
                        self.cache.k, self.cache.v, self.cache.tables,
                        self.cache.lengths, tokens, active,
                        self.decode_impl, lora=lora)
        except TransientDeviceError:
            self._stat["spec_fallbacks"].inc()
            logger.warning("serving: verify fault; degrading this step "
                           "to plain decode")
            return None
        if budget is not None:
            self._watchdog_note(time.perf_counter() - t0)
        self._stat["decode_steps"].inc()
        self._stat["spec_steps"].inc()
        if self.costs.enabled:
            # the verify program scores all G chunk positions per live
            # slot whatever gets accepted — the compute is spent either
            # way, so attribution bills the full chunk
            self.costs.charge_batched(
                "verify", [(self.slots[i], G, int(self.cache.lengths[i]))
                           for i in live])
        # the target's greedy choice at every chunk position — the SAME
        # fp32-cast device argmax the fused sampler's greedy lane takes,
        # so accepted tokens are bit-identical to what plain decode
        # would have emitted
        greedy = np.asarray(jax.device_get(  # dslint: disable=DS001 — accept/reject is host control flow; one transfer per verify step replaces spec_k+1 plain-decode transfers
            jnp.argmax(logits.astype(jnp.float32), axis=-1)))
        # sampled slots (and greedy slots that want logprobs) need the
        # full verify logits host-side for the fp64 Leviathan math
        logits_host = None
        if any(self._slot_params[i] is not None
               and (self._slot_params[i].sampled or self.slots[i].logprobs)
               for i in live):
            logits_host = np.asarray(jax.device_get(  # dslint: disable=DS001 — fp64 accept/resample is host math by design; one transfer per verify step
                logits.astype(jnp.float32)))
        proposed = accepted = emitted = 0
        accept_by_slot = {}
        for i in live:
            req = self.slots[i]
            params = self._slot_params[i]
            # leading agreement, capped so lengths never outgrow the
            # blocks actually allocated (caps >= 1: the plain preamble
            # guaranteed room for the committed token)
            k_live = max(0, min(self.spec_k, caps[i] - 1))
            if (self.spec_accept_floor > 0.0 and k_live > 1
                    and self._spec_obs[i] >= self.spec_adapt_warmup
                    and self._accept_ewma[i] < self.spec_accept_floor):
                self._stat["spec_k_capped"].inc()
                k_live = 1
            prop = proposals[i]
            if params is not None and params.sampled:
                # rejection-sampling verify against the target's fp64
                # sampling distributions at each chunk position;
                # position j decides generation index len(out) + j, and
                # the uniforms are Philox(seed, index) — counter-based,
                # so a chunk boundary is invisible to the draw stream
                rows = sampling.fp64_dist(
                    logits_host[i, :k_live + 1], params.temperature,
                    top_k=params.top_k, top_p=params.top_p)
                toks, lps, acc = sampling.spec_verify_tokens(
                    rows, prop[:k_live], params.seed, len(req.out))
            else:
                acc = 0
                while acc < k_live and greedy[i, acc] == prop[acc]:
                    acc += 1
                toks = [int(t) for t in prop[:acc]] + [int(greedy[i, acc])]
                lps = [None] * len(toks)
                if req.logprobs:
                    # log p under plain softmax of the verify logits —
                    # the greedy lane's logprob source in sample_tokens
                    lps = [math.log(max(float(  # dslint: disable=DS001 — fp64 host math over logits_host (already pulled once above), no device sync
                        sampling.fp64_dist(logits_host[i, j], 1.0)[t]),
                        1e-300)) for j, t in enumerate(toks)]
            if k_live > 0:
                self._accept_ewma[i] = (0.8 * self._accept_ewma[i]
                                        + 0.2 * (acc / k_live))
                self._spec_obs[i] += 1
            proposed += k_live
            accepted += acc
            accept_by_slot[i] = acc
            # commit acc + 1 tokens (accepted drafts + the pending one
            # whose K/V this chunk wrote), then trim any tail block only
            # the rejected draft suffix was using
            new_len = int(self.cache.lengths[i]) + acc + 1
            self.cache.advance(i, acc + 1)
            self.cache.rollback(i, new_len)
            self._stat["spec_slot_steps"].inc()
            for tok, lp in zip(toks, lps):
                emitted += 1
                self._emit_sampled(i, req, int(tok), lp, now)
                if req.state in TERMINAL_STATES:
                    break      # max_new/eos truncation, same order as off
        self._stat["spec_proposed"].inc(proposed)
        self._stat["spec_accepted"].inc(accepted)
        self._stat["spec_emitted"].inc(emitted)
        if self._h_accept is not None:
            if proposed:
                self._h_accept.observe(accepted / proposed)
            self._h_tps.observe(emitted / len(live))
        self.telemetry.tracer.event(
            "spec_verify", step=self._step_clock, k=self.spec_k,
            accepted=accept_by_slot, emitted=emitted)
        return len(live)

    # -- helpers ---------------------------------------------------------
    def _spill_step(self) -> None:
        """Host-tier daemon tick: runs right AFTER the decode dispatch
        (the gather it queues overlaps the decode program; last tick's
        gather is harvested here, a full step after dispatch — the
        double buffer) and never on the admission path. Billed inside
        the decode breakdown lap so the phase set is unchanged. The
        tick's host time answers to the step watchdog, but only an
        over-budget tick may strike — an in-budget tick must not reset
        the decode dispatch's own strikes."""
        if not self.host_tier:
            return
        t0 = time.perf_counter()
        sp0 = self.cache.host_spills
        self.cache.spill_tick()
        if self.costs.enabled:
            # refcount-zero spills have no owning request: the bytes
            # land in the accountant's system footprint
            self.costs.charge_spill(self.cache.host_spills - sp0)
        self._sync_host_stats()
        if self.step_time_budget_s is not None:
            elapsed = time.perf_counter() - t0
            if elapsed > self.step_time_budget_s:
                self._watchdog_note(elapsed)

    def _sync_host_stats(self) -> None:
        """Mirror the cache's host-tier counters into the serving stats
        (single source of truth stays in the cache) and feed the
        restore-latency histogram from the samples the cache buffered
        since the last tick."""
        c = self.cache
        self._stat["host_blocks"].set(c.host_blocks)
        self._stat["host_bytes"].set(c.host_bytes)
        self._stat["host_spills"].set(c.host_spills)
        self._stat["host_restores"].set(c.host_restores)
        self._stat["host_restore_failures"].set(c.host_restore_failures)
        samples = c.drain_restore_ms()
        if self._g_host_bytes is not None:
            self._g_host_bytes.set(c.host_bytes)
        if self._h_host_restore is not None:
            for ms in samples:
                self._h_host_restore.observe(ms)

    def _watchdog_note(self, elapsed: float, scale: int = 1) -> None:
        """Score one decode/verify dispatch against the step budget:
        consecutive over-budget dispatches accumulate strikes until the
        grace runs out, then ``step()`` raises DegradedError AFTER this
        step's bookkeeping (nothing lost or double-counted on resume).
        ``scale`` stretches the budget for dispatches that legitimately
        do more than one step of work — a fused horizon doing up to N
        decode iterations answers to N single-step budgets, not one."""
        budget = self.step_time_budget_s * max(1, int(scale))
        if elapsed > budget:
            self._over_budget += 1
            self._stat["watchdog_trips"].inc()
            self.telemetry.tracer.event(
                "watchdog", step=self._step_clock,
                elapsed_s=round(elapsed, 6),
                strikes=self._over_budget)
            if self._over_budget >= self.watchdog_grace:
                self._watchdog_msg = (
                    f"decode step over budget "
                    f"({elapsed * 1e3:.1f}ms > "
                    f"{budget * 1e3:.1f}ms) {self._over_budget} "
                    f"consecutive times — degraded")
        else:
            self._over_budget = 0
    def _deadline_slack(self, now: Optional[float]) -> Optional[float]:
        """Tightest remaining deadline margin among active slots (the
        requests a retry sleep would stall), or None when no slot
        carries a deadline. Clamped at 0 — an already-expired request
        must not turn the cap negative."""
        if now is None:
            return None
        slack = None
        for req in self.slots:
            if req is None or req.deadline is None:
                continue
            remain = max(0.0, req.deadline - now)
            slack = remain if slack is None else min(slack, remain)
        return slack

    def _device_call(self, site: str, fn, *args, now: Optional[float] = None):
        """Run a slot program with fault injection + transient-error
        retry. Faults (and any real pre-dispatch failure) fire BEFORE
        ``fn`` touches the donated pools, so a retry re-dispatches
        against intact buffers; backoff doubles per attempt with
        deterministic jitter from the injector's seeded rng. Each sleep
        is capped at the tightest remaining deadline among active slots
        (``now`` is the scheduler-clock step stamp): a backoff can
        never sleep a live request past its deadline — with no margin
        left, retries spin immediately and expiry decides at the next
        step."""
        delay = self.retry_backoff_s
        attempt = 0
        while True:
            try:
                self.faults.fire(site)
                # block inside the timed window: dispatch is async, and
                # every caller harvests the result immediately anyway —
                # blocking here makes device_time_s (the bench's
                # host/device ms-per-token split) and the watchdog's
                # elapsed measurement cover the actual execution instead
                # of just the enqueue
                t_dev = time.perf_counter()
                out = jax.block_until_ready(fn(*args))
                self.device_time_s += time.perf_counter() - t_dev
                return out
            except TransientDeviceError:
                if attempt >= self.max_retries:
                    raise
                attempt += 1
                self._stat["retries"].inc()
                pause = min(delay + self.faults.jitter(delay * 0.5), 0.5)
                slack = self._deadline_slack(now)
                if slack is not None:
                    pause = min(pause, slack)
                logger.warning(
                    f"serving: transient device error at {site} "
                    f"(attempt {attempt}/{self.max_retries}); retrying "
                    f"in {pause * 1e3:.1f}ms")
                time.sleep(pause)
                delay *= 2

    def _update_backpressure(self) -> None:
        if self.max_queue:
            self._stat["backpressure"].set(round(
                len(self.queue) / self.max_queue, 4))
        else:
            self._stat["backpressure"].set(0.0)

    def _sync_devices(self) -> None:
        """Sampled-step barrier (utils/timer device-sync discipline):
        drain pending pool work so a breakdown lap bills device time to
        the phase that dispatched it. Only the breakdown calls this,
        and only on sampled steps — the unsampled hot path stays
        sync-free (dslint DS001)."""
        if self._quant:
            jax.block_until_ready((self.cache.k, self.cache.v,
                                   self.cache.k_scale,
                                   self.cache.v_scale))
        else:
            jax.block_until_ready((self.cache.k, self.cache.v))

    def _sample_gauges(self) -> None:
        """Sampled-step gauge refresh: HBM block states + prefix hit
        rate. Host numpy reductions — cheap, but they ride the
        breakdown's sampling cadence, not every step."""
        self._g_held.set(int(self.cache.held_blocks))
        self._g_cached.set(int(self.cache.cached_blocks))
        self._g_free.set(int(self.cache.free_blocks))
        admitted = self._stat["admitted"].value
        self._g_hit_rate.set(
            round(self._stat["prefix_hits"].value / admitted, 4)
            if admitted else 0.0)
        if self._h_kv_err is not None:
            # half the hottest block's quantization step — an upper
            # bound on the elementwise |dequant - original| error; one
            # device_get, riding the sampled cadence only
            step = jax.device_get(jnp.maximum(  # dslint: disable=DS001 — sampled-cadence pull, mirrors the gauge refresh above
                jnp.max(self.cache.k_scale), jnp.max(self.cache.v_scale)))
            self._h_kv_err.observe(float(step) / 2.0)

    def _degraded(self, message: str) -> DegradedError:
        # the flight recorder fires BEFORE the error leaves the engine:
        # whatever the caller does with the exception, the postmortem
        # artifact is already on disk (noop twin when the recorder is
        # off — one attribute access on this already-cold path)
        self.flight.dump(f"degraded: {message}")
        return DegradedError(
            message,
            results={r.rid: r.tokens for r in self.finished},
            finished=list(self.finished),
            pending=self.pending_snapshot(),
            stats=dict(self.stats))

    def device_time_snapshot(self) -> float:
        """Monotonic snapshot of cumulative device dispatch+harvest wall
        seconds. ``device_time_s`` accumulates for the engine's whole
        lifetime; a bench timing one drive among many must take a
        before/after delta of THIS value instead of reading the raw
        accumulator (tools/infer_bench.py min-of-k loops)."""
        return float(self.device_time_s)

    def capture_profile(self, steps: int, outdir: str,
                        now: Optional[float] = None) -> str:
        """On-demand ``jax.profiler`` capture window: trace exactly
        ``steps`` scheduler iterations (each a horizon boundary — the
        capture never straddles a partial fused dispatch) into
        ``outdir`` (TensorBoard/XProf layout; ``tools/trace_analyze.py
        read <outdir>`` summarizes it). Returns ``outdir``."""
        jax.profiler.start_trace(outdir)
        try:
            for _ in range(max(1, int(steps))):
                if not self.busy:
                    break
                self.step(now)
        finally:
            jax.profiler.stop_trace()
        return outdir

    def _release_adapter(self, slot: int, req: ServeRequest) -> None:
        """Drop the slot's adapter pin (if it holds one) and zero its
        table row. The nonzero row IS the pin marker — a request whose
        acquire failed never set it, so release stays balanced."""
        if self.adapters is None or req.adapter_id is None:
            return
        if not self._slot_arows[slot].any():
            return
        self.adapters.release(req.adapter_id)
        self._slot_arows[slot] = 0

    def _finish(self, slot: int, req: ServeRequest, now: float,
                state: str = "done") -> None:
        """Retire a request: blocks back to the pool, slot reopened."""
        req.state = state
        req.finished_at = now
        self._release_adapter(slot, req)
        self.cache.free(slot)
        self.slots[slot] = None
        self.sampler.release(slot)
        self._slot_params[slot] = None
        self.finished.append(req)
        if state == "timeout":
            self._stat["timeouts"].inc()
        else:
            self._stat["completed"].inc()
        self.telemetry.tracer.event(
            "finish", rid=req.rid, step=self._step_clock, slot=slot,
            state=state, generated=len(req.out))

    def _emit_sampled(self, slot: int, req: ServeRequest, tok: int,
                      lp: Optional[float], now: float) -> None:
        """Emit one token the fused sampler (or the spec verify)
        already chose: record its logprob, feed the repetition-penalty
        seen mask, count sampled lanes, then run the shared terminal-
        state bookkeeping."""
        self.sampler.observe(slot, tok)
        if req.logprobs and lp is not None:
            req.out_logprobs.append(float(lp))
        if self.sampler.temps[slot] > 0.0:
            self._stat["sampled_tokens"].inc()
        self._emit_token(slot, req, tok, now)

    def _emit_token(self, slot: int, req: ServeRequest, tok: int,
                    now: float) -> None:
        """Record one emitted token: output list, latency stamps,
        TTFT/TPOT histograms, terminal-state check (stop sequence,
        max_new, eos). Tokens arrive already chosen — by the fused
        in-program sampler or by the speculative verify."""
        prev = req.token_times[-1] if req.token_times else None
        req.out.append(tok)
        req.token_times.append(now)
        if req.first_token_at is None:
            req.first_token_at = now
            if self._h_ttft is not None and req.submitted_at is not None:
                self._h_ttft.observe(max(0.0, now - req.submitted_at),
                                     at=now)
            self.telemetry.tracer.event(
                "first_token", rid=req.rid, step=self._step_clock, slot=slot)
        elif self._h_tpot is not None and prev is not None:
            self._h_tpot.observe(max(0.0, now - prev), at=now)
        if req.stop:
            for s in req.stop:
                ls = len(s)
                if ls and len(req.out) >= ls \
                        and req.out[-ls:] == [int(t) for t in s]:
                    # matched stop tokens stay IN out: the resume/drain
                    # contract replays the true emitted stream
                    self._stat["stop_hits"].inc()
                    self._finish(slot, req, now)
                    return
        if (len(req.out) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id)):
            self._finish(slot, req, now)

    def _evict_one(self, exclude: int) -> bool:
        """Preempt the most recently admitted live request (LIFO — the
        oldest work is closest to done) other than ``exclude``, skipping
        requests at the eviction cap: a pinned request cannot be chosen
        again, so the oldest victim of a storm is guaranteed forward
        progress."""
        victim = None
        capped = 0
        for i, r in enumerate(self.slots):
            if i == exclude or r is None:
                continue
            if r.evictions >= self.max_evictions:
                capped += 1
                continue
            if victim is None or r._admit_seq > self.slots[victim]._admit_seq:
                victim = i
        if victim is None:
            if capped:
                self._stat["evict_capped"].inc(capped)
            return False
        self._preempt(victim)
        return True

    def _preempt(self, slot: int) -> None:
        """Free the slot and requeue its request for recompute-on-resume:
        the new working prompt is prompt+generated, whose re-prefill
        reproduces the pre-eviction cache and next-token logits exactly."""
        req = self.slots[slot]
        logger.info(f"serving: evicting request {req.rid} from slot {slot} "
                    f"({self.cache.free_blocks} blocks free)")
        req._work = req.tokens
        req.state = "queued"
        req.evictions += 1
        self._stat["evictions"].inc()
        self.telemetry.tracer.event(
            "evict", rid=req.rid, step=self._step_clock, slot=slot,
            generated=len(req.out))
        self._release_adapter(slot, req)
        self.cache.free(slot)
        self.slots[slot] = None
        self.sampler.release(slot)
        self._slot_params[slot] = None
        self.queue.appendleft(req)
