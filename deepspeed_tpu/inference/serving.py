"""Continuous-batching serving scheduler over the paged KV-cache.

The static engine runs ONE fixed batch to completion: every row pays for
the slowest request, and a new arrival waits for the whole batch to
drain. This scheduler implements iteration-level (continuous) batching
as in Orca (Yu et al., OSDI '22): a fixed set of decode SLOTS, and on
every iteration

1. **admission** — queued requests claim free slots if the paged cache
   can cover their prompt while keeping the watermark reserve;
2. **prefill** — newly admitted requests prefill their prompt into
   their slot in fixed-width CHUNKS (one chunk per iteration per slot),
   so a long prompt never stalls the running decode batch for more than
   one chunk's latency;
3. **decode** — all decoding slots advance one token through the single
   compiled ``decode_slots`` program, each at its own position.

On cache exhaustion mid-decode the scheduler EVICTS the most recently
admitted request instead of OOMing: its blocks return to the pool and
the request requeues (front of the queue) with prompt+generated as its
new prompt — recompute-on-resume reproduces the exact pre-eviction
state, so greedy outputs are untouched (vLLM's recompute preemption).

The steady state is two compiled programs (prefill chunk, slot decode)
regardless of arrival pattern; all scheduling state is host numpy.

Greedy parity contract (tested): for any arrival pattern, every
request's output is token-for-token identical to a solo
``InferenceEngine.generate`` run of its prompt.
"""

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.paged_cache import CacheExhausted, PagedKVCache
from deepspeed_tpu.utils.logging import logger


@dataclass
class ServeRequest:
    """One generation request. ``out`` accumulates generated token ids;
    ``token_times`` the scheduler-clock stamp of each emitted token (the
    bench derives per-token latency percentiles from these)."""
    rid: Any
    prompt: np.ndarray
    max_new_tokens: int = 32
    eos_id: Optional[int] = None
    out: List[int] = field(default_factory=list)
    state: str = "queued"            # queued | prefill | decode | done
    token_times: List[float] = field(default_factory=list)
    submitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    evictions: int = 0
    _admit_seq: int = -1             # eviction picks the youngest
    _work: Optional[np.ndarray] = None   # prompt (+generated, on resume)

    @property
    def tokens(self) -> np.ndarray:
        """prompt + generated, the generate()-shaped result row."""
        return np.concatenate(
            [self.prompt, np.asarray(self.out, np.int32)])


class ServingEngine:
    """Continuous-batching front end for an ``InferenceEngine``.

    ``num_blocks``/``hbm_budget_bytes`` bound the paged cache (the HBM
    watermark); ``num_slots`` bounds the decode batch; ``prefill_chunk``
    bounds how much prompt work one iteration may do (decode latency
    stays O(chunk) under long-prompt arrivals).
    """

    def __init__(self, engine, *, num_slots: int = 4, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 prefill_chunk: int = 64, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0,
                 decode_impl: Optional[str] = None):
        if engine.is_encoder:
            raise ValueError("serving needs a causal decoder engine")
        self.engine = engine
        # decode attention path ("pallas" flash-decode through the block
        # table | "gather" dense reference); defaults to the engine's
        # resolved choice so env/platform selection applies uniformly.
        # Pinned for the run: impl is a static jit arg, so ONE impl keeps
        # steady state at two compiled programs.
        if decode_impl is None:
            self.decode_impl = engine.decode_impl
        else:
            from deepspeed_tpu.ops.attention.paged import resolve_decode_impl
            self.decode_impl = resolve_decode_impl(decode_impl)
        self.cache = PagedKVCache(
            engine.cfg, num_slots=num_slots, block_size=block_size,
            num_blocks=num_blocks, hbm_budget_bytes=hbm_budget_bytes,
            dtype=engine.dtype, max_seq_len=engine.max_seq_len)
        mesh = getattr(engine, "mesh", None)
        if mesh is not None:
            # place the fresh pools exactly where the jitted programs
            # will put them (replicated over the engine mesh): a first
            # prefill call with differently-placed pools keys a second,
            # single-use executable — one whole wasted XLA compile at
            # cold start (caught by test_serving_compile_count_contract)
            from jax.sharding import NamedSharding, PartitionSpec
            pool_sh = NamedSharding(mesh, PartitionSpec())
            self.cache.k = jax.device_put(self.cache.k, pool_sh)
            self.cache.v = jax.device_put(self.cache.v, pool_sh)
        self.num_slots = num_slots
        self.prefill_chunk = int(prefill_chunk)
        self.temperature = temperature
        self.top_k = top_k
        self._rng = jax.random.PRNGKey(seed)
        self.queue: deque = deque()
        self.slots: List[Optional[ServeRequest]] = [None] * num_slots
        self.finished: List[ServeRequest] = []
        self._progress = np.zeros((num_slots,), np.int64)  # prefilled toks
        self._admit_counter = 0
        self.stats = {"steps": 0, "occupancy_sum": 0, "peak_occupancy": 0,
                      "evictions": 0, "admitted": 0, "completed": 0,
                      "prefill_chunks": 0, "decode_steps": 0}

    # -- API -----------------------------------------------------------
    def submit(self, req: ServeRequest, now: float = 0.0) -> None:
        total = len(req.prompt) + req.max_new_tokens
        if total > self.engine.max_seq_len:
            raise ValueError(
                f"request {req.rid}: prompt {len(req.prompt)} + "
                f"max_new {req.max_new_tokens} exceeds max_seq_len "
                f"{self.engine.max_seq_len}")
        if self.cache.blocks_for(total) > self.cache.num_blocks - 1:
            raise ValueError(
                f"request {req.rid} needs more blocks than the whole pool")
        req.submitted_at = now
        req._work = np.asarray(req.prompt, np.int32)
        self.queue.append(req)

    @property
    def busy(self) -> bool:
        return bool(self.queue) or any(s is not None for s in self.slots)

    def step(self, now: Optional[float] = None) -> int:
        """One scheduler iteration: admit, prefill chunks, decode.
        Returns the number of decoding slots this iteration (the
        occupancy sample)."""
        if now is None:
            now = float(self.stats["steps"])
        self._admit()
        self._prefill_step(now)
        occ = self._decode_step(now)
        self.stats["steps"] += 1
        self.stats["occupancy_sum"] += occ
        self.stats["peak_occupancy"] = max(self.stats["peak_occupancy"], occ)
        return occ

    def run(self, requests=None, max_steps: int = 1_000_000,
            wall_clock: bool = False) -> Dict[Any, np.ndarray]:
        """Drain: submit ``requests`` (if given) and step until idle.
        Returns {rid: prompt+generated} like stacked generate() rows."""
        done: Dict[Any, np.ndarray] = {}
        for r in (requests or []):
            self.submit(r)
        steps = 0
        while self.busy:
            self.step(time.perf_counter() if wall_clock else None)
            steps += 1
            if steps > max_steps:
                raise RuntimeError(f"serving did not drain in {max_steps} "
                                   f"steps (queue {len(self.queue)})")
        for r in self.finished:
            done[r.rid] = r.tokens
        return done

    # -- phases ----------------------------------------------------------
    def _admit(self) -> None:
        # FIFO head-of-line: no queue jumping, so a preempted-and-
        # requeued request (appendleft) resumes before newer arrivals
        while self.queue:
            slot = next((i for i, s in enumerate(self.slots) if s is None),
                        None)
            if slot is None:
                break
            req = self.queue[0]
            occupied = any(s is not None for s in self.slots)
            if occupied:
                ok = self.cache.can_admit(len(req._work))
            else:
                # idle engine: skip the watermark so a lone request that
                # fits the pool always makes progress (no livelock)
                ok = (self.cache.blocks_for(len(req._work))
                      <= self.cache.free_blocks)
            if not ok:
                break
            self.queue.popleft()
            self.cache.allocate(slot, len(req._work))
            self.slots[slot] = req
            self._progress[slot] = 0
            req.state = "prefill"
            req._admit_seq = self._admit_counter
            self._admit_counter += 1
            self.stats["admitted"] += 1

    def _prefill_step(self, now: float) -> None:
        for slot, req in enumerate(self.slots):
            if req is None or req.state != "prefill":
                continue
            done = int(self._progress[slot])
            n = min(self.prefill_chunk, len(req._work) - done)
            chunk = np.zeros((self.prefill_chunk,), np.int32)
            chunk[:n] = req._work[done:done + n]
            logits, self.cache.k, self.cache.v = \
                self.engine.prefill_into_slot(
                    self.cache.k, self.cache.v, self.cache.tables[slot],
                    chunk, done, n)
            self.cache.advance(slot, n)
            self._progress[slot] = done + n
            self.stats["prefill_chunks"] += 1
            if self._progress[slot] == len(req._work):
                # final chunk: its last-position logits yield the next
                # token (== generate()'s prefill sample; on resume, the
                # recomputed position is exactly the pre-eviction one)
                self._emit(slot, req, logits, now)
                if req.state != "done":
                    req.state = "decode"

    def _decode_step(self, now: float) -> int:
        # every decoding slot needs room for ONE more token; exhaustion
        # evicts the youngest request rather than OOMing the pool
        for slot, req in enumerate(self.slots):
            if req is None or req.state != "decode":
                continue
            if self.cache.at_capacity(slot):
                # block budget exhausted: the kernel's next cache write
                # would clamp into the slot's LAST LIVE block — finish
                # (truncate) the request before it reaches the kernel.
                # Eviction is no escape: the resume prompt is just as
                # long, so a preempted slot would requeue forever.
                logger.warning(
                    f"serving: request {req.rid} hit the per-slot block "
                    f"budget ({self.cache.tokens_per_slot} tokens) in "
                    f"slot {slot}; finishing with {len(req.out)} of "
                    f"{req.max_new_tokens} tokens")
                self._finish(slot, req, now)
                continue
            while True:
                try:
                    self.cache.ensure_capacity(
                        slot, int(self.cache.lengths[slot]) + 1)
                    break
                except CacheExhausted:
                    if not self._evict_one(exclude=slot):
                        # last resort: preempt this very request
                        self._preempt(slot)
                        break
        live = [i for i, r in enumerate(self.slots)
                if r is not None and r.state == "decode"]
        if not live:
            return 0
        tokens = np.zeros((self.num_slots,), np.int32)
        active = np.zeros((self.num_slots,), bool)
        for i in live:
            tokens[i] = self.slots[i].out[-1]
            active[i] = True
        logits, self.cache.k, self.cache.v = self.engine.decode_slots(
            self.cache.k, self.cache.v, self.cache.tables,
            self.cache.lengths, tokens, active, impl=self.decode_impl)
        self.stats["decode_steps"] += 1
        for i in live:
            self.cache.advance(i, 1)
            self._emit(i, self.slots[i], logits[i:i + 1], now)
        return len(live)

    # -- helpers ---------------------------------------------------------
    def _finish(self, slot: int, req: ServeRequest, now: float) -> None:
        """Retire a request: blocks back to the pool, slot reopened."""
        req.state = "done"
        req.finished_at = now
        self.cache.free(slot)
        self.slots[slot] = None
        self.finished.append(req)
        self.stats["completed"] += 1

    def _emit(self, slot: int, req: ServeRequest, logits, now: float) -> None:
        self._rng, r = jax.random.split(self._rng)
        tok = int(np.asarray(self.engine._sample(
            logits, r, self.temperature, self.top_k))[0])
        req.out.append(tok)
        req.token_times.append(now)
        if req.first_token_at is None:
            req.first_token_at = now
        if (len(req.out) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id)):
            self._finish(slot, req, now)

    def _evict_one(self, exclude: int) -> bool:
        """Preempt the most recently admitted live request (LIFO — the
        oldest work is closest to done) other than ``exclude``."""
        victim = None
        for i, r in enumerate(self.slots):
            if i == exclude or r is None:
                continue
            if victim is None or r._admit_seq > self.slots[victim]._admit_seq:
                victim = i
        if victim is None:
            return False
        self._preempt(victim)
        return True

    def _preempt(self, slot: int) -> None:
        """Free the slot and requeue its request for recompute-on-resume:
        the new working prompt is prompt+generated, whose re-prefill
        reproduces the pre-eviction cache and next-token logits exactly."""
        req = self.slots[slot]
        logger.info(f"serving: evicting request {req.rid} from slot {slot} "
                    f"({self.cache.free_blocks} blocks free)")
        req._work = req.tokens
        req.state = "queued"
        req.evictions += 1
        self.stats["evictions"] += 1
        self.cache.free(slot)
        self.slots[slot] = None
        self.queue.appendleft(req)

