"""Replica-fleet serving router: health-gated dispatch over N
:class:`~deepspeed_tpu.inference.serving.ServingEngine` replicas.

A single serving engine is a single failure domain: one watchdog trip
or wedged device degrades ALL in-flight traffic. The router is the
scale-out tier above it (the Orca/vLLM deployment shape): N replicas —
each holding its own paged KV pool and slots — behind one
:class:`~deepspeed_tpu.inference.serving.ServeRequest`-shaped front
door, stepped round-robin in one host loop.

**Dispatch** is least-loaded and deadline-aware, read off each
replica's live scheduler state (queue depth + occupied slots — the
same numbers its registry-backed ``stats`` export): a request lands on
the replica with the most headroom. Requests WITHOUT a deadline first
consult the prefix-affinity map — same-leading-tokens traffic (shared
system prompts) returns to the replica whose prefix-cache blocks are
already warm, unless that replica is more than
``affinity_max_imbalance`` requests busier than the best candidate.
Deadline-carrying requests skip affinity entirely: their enemy is
queue wait, not a cold prefill.

**Health** is a per-replica state machine with a consecutive-failure
circuit breaker::

    healthy --failure--> suspect --(breaker_threshold)--> broken
       ^                   |                                 |
       |<----success-------+                          warm restart
       |                                                     v
       +<--- probe completes --- recovering <----------------+

A transient failure (retry exhaustion, an injected ``device_error`` at
``router.step``) moves the replica to ``suspect``; ``breaker_threshold``
consecutive failures trip the breaker to ``broken``. A ``crash`` or a
replica-raised :class:`DegradedError` breaks it immediately. A broken
replica takes no traffic until :meth:`restart_replica` rebuilds it via
``replica_factory`` — warm-started from the newest VALID crash-safe
checkpoint tag (``runtime/checkpointing.py`` walk-back: the ``latest``
pointer if it validates, else newest-first over ``list_tags``) — and it
rejoins as ``recovering``: half-open, admitting at most
``probe_admissions`` in-flight probe requests; the first probe that
completes cleanly closes the breaker (``healthy``), a failure while
recovering re-opens it.

**Drain** is the failure-isolation contract: when a replica breaks,
the router merges its finished ``results``, takes its
``pending_snapshot(release=True)`` (freeing the dead pool's block refs
including prefix-cache pins), dedups entries already terminal
fleet-wide, and resubmits the remainder onto survivors. A resumed
request re-prefills prompt + already-emitted tokens — the same
recompute-on-resume path eviction uses — so drained output is
TOKEN-IDENTICAL to an undisturbed run: greedy trivially, and sampled
requests too, because the per-token sampling key is a pure function
of (seed, tokens emitted so far), so seed + ``out`` in the snapshot
IS the key-chain state (docs/SAMPLING.md; tests/test_router.py and
tests/test_sampling.py pin both against solo references). When no dispatchable replica remains the
router raises a fleet-level :class:`DegradedError` carrying merged
results and the orphaned pending entries: total degrade still loses
nothing.

**Chaos**: three new fault sites — ``router.dispatch`` (after target
choice, before submit), ``router.step`` (before each per-replica
step), ``router.drain`` (before any drain state moves) — all fire
before state mutates, so retries replay safely. The router itself is
pure host scheduling: it adds ZERO device programs, and replicas
sharing one ``InferenceEngine`` share its per-instance executables, so
the fleet holds the serving compile contract (2 programs + 1 spec
+ 1 COW) under active chaos.

**Telemetry** (docs/OBSERVABILITY.md): ``router_*`` metrics — per-
replica health gauges (``router_replica_health_r<i>``: 0 healthy /
1 suspect / 2 broken / 3 recovering / 4 retired), replicas-by-state
gauges (``router_replicas_<state>``), ``router_drained_requests``,
``router_breaker_trips``, a ``router_dispatch_queue_wait`` histogram —
plus ``dispatch`` / ``drain`` / ``breaker`` / ``restart`` / ``scale``
tracer events in the same timeline as the replicas' request
lifecycles. :meth:`ReplicaRouter.fleet_snapshot` and the router's
:meth:`~ReplicaRouter.to_prometheus` merge every distinct registry in
the fleet (``telemetry.metrics.merge_registries``) into one view.

**Elasticity**: the fleet is no longer fixed-size. :meth:`add_replica`
grows it (via an explicit engine or ``replica_factory`` warm-started
from the newest valid checkpoint tag); :meth:`retire_replica` drains a
replica's in-flight work onto survivors through the SAME snapshot path
a breaker drain uses and parks it ``retired`` (terminal: never stepped,
never dispatched to). An optional ``autoscale`` controller
(:class:`~deepspeed_tpu.inference.autoscale.SLOController`) is ticked
once per :meth:`step` and drives both actuators plus the
``shed_batch`` admission gate from windowed fleet metrics — default
``None``, in which case router behavior is bit-identical to the
fixed-fleet shape (docs/OBSERVABILITY.md).

**Disaggregation** (docs/ROBUSTNESS.md): replicas optionally carry a
role — ``prefill`` / ``decode`` / ``mixed`` (the default; ``roles=None``
keeps the fleet bit-identical to the role-less shape). A prefill
replica runs chunked prefill only: it emits the FIRST token (TTFT is
stamped where the prefill ran), parks the request in a ``handoff``
slot, and the router migrates the finished KV prefix to a decode
replica through a CRC-verified host-DRAM staging pool — the host
tier's gather/scatter transfer path generalized replica-to-replica
(per-array CRC32 at put, free-list-only landing at the destination,
int8 ``_q`` twins carrying their scale sidecars). The request itself
rides the snapshot envelope (``snapshot_entry`` extended with a
``kv_handle``) and resumes decode WITHOUT re-prefilling: admission
adopts the parked chain. Three chaos sites guard the channel —
``router.migrate_gather``, ``router.migrate_scatter``,
``router.migrate_corrupt`` — and the ladder is absolute: ANY failure
(transient device error, CRC mismatch, host-budget or capacity
refusal, crash, mid-migration retire or breaker-break) discards the
partial landing, frees both sides, and re-dispatches the request for
a cold re-prefill on the decode side. Token-identical either way,
because snapshot resume re-prefills prompt + already-emitted tokens
and the sampling key chain is position-pure (docs/SAMPLING.md).
``router_migrations`` / ``router_migration_fallbacks`` count the two
outcomes, ``router_replicas_role_<role>`` gauges the pool shapes, and
the ``migrate`` tracer event records every attempt.
"""

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

import numpy as np

from deepspeed_tpu.inference.host_tier import HostBlockPool, HostCorruption
from deepspeed_tpu.inference.paged_cache import CacheExhausted
from deepspeed_tpu.inference.serving import (DegradedError, ServeRequest,
                                             ServingEngine, _StatsView,
                                             snapshot_entry)
from deepspeed_tpu.runtime.checkpointing import (get_latest_tag, list_tags,
                                                 validate_tag)
from deepspeed_tpu.telemetry import (NOOP, MetricsRegistry, NoopTelemetry,
                                     Telemetry, merge_registries,
                                     resolve_telemetry)
from deepspeed_tpu.telemetry.flight import FlightRecorder, NOOP_FLIGHT
from deepspeed_tpu.utils import faults as faults_lib
from deepspeed_tpu.utils.env import flag_names, resolve_flag
from deepspeed_tpu.utils.faults import InjectedCrash, TransientDeviceError
from deepspeed_tpu.utils.logging import logger

# health states, in escalation order; gauge codes are the indices.
# RETIRED is terminal and reachable only through retire_replica (scale-
# down) — unlike BROKEN it is deliberate, drained, and never restarted.
HEALTHY, SUSPECT, BROKEN, RECOVERING, RETIRED = (
    "healthy", "suspect", "broken", "recovering", "retired")
HEALTH_CODES = {HEALTHY: 0, SUSPECT: 1, BROKEN: 2, RECOVERING: 3,
                RETIRED: 4}

# replica roles (disaggregated prefill/decode fleets): a "prefill"
# replica runs chunked prefill only and hands finished prefixes off; a
# "decode" replica lands migrations and decodes; "mixed" (the default)
# does both — an all-mixed fleet is bit-identical to the role-less one.
ROLES = ("prefill", "decode", "mixed")

_ROUTER_STAT_FIELDS = (
    ("steps", "c", "router scheduler iterations"),
    ("dispatched", "c", "requests dispatched to a replica"),
    ("affinity_hits", "c", "dispatches routed by prefix affinity"),
    ("adapter_affinity_hits", "c", "dispatches routed by adapter affinity "
                                   "(the target already holds the "
                                   "request's LoRA adapter pool-resident)"),
    ("redispatches", "c", "dispatch retries after a dispatch-site fault"),
    ("drained_requests", "c",
     "in-flight requests drained from a broken replica onto survivors"),
    ("breaker_trips", "c", "circuit-breaker openings (replica -> broken)"),
    ("restarts", "c", "replica warm restarts"),
    ("fleet_degraded", "c",
     "total-degrade events (no dispatchable replica left)"),
    ("scale_ups", "c", "replicas added to the fleet (add_replica)"),
    ("retires", "c", "replicas retired from the fleet (retire_replica)"),
    ("shed", "c",
     "requests shed router-side by the tightened-admission gate"),
    ("migrations", "c",
     "KV migrations landed prefill->decode (disaggregated handoff)"),
    ("migration_fallbacks", "c",
     "migrations degraded to a cold re-prefill on the decode side"),
)


class _Replica:
    """Router-side record for one replica: the engine, its health
    state, the consecutive-failure count the breaker watches, and the
    probe rids whose clean completion closes a half-open breaker."""

    def __init__(self, idx: int, srv: ServingEngine,
                 role: str = "mixed"):
        self.idx = idx
        self.srv = srv
        self.role = role
        self.health = HEALTHY
        self.failures = 0            # consecutive, reset on success
        self.probe_rids: Set[Any] = set()
        self.restarts = 0


class ReplicaRouter:
    """Least-loaded / deadline-aware / prefix-affine dispatcher over N
    serving replicas with circuit-breaker health tracking and drain-on-
    failure (module docstring has the full contract).

    - ``replicas``: the ServingEngine fleet (sharing one
      ``InferenceEngine`` shares its compiled programs).
    - ``roles``: optional per-replica role list (``prefill`` /
      ``decode`` / ``mixed``); None = all ``mixed``, bit-identical to
      the role-less fleet (module docstring, **Disaggregation**).
    - ``replica_factory``: ``(replica_id, checkpoint_tag) ->
      ServingEngine`` used by :meth:`restart_replica`; ``ckpt_dir``
      points the warm restart at a crash-safe checkpoint directory
      (tag resolved by newest-valid walk-back, None when absent).
    - ``breaker_threshold``: consecutive transient failures before the
      breaker trips the replica to ``broken``.
    - ``probe_admissions``: max in-flight requests a ``recovering``
      replica may hold (half-open admission window).
    - ``affinity_tokens`` / ``affinity_max_imbalance``: prefix-affinity
      key width and the extra backlog an affine replica may carry
      before least-loaded wins.
    - ``faults`` / ``telemetry``: as on ``ServingEngine`` (pass one
      shared :class:`~deepspeed_tpu.telemetry.Telemetry` to aggregate
      fleet metrics into one registry).
    - ``autoscale``: optional SLO controller with an
      ``on_step(router, now)`` hook, ticked once per :meth:`step`
      (see :mod:`deepspeed_tpu.inference.autoscale`). Default None —
      the fixed-fleet bit-reference.
    """

    def __init__(self, replicas: Sequence[ServingEngine], *,
                 roles: Optional[Sequence[str]] = None,
                 replica_factory: Optional[Callable] = None,
                 ckpt_dir: Optional[str] = None,
                 breaker_threshold: int = 3,
                 probe_admissions: int = 2,
                 affinity_tokens: int = 16,
                 affinity_max_imbalance: int = 4,
                 faults: Optional[faults_lib.FaultInjector] = None,
                 telemetry=None,
                 autoscale=None,
                 flight_recorder: Optional[bool] = None,
                 flight_dir: Optional[str] = None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        role_list = (["mixed"] * len(replicas) if roles is None
                     else [str(r) for r in roles])
        if len(role_list) != len(replicas):
            raise ValueError("roles must name one role per replica")
        for r in role_list:
            if r not in ROLES:
                raise ValueError(f"unknown replica role {r!r} "
                                 f"(expected one of {ROLES})")
        if "prefill" in role_list and not any(
                r != "prefill" for r in role_list):
            raise ValueError(
                "a disaggregated fleet needs at least one decode-"
                "capable (decode/mixed) replica")
        self.replicas = [_Replica(i, srv, role=role_list[i])
                         for i, srv in enumerate(replicas)]
        for rep in self.replicas:
            # the router is the single source of truth for roles: a
            # prefill replica parks finished prefills for migration
            # instead of decoding them (serving.py handoff contract)
            rep.srv.prefill_only = (rep.role == "prefill")
        self.replica_factory = replica_factory
        self.ckpt_dir = ckpt_dir
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.probe_admissions = max(1, int(probe_admissions))
        self.affinity_tokens = int(affinity_tokens)
        self.affinity_max_imbalance = int(affinity_max_imbalance)
        self.faults = faults if faults is not None else faults_lib.active()
        if isinstance(telemetry, (Telemetry, NoopTelemetry)):
            self.telemetry = telemetry
        elif resolve_telemetry(telemetry):
            self.telemetry = Telemetry()
        else:
            self.telemetry = NOOP
        self.metrics = (self.telemetry.registry if self.telemetry.enabled
                        else MetricsRegistry())
        self._stat = {}
        for key, kind, help_ in _ROUTER_STAT_FIELDS:
            make = (self.metrics.counter if kind == "c"
                    else self.metrics.gauge)
            self._stat[key] = make(f"router_{key}", help_)
        self.stats = _StatsView(self._stat)
        # per-replica health gauges: the registry has no label support,
        # so each replica gets its own name (indices only grow —
        # add_replica appends, retire parks the gauge at 4 — so the
        # scrape series stay stable)
        self._g_health = [self._mk_health_gauge(i)
                          for i in range(len(self.replicas))]
        # fleet-shape gauges: replicas currently in each health state,
        # the controller's (and any scraper's) one-look fleet view
        self._g_state = {
            state: self.metrics.gauge(
                f"router_replicas_{state}",
                f"replicas currently {state}")
            for state in HEALTH_CODES}
        # pool-shape gauges (disaggregated fleets): non-retired
        # replicas per role, the SLO controller's per-pool capacity view
        self._g_role = {
            role: self.metrics.gauge(
                f"router_replicas_role_{role}",
                f"non-retired replicas with the {role} role")
            for role in ROLES}
        self._update_state_gauges()
        self._h_qwait = (self.metrics.histogram(
            "router_dispatch_queue_wait",
            "submit-to-(re)dispatch wait (scheduler clock units; >0 "
            "only for drained/redispatched requests)")
            if self.telemetry.enabled else None)
        # fleet-merged terminal state captured off broken replicas
        # before their engines are discarded; live replicas keep their
        # own `finished` until results() merges everything
        self._results: Dict[Any, np.ndarray] = {}
        self._finished: List[ServeRequest] = []
        self._orphans: List[ServeRequest] = []   # undispatchable drain work
        self._affinity: Dict[bytes, int] = {}
        # adapter affinity (docs/ADAPTERS.md): last replica that served
        # each adapter_id — steering a tenant back there turns its next
        # admission into a pool hit instead of a reload, under the SAME
        # imbalance cap the prefix affinity honors
        self._adapter_affinity: Dict[str, int] = {}
        self._rr = 0                             # round-robin step cursor
        self._clock = 0
        # SLO controller hook: ticked once per step() when set; the
        # shed_batch gate is its admission actuator (submit() sheds
        # priority="batch" requests while tightened). Default None =
        # the fixed-fleet bit-reference.
        self.autoscale = autoscale
        self.shed_batch = False
        # fleet flight recorder (telemetry/flight.py): breaker breaks
        # and total degrades write a postmortem artifact bundling the
        # fleet view — per-replica engines keep their own recorders
        if resolve_flag("DS_FLIGHT_RECORDER", flight_recorder):
            self.flight = FlightRecorder(
                outdir=flight_dir or (resolve_flag("DS_FLIGHT_DIR")
                                      or None),
                sections=self._flight_sections(), label="router")
        else:
            self.flight = NOOP_FLIGHT
        # replica-to-replica migration channel: one CRC-verified host
        # staging pool for the whole fleet — the host tier's spill
        # storage generalized to carry KV between pools
        # (docs/KV_TIERING.md). Only disaggregated fleets exercise it;
        # warming every replica's gather/scatter lane up front means
        # steady-state migrations compile nothing (CompileWatch(0)).
        self._mig_pool = HostBlockPool()
        if any(rep.role == "prefill" for rep in self.replicas):
            for rep in self.replicas:
                rep.srv.cache.warm_migration()

    def _flight_sections(self) -> Dict:
        """Fleet postmortem section providers (called only at dump
        time): merged fleet metrics + health, the router's own tracer
        ring, autoscaler decisions, fired faults, resolved flags, and
        every replica's cost-accounting state."""
        return {
            "tracer": lambda: [list(r)
                               for r in self.telemetry.tracer.records()],
            "metrics": lambda: self.fleet_snapshot(),
            "stats": lambda: dict(self.stats),
            "autoscale": lambda: (list(self.autoscale.decisions)
                                  if self.autoscale is not None else []),
            "faults": lambda: [list(f) for f in self.faults.fired],
            "flags": lambda: {n: resolve_flag(n) for n in flag_names()},
            "costs": lambda: {
                f"r{rep.idx}": rep.srv.costs.snapshot()
                for rep in self.replicas},
            "requests": lambda: [
                dict(row, replica=rep.idx)
                for rep in self.replicas
                for row in rep.srv._flight_requests()],
        }

    def _mk_health_gauge(self, i: int):
        return self.metrics.gauge(
            f"router_replica_health_r{i}",
            "replica health (0 healthy / 1 suspect / 2 broken / "
            "3 recovering / 4 retired)")

    def _update_state_gauges(self) -> None:
        for state, g in self._g_state.items():
            g.set(sum(1 for rep in self.replicas if rep.health == state))
        for role, g in self._g_role.items():
            g.set(sum(1 for rep in self.replicas
                      if rep.role == role and rep.health != RETIRED))

    # -- API -----------------------------------------------------------
    def submit(self, req: ServeRequest, now: float = 0.0) -> bool:
        """Dispatch ``req`` to the best dispatchable replica. Returns
        the target's ``submit`` result (False = shed by its bounded
        queue, or here by the tightened-admission gate). Raises a
        fleet-level :class:`DegradedError` when no replica can take
        traffic."""
        if self.shed_batch and req.priority == "batch":
            # admission tightened by the SLO controller: batch-class
            # traffic sheds at the front door (same terminal shape as
            # an engine-side queue-bound shed) so interactive traffic
            # keeps the fleet's headroom
            req.state = "shed"
            req.finished_at = now
            self._results.setdefault(req.rid, req.tokens)
            self._finished.append(req)
            self._stat["shed"].inc()
            self.telemetry.tracer.event(
                "shed", rid=req.rid, step=self._clock,
                reason="admission tightened", priority=req.priority)
            return False
        ok = self._dispatch(req, now)
        if self._orphans:
            raise self._fleet_degraded(
                f"no dispatchable replica for request {req.rid!r}")
        return bool(ok)

    @property
    def busy(self) -> bool:
        return any(rep.health not in (BROKEN, RETIRED) and rep.srv.busy
                   for rep in self.replicas)

    def step(self, now: Optional[float] = None) -> int:
        """One fleet iteration: step every non-broken busy replica once,
        in round-robin rotation, firing the ``router.step`` chaos site
        per replica. Failures feed the breaker; a broken replica's
        in-flight work drains onto survivors before the step returns.
        Returns the fleet-wide decode occupancy."""
        if now is None:
            now = float(self._clock)
        occ = 0
        n = len(self.replicas)
        for k in range(n):
            rep = self.replicas[(self._rr + k) % n]
            if rep.health in (BROKEN, RETIRED) or not rep.srv.busy:
                continue
            try:
                self.faults.fire("router.step")
                occ += rep.srv.step(now)
            except TransientDeviceError as e:
                self._note_failure(rep, now, str(e))
            except DegradedError as e:
                # the replica's own watchdog/non-drain contract fired:
                # its scheduler state is still consistent, so the
                # standard drain path recovers everything it held
                self._break(rep, now, f"degraded: {e}")
                self._drain(rep, now)
            except InjectedCrash as e:
                self._break(rep, now, f"crash: {e}")
                self._drain(rep, now)
            else:
                self._note_success(rep, now)
        # disaggregated handoff harvest: a prefill-role replica whose
        # chunked prefill just finished parks the request in a handoff
        # slot — migrate each one to a decode-capable replica now, or
        # degrade it to a cold re-prefill (never leave it wedged)
        for rep in list(self.replicas):
            if rep.health in (BROKEN, RETIRED) or not rep.srv.prefill_only:
                continue
            for slot, hreq in list(rep.srv.ready_handoffs()):
                if rep.health in (BROKEN, RETIRED):
                    break     # a crash mid-harvest already drained it
                self._migrate(rep, slot, hreq, now)
        self._rr = (self._rr + 1) % n
        self._clock += 1
        self._stat["steps"].inc()
        if self._orphans:
            # a drain this step could not place everything: ONE
            # fleet-level raise carrying every orphaned request
            raise self._fleet_degraded(
                "no dispatchable replica left for drained work")
        if self.autoscale is not None:
            # controller tick AFTER the fleet stepped (so the windowed
            # metrics include this iteration's tokens) and AFTER the
            # orphan check (a degraded fleet raises, it doesn't scale)
            self.autoscale.on_step(self, now)
        return occ

    def run(self, requests=None, max_steps: int = 1_000_000,
            wall_clock: bool = False) -> Dict[Any, np.ndarray]:
        """Submit ``requests`` and step the fleet until idle. Returns
        fleet-merged {rid: prompt+generated}. Raises the fleet-level
        :class:`DegradedError` (with merged results + pending) on total
        degrade or non-drain."""
        for r in (requests or []):
            self.submit(r, now=time.perf_counter() if wall_clock else 0.0)
        steps = 0
        while self.busy:
            self.step(time.perf_counter() if wall_clock else None)
            steps += 1
            if steps > max_steps:
                raise self._fleet_degraded(
                    f"fleet did not drain in {max_steps} steps")
        return self.results()

    def results(self) -> Dict[Any, np.ndarray]:
        """Fleet-merged {rid: prompt+generated}: terminal work captured
        off broken replicas, overlaid with every live replica's
        finished list (a drained rid's survivor-side completion wins)."""
        merged = dict(self._results)
        for rep in self.replicas:
            for r in rep.srv.finished:
                merged[r.rid] = r.tokens
        return merged

    def health(self) -> List[str]:
        """Per-replica health states, by replica index."""
        return [rep.health for rep in self.replicas]

    def restart_replica(self, idx: int, now: float = 0.0) -> Optional[str]:
        """Warm-restart a broken replica through ``replica_factory``,
        loading from the newest VALID checkpoint tag under ``ckpt_dir``
        (walk-back semantics; None when no valid tag exists). The
        rebuilt replica rejoins as ``recovering`` — half-open until a
        probe request completes cleanly. Returns the tag used."""
        rep = self.replicas[idx]
        if rep.health != BROKEN:
            raise ValueError(
                f"replica {idx} is {rep.health}, not broken")
        if self.replica_factory is None:
            raise RuntimeError(
                "restart_replica needs a replica_factory")
        tag = self._restart_tag()
        rep.srv = self.replica_factory(idx, tag)
        rep.failures = 0
        rep.probe_rids = set()
        rep.restarts += 1
        self._set_health(rep, RECOVERING, now, reason="warm restart")
        self._stat["restarts"].inc()
        self.telemetry.tracer.event("restart", step=self._clock,
                                    replica=idx, tag=tag)
        logger.info(f"router: replica {idx} warm-restarted from "
                    f"checkpoint tag {tag!r}; recovering")
        return tag

    # -- elasticity ----------------------------------------------------
    def add_replica(self, srv: Optional[ServingEngine] = None,
                    now: float = 0.0, reason: str = "",
                    role: str = "mixed") -> int:
        """Grow the fleet by one replica and return its index. With no
        explicit engine the replica comes from ``replica_factory``,
        warm-started from the newest valid checkpoint tag (the same
        walk-back :meth:`restart_replica` uses). The newcomer joins
        ``healthy`` and is immediately dispatchable; sharing the
        fleet's ``InferenceEngine`` means it shares the already-
        compiled programs, so scale-up compiles nothing. ``role``
        places the newcomer in a disaggregated pool (default
        ``mixed`` — the role-less shape)."""
        if role not in ROLES:
            raise ValueError(f"unknown replica role {role!r} "
                             f"(expected one of {ROLES})")
        idx = len(self.replicas)
        if srv is None:
            if self.replica_factory is None:
                raise RuntimeError(
                    "add_replica needs an engine or a replica_factory")
            srv = self.replica_factory(idx, self._restart_tag())
        self.replicas.append(_Replica(idx, srv, role=role))
        srv.prefill_only = (role == "prefill")
        if any(rep.role == "prefill" for rep in self.replicas):
            # the newcomer may source or land migrations: pre-compile
            # its gather/scatter lane outside the steady state
            srv.cache.warm_migration()
        self._g_health.append(self._mk_health_gauge(idx))
        self._g_health[idx].set(HEALTH_CODES[HEALTHY])
        self._update_state_gauges()
        self._stat["scale_ups"].inc()
        self.telemetry.tracer.event(
            "scale", step=self._clock, action="add", replica=idx,
            reason=reason, role=role)
        logger.info(f"router: replica {idx} added as {role} "
                    f"({reason or 'manual'})")
        return idx

    def retire_replica(self, idx: int, now: float = 0.0,
                       reason: str = "") -> int:
        """Scale-down: permanently remove replica ``idx`` from
        rotation. Its in-flight work drains onto survivors through the
        SAME snapshot/release path a breaker drain uses (so retiring a
        busy replica is token-lossless), then the replica parks
        ``retired`` — never stepped, never dispatched to, never
        restarted. Refuses to retire the last replica able to take
        traffic. Returns the number of requests drained across."""
        rep = self.replicas[idx]
        if rep.health == RETIRED:
            raise ValueError(f"replica {idx} is already retired")
        survivors = [r for r in self.replicas
                     if r.idx != idx and r.health not in (BROKEN, RETIRED)]
        if not survivors:
            raise ValueError(
                "cannot retire the last dispatchable replica")
        if rep.role != "prefill" and all(s.role == "prefill"
                                         for s in survivors):
            raise ValueError(
                "cannot retire the last decode-capable replica")
        # settle in-flight migrations FIRST (the abort_transfers
        # discipline): finished prefills parked in handoff slots
        # migrate out while the replica can still gather; anything that
        # cannot land degrades to a cold re-prefill on a survivor
        for slot, hreq in list(rep.srv.ready_handoffs()):
            if rep.health in (BROKEN, RETIRED):
                break         # a crash mid-settle already drained it
            self._migrate(rep, slot, hreq, now)
        self._set_health(rep, RETIRED, now, reason=reason or "scale-down")
        placed = self._drain(rep, now)
        self._stat["retires"].inc()
        self.telemetry.tracer.event(
            "scale", step=self._clock, action="retire", replica=idx,
            reason=reason, resumed=placed)
        logger.info(f"router: replica {idx} retired "
                    f"({reason or 'manual'}; {placed} drained)")
        if self._orphans:
            raise self._fleet_degraded(
                f"no dispatchable replica for work drained off "
                f"retired replica {idx}")
        return placed

    # -- fleet observability -------------------------------------------
    def fleet_registries(self) -> List[MetricsRegistry]:
        """Every distinct metrics registry in the fleet (router +
        replicas), deduped by identity — replicas sharing one
        ``Telemetry`` contribute their registry once."""
        regs: List[MetricsRegistry] = []
        seen: Set[int] = set()
        for reg in [self.metrics] + [rep.srv.metrics
                                     for rep in self.replicas]:
            if id(reg) not in seen:
                seen.add(id(reg))
                regs.append(reg)
        return regs

    def fleet_snapshot(self) -> Dict[str, Dict]:
        """Fleet-merged registry snapshot (counters/gauges summed,
        histograms bucket-merged across replicas) plus the fleet shape:
        per-replica health and replicas-by-state counts."""
        snap = merge_registries(self.fleet_registries()).snapshot()
        health = self.health()
        snap["fleet"] = {
            "replicas": len(health),
            "health": health,
            "by_state": {state: health.count(state)
                         for state in HEALTH_CODES},
        }
        return snap

    def to_prometheus(self) -> str:
        """Merged Prometheus text exposition across every registry in
        the fleet — one scrape body for the whole deployment."""
        return merge_registries(self.fleet_registries()).to_prometheus()

    # -- dispatch ------------------------------------------------------
    def _affinity_key(self, prompt) -> Optional[bytes]:
        if len(prompt) == 0:
            return None
        lead = np.asarray(prompt[:self.affinity_tokens], np.int32)
        return lead.tobytes()

    def _load(self, rep: _Replica) -> int:
        srv = rep.srv
        return len(srv.queue) + sum(1 for s in srv.slots if s is not None)

    def _dispatchable(self, rep: _Replica) -> bool:
        if rep.health in (BROKEN, RETIRED):
            return False
        if rep.health == RECOVERING:
            # half-open: a recovering replica holds at most
            # probe_admissions in-flight requests until a probe
            # completion closes the breaker
            return self._load(rep) < self.probe_admissions
        return True

    def _choose(self, req: ServeRequest,
                excluded: Set[int]) -> Optional[_Replica]:
        cands = [rep for rep in self.replicas
                 if rep.idx not in excluded and self._dispatchable(rep)]
        # role fence (disaggregated fleets): resumed/migrated work needs
        # a decode-capable target — a prefill-only replica would just
        # hand it off again. Fresh work prefers the prefill pool but may
        # still land on decode replicas when it is the only pool left
        # (they are full engines; role is policy, not capability).
        if bool(len(req.out)):
            cands = [rep for rep in cands if rep.role != "prefill"]
        else:
            pref = [rep for rep in cands if rep.role != "decode"]
            if pref:
                cands = pref
        if not cands:
            return None
        best = min(cands, key=lambda rep: (self._load(rep), rep.idx))
        if req.deadline is None:
            # adapter affinity outranks prefix affinity: a pool reload
            # (H2D copy at admission) costs more than re-prefilling a
            # shared prefix, and a deadline still outranks both
            aid = req.adapter_id
            idx = (self._adapter_affinity.get(aid)
                   if aid is not None else None)
            if idx is not None and idx != best.idx:
                aff = next((rep for rep in cands if rep.idx == idx), None)
                if aff is not None and (self._load(aff) <= self._load(best)
                                        + self.affinity_max_imbalance):
                    self._stat["adapter_affinity_hits"].inc()
                    return aff
            key = self._affinity_key(req.prompt)
            idx = self._affinity.get(key) if key is not None else None
            if idx is not None and idx != best.idx:
                aff = next((rep for rep in cands if rep.idx == idx), None)
                if aff is not None and (self._load(aff) <= self._load(best)
                                        + self.affinity_max_imbalance):
                    self._stat["affinity_hits"].inc()
                    return aff
        return best

    def _dispatch(self, req: ServeRequest, now: float,
                  excluded: Optional[Set[int]] = None) -> Optional[bool]:
        """Pick a target and submit. The ``router.dispatch`` site fires
        AFTER the choice and BEFORE the submit, so nothing has mutated
        when a fault retries the dispatch against the next-best
        replica; a ``crash`` there kills the chosen replica (which then
        drains). With no dispatchable replica left, the request joins
        ``_orphans`` and None is returned — the CALLER raises the one
        fleet-level DegradedError once it has orphaned everything it
        holds, so the error's pending is complete."""
        excluded = set(excluded or ())
        while True:
            rep = self._choose(req, excluded)
            if rep is None:
                self._orphans.append(req)
                return None
            try:
                self.faults.fire("router.dispatch")
            except TransientDeviceError as e:
                self._stat["redispatches"].inc()
                self._note_failure(rep, now, str(e))
                excluded.add(rep.idx)
                continue
            except InjectedCrash as e:
                self._break(rep, now, f"crash: {e}")
                excluded.add(rep.idx)
                self._drain(rep, now)
                continue
            if self._h_qwait is not None and req.submitted_at is not None:
                self._h_qwait.observe(max(0.0, now - req.submitted_at),
                                      at=now)
            ok = rep.srv.submit(req, now=now)
            key = self._affinity_key(req.prompt)
            if ok and key is not None:
                self._affinity[key] = rep.idx
            if ok and req.adapter_id is not None:
                self._adapter_affinity[req.adapter_id] = rep.idx
            if ok and rep.health == RECOVERING:
                rep.probe_rids.add(req.rid)
            self._stat["dispatched"].inc()
            self.telemetry.tracer.event(
                "dispatch", rid=req.rid, step=self._clock,
                replica=rep.idx, load=self._load(rep),
                resumed=bool(req.out))
            return ok

    # -- health --------------------------------------------------------
    def _set_health(self, rep: _Replica, state: str, now: float,
                    reason: str = "") -> None:
        if rep.health == state:
            return
        prev, rep.health = rep.health, state
        self._g_health[rep.idx].set(HEALTH_CODES[state])
        self._update_state_gauges()
        self.telemetry.tracer.event(
            "breaker", step=self._clock, replica=rep.idx,
            state=state, prev=prev, reason=reason)

    def _break(self, rep: _Replica, now: float, reason: str) -> None:
        if rep.health == BROKEN:
            return
        logger.warning(f"router: replica {rep.idx} broken ({reason})")
        self._set_health(rep, BROKEN, now, reason=reason)
        self._stat["breaker_trips"].inc()
        rep.failures = 0
        self.flight.dump(f"breaker: replica {rep.idx} broken ({reason})")

    def _note_failure(self, rep: _Replica, now: float, reason: str) -> None:
        """Feed the breaker: suspect on the first failure, broken (and
        drained) at the threshold; any failure while recovering
        re-opens the breaker immediately."""
        rep.failures += 1
        if rep.health == RECOVERING:
            self._break(rep, now, f"probe failed: {reason}")
            self._drain(rep, now)
        elif rep.failures >= self.breaker_threshold:
            self._break(rep, now,
                        f"{rep.failures} consecutive failures: {reason}")
            self._drain(rep, now)
        elif rep.health == HEALTHY:
            logger.warning(
                f"router: replica {rep.idx} suspect ({reason})")
            self._set_health(rep, SUSPECT, now, reason=reason)

    def _note_success(self, rep: _Replica, now: float) -> None:
        rep.failures = 0
        if rep.health == SUSPECT:
            self._set_health(rep, HEALTHY, now, reason="clean step")
        elif rep.health == RECOVERING and rep.probe_rids:
            # a probe that ran to state=done proves the rebuilt replica
            # end-to-end (admission, prefill, decode, retire) — close
            # the breaker
            done = {r.rid for r in rep.srv.finished if r.state == "done"}
            if rep.probe_rids & done:
                rep.probe_rids = set()
                self._set_health(rep, HEALTHY, now,
                                 reason="probe completed")
                logger.info(f"router: replica {rep.idx} recovered")

    # -- migration (disaggregated prefill/decode) ----------------------
    def _decode_target(self, src: _Replica) -> Optional[_Replica]:
        """Least-loaded decode-capable replica other than ``src`` — the
        landing side of a KV migration."""
        cands = [rep for rep in self.replicas
                 if rep.idx != src.idx and rep.role != "prefill"
                 and self._dispatchable(rep)]
        if not cands:
            return None
        return min(cands, key=lambda rep: (self._load(rep), rep.idx))

    def _resume_in_place(self, req: ServeRequest, entry: Dict) -> None:
        """Rebuild ``req`` from its snapshot entry IN the same object:
        the caller that submitted the request keeps its reference, so
        ``state``/``finished_at``/``tokens`` stay observable through
        the migration (load_gen's drive records per-request SLOs off
        the objects it submitted). Unlike a cross-drain resume, the
        fleet shares one scheduler clock, so the original latency
        stamps remain comparable — they are restored by
        ``_restamp`` after the destination's submit re-stamps them."""
        fresh = ServeRequest.from_snapshot(entry)
        req.__dict__.update(fresh.__dict__)

    @staticmethod
    def _restamp(req: ServeRequest, stamps: tuple) -> None:
        """Put back the pre-migration latency stamps: ``submitted_at``
        (submit re-stamped it), ``first_token_at`` (the first token
        REALLY left the prefill replica before the handoff — TTFT must
        not be re-measured, nor the TTFT histogram double-observed)
        and the already-emitted tokens' ``token_times``."""
        req.submitted_at, req.first_token_at = stamps[0], stamps[1]
        req.token_times = list(stamps[2]) + list(req.token_times)

    def _migrate(self, src: _Replica, slot: int, req: ServeRequest,
                 now: float) -> bool:
        """Move one finished prefill's KV chain from ``src`` (handoff
        slot ``slot``) to a decode-capable replica through the
        CRC-verified host-DRAM channel — per-array CRC32 on the way in,
        free-list-only landing on the way out — then resume the request
        there WITHOUT re-prefilling (admission adopts the parked chain).

        Degradation ladder (docs/ROBUSTNESS.md): ANY failure — a fault
        at a ``router.migrate_*`` site, host-budget refusal, CRC
        mismatch, destination capacity refusal, or a crash that breaks
        either endpoint — discards the partial landing, frees both
        sides, and re-dispatches the request for a cold re-prefill on
        the decode side. Token-identical either way (snapshot resume
        re-prefills prompt + already-emitted tokens); counted in
        ``router_migration_fallbacks``. Returns True only for a landed
        migration."""
        keys: List[int] = []
        dest: Optional[_Replica] = None
        stage = "gather"
        try:
            dest = self._decode_target(src)
            if dest is None:
                raise TransientDeviceError(
                    "no decode-capable replica to land the migration")
            self.faults.fire("router.migrate_gather")
            handle = src.srv.cache.migrate_gather(slot, self._mig_pool)
            keys = list(handle["keys"])
            fault = self.faults.fire("router.migrate_corrupt")
            if fault is not None and keys:
                # flip a real stored byte: the genuine per-array CRC32
                # verify in land_parked drives the degrade below —
                # corrupted KV can never reach attention as cached truth
                self._mig_pool.corrupt(keys[0])
            stage = "scatter"
            self.faults.fire("router.migrate_scatter")
            dest.srv.cache.land_parked(req.rid, keys, self._mig_pool,
                                       handle["length"])
        except InjectedCrash as e:
            # a crash breaks the acting endpoint: the gather side is
            # the source, the scatter side is the destination
            victim = src if stage == "gather" else dest
            for k in keys:
                self._mig_pool.discard(k)
            if dest is not None:
                dest.srv.cache.drop_parked(req.rid)
            self._break(victim, now, f"crash: {e}")
            self._drain(victim, now)
            if victim is src:
                # the drain just snapshotted the handoff request,
                # resumed it cold on a survivor, and counted it in
                # migration_fallbacks — nothing left to settle here
                return False
            self._migration_fallback(src, req, now, f"crash: {e}",
                                     dest=dest)
            return False
        except (TransientDeviceError, CacheExhausted, HostCorruption) as e:
            self._migration_fallback(src, req, now, str(e), keys=keys,
                                     dest=dest)
            return False
        # landed: the host copies served their purpose; the destination
        # owns the device-resident chain (parked until admission adopts)
        for k in keys:
            self._mig_pool.discard(k)
        entry = snapshot_entry(req, kv_handle={
            "blocks": int(handle["n_blocks"]),
            "length": int(handle["length"]),
            "src": src.idx, "dest": dest.idx})
        src.srv.release_handoff(req.rid)
        stamps = (req.submitted_at, req.first_token_at,
                  list(req.token_times))
        self._resume_in_place(req, entry)
        ok = dest.srv.submit(req, now=now)
        if not ok:
            # bounded-queue shed at the destination: free the landing
            # and degrade cold on whoever has room
            dest.srv.cache.drop_parked(req.rid)
            self._stat["migration_fallbacks"].inc()
            self.telemetry.tracer.event(
                "migrate", rid=req.rid, step=self._clock, src=src.idx,
                dest=dest.idx, ok=False,
                reason="destination queue full")
            self._dispatch(req, now, excluded={src.idx, dest.idx})
            self._restamp(req, stamps)
            return False
        self._restamp(req, stamps)
        if dest.health == RECOVERING:
            dest.probe_rids.add(req.rid)
        self._stat["migrations"].inc()
        self.telemetry.tracer.event(
            "migrate", rid=req.rid, step=self._clock, src=src.idx,
            dest=dest.idx, blocks=int(handle["n_blocks"]),
            length=int(handle["length"]), ok=True)
        return True

    def _migration_fallback(self, src: _Replica, req: ServeRequest,
                            now: float, reason: str,
                            keys: Sequence[int] = (),
                            dest: Optional[_Replica] = None) -> None:
        """Bottom rung of the migration ladder: discard the host
        copies and any partial landing, free the source's handoff
        slot, and re-dispatch the request for a cold re-prefill on the
        decode side — the same recompute-on-resume path drains use, so
        the output stays token-identical."""
        for k in keys:
            self._mig_pool.discard(k)
        if dest is not None:
            dest.srv.cache.drop_parked(req.rid)
        entry = snapshot_entry(req)
        src.srv.release_handoff(req.rid)
        self._stat["migration_fallbacks"].inc()
        self.telemetry.tracer.event(
            "migrate", rid=req.rid, step=self._clock, src=src.idx,
            dest=(dest.idx if dest is not None else None), ok=False,
            reason=reason)
        stamps = (req.submitted_at, req.first_token_at,
                  list(req.token_times))
        self._resume_in_place(req, entry)
        self._dispatch(req, now, excluded={src.idx})
        self._restamp(req, stamps)

    # -- drain ---------------------------------------------------------
    def _drain(self, rep: _Replica, now: float) -> int:
        """Move a broken replica's work to survivors: merge its
        terminal results, snapshot-and-release its in-flight requests
        (freeing the dead pool's block refs and prefix pins), dedup
        rids already terminal fleet-wide, and resubmit the rest.

        Never raises: undispatchable work (no survivors, or a ``crash``
        injected at ``router.drain``) lands in ``_orphans``, and the
        entry point that triggered the drain raises ONE fleet-level
        :class:`DegradedError` carrying all of it — total degrade
        loses nothing."""
        crashed = False
        for _attempt in range(3):
            try:
                self.faults.fire("router.drain")
                break
            except TransientDeviceError:
                # fired before any state moved: retrying the drain is
                # safe, and a drain must not die to a transient
                self._stat["redispatches"].inc()
                continue
            except InjectedCrash:
                # crash mid-drain: the drain logic is dead — orphan the
                # whole snapshot (escalates to total degrade upstream)
                crashed = True
                break
        self._absorb_terminal(rep)
        # pending_snapshot(release=True) settles the dead replica's
        # in-flight host-tier spills first (abort_transfers); record how
        # many were cut short so a chaos run's timeline shows the
        # drain/spill interaction explicitly. Migrations cut short the
        # same way — finished prefills still parked in handoff slots
        # (source side) and landed chains not yet adopted (destination
        # side, freed by abort_parked) — degrade to cold re-prefills
        # through the snapshot resume below and count as fallbacks.
        mig_cut = len(rep.srv.ready_handoffs())
        parked_aborts_before = rep.srv.cache.parked_aborts
        spill_aborts_before = rep.srv.cache.host_spill_aborts
        snap = rep.srv.pending_snapshot(release=True)
        spill_aborts = rep.srv.cache.host_spill_aborts - spill_aborts_before
        mig_cut += rep.srv.cache.parked_aborts - parked_aborts_before
        if mig_cut:
            self._stat["migration_fallbacks"].inc(mig_cut)
        reqs = [ServeRequest.from_snapshot(s) for s in snap
                if s["rid"] not in self._results]
        placed = 0
        failed = crashed
        for req in reqs:
            if failed:
                self._orphans.append(req)
                continue
            if self._dispatch(req, now, excluded={rep.idx}) is None:
                failed = True        # req orphaned; orphan the rest too
                continue
            placed += 1
            self._stat["drained_requests"].inc()
        self.telemetry.tracer.event(
            "drain", step=self._clock, replica=rep.idx,
            resumed=placed, rids=[r.rid for r in reqs],
            spill_aborts=spill_aborts, migrations_cut=mig_cut)
        logger.warning(
            f"router: drained {placed}/{len(reqs)} in-flight requests "
            f"from replica {rep.idx} onto survivors")
        return placed

    def _absorb_terminal(self, rep: _Replica) -> None:
        """Capture a dead replica's finished requests before its engine
        is discarded (first writer wins: a rid already captured from an
        earlier break keeps its tokens)."""
        for r in rep.srv.finished:
            if r.rid not in self._results:
                self._results[r.rid] = r.tokens
                self._finished.append(r)

    def _fleet_degraded(self, message: str) -> DegradedError:
        self._stat["fleet_degraded"].inc()
        orphans, self._orphans = self._orphans, []
        merged = self.results()
        pending = [snapshot_entry(r) for r in orphans]
        for rep in self.replicas:
            if rep.health not in (BROKEN, RETIRED):
                pending.extend(
                    s for s in rep.srv.pending_snapshot()
                    if s["rid"] not in merged)
        self.telemetry.tracer.event("degraded", step=self._clock,
                                    message=message)
        self.flight.dump(f"fleet degraded: {message}")
        return DegradedError(
            message, results=merged, finished=list(self._finished),
            pending=pending, stats=dict(self.stats))

    # -- checkpoint walk-back ------------------------------------------
    def _restart_tag(self) -> Optional[str]:
        """Newest valid checkpoint tag under ``ckpt_dir``: the
        ``latest`` pointer when it validates, else newest-first over
        ``list_tags`` (a torn/corrupt tag is skipped, never loaded)."""
        if self.ckpt_dir is None:
            return None
        tag = get_latest_tag(self.ckpt_dir)
        if tag is not None and validate_tag(self.ckpt_dir, tag):
            return tag
        for cand in list_tags(self.ckpt_dir):
            if validate_tag(self.ckpt_dir, cand):
                return cand
        return None
