from deepspeed_tpu.inference.autoscale import SLOController
from deepspeed_tpu.inference.paged_cache import CacheExhausted, PagedKVCache
from deepspeed_tpu.inference.router import ReplicaRouter
from deepspeed_tpu.inference.serving import (DegradedError, ServeRequest,
                                             ServingEngine)

__all__ = ["CacheExhausted", "DegradedError", "PagedKVCache",
           "ReplicaRouter", "SLOController", "ServeRequest",
           "ServingEngine"]
