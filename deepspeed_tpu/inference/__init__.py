from deepspeed_tpu.inference.paged_cache import CacheExhausted, PagedKVCache
from deepspeed_tpu.inference.serving import ServeRequest, ServingEngine

__all__ = ["CacheExhausted", "PagedKVCache", "ServeRequest",
           "ServingEngine"]
