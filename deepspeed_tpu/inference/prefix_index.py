"""Host-side radix index over token-id block chunks — the shared-prefix
lookup structure behind the paged KV-cache's automatic prefix caching.

A fleet serving millions of users sends the same system prompt with
every request; without sharing, each request re-prefills it and holds a
private copy of its K/V in HBM. This index is the cross-request memory
(vLLM automatic prefix caching, Kwon et al. SOSP '23; SGLang
RadixAttention): a radix tree whose edges are FULL block-sized token
chunks and whose nodes each name one pool block holding that chunk's
K/V. Because the transformer is causal, a block's K/V depend only on
the tokens at and before it — two requests that agree on their first
``k * block_size`` tokens can read the very same ``k`` pool blocks.

The index is pure host-side bookkeeping (dicts over numpy token
chunks): it never appears in a device program, so lookups, inserts and
evictions happen every scheduler iteration without any recompile — the
paged-serving two-program contract is untouched.

Division of labor with :class:`~deepspeed_tpu.inference.paged_cache.
PagedKVCache`: the index maps token prefixes to block ids and keeps LRU
order; the CACHE owns refcounts and decides reclaim eligibility
(``refcount == 0``), passing that predicate into
:meth:`PrefixIndex.pop_evictable`. Only LEAF nodes are evictable — an
interior block can never be reclaimed before its descendants, so a
cached chain never dangles (and since every mapped chain claims all its
ancestors, an interior node's refcount is always >= any descendant's).

Matching returns the longest cached chain of full blocks plus, when the
query diverges (or simply ends) inside the NEXT block, a copy-on-write
candidate: the child block sharing the longest leading run of tokens.
The caller copies that block into a fresh one and overwrites from the
divergence point — mid-block reuse without ever mutating shared state.

**Tier tags** (docs/KV_TIERING.md): with the host-DRAM tier on, a node
lives in exactly one of two tiers — ``"device"`` (``block`` names a
pool block, registered in ``_by_block``) or ``"host"`` (``host_key``
names a :class:`~deepspeed_tpu.inference.host_tier.HostBlockPool`
entry, registered in ``_by_host``; ``block`` is -1). :meth:`match`
returns a parallel ``tiers`` list so the cache can restore host links
in the chain before mapping it; device-side reclaim
(:meth:`pop_evictable` / :meth:`evictable_count`) sees ONLY the device
tier, so a spilled block can never be double-claimed. The cache owns
the host bytes — this index only carries the tags and the LRU order.
"""

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np


def _chunk_key(tokens: np.ndarray) -> bytes:
    return np.ascontiguousarray(tokens, dtype=np.int32).tobytes()


class _Node:
    """One cached block: the full token chunk it holds, the pool block
    id, and radix-tree links. ``last_used`` is the index's logical tick
    (monotonic), not wall time — LRU must be deterministic for tests.
    ``tier`` is ``"device"`` (``block`` valid) or ``"host"``
    (``host_key`` valid, ``block`` = -1)."""

    __slots__ = ("chunk", "block", "parent", "children", "last_used",
                 "tier", "host_key")

    def __init__(self, chunk: np.ndarray, block: int,
                 parent: Optional["_Node"]):
        self.chunk = chunk
        self.block = block
        self.parent = parent
        self.children: Dict[bytes, "_Node"] = {}
        self.last_used = 0
        self.tier = "device"
        self.host_key: Optional[int] = None


@dataclass
class PrefixMatch:
    """Result of :meth:`PrefixIndex.match`: ``block_ids`` is the chain
    of fully-shared blocks (map read-only), ``cow_src``/``cow_tokens``
    the optional partially-matching block to copy-on-write (reuse its
    first ``cow_tokens`` positions). ``matched`` counts total reusable
    tokens: ``len(block_ids) * block_size + cow_tokens``.

    ``tiers`` parallels ``block_ids``: ``"device"`` entries are pool
    block ids, ``"host"`` entries are host-pool keys the cache must
    restore before the chain is mappable (the COW candidate is always
    device-tier). Empty ``tiers`` with a non-empty chain means
    all-device — the single-tier reading every pre-tier caller used."""
    block_ids: List[int] = field(default_factory=list)
    matched: int = 0
    cow_src: Optional[int] = None
    cow_tokens: int = 0
    tiers: List[str] = field(default_factory=list)


class PrefixIndex:
    """Radix tree of full-block token chunks -> pool block ids."""

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._root = _Node(np.zeros((0,), np.int32), -1, None)
        self._by_block: Dict[int, _Node] = {}
        # host-tier nodes keyed by their HostBlockPool key — kept OUT of
        # _by_block so every device-side predicate (``refcount[b]``,
        # ``b in index``) stays safe against key/id collisions
        self._by_host: Dict[int, _Node] = {}
        self._tick = 0

    def __len__(self) -> int:
        """Device-tier nodes only (the pre-tier contract);
        :meth:`host_len` counts the spilled side."""
        return len(self._by_block)

    def __contains__(self, block_id: int) -> bool:
        return int(block_id) in self._by_block

    def host_len(self) -> int:
        return len(self._by_host)

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.last_used = self._tick

    # -- lookup --------------------------------------------------------
    def match(self, tokens: np.ndarray, max_tokens: int,
              touch: bool = True) -> PrefixMatch:
        """Longest cached prefix of ``tokens``, capped at ``max_tokens``
        (the caller caps at ``len(tokens) - 1`` so at least one token is
        always left to prefill — the final chunk's logits emit the first
        generated token). ``touch=False`` peeks without disturbing LRU
        order (admission-control precheck)."""
        tokens = np.asarray(tokens, np.int32)
        bs = self.block_size
        max_tokens = min(int(max_tokens), len(tokens))
        node = self._root
        m = PrefixMatch()
        while m.matched + bs <= max_tokens:
            child = node.children.get(
                _chunk_key(tokens[m.matched:m.matched + bs]))
            if child is None:
                break
            node = child
            m.block_ids.append(child.block if child.tier == "device"
                               else child.host_key)
            m.tiers.append(child.tier)
            m.matched += bs
            if touch:
                self._touch(child)
        # divergent / final partial block: the child sharing the longest
        # leading token run is the copy-on-write candidate — device-tier
        # only (a host block's bytes are not addressable by the COW copy
        # program; a spilled near-miss degrades to a plain miss)
        rem = tokens[m.matched:max_tokens]
        if len(rem) > 0:
            best, best_j = None, 0
            for child in node.children.values():
                if child.tier != "device":
                    continue
                j = _common_prefix_len(child.chunk, rem)
                if j > best_j:
                    best, best_j = child, j
            if best is not None:
                m.cow_src = best.block
                m.cow_tokens = best_j
                m.matched += best_j
                if touch:
                    self._touch(best)
        return m

    # -- registration --------------------------------------------------
    def insert(self, tokens: np.ndarray, block_ids: List[int],
               on_host_displaced: Optional[Callable[[int], None]] = None
               ) -> int:
        """Register a chain: chunk ``i`` of ``tokens`` lives in
        ``block_ids[i]``. Chunks already cached keep their EXISTING
        block (the caller's duplicate stays private and is freed with
        its slot); new chunks extend the tree. Returns how many blocks
        were newly registered.

        A chunk whose node sits in the HOST tier is upgraded in place:
        the registering slot just prefilled a fresh device copy (that's
        why it is re-registering), which is at least as authoritative
        as the spilled bytes — the node flips back to device on the new
        block and ``on_host_displaced(host_key)`` lets the cache
        discard the now-redundant host entry."""
        tokens = np.asarray(tokens, np.int32)
        bs = self.block_size
        n_full = min(len(tokens) // bs, len(block_ids))
        node = self._root
        added = 0
        for i in range(n_full):
            chunk = tokens[i * bs:(i + 1) * bs]
            key = _chunk_key(chunk)
            child = node.children.get(key)
            if child is None:
                bid = int(block_ids[i])
                if bid in self._by_block:
                    # one physical block holds one chunk; a block cannot
                    # be registered under two chains
                    raise ValueError(
                        f"block {bid} is already registered in the index")
                child = _Node(chunk.copy(), bid, node)
                node.children[key] = child
                self._by_block[bid] = child
                added += 1
            elif child.tier == "host":
                bid = int(block_ids[i])
                if bid in self._by_block:
                    raise ValueError(
                        f"block {bid} is already registered in the index")
                displaced = child.host_key
                del self._by_host[displaced]
                child.tier = "device"
                child.host_key = None
                child.block = bid
                self._by_block[bid] = child
                if on_host_displaced is not None:
                    on_host_displaced(displaced)
                added += 1
            self._touch(child)
            node = child
        return added

    # -- tier transitions ----------------------------------------------
    def to_host(self, block_id: int, host_key: int) -> None:
        """Flip a device node to the host tier: ``block_id`` leaves the
        device namespace (the pool block is the CACHE's to free) and
        ``host_key`` names the spilled bytes from here on."""
        node = self._by_block.pop(int(block_id))
        node.tier = "host"
        node.host_key = int(host_key)
        node.block = -1
        self._by_host[node.host_key] = node

    def to_device(self, host_key: int, block_id: int) -> None:
        """Flip a host node back to the device tier onto the freshly
        restored ``block_id`` (the cache already scattered the bytes)."""
        node = self._by_host.pop(int(host_key))
        node.tier = "device"
        node.host_key = None
        node.block = int(block_id)
        self._by_block[node.block] = node

    def spill_candidates(self, can_spill: Callable[[int], bool],
                         limit: int) -> List[int]:
        """Up to ``limit`` device-tier blocks passing ``can_spill``
        (the cache's refcount-0-and-not-in-transfer test), least
        recently used first — the spill daemon's shopping list. Unlike
        :meth:`pop_evictable` this may name INTERIOR nodes: a spilled
        interior keeps its subtree reachable (the chain restores link
        by link), whereas device eviction severs it."""
        cands = [n for n in self._by_block.values() if can_spill(n.block)]
        cands.sort(key=lambda n: n.last_used)
        return [n.block for n in cands[:int(limit)]]

    def remove_subtree(self, host_key: int):
        """Remove the host node ``host_key`` AND every descendant (their
        prefixes run through the doomed chunk, so none is servable once
        it goes). Returns ``(device_ids, host_keys)`` of everything
        unregistered — the cache reclaims the pool blocks it can and
        discards the host entries. The corruption degrade path."""
        node = self._by_host.get(int(host_key))
        if node is None:
            return [], []
        dev: List[int] = []
        hosts: List[int] = []

        def walk(n: _Node) -> None:
            for c in list(n.children.values()):
                walk(c)
            n.children.clear()
            if n.tier == "host":
                hosts.append(n.host_key)
                self._by_host.pop(n.host_key, None)
            else:
                dev.append(n.block)
                self._by_block.pop(n.block, None)
            n.parent.children.pop(_chunk_key(n.chunk), None)

        walk(node)
        return dev, hosts

    # -- eviction ------------------------------------------------------
    def _host_pinned(self) -> frozenset:
        """Device blocks no leaf-first cascade can ever reach: the
        ancestors of host-tier nodes. A host child never leaves via
        device eviction, so its device ancestors are permanently
        interior — counting them as reclaimable would let the
        allocator's availability check pass and then strand
        ``pop_evictable`` mid-allocation."""
        if not self._by_host:
            return frozenset()
        pinned = set()
        for n in self._by_host.values():
            p = n.parent
            while p is not None and p is not self._root:
                if p.tier == "device":
                    if p.block in pinned:
                        break       # shared ancestor chain already walked
                    pinned.add(p.block)
                p = p.parent
        return frozenset(pinned)

    def evictable_count(self, can_evict: Callable[[int], bool]) -> int:
        """How many cached blocks could be reclaimed right now — every
        indexed block the predicate clears, since leaf-first pops expose
        interior nodes as they go (refcount(parent) >= refcount(child),
        so a clearable interior implies clearable descendants) — minus
        the ancestors of host-tier nodes, which the cascade can never
        expose (see :meth:`_host_pinned`)."""
        blocked = self._host_pinned()
        return sum(1 for bid in self._by_block
                   if can_evict(bid) and bid not in blocked)

    def pop_evictable(self, can_evict: Callable[[int], bool]
                      ) -> Optional[int]:
        """Remove and return the least-recently-used LEAF block passing
        ``can_evict`` (the cache's ``refcount == 0`` test), or None.
        Evicting a leaf may expose its parent as the next candidate."""
        victim = None
        for node in self._by_block.values():
            if node.children or not can_evict(node.block):
                continue
            if victim is None or node.last_used < victim.last_used:
                victim = node
        if victim is None:
            return None
        self._remove(victim)
        return victim.block

    def remove_block(self, block_id: int) -> bool:
        """Unregister ``block_id`` if it is a leaf; False otherwise."""
        node = self._by_block.get(int(block_id))
        if node is None or node.children:
            return False
        self._remove(node)
        return True

    def _remove(self, node: _Node) -> None:
        assert not node.children, "evicting an interior node"
        del self._by_block[node.block]
        node.parent.children.pop(_chunk_key(node.chunk), None)


def _common_prefix_len(a: np.ndarray, b: np.ndarray) -> int:
    n = min(len(a), len(b))
    if n == 0:
        return 0
    neq = np.nonzero(a[:n] != b[:n])[0]
    return int(neq[0]) if len(neq) else n
