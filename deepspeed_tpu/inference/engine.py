"""Inference engine: TP-sharded KV-cache generation.

Capability analog of the reference inference stack
(ref: deepspeed/inference/engine.py:23 InferenceEngine — MP group creation
:143, injection :225, checkpoint load :281, forward :355; fused kernel
modules ops/transformer/inference/transformer_inference.py:113/408/549 with
KV-cache management via the global Context workspace). TPU-native design:

- "kernel injection" = running the model through our fused JAX/Pallas GPT
  blocks (flash attention prefill, fused decode attention); policies
  (inference/policy.py) map foreign checkpoints (HF GPT-2 et al) into this
  layout — the analog of replace_transformer_layer
  (module_inject/replace_module.py:123);
- tensor parallelism = the same Megatron partition rules as training; the
  attn/MLP output allreduces the reference issues by hand
  (LinearAllreduce, transformer_inference.py MP allreduce) come from XLA;
- the KV cache is a preallocated [L, B, S_max, Hkv, D] pytree (Hkv =
  cfg.kv_heads; smaller than H under grouped-query attention) threaded
  functionally through a jitted, cache-donating decode step; generation is
  a host loop over compiled prefill + decode programs.
"""

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.inference import sampling
from deepspeed_tpu.models import gpt as gpt_lib
from deepspeed_tpu.ops import quantizer
from deepspeed_tpu.models.gpt import (GPTConfig, _dense,
                                      _norm, _qkv_split_rotary)
from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.parallel import sharding as sharding_lib
from deepspeed_tpu.utils.logging import log_dist, logger

PyTree = Any


@dataclass
class InferenceConfig:
    mp_size: int = 1
    dtype: Any = jnp.bfloat16
    max_seq_len: int = 2048
    max_batch_size: int = 8
    replace_with_kernel_inject: bool = True   # API parity; always fused here


def quantize_weights_int8(params):
    """Weight-only int8: every matmul kernel (block projections, MoE
    expert stacks, the untied lm_head) becomes {"q": int8, "scale":
    fp32 per-output-channel}; norms/embeddings/biases stay float.
    Dequantization happens at the matmul (gpt._kernel_of), so weights
    sit in HBM at 1 byte/param — the serving analog of the reference's
    int8 kernel-inject path (ref: replace_module.py quantize path,
    csrc/transformer/inference dequant kernels). Capability: llama-7B
    weights drop 13.5GB(bf16) -> 6.7GB on a 16GB chip."""
    def quant_leaf(w):
        a = jnp.max(jnp.abs(w), axis=-2, keepdims=True)
        scale = (a.astype(jnp.float32) / 127.0) + 1e-12
        q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
        return {"q": q, "scale": scale.astype(jnp.float32)}

    def walk(tree):
        if isinstance(tree, dict):
            if "kernel" in tree and tree["kernel"].ndim >= 2:
                out = {k: v for k, v in tree.items() if k != "kernel"}
                out.update(quant_leaf(tree["kernel"]))
                return out
            return {k: walk(v) for k, v in tree.items()}
        return tree

    out = dict(params)
    if "block" in out:
        out["block"] = walk(out["block"])
    if "lm_head" in out:
        out["lm_head"] = walk(out["lm_head"])
    return out


def _mlp(h, p, cfg, lora=None):
    lr = (lambda t: None) if lora is None else lora.get
    m = _dense(h, p["mlp_in"], lora=lr("mlp_in"))
    if cfg.activation == "swiglu":
        m = jax.nn.silu(_dense(h, p["mlp_gate"], lora=lr("mlp_gate"))) * m
    else:
        m = jax.nn.gelu(m, approximate=True)
    return _dense(m, p["mlp_out"], lora=lr("mlp_out"))


def _block_prefill(x, p, cfg: GPTConfig, kv_mask=None, positions=None):
    """Forward one block over the full prompt, returning (y, k, v).

    The cached k/v are post-rotary so decode never re-rotates history.
    kv_mask: [B, S] prompt validity (left-padded batched prompts);
    positions: optional [B, S] per-row rotary positions."""
    B, S, D = x.shape
    h = _norm(x, p["ln1"], cfg)
    qkv = _dense(h, p["qkv"])
    q, k, v = gpt_lib._qkv_split_rotary(qkv, cfg, positions, B, S)
    attn = gpt_lib._attention(q, k, v, cfg, kv_mask=kv_mask).reshape(B, S, D)
    attn = _dense(attn, p["attn_out"])
    if cfg.parallel_residual:
        return x + attn + _ffn(h, p, cfg), k, v
    x = x + attn
    h = _norm(x, p["ln2"], cfg)
    return x + _ffn(h, p, cfg), k, v


def _ffn(h, p, cfg, lora=None):
    """Dense MLP or MoE FFN for one block (ref MoE inference path:
    ops/transformer/inference/moe_inference.py). ``lora`` (multi-tenant
    serving, inference/adapters.py) applies to the dense MLP targets
    only — MoE expert stacks are not adaptable pool targets.

    The MoE eval path NEVER drops a token (GShard capacity bounds
    training dispatch; it must not change eval semantics — the gate's
    1.0-eval-capacity default silently dropped tokens here, caught by
    the Mixtral HF-parity test) and avoids the no-drop dispatch tensors
    (capacity = S makes the one-hot combine O(E*S^2)): every expert
    runs on every token — O(E*T*d) memory, E/k extra expert flops — and
    tokens mix their top-k renormalized softmax weights, exactly
    Mixtral's softmax-over-top-k router semantics."""
    if "moe" not in p:
        return _mlp(h, p, cfg, lora=lora)
    from deepspeed_tpu.moe.experts import ffn_expert_fn
    k = getattr(cfg, "moe_k", 1)
    B, S, D = h.shape
    ex = p["moe"]["experts"]
    # int8-quantized expert stacks carry "q" instead of "kernel"
    E = next(iter(ex["wi"].values())).shape[0]
    logits = h.reshape(-1, D).astype(jnp.float32) @ p["moe"]["gate"]["wg"]
    probs = jax.nn.softmax(logits, axis=-1)               # [T, E]
    top_p, top_i = jax.lax.top_k(probs, k)
    # weight convention MUST match what the checkpoint trained with
    # (cfg.gate_weighting): GShard top-1 weighs by the RAW softmax prob
    # (sharded_moe.top1gating) while Mixtral's softmax-over-top-k
    # renormalizes (1.0 at k=1); the two agree at k=2
    gshard = getattr(cfg, "gate_weighting", "gshard") == "gshard"
    w = (top_p if (k == 1 and gshard)
         else top_p / jnp.clip(top_p.sum(-1, keepdims=True), 1e-9))
    w_full = jnp.sum(jax.nn.one_hot(top_i, E) * w[..., None], axis=-2)
    outs = ffn_expert_fn(ex, jnp.broadcast_to(
        h.reshape(1, -1, D), (E, B * S, D)))              # [E, T, D]
    y = jnp.einsum("etd,te->td", outs, w_full.astype(h.dtype))
    return y.reshape(B, S, D)


def _block_decode(x, k_cache, v_cache, pos, p, cfg: GPTConfig,
                  cache_mask=None, row_pos=None):
    """One block for ONE new token. x: [B, 1, D]; caches [B, S_max, Hkv, Dh].
    Fused decode attention with positional masking over the cache
    (ref: softmax_context + KV-cache path, transformer_inference.py:113).
    cache_mask: optional [B, S_max] validity (0 = left-padding slot);
    row_pos: optional [B] per-row logical positions for rotary."""
    B, _, D = x.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    S_max = k_cache.shape[1]

    Hkv = cfg.kv_heads
    group = H // Hkv
    h = _norm(x, p["ln1"], cfg)
    qkv = _dense(h, p["qkv"])
    q, k, v = jnp.split(qkv, [H * Dh, (H + Hkv) * Dh], axis=-1)
    if cfg.rotary_dim:
        from deepspeed_tpu.ops.attention.rotary import apply_rotary
        rp = pos[None] if row_pos is None else row_pos[:, None]
        q, k = apply_rotary(q.reshape(B, 1, H, Dh), k.reshape(B, 1, Hkv, Dh),
                            rp, cfg.rotary_dim, base=cfg.rope_theta)
        q = q.reshape(B, 1, H, Dh)
        k = k.reshape(B, 1, Hkv, Dh)
    q = q.reshape(B, Hkv, group, Dh)
    k_cache = jax.lax.dynamic_update_slice_in_dim(
        k_cache, k.reshape(B, 1, Hkv, Dh), pos, axis=1)
    v_cache = jax.lax.dynamic_update_slice_in_dim(
        v_cache, v.reshape(B, 1, Hkv, Dh), pos, axis=1)

    # grouped decode attention: q heads grouped per shared kv head
    scores = jnp.einsum("bkgd,bskd->bkgs", q, k_cache).astype(jnp.float32)
    scores *= cfg.attn_scale if cfg.attn_scale is not None \
        else 1.0 / np.sqrt(Dh)
    idx = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, S_max), 3)
    scores = jnp.where(idx <= pos, scores, -1e30)
    if cfg.attn_window is not None:
        # logical distance == cache-index distance even under left
        # padding (both the query and every cached slot shift by the
        # same per-row pad)
        scores = jnp.where(idx > pos - cfg.attn_window, scores, -1e30)
    if cache_mask is not None:
        scores = jnp.where(cache_mask[:, None, None, :] > 0, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    attn = jnp.einsum("bkgs,bskd->bkgd", probs, v_cache).reshape(B, 1, D)
    attn = _dense(attn, p["attn_out"])
    if cfg.parallel_residual:
        return x + attn + _ffn(h, p, cfg), k_cache, v_cache
    x = x + attn
    h = _norm(x, p["ln2"], cfg)
    return x + _ffn(h, p, cfg), k_cache, v_cache


def _block_extend(x, k_cache, v_cache, pos, p, cfg: GPTConfig):
    """Decode block for G new tokens at STATIC cache positions
    [pos, pos+G) — the chunk-verify block of the static speculative path
    (inference/speculative.py), shared here so the paged verify block
    below and the static path dedupe one copy of the G-query decode
    math. x: [B, G, D]; caches [B, S_max, Hkv, Dh]. Causality: query i
    sees cache slots <= pos + i (its own prefix included)."""
    B, G, D = x.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    Hkv = cfg.kv_heads
    group = H // Hkv
    S_max = k_cache.shape[1]

    h = _norm(x, p["ln1"], cfg)
    qkv = _dense(h, p["qkv"])
    q, k, v = _qkv_split_rotary(qkv, cfg, pos + jnp.arange(G), B, G)
    k_cache = jax.lax.dynamic_update_slice_in_dim(k_cache, k, pos, axis=1)
    v_cache = jax.lax.dynamic_update_slice_in_dim(v_cache, v, pos, axis=1)

    qg = q.reshape(B, G, Hkv, group, Dh)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg,
                        k_cache).astype(jnp.float32)
    scores *= cfg.attn_scale if cfg.attn_scale is not None \
        else 1.0 / np.sqrt(Dh)
    idx = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, 1, S_max), 4)
    qi = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, G, 1), 3)
    scores = jnp.where(idx <= pos + qi, scores, -1e30)
    if cfg.attn_window is not None:
        scores = jnp.where(idx > pos + qi - cfg.attn_window, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    attn = jnp.einsum("bkgqs,bskd->bqkgd", probs, v_cache)
    attn = attn.reshape(B, G, D)
    attn = _dense(attn, p["attn_out"])
    if cfg.parallel_residual:
        return x + attn + _ffn(h, p, cfg), k_cache, v_cache
    x = x + attn
    h = _norm(x, p["ln2"], cfg)
    return x + _ffn(h, p, cfg), k_cache, v_cache


def _gather_blocks(pool, tables):
    """Gather a block pool [N, block, Hkv, Dh] through block tables
    [B, NB] into the virtual contiguous cache [B, NB*block, Hkv, Dh].
    Cache position s of row b lives at pool[tables[b, s // block],
    s % block] — the PagedAttention indirection, done as one XLA gather
    so the decode einsums below are unchanged from the static path."""
    g = pool[tables]
    B, NB, bs = g.shape[0], g.shape[1], g.shape[2]
    return g.reshape(B, NB * bs, g.shape[3], g.shape[4])


def _block_decode_paged(x, k_pool, v_pool, tables, lengths, active, p,
                        cfg: GPTConfig, impl: str = "gather",
                        k_scale=None, v_scale=None, lora=None):
    """One block for ONE new token per slot, K/V addressed through block
    tables — the paged generalization of _block_decode. x: [B, 1, D];
    pools [N, block, Hkv, Dh]; tables [B, NB]; lengths [B] per-slot
    cache positions (each slot decodes at its OWN position — the
    continuous-batching contract); active [B] bool (inactive slots'
    writes land in trash block 0 and their logits are ignored).

    impl="gather" materializes the virtual cache with _gather_blocks
    (the bit-reference, portable everywhere); impl="pallas" attends
    THROUGH the table with the flash-decode kernel (ops/attention/
    paged.py) — one pool-block DMA per occupied block, no dense copy.

    With ``k_scale``/``v_scale`` (``[N, Hkv]`` fp32) the pools are int8:
    the write becomes read-modify-requantize of each slot's current
    block (dequantize, insert the token, zero stale lanes, requantize —
    ops/quantizer KV helpers), the scales update alongside, and the
    returns grow to a 5-tuple. ``k_scale=None`` (the default) traces the
    exact pre-quant program — the bit-reference path is untouched.

    ``lora`` (multi-tenant adapter serving, inference/adapters.py) is a
    dict target -> per-slot gathered rank-block factors handed through
    to :func:`~deepspeed_tpu.models.gpt._dense`; ``lora=None`` (the
    default) traces the exact base-only program."""
    B, _, D = x.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    Hkv = cfg.kv_heads
    group = H // Hkv
    bs = k_pool.shape[1]
    NB = tables.shape[1]
    lr = (lambda t: None) if lora is None else lora.get

    h = _norm(x, p["ln1"], cfg)
    qkv = _dense(h, p["qkv"], lora=lr("qkv"))
    q, k, v = jnp.split(qkv, [H * Dh, (H + Hkv) * Dh], axis=-1)
    if cfg.rotary_dim:
        from deepspeed_tpu.ops.attention.rotary import apply_rotary
        q, k = apply_rotary(q.reshape(B, 1, H, Dh), k.reshape(B, 1, Hkv, Dh),
                            lengths[:, None], cfg.rotary_dim,
                            base=cfg.rope_theta)
    q = q.reshape(B, Hkv, group, Dh)
    k = k.reshape(B, Hkv, Dh)
    v = v.reshape(B, Hkv, Dh)

    # scatter the new token's K/V into each slot's current block; a slot
    # whose block budget is exhausted (lengths == NB*bs) would CLAMP to
    # the last block's live data — route it to the trash block instead
    # (serving.py finishes such slots before they reach here; the mask
    # is the engine-side belt to that suspender)
    in_cap = lengths < NB * bs
    blk = jnp.take_along_axis(
        tables, jnp.clip(lengths // bs, 0, NB - 1)[:, None], axis=1)[:, 0]
    blk = jnp.where(jnp.logical_and(active, in_cap), blk, 0)
    off = lengths % bs
    if k_scale is None:
        k_pool = k_pool.at[blk, off].set(k)
        v_pool = v_pool.at[blk, off].set(v)
    else:
        kb = quantizer.kv_dequantize_blocks(k_pool[blk], k_scale[blk])
        vb = quantizer.kv_dequantize_blocks(v_pool[blk], v_scale[blk])
        rows = jnp.arange(B)
        kb = kb.at[rows, off].set(k.astype(jnp.float32))
        vb = vb.at[rows, off].set(v.astype(jnp.float32))
        # lanes past the new token are a previous owner's garbage
        live = jnp.arange(bs)[None, :] <= off[:, None]
        kq, ksn = quantizer.kv_requantize_blocks(kb, live)
        vq, vsn = quantizer.kv_requantize_blocks(vb, live)
        k_pool = k_pool.at[blk].set(kq)
        v_pool = v_pool.at[blk].set(vq)
        k_scale = k_scale.at[blk].set(ksn)
        v_scale = v_scale.at[blk].set(vsn)

    scale = cfg.attn_scale if cfg.attn_scale is not None \
        else 1.0 / np.sqrt(Dh)
    if impl == "pallas":
        from deepspeed_tpu.ops.attention.paged import paged_decode_attention
        attn = paged_decode_attention(
            q, k_pool, v_pool, tables, lengths, scale=float(scale),
            window=cfg.attn_window, k_scale=k_scale,
            v_scale=v_scale).reshape(B, 1, D)
    else:
        if k_scale is None:
            kc = _gather_blocks(k_pool, tables)  # [B, NB*bs, Hkv, Dh]
            vc = _gather_blocks(v_pool, tables)
        else:
            kc = quantizer.kv_dequantize_blocks(
                k_pool[tables], k_scale[tables],
                dtype=x.dtype).reshape(B, NB * bs, Hkv, Dh)
            vc = quantizer.kv_dequantize_blocks(
                v_pool[tables], v_scale[tables],
                dtype=x.dtype).reshape(B, NB * bs, Hkv, Dh)
        scores = jnp.einsum("bkgd,bskd->bkgs", q, kc).astype(jnp.float32)
        scores *= scale
        idx = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, NB * bs), 3)
        pos = lengths[:, None, None, None]
        scores = jnp.where(idx <= pos, scores, -1e30)
        if cfg.attn_window is not None:
            # block tables keep logical order, so cache-index distance IS
            # logical distance — same banding as the static decode
            scores = jnp.where(idx > pos - cfg.attn_window, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        attn = jnp.einsum("bkgs,bskd->bkgd", probs, vc).reshape(B, 1, D)
    attn = _dense(attn, p["attn_out"], lora=lr("attn_out"))
    if cfg.parallel_residual:
        y = x + attn + _ffn(h, p, cfg, lora=lora)
    else:
        x = x + attn
        h = _norm(x, p["ln2"], cfg)
        y = x + _ffn(h, p, cfg, lora=lora)
    if k_scale is None:
        return y, k_pool, v_pool
    return y, k_pool, v_pool, k_scale, v_scale


def _block_verify_paged(x, k_pool, v_pool, tables, lengths, active, p,
                        cfg: GPTConfig, impl: str = "gather",
                        k_scale=None, v_scale=None, lora=None):
    """One block for a G-token SPECULATIVE CHUNK per slot, K/V addressed
    through block tables — the q_len>1 generalization of
    _block_decode_paged for draft/verify serving. x: [B, G, D]; chunk
    token i of slot b sits at cache position lengths[b] + i. The chunk's
    K/V are scattered into the slot's CURRENT blocks before attention
    (within-chunk causality is then just the position mask); after the
    scheduler's accept/reject, ``lengths`` advances past the accepted
    prefix only — stale rejected entries are overwritten by the next
    chunk before any query can attend them, no copy needed.

    Writes beyond the slot's allocated capacity (tokens_per_slot) route
    to the trash block, mirroring _block_decode_paged: the scheduler
    caps acceptance at the allocated capacity so logits from those
    positions are never used.

    With ``k_scale``/``v_scale`` the pools are int8 and the write is a
    read-modify-requantize of the W consecutive blocks the G-token chunk
    can straddle (W = 1 + ceil((G-1)/block)); returns grow to a 5-tuple.
    ``k_scale=None`` traces the exact pre-quant program; ``lora=None``
    the exact base-only program (see _block_decode_paged)."""
    B, G, D = x.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    Hkv = cfg.kv_heads
    group = H // Hkv
    bs = k_pool.shape[1]
    NB = tables.shape[1]
    lr = (lambda t: None) if lora is None else lora.get

    h = _norm(x, p["ln1"], cfg)
    qkv = _dense(h, p["qkv"], lora=lr("qkv"))
    pos = lengths[:, None] + jnp.arange(G, dtype=jnp.int32)[None]  # [B, G]
    q, k, v = _qkv_split_rotary(qkv, cfg, pos, B, G)
    qg = q.reshape(B, G, Hkv, group, Dh)

    # scatter the chunk's K/V through the block table; out-of-capacity
    # or inactive lanes land in trash block 0 (same belt-and-suspender
    # as the one-token decode scatter)
    in_cap = pos < NB * bs
    if k_scale is None:
        blk = jnp.take_along_axis(tables, jnp.clip(pos // bs, 0, NB - 1),
                                  axis=1)                        # [B, G]
        blk = jnp.where(jnp.logical_and(active[:, None], in_cap), blk, 0)
        off = pos % bs
        k_pool = k_pool.at[blk, off].set(k)
        v_pool = v_pool.at[blk, off].set(v)
    else:
        # read-modify-requantize the W consecutive table entries the
        # chunk can touch, starting at the block holding position
        # lengths[b]
        W = 1 + (G + bs - 2) // bs
        j0 = lengths // bs                                       # [B]
        wj = j0[:, None] + jnp.arange(W, dtype=jnp.int32)[None]  # [B, W]
        wjc = jnp.clip(wj, 0, NB - 1)
        blkw = jnp.take_along_axis(tables, wjc, axis=1)          # [B, W]
        kb = quantizer.kv_dequantize_blocks(k_pool[blkw], k_scale[blkw])
        vb = quantizer.kv_dequantize_blocks(v_pool[blkw], v_scale[blkw])
        # chunk token i of slot b lands at window-flat lane
        # (pos//bs - j0)*bs + pos%bs; masked lanes drop out of bounds
        tgt = (pos // bs - j0[:, None]) * bs + pos % bs          # [B, G]
        writable = jnp.logical_and(active[:, None], in_cap)
        tgt = jnp.where(writable, tgt, W * bs)
        rows = jnp.arange(B, dtype=jnp.int32)[:, None]
        kb = kb.reshape(B, W * bs, Hkv, Dh).at[rows, tgt].set(
            k.astype(jnp.float32),
            mode="drop").reshape(B, W, bs, Hkv, Dh)
        vb = vb.reshape(B, W * bs, Hkv, Dh).at[rows, tgt].set(
            v.astype(jnp.float32),
            mode="drop").reshape(B, W, bs, Hkv, Dh)
        # lanes at global positions past the chunk's end are stale
        glob = wj[:, :, None] * bs + \
            jnp.arange(bs, dtype=jnp.int32)[None, None, :]       # [B, W, bs]
        new_len = jnp.minimum(lengths + G, NB * bs)
        live = glob < new_len[:, None, None]
        kq, ksn = quantizer.kv_requantize_blocks(kb, live)
        vq, vsn = quantizer.kv_requantize_blocks(vb, live)
        # window entries past the slot's last written block (and inactive
        # slots entirely) route to the trash block
        jhi = jnp.minimum((lengths + G - 1) // bs, NB - 1)
        touched = jnp.logical_and(wj <= jhi[:, None], active[:, None])
        blkw = jnp.where(touched, blkw, 0)
        k_pool = k_pool.at[blkw].set(kq)
        v_pool = v_pool.at[blkw].set(vq)
        k_scale = k_scale.at[blkw].set(ksn)
        v_scale = v_scale.at[blkw].set(vsn)

    scale = cfg.attn_scale if cfg.attn_scale is not None \
        else 1.0 / np.sqrt(Dh)
    if impl == "pallas":
        from deepspeed_tpu.ops.attention.paged import paged_verify_attention
        attn = paged_verify_attention(
            qg, k_pool, v_pool, tables, lengths, scale=float(scale),
            window=cfg.attn_window, k_scale=k_scale,
            v_scale=v_scale).reshape(B, G, D)
    else:
        if k_scale is None:
            kc = _gather_blocks(k_pool, tables)  # [B, NB*bs, Hkv, Dh]
            vc = _gather_blocks(v_pool, tables)
        else:
            kc = quantizer.kv_dequantize_blocks(
                k_pool[tables], k_scale[tables],
                dtype=x.dtype).reshape(B, NB * bs, Hkv, Dh)
            vc = quantizer.kv_dequantize_blocks(
                v_pool[tables], v_scale[tables],
                dtype=x.dtype).reshape(B, NB * bs, Hkv, Dh)
        scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, kc).astype(jnp.float32)
        scores *= scale
        idx = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, 1, NB * bs), 4)
        qpos = pos[:, None, None, :, None]
        scores = jnp.where(idx <= qpos, scores, -1e30)
        if cfg.attn_window is not None:
            scores = jnp.where(idx > qpos - cfg.attn_window, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        attn = jnp.einsum("bkgqs,bskd->bqkgd", probs, vc).reshape(B, G, D)
    attn = _dense(attn, p["attn_out"], lora=lr("attn_out"))
    if cfg.parallel_residual:
        y = x + attn + _ffn(h, p, cfg, lora=lora)
    else:
        x = x + attn
        h = _norm(x, p["ln2"], cfg)
        y = x + _ffn(h, p, cfg, lora=lora)
    if k_scale is None:
        return y, k_pool, v_pool
    return y, k_pool, v_pool, k_scale, v_scale


def _block_prefill_paged(x, k_pool, v_pool, table_row, positions, n_valid,
                         p, cfg: GPTConfig, k_scale=None, v_scale=None,
                         lora=None):
    """Forward one block over a PROMPT CHUNK for one slot, writing the
    chunk's K/V through the slot's block table and attending over the
    slot's full cache so far (history from earlier chunks + this chunk)
    — the prefill-chunking path that keeps decode latency bounded for
    long prompts. x: [1, C, D]; positions: [C] global cache positions of
    the chunk tokens; n_valid: how many of the C lanes are real (the
    chunk is padded to a fixed width so ONE compiled program serves
    every chunk).

    With ``k_scale``/``v_scale`` the pools are int8: the slot's whole
    virtual row (gathered for attention anyway) is dequantized, the
    chunk inserted, and ONLY the chunk-touched blocks requantized —
    untouched blocks (including shared prefix blocks mapped read-only)
    are written back byte-identical, so sharing semantics are
    preserved. Returns grow to a 5-tuple; ``k_scale=None`` traces the
    exact pre-quant program; ``lora=None`` the exact base-only program
    (see _block_decode_paged; here the gathered factors carry the
    prefill row's B=1 leading dim)."""
    B, C, D = x.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    Hkv = cfg.kv_heads
    group = H // Hkv
    bs = k_pool.shape[1]
    NB = table_row.shape[0]
    lr = (lambda t: None) if lora is None else lora.get

    h = _norm(x, p["ln1"], cfg)
    qkv = _dense(h, p["qkv"], lora=lr("qkv"))
    q, k, v = gpt_lib._qkv_split_rotary(qkv, cfg, positions[None], B, C)

    valid = jnp.arange(C) < n_valid
    if k_scale is None:
        blk = table_row[jnp.clip(positions // bs, 0, NB - 1)]
        blk = jnp.where(valid, blk, 0)       # padded lanes -> trash block
        off = positions % bs
        k_pool = k_pool.at[blk, off].set(k[0])
        v_pool = v_pool.at[blk, off].set(v[0])

        kc = k_pool[table_row].reshape(NB * bs, Hkv, Dh)
        vc = v_pool[table_row].reshape(NB * bs, Hkv, Dh)
    else:
        kb = quantizer.kv_dequantize_blocks(k_pool[table_row],
                                            k_scale[table_row])
        vb = quantizer.kv_dequantize_blocks(v_pool[table_row],
                                            v_scale[table_row])
        tgt = jnp.where(jnp.logical_and(valid, positions < NB * bs),
                        positions, NB * bs)  # padded lanes drop
        kb = kb.reshape(NB * bs, Hkv, Dh).at[tgt].set(
            k[0].astype(jnp.float32), mode="drop").reshape(NB, bs, Hkv, Dh)
        vb = vb.reshape(NB * bs, Hkv, Dh).at[tgt].set(
            v[0].astype(jnp.float32), mode="drop").reshape(NB, bs, Hkv, Dh)
        start = positions[0]
        new_total = start + n_valid
        glob = jnp.arange(NB, dtype=jnp.int32)[:, None] * bs + \
            jnp.arange(bs, dtype=jnp.int32)[None]
        live = glob < new_total
        kq, ksn = quantizer.kv_requantize_blocks(kb, live)
        vq, vsn = quantizer.kv_requantize_blocks(vb, live)
        # requantize only the chunk-touched blocks; everything else is
        # scattered back byte-identical (shared prefix blocks included)
        j = jnp.arange(NB, dtype=jnp.int32)
        j0 = start // bs
        j1 = jnp.maximum(start + n_valid - 1, start) // bs
        touched = jnp.logical_and(j >= j0, j <= j1)
        kq = jnp.where(touched[:, None, None, None], kq,
                       k_pool[table_row])
        vq = jnp.where(touched[:, None, None, None], vq,
                       v_pool[table_row])
        ksn = jnp.where(touched[:, None], ksn, k_scale[table_row])
        vsn = jnp.where(touched[:, None], vsn, v_scale[table_row])
        k_pool = k_pool.at[table_row].set(kq)
        v_pool = v_pool.at[table_row].set(vq)
        k_scale = k_scale.at[table_row].set(ksn)
        v_scale = v_scale.at[table_row].set(vsn)
        # attend over exactly what the pool now holds
        kc = quantizer.kv_dequantize_blocks(
            kq, ksn, dtype=x.dtype).reshape(NB * bs, Hkv, Dh)
        vc = quantizer.kv_dequantize_blocks(
            vq, vsn, dtype=x.dtype).reshape(NB * bs, Hkv, Dh)
    qg = q[0].reshape(C, Hkv, group, Dh)
    scores = jnp.einsum("ckgd,skd->ckgs", qg, kc).astype(jnp.float32)
    scores *= cfg.attn_scale if cfg.attn_scale is not None \
        else 1.0 / np.sqrt(Dh)
    sidx = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, NB * bs), 3)
    qpos = positions[:, None, None, None]
    scores = jnp.where(sidx <= qpos, scores, -1e30)
    if cfg.attn_window is not None:
        scores = jnp.where(sidx > qpos - cfg.attn_window, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    attn = jnp.einsum("ckgs,skd->ckgd", probs, vc).reshape(1, C, D)
    attn = _dense(attn, p["attn_out"], lora=lr("attn_out"))
    if cfg.parallel_residual:
        y = x + attn + _ffn(h, p, cfg, lora=lora)
    else:
        x = x + attn
        h = _norm(x, p["ln2"], cfg)
        y = x + _ffn(h, p, cfg, lora=lora)
    if k_scale is None:
        return y, k_pool, v_pool
    return y, k_pool, v_pool, k_scale, v_scale


class InferenceEngine:
    """Generation engine over a GPT-layout parameter pytree.

    Construct via ``deepspeed_tpu.init_inference(model=...)`` where model is
    either (GPTConfig, params) from this framework or anything a policy in
    inference/policy.py can convert (e.g. an HF GPT-2 checkpoint).
    """

    def __init__(self, model=None, *, config: Optional[GPTConfig] = None,
                 params: Optional[PyTree] = None, mp_size: int = 1,
                 dtype=jnp.bfloat16, max_seq_len: Optional[int] = None,
                 mesh: Optional[Mesh] = None,
                 replace_with_kernel_inject: bool = True,
                 checkpoint: Optional[str] = None,
                 decode_impl: Optional[str] = None, **kwargs):
        if model is not None and (config is None or params is None):
            from deepspeed_tpu.inference.policy import resolve_model
            config, params = resolve_model(model)
        if checkpoint is not None:
            # trained weights from a sharded training checkpoint override
            # whatever the model/policy supplied (ref: engine.py:281
            # _load_checkpoint resharding trained weights into the skeleton)
            from deepspeed_tpu.runtime.checkpointing import \
                load_fp32_state_dict_from_zero_checkpoint
            params = load_fp32_state_dict_from_zero_checkpoint(checkpoint)
        assert config is not None and params is not None, \
            "need a model config: pass (config, params), or a model a " \
            "policy understands (checkpoint= supplies weights only)"
        self.cfg = config
        self.dtype = dtype
        self.max_seq_len = max_seq_len or config.max_seq_len
        self.mp_size = mp_size
        self.latency_ms: Dict[str, float] = {}
        # paged decode attention path: "pallas" (flash-decode through the
        # block table) or "gather" (dense reference); default resolves
        # DS_PAGED_DECODE_IMPL then platform (pallas on TPU)
        from deepspeed_tpu.ops.attention.paged import resolve_decode_impl
        self.decode_impl = resolve_decode_impl(decode_impl)

        if mesh is None:
            n = len(jax.devices())
            assert n % mp_size == 0, (n, mp_size)
            mesh = mesh_lib.make_mesh(
                mesh_lib.MeshSpec(data=n // mp_size, model=mp_size))
        self.mesh = mesh

        from deepspeed_tpu.models.bert import BertConfig as _BertConfig
        self.is_encoder = isinstance(config, _BertConfig)
        if self.is_encoder and config.dtype != dtype:
            # bert.encode casts by cfg.dtype; keep it in the engine dtype
            import dataclasses
            self.cfg = config = dataclasses.replace(config, dtype=dtype)

        # dtype conversion (ref: engine.py:335 _convert_to_dtype) + TP placement
        # dtype=jnp.int8 selects weight-only int8 (API parity with the
        # reference's init_inference(dtype=torch.int8) quantize path):
        # kernels stored int8 + per-channel scales, activations bf16
        self.quantized = (jnp.dtype(dtype) == jnp.int8)
        if self.quantized:
            from deepspeed_tpu.utils import on_tpu
            dtype = jnp.bfloat16 if on_tpu() else jnp.float32
            self.dtype = dtype
        params = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, dtype) if jnp.issubdtype(
                jnp.asarray(x).dtype, jnp.floating) else jnp.asarray(x),
            params)
        if self.quantized:
            if self.is_encoder:
                raise ValueError("weight-only int8 currently covers the "
                                 "decoder path (GPT/llama/MoE layouts)")
            params = quantize_weights_int8(params)
        if mp_size > 1:
            from deepspeed_tpu.models.bert import bert_partition_rules
            rules = bert_partition_rules() if self.is_encoder \
                else gpt_lib.gpt_partition_rules()
            if self.quantized:
                # int8 records replace kernel with q (same shape, same
                # spec) + a [..., 1, out] per-channel scale whose -2 axis
                # must stay unsharded (size 1)
                from deepspeed_tpu.parallel.sharding import PartitionRule
                extra = []
                for r in rules:
                    pat = r.pattern.pattern
                    if "/kernel" in pat:
                        extra.append(PartitionRule(
                            pat.replace("/kernel", "/q"), r.spec))
                        sc = list(r.spec)
                        if len(sc) >= 2:
                            sc[-2] = None
                        extra.append(PartitionRule(
                            pat.replace("/kernel", "/scale"), P(*sc)))
                rules = rules + extra
        else:
            rules = []
        pspecs = sharding_lib.param_specs(params, mesh, zero_stage=0,
                                          rules=rules)
        self.params = jax.device_put(
            params, sharding_lib.to_named(pspecs, mesh))

        if self.is_encoder:
            self._forward = jax.jit(self._encoder_forward_fn)
            self._prefill = self._decode = None
        else:
            self._prefill = jax.jit(self._prefill_fn)
            self._decode = jax.jit(self._decode_fn, donate_argnums=(1,))
            self._forward = jax.jit(self._forward_fn)
            # paged-serving programs: the steady-state continuous-batching
            # loop is exactly these two compiled programs regardless of
            # arrival pattern; pools are donated so the cache never
            # doubles in HBM across a step
            self._prefill_slot = jax.jit(self._prefill_slot_fn,
                                         donate_argnums=(1, 2))
            # impl is static: each attention path ("gather" | "pallas")
            # is its own compiled program; a serving run pins one impl so
            # steady state remains two programs
            self._decode_slots = jax.jit(self._decode_slots_fn,
                                         donate_argnums=(1, 2),
                                         static_argnums=(7,))
            # fused multi-step decode (DS_DECODE_HORIZON > 1,
            # docs/MULTISTEP.md): the SAME donated-pool decode body
            # scanned N times on-device with the stop/length predicates
            # as in-program masks. n_steps joins impl as a static — a
            # serving run pins one N, so steady state stays at the same
            # program count, and N=1 serving never compiles this family
            self._decode_horizon = jax.jit(self._decode_horizon_fn,
                                           donate_argnums=(1, 2),
                                           static_argnums=(7, 8))
            # speculative verify: all k+1 chunk positions per slot in
            # ONE extended-decode program — when serving runs with
            # spec_decode on, this REPLACES the plain decode program in
            # steady state (the chunk width G is fixed per serving
            # engine, so one program serves every step)
            self._verify_slots = jax.jit(self._verify_slots_fn,
                                         donate_argnums=(1, 2),
                                         static_argnums=(7,))
            # static-path chunk verify (inference/speculative.py): the
            # dense-cache twin of _verify_slots, kept here so the
            # speculative module shares the engine's compiled program
            # cache instead of duplicating the block math
            self._extend = jax.jit(self._extend_fn, donate_argnums=(1,))
            # prefix-cache copy-on-write block copy: src/dst are traced
            # scalars, so every divergence reuses ONE compiled program
            # (warmed at ServingEngine construction — the steady-state
            # compile contract stays at zero recompiles with the prefix
            # cache on)
            self._cow_blocks = jax.jit(self._cow_blocks_fn,
                                       donate_argnums=(0, 1))
            # int8 KV-cache twins (DS_KV_QUANT=int8): same program COUNT
            # as the fp path — a quantized serving run compiles ONLY
            # these (the fp programs above stay cold), so the steady-
            # state compile contract is unchanged. The scale pools are
            # donated alongside the int8 pools.
            self._prefill_slot_q = jax.jit(self._prefill_slot_q_fn,
                                           donate_argnums=(1, 2, 3, 4))
            self._decode_slots_q = jax.jit(self._decode_slots_q_fn,
                                           donate_argnums=(1, 2, 3, 4),
                                           static_argnums=(9,))
            self._decode_horizon_q = jax.jit(self._decode_horizon_q_fn,
                                             donate_argnums=(1, 2, 3, 4),
                                             static_argnums=(9, 10))
            self._verify_slots_q = jax.jit(self._verify_slots_q_fn,
                                           donate_argnums=(1, 2, 3, 4),
                                           static_argnums=(9,))
            self._cow_blocks_q = jax.jit(self._cow_blocks_q_fn,
                                         donate_argnums=(0, 1, 2, 3))
            # multi-tenant LoRA twins (DS_LORA_SERVE=on, inference/
            # adapters.py): adapter pools + the per-slot adapter-table
            # rows ride at the END of each signature as traced DATA —
            # donate/static indices are unchanged, and the pools are
            # never donated (read-only, shared across steps and slots).
            # A lora run compiles ONLY these (base-only serving keeps
            # the fp/_q programs cold and vice versa), so the steady-
            # state program COUNT contract holds either way, for ANY
            # number of registered adapters
            self._prefill_slot_l = jax.jit(self._prefill_slot_l_fn,
                                           donate_argnums=(1, 2))
            self._decode_slots_l = jax.jit(self._decode_slots_l_fn,
                                           donate_argnums=(1, 2),
                                           static_argnums=(7,))
            self._decode_horizon_l = jax.jit(self._decode_horizon_l_fn,
                                             donate_argnums=(1, 2),
                                             static_argnums=(7, 8))
            self._verify_slots_l = jax.jit(self._verify_slots_l_fn,
                                           donate_argnums=(1, 2),
                                           static_argnums=(7,))
            self._prefill_slot_ql = jax.jit(self._prefill_slot_ql_fn,
                                            donate_argnums=(1, 2, 3, 4))
            self._decode_slots_ql = jax.jit(self._decode_slots_ql_fn,
                                            donate_argnums=(1, 2, 3, 4),
                                            static_argnums=(9,))
            self._decode_horizon_ql = jax.jit(self._decode_horizon_ql_fn,
                                              donate_argnums=(1, 2, 3, 4),
                                              static_argnums=(9, 10))
            self._verify_slots_ql = jax.jit(self._verify_slots_ql_fn,
                                            donate_argnums=(1, 2, 3, 4),
                                            static_argnums=(9,))
            # host-tier transfer programs (DS_KV_HOST_TIER=on): the
            # spill gather keeps the pools live (the copy rides out
            # while decode keeps serving), the restore scatter donates
            # them like COW. ids/dst are traced, widths fixed per cache,
            # so steady state adds ZERO programs beyond the two warmed
            # at ServingEngine construction (paged_cache.warm_host_tier)
            self._gather_blocks = jax.jit(self._gather_blocks_fn)
            self._scatter_block = jax.jit(self._scatter_block_fn,
                                          donate_argnums=(0, 1))
            self._gather_blocks_q = jax.jit(self._gather_blocks_q_fn)
            self._scatter_block_q = jax.jit(self._scatter_block_q_fn,
                                            donate_argnums=(0, 1, 2, 3))
        log_dist(f"inference engine: {config.n_layers}L/{config.d_model}d "
                 f"mp={mp_size} dtype={jnp.dtype(dtype).name} "
                 f"{'encoder' if self.is_encoder else 'decoder'}",
                 ranks=[0])

    # ------------------------------------------------------------------
    # params are threaded explicitly (never via self) so jit treats the
    # weights as arguments, not baked-in constants
    def _embed(self, params, tokens):
        S = tokens.shape[1]
        x = params["wte"]["embedding"][tokens]
        if self.cfg.use_wpe:
            x = x + params["wpe"]["embedding"][:S][None]
        return x

    def _logits(self, params, x):
        from deepspeed_tpu.models.gpt import _kernel_of
        x = _norm(x, params["ln_f"], self.cfg)
        if self.cfg.tie_embeddings:
            return x @ params["wte"]["embedding"].T
        logits = x @ _kernel_of(params["lm_head"], x.dtype)
        if "bias" in params["lm_head"]:
            logits = logits + params["lm_head"]["bias"]
        return logits

    def _prefill_fn(self, params, tokens, attn_mask=None):
        """Run the prompt, build the cache, return last-position logits.

        attn_mask: optional [B, S] validity for LEFT-padded batched
        prompts (1 = real token); positional embeddings restart per row
        and padded keys never receive attention."""
        cfg = self.cfg
        B, S = tokens.shape
        S_max = self.max_seq_len
        positions = None
        if attn_mask is None:
            x = self._embed(params, tokens)
        else:
            # per-row positions restart after the left padding
            positions = jnp.clip(
                jnp.cumsum(attn_mask.astype(jnp.int32), axis=1) - 1,
                0, None)
            x = params["wte"]["embedding"][tokens]
            if cfg.use_wpe:
                x = x + params["wpe"]["embedding"][positions]

        def body(x, layer_p):
            y, k, v = _block_prefill(x, layer_p, cfg, kv_mask=attn_mask,
                                     positions=positions)
            return y, (k, v)

        x, (ks, vs) = jax.lax.scan(body, x, params["block"])
        # ks: [L, B, S, H, Dh] -> pad to S_max
        pad = [(0, 0), (0, 0), (0, S_max - S), (0, 0), (0, 0)]
        cache = {"k": jnp.pad(ks, pad), "v": jnp.pad(vs, pad)}
        if attn_mask is not None:
            # decode slots (>= S) are always valid once written
            cache["mask"] = jnp.concatenate(
                [attn_mask.astype(jnp.float32),
                 jnp.ones((B, S_max - S), jnp.float32)], axis=1)
        logits = self._logits(params, x[:, -1:])
        return logits, cache

    def _decode_fn(self, params, cache, token, pos, row_pos=None):
        """One token step. token: [B, 1]; pos: scalar cache index;
        row_pos: optional [B] per-row LOGICAL positions (left-padded
        batches, where real lengths differ from the cache index)."""
        cfg = self.cfg
        x = params["wte"]["embedding"][token]
        if cfg.use_wpe:
            wpe = params["wpe"]["embedding"]
            if row_pos is not None:
                x = x + wpe[row_pos][:, None]
            else:
                x = x + jax.lax.dynamic_slice_in_dim(wpe, pos, 1)[None]
        cache_mask = cache.get("mask")

        def body(x, layer):
            layer_p, kc, vc = layer
            y, kc, vc = _block_decode(x, kc, vc, pos, layer_p, cfg,
                                      cache_mask=cache_mask,
                                      row_pos=row_pos)
            return y, (kc, vc)

        x, (ks, vs) = jax.lax.scan(body, x,
                                   (params["block"], cache["k"], cache["v"]))
        logits = self._logits(params, x)
        out = {"k": ks, "v": vs}
        if cache_mask is not None:
            out["mask"] = cache_mask
        return logits, out

    def _prefill_slot_fn(self, params, k_pool, v_pool, table_row, tokens,
                         start, n_valid, key, gen_count, temp, top_k,
                         top_p, rep_pen, seen_row):
        """Prefill ONE prompt chunk into one serving slot's paged cache.

        tokens: [C] fixed-width chunk (padded; n_valid real tokens);
        start: scalar — tokens already cached for this slot (0 for the
        first chunk, the resume point for later chunks / requeued
        requests, the MATCHED BOUNDARY for a prefix-cache hit whose
        shared blocks are already resident); table_row: [NB] the slot's
        block table. The trailing args are the slot's sampling lane
        (inference/sampling.py — all DATA, so the compile contract is
        untouched); the fused sampler runs on the last valid position,
        meaningful once the final chunk lands. Returns the last-valid-
        position logits, the sampled/greedy token [1], its logprob [1],
        and the updated (donated) pools."""
        cfg = self.cfg
        C = tokens.shape[0]
        positions = start + jnp.arange(C, dtype=jnp.int32)
        x = params["wte"]["embedding"][tokens][None]
        if cfg.use_wpe:
            safe = jnp.clip(positions, 0, self.max_seq_len - 1)
            x = x + params["wpe"]["embedding"][safe][None]

        def body(x, layer):
            layer_p, kp, vp = layer
            y, kp, vp = _block_prefill_paged(x, kp, vp, table_row,
                                             positions, n_valid, layer_p,
                                             cfg)
            return y, (kp, vp)

        x, (ks, vs) = jax.lax.scan(body, x,
                                   (params["block"], k_pool, v_pool))
        last = jnp.clip(n_valid - 1, 0, C - 1)
        x_last = jax.lax.dynamic_slice_in_dim(x, last, 1, axis=1)
        logits = self._logits(params, x_last)
        tok, lp = sampling.sample_tokens(
            logits[:, -1], key.reshape(1, 2), gen_count.reshape(1),
            temp.reshape(1), top_k.reshape(1), top_p.reshape(1),
            rep_pen.reshape(1), seen_row.reshape(1, -1))
        return logits, tok, lp, ks, vs

    def _decode_slots_fn(self, params, k_pool, v_pool, tables, lengths,
                         tokens, active, impl, keys, gen_counts, temps,
                         top_ks, top_ps, rep_pens, seen):
        """One decode step for EVERY serving slot at once. tokens: [B]
        (each slot's pending token); lengths: [B] per-slot cache
        positions; active: [B] (inactive slots run but write to the
        trash block and their logits are discarded). The slot-batched
        shape is static, so any mix of requests reuses this one
        compiled program. impl is a STATIC jit argument ("gather" |
        "pallas") selecting the attention path per compiled program —
        see _block_decode_paged. The trailing args are the slot-indexed
        sampling arrays (inference/sampling.py) — DATA, never statics,
        so arbitrarily mixed greedy/sampled batches reuse this one
        program; the fused sampler emits each slot's next token (and
        its logprob) in the same dispatch as the forward step."""
        cfg = self.cfg
        x = params["wte"]["embedding"][tokens[:, None]]
        if cfg.use_wpe:
            safe = jnp.clip(lengths, 0, self.max_seq_len - 1)
            x = x + params["wpe"]["embedding"][safe][:, None]

        def body(x, layer):
            layer_p, kp, vp = layer
            y, kp, vp = _block_decode_paged(x, kp, vp, tables, lengths,
                                            active, layer_p, cfg,
                                            impl=impl)
            return y, (kp, vp)

        x, (ks, vs) = jax.lax.scan(body, x,
                                   (params["block"], k_pool, v_pool))
        logits = self._logits(params, x)
        toks, lps = sampling.sample_tokens(logits[:, -1], keys, gen_counts,
                                           temps, top_ks, top_ps, rep_pens,
                                           seen)
        return logits, toks, lps, ks, vs

    def _verify_slots_fn(self, params, k_pool, v_pool, tables, lengths,
                         tokens, active, impl="gather"):
        """One speculative VERIFY step for every serving slot at once:
        score all G chunk positions (pending token + G-1 draft tokens)
        per slot in one compiled program. tokens: [B, G] (chunk token i
        of slot b sits at cache position lengths[b] + i); returns logits
        [B, G, V] + updated (donated) pools. The slot-batched shape and
        the chunk width are static, so any mix of requests — across
        eviction, requeue and prefix-cache hits — reuses this ONE
        program; impl is a static jit argument exactly like
        _decode_slots_fn."""
        cfg = self.cfg
        B, G = tokens.shape
        x = params["wte"]["embedding"][tokens]
        if cfg.use_wpe:
            pos = lengths[:, None] + jnp.arange(G, dtype=jnp.int32)[None]
            safe = jnp.clip(pos, 0, self.max_seq_len - 1)
            x = x + params["wpe"]["embedding"][safe]

        def body(x, layer):
            layer_p, kp, vp = layer
            y, kp, vp = _block_verify_paged(x, kp, vp, tables, lengths,
                                            active, layer_p, cfg,
                                            impl=impl)
            return y, (kp, vp)

        x, (ks, vs) = jax.lax.scan(body, x,
                                   (params["block"], k_pool, v_pool))
        return self._logits(params, x), ks, vs

    def _extend_fn(self, params, cache, tokens, pos):
        """G-token chunk verify over the STATIC dense cache (the
        speculative.py path): logits [B, G, V] + updated cache.
        tokens: [B, G]; pos: scalar first cache index of the chunk.
        The paged twin is _verify_slots_fn."""
        cfg = self.cfg

        x = params["wte"]["embedding"][tokens]
        if cfg.use_wpe:
            G = tokens.shape[1]
            x = x + jax.lax.dynamic_slice_in_dim(
                params["wpe"]["embedding"], pos, G)[None]

        def body(x, layer):
            layer_p, kc, vc = layer
            y, kc, vc = _block_extend(x, kc, vc, pos, layer_p, cfg)
            return y, (kc, vc)

        x, (ks, vs) = jax.lax.scan(body, x,
                                   (params["block"], cache["k"],
                                    cache["v"]))
        logits = self._logits(params, x)
        return logits, {"k": ks, "v": vs}

    def _cow_blocks_fn(self, k_pool, v_pool, src, dst):
        """Copy pool block ``src`` -> ``dst`` across every layer — the
        device half of prefix-cache copy-on-write (paged_cache._cow).
        Pools are donated, so the copy is in-place in HBM."""
        return (k_pool.at[:, dst].set(k_pool[:, src]),
                v_pool.at[:, dst].set(v_pool[:, src]))

    def cow_blocks(self, k_pool, v_pool, src, dst):
        return self._cow_blocks(k_pool, v_pool,  # dslint: disable=DS012 — caller paged_cache._cow fires cache.cow before delegating here
                                jnp.asarray(src, jnp.int32),
                                jnp.asarray(dst, jnp.int32))

    def _prefill_slot_q_fn(self, params, k_pool, v_pool, k_scale, v_scale,
                           table_row, tokens, start, n_valid, key,
                           gen_count, temp, top_k, top_p, rep_pen,
                           seen_row):
        """int8-pool twin of _prefill_slot_fn: the per-layer scale pools
        ([L, N, Hkv] fp32) thread through the scan alongside the pools
        and the block write is the read-modify-requantize path of
        _block_prefill_paged. Carries the same fused sampling lane as
        the fp program."""
        cfg = self.cfg
        C = tokens.shape[0]
        positions = start + jnp.arange(C, dtype=jnp.int32)
        x = params["wte"]["embedding"][tokens][None]
        if cfg.use_wpe:
            safe = jnp.clip(positions, 0, self.max_seq_len - 1)
            x = x + params["wpe"]["embedding"][safe][None]

        def body(x, layer):
            layer_p, kp, vp, ksp, vsp = layer
            y, kp, vp, ksp, vsp = _block_prefill_paged(
                x, kp, vp, table_row, positions, n_valid, layer_p, cfg,
                k_scale=ksp, v_scale=vsp)
            return y, (kp, vp, ksp, vsp)

        x, (ks, vs, kss, vss) = jax.lax.scan(
            body, x, (params["block"], k_pool, v_pool, k_scale, v_scale))
        last = jnp.clip(n_valid - 1, 0, C - 1)
        x_last = jax.lax.dynamic_slice_in_dim(x, last, 1, axis=1)
        logits = self._logits(params, x_last)
        tok, lp = sampling.sample_tokens(
            logits[:, -1], key.reshape(1, 2), gen_count.reshape(1),
            temp.reshape(1), top_k.reshape(1), top_p.reshape(1),
            rep_pen.reshape(1), seen_row.reshape(1, -1))
        return logits, tok, lp, ks, vs, kss, vss

    def _decode_slots_q_fn(self, params, k_pool, v_pool, k_scale, v_scale,
                           tables, lengths, tokens, active, impl, keys,
                           gen_counts, temps, top_ks, top_ps, rep_pens,
                           seen):
        """int8-pool twin of _decode_slots_fn (see _block_decode_paged's
        quantized write path). Carries the same fused sampling lanes as
        the fp program."""
        cfg = self.cfg
        x = params["wte"]["embedding"][tokens[:, None]]
        if cfg.use_wpe:
            safe = jnp.clip(lengths, 0, self.max_seq_len - 1)
            x = x + params["wpe"]["embedding"][safe][:, None]

        def body(x, layer):
            layer_p, kp, vp, ksp, vsp = layer
            y, kp, vp, ksp, vsp = _block_decode_paged(
                x, kp, vp, tables, lengths, active, layer_p, cfg,
                impl=impl, k_scale=ksp, v_scale=vsp)
            return y, (kp, vp, ksp, vsp)

        x, (ks, vs, kss, vss) = jax.lax.scan(
            body, x, (params["block"], k_pool, v_pool, k_scale, v_scale))
        logits = self._logits(params, x)
        toks, lps = sampling.sample_tokens(logits[:, -1], keys, gen_counts,
                                           temps, top_ks, top_ps, rep_pens,
                                           seen)
        return logits, toks, lps, ks, vs, kss, vss

    def _verify_slots_q_fn(self, params, k_pool, v_pool, k_scale, v_scale,
                           tables, lengths, tokens, active, impl="gather"):
        """int8-pool twin of _verify_slots_fn (see _block_verify_paged's
        quantized write path)."""
        cfg = self.cfg
        B, G = tokens.shape
        x = params["wte"]["embedding"][tokens]
        if cfg.use_wpe:
            pos = lengths[:, None] + jnp.arange(G, dtype=jnp.int32)[None]
            safe = jnp.clip(pos, 0, self.max_seq_len - 1)
            x = x + params["wpe"]["embedding"][safe]

        def body(x, layer):
            layer_p, kp, vp, ksp, vsp = layer
            y, kp, vp, ksp, vsp = _block_verify_paged(
                x, kp, vp, tables, lengths, active, layer_p, cfg,
                impl=impl, k_scale=ksp, v_scale=vsp)
            return y, (kp, vp, ksp, vsp)

        x, (ks, vs, kss, vss) = jax.lax.scan(
            body, x, (params["block"], k_pool, v_pool, k_scale, v_scale))
        return self._logits(params, x), ks, vs, kss, vss

    @staticmethod
    def _gather_lora(lora_a, lora_b, ablocks):
        """Per-layer slice of the adapter pools -> per-slot gathered
        factors for gpt._dense's lora hook. ``lora_a[t]``: [NB, in, rb]
        (the scan already consumed the leading L); ``ablocks``:
        [B, NBa] per-slot pool-block rows (traced data — any adapter
        mix reuses the one program). Base-only rows are all zeros and
        gather the permanent trash block."""
        return {t: (lora_a[t][ablocks], lora_b[t][ablocks])
                for t in lora_a}

    def _prefill_slot_l_fn(self, params, k_pool, v_pool, table_row, tokens,
                           start, n_valid, key, gen_count, temp, top_k,
                           top_p, rep_pen, seen_row, lora_a, lora_b,
                           ablock_row):
        """LoRA twin of _prefill_slot_fn: the adapter pools thread
        through the scan alongside the block params and the slot's
        adapter-table row selects its rank blocks (inference/
        adapters.py). An all-zeros row gathers the trash block — the
        base-only prefill bit-for-bit."""
        cfg = self.cfg
        C = tokens.shape[0]
        positions = start + jnp.arange(C, dtype=jnp.int32)
        x = params["wte"]["embedding"][tokens][None]
        if cfg.use_wpe:
            safe = jnp.clip(positions, 0, self.max_seq_len - 1)
            x = x + params["wpe"]["embedding"][safe][None]

        def body(x, layer):
            layer_p, kp, vp, la, lb = layer
            lora = self._gather_lora(la, lb, ablock_row[None])
            y, kp, vp = _block_prefill_paged(x, kp, vp, table_row,
                                             positions, n_valid, layer_p,
                                             cfg, lora=lora)
            return y, (kp, vp)

        x, (ks, vs) = jax.lax.scan(
            body, x, (params["block"], k_pool, v_pool, lora_a, lora_b))
        last = jnp.clip(n_valid - 1, 0, C - 1)
        x_last = jax.lax.dynamic_slice_in_dim(x, last, 1, axis=1)
        logits = self._logits(params, x_last)
        tok, lp = sampling.sample_tokens(
            logits[:, -1], key.reshape(1, 2), gen_count.reshape(1),
            temp.reshape(1), top_k.reshape(1), top_p.reshape(1),
            rep_pen.reshape(1), seen_row.reshape(1, -1))
        return logits, tok, lp, ks, vs

    def _decode_slots_l_fn(self, params, k_pool, v_pool, tables, lengths,
                           tokens, active, impl, keys, gen_counts, temps,
                           top_ks, top_ps, rep_pens, seen, lora_a, lora_b,
                           ablocks):
        """LoRA twin of _decode_slots_fn: one compiled program decodes
        any mix of adapters and base-only slots — ``ablocks`` [B, NBa]
        is traced data exactly like the sampling lanes."""
        cfg = self.cfg
        x = params["wte"]["embedding"][tokens[:, None]]
        if cfg.use_wpe:
            safe = jnp.clip(lengths, 0, self.max_seq_len - 1)
            x = x + params["wpe"]["embedding"][safe][:, None]

        def body(x, layer):
            layer_p, kp, vp, la, lb = layer
            lora = self._gather_lora(la, lb, ablocks)
            y, kp, vp = _block_decode_paged(x, kp, vp, tables, lengths,
                                            active, layer_p, cfg,
                                            impl=impl, lora=lora)
            return y, (kp, vp)

        x, (ks, vs) = jax.lax.scan(
            body, x, (params["block"], k_pool, v_pool, lora_a, lora_b))
        logits = self._logits(params, x)
        toks, lps = sampling.sample_tokens(logits[:, -1], keys, gen_counts,
                                           temps, top_ks, top_ps, rep_pens,
                                           seen)
        return logits, toks, lps, ks, vs

    def _verify_slots_l_fn(self, params, k_pool, v_pool, tables, lengths,
                           tokens, active, impl="gather", lora_a=None,
                           lora_b=None, ablocks=None):
        """LoRA twin of _verify_slots_fn: each slot's draft chunk is
        scored under ITS adapter (speculative decode composes with
        multi-tenant serving — the verify distribution is the adapted
        model's, so accept/reject stays lossless per tenant)."""
        cfg = self.cfg
        B, G = tokens.shape
        x = params["wte"]["embedding"][tokens]
        if cfg.use_wpe:
            pos = lengths[:, None] + jnp.arange(G, dtype=jnp.int32)[None]
            safe = jnp.clip(pos, 0, self.max_seq_len - 1)
            x = x + params["wpe"]["embedding"][safe]

        def body(x, layer):
            layer_p, kp, vp, la, lb = layer
            lora = self._gather_lora(la, lb, ablocks)
            y, kp, vp = _block_verify_paged(x, kp, vp, tables, lengths,
                                            active, layer_p, cfg,
                                            impl=impl, lora=lora)
            return y, (kp, vp)

        x, (ks, vs) = jax.lax.scan(
            body, x, (params["block"], k_pool, v_pool, lora_a, lora_b))
        return self._logits(params, x), ks, vs

    def _prefill_slot_ql_fn(self, params, k_pool, v_pool, k_scale, v_scale,
                            table_row, tokens, start, n_valid, key,
                            gen_count, temp, top_k, top_p, rep_pen,
                            seen_row, lora_a, lora_b, ablock_row):
        """int8-pool + LoRA combo twin (DS_KV_QUANT=int8 with
        DS_LORA_SERVE=on): quantized KV write path, adapted
        projections."""
        cfg = self.cfg
        C = tokens.shape[0]
        positions = start + jnp.arange(C, dtype=jnp.int32)
        x = params["wte"]["embedding"][tokens][None]
        if cfg.use_wpe:
            safe = jnp.clip(positions, 0, self.max_seq_len - 1)
            x = x + params["wpe"]["embedding"][safe][None]

        def body(x, layer):
            layer_p, kp, vp, ksp, vsp, la, lb = layer
            lora = self._gather_lora(la, lb, ablock_row[None])
            y, kp, vp, ksp, vsp = _block_prefill_paged(
                x, kp, vp, table_row, positions, n_valid, layer_p, cfg,
                k_scale=ksp, v_scale=vsp, lora=lora)
            return y, (kp, vp, ksp, vsp)

        x, (ks, vs, kss, vss) = jax.lax.scan(
            body, x, (params["block"], k_pool, v_pool, k_scale, v_scale,
                      lora_a, lora_b))
        last = jnp.clip(n_valid - 1, 0, C - 1)
        x_last = jax.lax.dynamic_slice_in_dim(x, last, 1, axis=1)
        logits = self._logits(params, x_last)
        tok, lp = sampling.sample_tokens(
            logits[:, -1], key.reshape(1, 2), gen_count.reshape(1),
            temp.reshape(1), top_k.reshape(1), top_p.reshape(1),
            rep_pen.reshape(1), seen_row.reshape(1, -1))
        return logits, tok, lp, ks, vs, kss, vss

    def _decode_slots_ql_fn(self, params, k_pool, v_pool, k_scale, v_scale,
                            tables, lengths, tokens, active, impl, keys,
                            gen_counts, temps, top_ks, top_ps, rep_pens,
                            seen, lora_a, lora_b, ablocks):
        """int8-pool + LoRA combo twin of _decode_slots_fn."""
        cfg = self.cfg
        x = params["wte"]["embedding"][tokens[:, None]]
        if cfg.use_wpe:
            safe = jnp.clip(lengths, 0, self.max_seq_len - 1)
            x = x + params["wpe"]["embedding"][safe][:, None]

        def body(x, layer):
            layer_p, kp, vp, ksp, vsp, la, lb = layer
            lora = self._gather_lora(la, lb, ablocks)
            y, kp, vp, ksp, vsp = _block_decode_paged(
                x, kp, vp, tables, lengths, active, layer_p, cfg,
                impl=impl, k_scale=ksp, v_scale=vsp, lora=lora)
            return y, (kp, vp, ksp, vsp)

        x, (ks, vs, kss, vss) = jax.lax.scan(
            body, x, (params["block"], k_pool, v_pool, k_scale, v_scale,
                      lora_a, lora_b))
        logits = self._logits(params, x)
        toks, lps = sampling.sample_tokens(logits[:, -1], keys, gen_counts,
                                           temps, top_ks, top_ps, rep_pens,
                                           seen)
        return logits, toks, lps, ks, vs, kss, vss

    def _verify_slots_ql_fn(self, params, k_pool, v_pool, k_scale, v_scale,
                            tables, lengths, tokens, active, impl="gather",
                            lora_a=None, lora_b=None, ablocks=None):
        """int8-pool + LoRA combo twin of _verify_slots_fn."""
        cfg = self.cfg
        B, G = tokens.shape
        x = params["wte"]["embedding"][tokens]
        if cfg.use_wpe:
            pos = lengths[:, None] + jnp.arange(G, dtype=jnp.int32)[None]
            safe = jnp.clip(pos, 0, self.max_seq_len - 1)
            x = x + params["wpe"]["embedding"][safe]

        def body(x, layer):
            layer_p, kp, vp, ksp, vsp, la, lb = layer
            lora = self._gather_lora(la, lb, ablocks)
            y, kp, vp, ksp, vsp = _block_verify_paged(
                x, kp, vp, tables, lengths, active, layer_p, cfg,
                impl=impl, k_scale=ksp, v_scale=vsp, lora=lora)
            return y, (kp, vp, ksp, vsp)

        x, (ks, vs, kss, vss) = jax.lax.scan(
            body, x, (params["block"], k_pool, v_pool, k_scale, v_scale,
                      lora_a, lora_b))
        return self._logits(params, x), ks, vs, kss, vss

    def _decode_horizon_core(self, params, k_pool, v_pool, tables, lengths,
                             tokens, active, impl, n_steps, lanes, preds,
                             k_scale=None, v_scale=None, lora_ops=None):
        """N fused decode iterations in ONE compiled program
        (docs/MULTISTEP.md): the _decode_slots_fn body — paged attention
        with trash-block write routing, the fused sampler with its pure
        fold_in key chain advanced per iteration — wrapped in an OUTER
        lax.scan over the step index, with the budget / eos /
        stop-sequence predicates evaluated in-program as per-slot done
        masks. A finished lane FREEZES: its length stops advancing (so
        its writes route to the trash block through the active mask),
        its carried token stops updating, and its later sampled lanes
        are dead outputs the harvest never reads (``produced`` counts
        the real ones). Iteration 0 is bit-identical to the N=1 decode
        program, and each later live iteration sees exactly the state
        the next N=1 dispatch would have seen (the key chain advances by
        the per-slot emitted count), so token streams match N=1
        bit-for-bit.

        Shared by all four twins — quant (``k_scale``/``v_scale``) and
        LoRA (``lora_ops``) compose by Python-level xs-tuple layout, not
        new hand-written scan bodies. ``preds``: budgets [B] (tokens
        this slot may emit this horizon), eos_ids [B] (-1 = none),
        stop_ids [B, S, W] right-aligned, stop_lens [B, S] (0 = unused
        row), tail [B, W] (the slot's last W emitted tokens, -1
        padded). Returns ([N, B] tokens, [N, B] logprobs, [B] produced,
        [B] done, pools...)."""
        cfg = self.cfg
        keys, gen_counts, temps, top_ks, top_ps, rep_pens, seen = lanes
        budgets, eos_ids, stop_ids, stop_lens, tail = preds
        B = tokens.shape[0]
        W = tail.shape[1]
        quant = k_scale is not None
        rows = jnp.arange(B)

        def step(carry, i):
            tok, lens, live, produced, seen_c, tail_c, pools = carry
            lane_active = jnp.logical_and(active, live)
            x = params["wte"]["embedding"][tok[:, None]]
            if cfg.use_wpe:
                safe = jnp.clip(lens, 0, self.max_seq_len - 1)
                x = x + params["wpe"]["embedding"][safe][:, None]

            xs = (params["block"],) + pools
            if lora_ops is not None:
                xs = xs + (lora_ops[0], lora_ops[1])

            def body(x, layer):
                kw = {}
                if quant:
                    kw["k_scale"], kw["v_scale"] = layer[3], layer[4]
                if lora_ops is not None:
                    kw["lora"] = self._gather_lora(layer[-2], layer[-1],
                                                   lora_ops[2])
                out = _block_decode_paged(x, layer[1], layer[2], tables,
                                          lens, lane_active, layer[0],
                                          cfg, impl=impl, **kw)
                return out[0], tuple(out[1:])

            x, pools = jax.lax.scan(body, x, xs)
            logits = self._logits(params, x)
            toks_i, lps_i = sampling.sample_tokens(
                logits[:, -1], keys, gen_counts + i, temps, top_ks,
                top_ps, rep_pens, seen_c)

            emit = lane_active
            tok = jnp.where(emit, toks_i, tok)
            lens = lens + emit.astype(jnp.int32)
            produced = produced + emit.astype(jnp.int32)
            # the host mirror marks ``seen`` only on penalized lanes;
            # marking every emitting lane is bitwise-inert at pen==1.0
            # (the penalty divides by 1.0), so one program serves both
            marked = seen_c.at[rows, toks_i].set(True)
            seen_c = jnp.where(emit[:, None], marked, seen_c)
            rolled = jnp.concatenate([tail_c[:, 1:], toks_i[:, None]], 1)
            tail_c = jnp.where(emit[:, None], rolled, tail_c)

            total = gen_counts + produced
            budget_done = produced >= budgets
            eos_done = jnp.logical_and(eos_ids >= 0, toks_i == eos_ids)
            at = jnp.arange(W, dtype=jnp.int32)
            # right-aligned suffix compare, gated so the -1 tail padding
            # of a short stream can never satisfy a real stop row
            valid = at[None, None, :] >= (W - stop_lens)[:, :, None]
            hit = jnp.all(jnp.logical_or(jnp.logical_not(valid),
                                         tail_c[:, None, :] == stop_ids),
                          axis=-1)
            hit = jnp.logical_and(hit, stop_lens > 0)
            hit = jnp.logical_and(hit, total[:, None] >= stop_lens)
            done_now = jnp.logical_and(
                emit, budget_done | eos_done | jnp.any(hit, axis=-1))
            live = jnp.logical_and(live, jnp.logical_not(done_now))
            return (tok, lens, live, produced, seen_c, tail_c,
                    pools), (toks_i, lps_i)

        pools0 = (k_pool, v_pool) + ((k_scale, v_scale) if quant else ())
        init = (tokens, lengths, active, jnp.zeros_like(lengths), seen,
                tail, pools0)
        carry, (toks, lps) = jax.lax.scan(
            step, init, jnp.arange(n_steps, dtype=jnp.int32))
        _, _, live, produced, _, _, pools = carry
        return (toks, lps, produced, jnp.logical_not(live)) + pools

    def _decode_horizon_fn(self, params, k_pool, v_pool, tables, lengths,
                           tokens, active, impl, n_steps, keys, gen_counts,
                           temps, top_ks, top_ps, rep_pens, seen, budgets,
                           eos_ids, stop_ids, stop_lens, tail):
        """Fused multi-step decode for every serving slot
        (_decode_horizon_core): n_steps joins impl as a STATIC jit
        argument — a serving run pins one N, so the steady-state
        program count is unchanged (and N=1 serving never compiles
        this family at all)."""
        return self._decode_horizon_core(
            params, k_pool, v_pool, tables, lengths, tokens, active,
            impl, n_steps,
            (keys, gen_counts, temps, top_ks, top_ps, rep_pens, seen),
            (budgets, eos_ids, stop_ids, stop_lens, tail))

    def _decode_horizon_q_fn(self, params, k_pool, v_pool, k_scale,
                             v_scale, tables, lengths, tokens, active,
                             impl, n_steps, keys, gen_counts, temps,
                             top_ks, top_ps, rep_pens, seen, budgets,
                             eos_ids, stop_ids, stop_lens, tail):
        """int8-pool twin of _decode_horizon_fn: the scale pools thread
        through the same core's scan carry (see _block_decode_paged's
        quantized write path)."""
        return self._decode_horizon_core(
            params, k_pool, v_pool, tables, lengths, tokens, active,
            impl, n_steps,
            (keys, gen_counts, temps, top_ks, top_ps, rep_pens, seen),
            (budgets, eos_ids, stop_ids, stop_lens, tail),
            k_scale=k_scale, v_scale=v_scale)

    def _decode_horizon_l_fn(self, params, k_pool, v_pool, tables, lengths,
                             tokens, active, impl, n_steps, keys,
                             gen_counts, temps, top_ks, top_ps, rep_pens,
                             seen, budgets, eos_ids, stop_ids, stop_lens,
                             tail, lora_a, lora_b, ablocks):
        """LoRA twin of _decode_horizon_fn: the adapter pools ride the
        same core's xs layout, gathered per layer per iteration."""
        return self._decode_horizon_core(
            params, k_pool, v_pool, tables, lengths, tokens, active,
            impl, n_steps,
            (keys, gen_counts, temps, top_ks, top_ps, rep_pens, seen),
            (budgets, eos_ids, stop_ids, stop_lens, tail),
            lora_ops=(lora_a, lora_b, ablocks))

    def _decode_horizon_ql_fn(self, params, k_pool, v_pool, k_scale,
                              v_scale, tables, lengths, tokens, active,
                              impl, n_steps, keys, gen_counts, temps,
                              top_ks, top_ps, rep_pens, seen, budgets,
                              eos_ids, stop_ids, stop_lens, tail, lora_a,
                              lora_b, ablocks):
        """int8-pool + LoRA combo twin of _decode_horizon_fn."""
        return self._decode_horizon_core(
            params, k_pool, v_pool, tables, lengths, tokens, active,
            impl, n_steps,
            (keys, gen_counts, temps, top_ks, top_ps, rep_pens, seen),
            (budgets, eos_ids, stop_ids, stop_lens, tail),
            k_scale=k_scale, v_scale=v_scale,
            lora_ops=(lora_a, lora_b, ablocks))

    def _cow_blocks_q_fn(self, k_pool, v_pool, k_scale, v_scale, src, dst):
        """Quantized-pool COW: the block's scales travel with its int8
        payload (paged_cache._cow wires this in when kv_quant=int8)."""
        return (k_pool.at[:, dst].set(k_pool[:, src]),
                v_pool.at[:, dst].set(v_pool[:, src]),
                k_scale.at[:, dst].set(k_scale[:, src]),
                v_scale.at[:, dst].set(v_scale[:, src]))

    def cow_blocks_q(self, k_pool, v_pool, k_scale, v_scale, src, dst):
        return self._cow_blocks_q(k_pool, v_pool, k_scale, v_scale,  # dslint: disable=DS012 — caller paged_cache._cow fires cache.cow before delegating here
                                  jnp.asarray(src, jnp.int32),
                                  jnp.asarray(dst, jnp.int32))

    def _gather_blocks_fn(self, k_pool, v_pool, ids):
        """Pull a fixed-width batch of pool blocks (device half of a
        host-tier spill, paged_cache.spill_tick, and of a replica-to-
        replica KV migration, paged_cache.migrate_gather — both ride
        the SAME compiled program). Pools stay live — the gathered
        copy is what travels to host."""
        return k_pool[:, ids], v_pool[:, ids]

    def gather_blocks(self, k_pool, v_pool, ids):
        return self._gather_blocks(k_pool, v_pool,
                                   jnp.asarray(ids, jnp.int32))

    def _scatter_block_fn(self, k_pool, v_pool, k_blk, v_blk, dst):
        """Write one restored block back into the donated pools (device
        half of a host-tier restore, paged_cache._dispatch_restore,
        and of a migration landing, paged_cache.land_parked — the
        destination replica reuses this program to place migrated
        blocks free-list-only)."""
        return (k_pool.at[:, dst].set(k_blk),
                v_pool.at[:, dst].set(v_blk))

    def scatter_block(self, k_pool, v_pool, k_blk, v_blk, dst):
        return self._scatter_block(k_pool, v_pool, k_blk, v_blk,  # dslint: disable=DS012 — caller paged_cache._dispatch_restore fires cache.restore before delegating here
                                   jnp.asarray(dst, jnp.int32))

    def _gather_blocks_q_fn(self, k_pool, v_pool, k_scale, v_scale, ids):
        """Quantized-pool spill gather: int8 payload plus fp32 scale
        sidecars travel together (docs/KV_TIERING.md)."""
        return (k_pool[:, ids], v_pool[:, ids],
                k_scale[:, ids], v_scale[:, ids])

    def gather_blocks_q(self, k_pool, v_pool, k_scale, v_scale, ids):
        return self._gather_blocks_q(k_pool, v_pool, k_scale, v_scale,
                                     jnp.asarray(ids, jnp.int32))

    def _scatter_block_q_fn(self, k_pool, v_pool, k_scale, v_scale,
                            k_blk, v_blk, ks_blk, vs_blk, dst):
        """Quantized-pool restore scatter: payload and scales land
        together."""
        return (k_pool.at[:, dst].set(k_blk),
                v_pool.at[:, dst].set(v_blk),
                k_scale.at[:, dst].set(ks_blk),
                v_scale.at[:, dst].set(vs_blk))

    def scatter_block_q(self, k_pool, v_pool, k_scale, v_scale,
                        k_blk, v_blk, ks_blk, vs_blk, dst):
        return self._scatter_block_q(k_pool, v_pool, k_scale, v_scale,  # dslint: disable=DS012 — caller paged_cache._dispatch_restore fires cache.restore before delegating here
                                     k_blk, v_blk, ks_blk, vs_blk,
                                     jnp.asarray(dst, jnp.int32))

    def sync(self, *values) -> None:
        """Barrier on device values (pools, logits): the telemetry
        step-time breakdown's sampled sync point — same discipline as
        utils/timer's ``_device_sync``, but scoped to the values the
        serving step actually produced so it keys no new programs."""
        jax.block_until_ready(values)

    # public wrappers: host-side numpy in, device pools threaded through.
    # The fault-injection sites fire BEFORE any dispatch touches the
    # donated pools, so a TransientDeviceError here is retryable by the
    # serving engine against intact buffers (utils/faults).
    @staticmethod
    def _samp_lanes(sample_state, batch, vocab, scalar=False):
        """Coerce a host ``sample_state`` tuple (sampling.SlotSamplerState
        ``lanes()``/``lane()``) to traced arrays; None synthesizes the
        all-greedy lanes so legacy callers keep their behavior (and the
        one compiled program — greedy lanes are values, not a different
        signature). ``scalar`` selects the single-slot (prefill) lane
        shape."""
        if sample_state is None:
            st = sampling.greedy_state(batch, vocab)
            sample_state = tuple(a[0] for a in st) if scalar else st
        keys, gens, temps, top_ks, top_ps, pens, seen = sample_state
        return (jnp.asarray(keys, jnp.uint32), jnp.asarray(gens, jnp.int32),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(top_ks, jnp.int32),
                jnp.asarray(top_ps, jnp.float32),
                jnp.asarray(pens, jnp.float32), jnp.asarray(seen, bool))

    @staticmethod
    def _lora_operands(lora):
        """Coerce the serving engine's ``lora`` kwarg — ``(a_pool,
        b_pool, ablocks)`` from AdapterPool.lora_args — to the trailing
        traced operands of the ``_l``/``_ql`` twins. None selects the
        base-only program (and keeps the lora twins cold)."""
        if lora is None:
            return ()
        a_pool, b_pool, ablocks = lora
        return (a_pool, b_pool, jnp.asarray(ablocks, jnp.int32))

    def prefill_into_slot(self, k_pool, v_pool, table_row, tokens, start,
                          n_valid, k_scale=None, v_scale=None,
                          sample_state=None, lora=None):
        from deepspeed_tpu.utils.faults import maybe_fire
        maybe_fire("engine.prefill")
        legacy = sample_state is None
        lanes = self._samp_lanes(sample_state, 1, self.cfg.vocab_size,
                                 scalar=True)
        largs = self._lora_operands(lora)
        if k_scale is None:
            pf = self._prefill_slot if lora is None else self._prefill_slot_l
            out = pf(
                self.params, k_pool, v_pool,
                jnp.asarray(table_row, jnp.int32),
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(start, jnp.int32),
                jnp.asarray(n_valid, jnp.int32), *lanes, *largs)
            return (out[0],) + out[3:] if legacy else out
        # ``cache.quantize`` fires before the dispatch touches the
        # donated pools OR scale pools: a TransientDeviceError here is
        # retryable against intact buffers
        maybe_fire("cache.quantize")
        pf = (self._prefill_slot_q if lora is None
              else self._prefill_slot_ql)
        out = pf(
            self.params, k_pool, v_pool, k_scale, v_scale,  # dslint: disable=DS003 — exclusive branch: the fp dispatch above already returned
            jnp.asarray(table_row, jnp.int32),
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(start, jnp.int32), jnp.asarray(n_valid, jnp.int32),
            *lanes, *largs)
        return (out[0],) + out[3:] if legacy else out

    def decode_slots(self, k_pool, v_pool, tables, lengths, tokens, active,
                     impl=None, k_scale=None, v_scale=None,
                     sample_state=None, lora=None):
        from deepspeed_tpu.utils.faults import maybe_fire
        maybe_fire("engine.decode")
        legacy = sample_state is None
        lanes = self._samp_lanes(sample_state, len(np.asarray(tokens)),
                                 self.cfg.vocab_size)
        largs = self._lora_operands(lora)
        if k_scale is None:
            df = self._decode_slots if lora is None else self._decode_slots_l
            out = df(
                self.params, k_pool, v_pool,
                jnp.asarray(tables, jnp.int32),
                jnp.asarray(lengths, jnp.int32),
                jnp.asarray(tokens, jnp.int32), jnp.asarray(active, bool),
                self.decode_impl if impl is None else impl, *lanes, *largs)
            return (out[0],) + out[3:] if legacy else out
        maybe_fire("cache.quantize")
        df = (self._decode_slots_q if lora is None
              else self._decode_slots_ql)
        out = df(
            self.params, k_pool, v_pool, k_scale, v_scale,  # dslint: disable=DS003 — exclusive branch: the fp dispatch above already returned
            jnp.asarray(tables, jnp.int32),
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(tokens, jnp.int32), jnp.asarray(active, bool),
            self.decode_impl if impl is None else impl, *lanes, *largs)
        return (out[0],) + out[3:] if legacy else out

    def decode_horizon(self, k_pool, v_pool, tables, lengths, tokens,
                       active, n_steps, budgets, eos_ids, stop_ids,
                       stop_lens, tail, impl=None, k_scale=None,
                       v_scale=None, sample_state=None, lora=None):
        """Fused multi-step decode for every serving slot: n_steps
        iterations of the decode body in ONE dispatch, with per-slot
        emission budgets and eos/stop predicates freezing finished
        lanes in-program (_decode_horizon_core, docs/MULTISTEP.md).
        Returns ([n_steps, B] tokens, [n_steps, B] logprobs, [B]
        produced counts, [B] done flags, updated pools). The
        ``engine.decode`` site (and ``cache.quantize`` with int8 pools)
        fires BEFORE the dispatch touches the donated pools, so the
        serving engine can degrade a faulted horizon to single-step
        decode against intact buffers."""
        from deepspeed_tpu.utils.faults import maybe_fire
        maybe_fire("engine.decode")
        lanes = self._samp_lanes(sample_state, len(np.asarray(tokens)),
                                 self.cfg.vocab_size)
        largs = self._lora_operands(lora)
        preds = (jnp.asarray(budgets, jnp.int32),
                 jnp.asarray(eos_ids, jnp.int32),
                 jnp.asarray(stop_ids, jnp.int32),
                 jnp.asarray(stop_lens, jnp.int32),
                 jnp.asarray(tail, jnp.int32))
        if k_scale is None:
            df = (self._decode_horizon if lora is None
                  else self._decode_horizon_l)
            return df(
                self.params, k_pool, v_pool,
                jnp.asarray(tables, jnp.int32),
                jnp.asarray(lengths, jnp.int32),
                jnp.asarray(tokens, jnp.int32), jnp.asarray(active, bool),
                self.decode_impl if impl is None else impl, int(n_steps),
                *lanes, *preds, *largs)
        maybe_fire("cache.quantize")
        df = (self._decode_horizon_q if lora is None
              else self._decode_horizon_ql)
        return df(
            self.params, k_pool, v_pool, k_scale, v_scale,  # dslint: disable=DS003 — exclusive branch: the fp dispatch above already returned
            jnp.asarray(tables, jnp.int32),
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(tokens, jnp.int32), jnp.asarray(active, bool),
            self.decode_impl if impl is None else impl, int(n_steps),
            *lanes, *preds, *largs)

    def verify_slots(self, k_pool, v_pool, tables, lengths, tokens, active,
                     impl=None, k_scale=None, v_scale=None, lora=None):
        """Speculative chunk verify for every serving slot (tokens:
        [B, G] — each slot's pending token followed by its draft
        proposals). The ``engine.verify`` fault site (and
        ``cache.quantize`` with int8 pools) fires BEFORE the dispatch
        touches the donated pools, so the serving engine can degrade a
        faulted verify to a plain one-token decode against intact
        buffers."""
        from deepspeed_tpu.utils.faults import maybe_fire
        maybe_fire("engine.verify")
        largs = self._lora_operands(lora)
        if k_scale is None:
            vf = self._verify_slots if lora is None else self._verify_slots_l
            return vf(
                self.params, k_pool, v_pool,
                jnp.asarray(tables, jnp.int32),
                jnp.asarray(lengths, jnp.int32),
                jnp.asarray(tokens, jnp.int32), jnp.asarray(active, bool),
                self.decode_impl if impl is None else impl, *largs)
        maybe_fire("cache.quantize")
        vf = (self._verify_slots_q if lora is None
              else self._verify_slots_ql)
        return vf(
            self.params, k_pool, v_pool, k_scale, v_scale,  # dslint: disable=DS003 — exclusive branch: the fp dispatch above already returned
            jnp.asarray(tables, jnp.int32),
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(tokens, jnp.int32), jnp.asarray(active, bool),
            self.decode_impl if impl is None else impl, *largs)

    def _forward_fn(self, params, tokens):
        x = self._embed(params, tokens)
        x, _ = jax.lax.scan(
            lambda c, l: (_block_prefill(c, l, self.cfg)[0], None),
            x, params["block"])
        return self._logits(params, x)

    def _encoder_forward_fn(self, params, tokens):
        """BERT-family path: encoder hidden states, or MLM logits when the
        converted checkpoint ships the prediction head
        (ref: HFBertLayerPolicy application, replace_module.py:123)."""
        from deepspeed_tpu.models import bert as bert_lib
        x = bert_lib.encode(params, tokens, self.cfg, deterministic=True)
        if "mlm" not in params:
            return x
        dtype = x.dtype
        h = bert_lib._mlm_hidden(params, x, self.cfg)
        return h @ params["embeddings"]["word"].astype(dtype).T + \
            params["mlm"]["decoder_bias"].astype(dtype)

    # ------------------------------------------------------------------
    def forward(self, tokens) -> jnp.ndarray:
        """Full-sequence logits (ref: engine.py:355 forward)."""
        import time
        t0 = time.perf_counter()
        tokens = jnp.asarray(tokens, jnp.int32)
        out = self._forward(self.params, tokens)
        jax.block_until_ready(out)
        self.latency_ms["forward"] = (time.perf_counter() - t0) * 1e3
        return out

    def __call__(self, tokens):
        return self.forward(tokens)

    def _gen_setup(self, tokens, max_new_tokens, attention_mask):
        """Shared generate() entry: prefill (+ optional left-pad mask)."""
        import time
        if self.is_encoder:
            raise NotImplementedError(
                "generate() needs a causal decoder; BERT-family models "
                "support forward() only")
        tokens = jnp.asarray(tokens, jnp.int32)
        B, S = tokens.shape
        assert S + max_new_tokens <= self.max_seq_len
        row_len = None
        if attention_mask is not None:
            attention_mask = jnp.asarray(attention_mask, jnp.float32)
            assert attention_mask.shape == (B, S)
            row_len = attention_mask.sum(axis=1).astype(jnp.int32)  # [B]

        t0 = time.perf_counter()
        logits, cache = self._prefill(self.params, tokens, attention_mask)
        jax.block_until_ready(logits)
        self.latency_ms["prefill"] = (time.perf_counter() - t0) * 1e3
        return tokens, S, logits, cache, row_len

    def generate(self, tokens, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0, attention_mask=None) -> np.ndarray:
        """Greedy (temperature=0) or sampled generation.

        attention_mask: [B, S] for LEFT-padded variable-length prompts
        (1 = real token) — rows generate as if run unpadded."""
        import time
        tokens, S, logits, cache, row_len = self._gen_setup(
            tokens, max_new_tokens, attention_mask)

        rng = jax.random.PRNGKey(seed)
        out = [np.asarray(tokens)]

        def pick(logits, rng):
            return self._sample(logits, rng, temperature, top_k)

        t0 = time.perf_counter()
        token = pick(logits, rng)
        dev_out = []
        for i in range(max_new_tokens):
            # keep the token on device: a per-step np.asarray would block
            # the dispatch queue once per token (dslint DS001); the loop
            # only enqueues work and ONE batched pull lands every token
            dev_out.append(token)
            if i == max_new_tokens - 1:
                break
            rng, r = jax.random.split(rng)
            logits, cache = self._decode(  # dslint: disable=DS012 — offline batch API; chaos coverage targets the serving dispatches (engine.decode fires in decode_slots)
                self.params, cache, token[:, None],
                jnp.asarray(S + i, jnp.int32),
                None if row_len is None else row_len + i)
            token = pick(logits, r)
        out.extend(t[:, None] for t in jax.device_get(dev_out))
        self.latency_ms["decode_per_token"] = \
            (time.perf_counter() - t0) * 1e3 / max(1, max_new_tokens - 1)
        return np.concatenate(out, axis=1)

    # ------------------------------------------------------------------
    # fused generation: the whole decode loop is ONE compiled program
    # (lax.scan over decode steps) — no host round-trip per token. The
    # reference's generation loop is host-driven (its per-token latency
    # rides PCIe/launch overheads); on TPU the scan keeps the chip busy
    # end-to-end and is the path production serving uses.
    def _sample(self, logits, rng, temperature: float, top_k: int):
        logits = logits[:, -1].astype(jnp.float32)
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        logits = logits / temperature
        if top_k > 0:
            # k-th largest via lax.top_k (O(V log k)) — same threshold
            # the full jnp.sort produced, cheaper (gshard sampler idiom)
            k_eff = min(top_k, logits.shape[-1])
            kth = jax.lax.top_k(logits, k_eff)[0][:, -1][:, None]
            logits = jnp.where(logits < kth, -1e30, logits)
        return jax.random.categorical(rng, logits, axis=-1)

    def _generate_scan_fn(self, params, cache, token, start_pos, row_len,
                          rng, n_steps: int, temperature: float,
                          top_k: int):
        def step(carry, i):
            tok, pos, cache, rng = carry
            rng, r = jax.random.split(rng)
            logits, cache = self._decode_fn(
                params, cache, tok[:, None], pos,
                None if row_len is None else row_len + i)
            nxt = self._sample(logits, r, temperature, top_k)
            return (nxt, pos + 1, cache, rng), nxt

        (_, _, _, _), toks = jax.lax.scan(
            step, (token, start_pos, cache, rng),
            jnp.arange(n_steps), length=n_steps)
        return toks  # [n_steps, B]

    def generate_fused(self, tokens, max_new_tokens: int = 32,
                       temperature: float = 0.0, top_k: int = 0,
                       seed: int = 0, attention_mask=None) -> np.ndarray:
        """generate() semantics, decode loop fused into one XLA program."""
        import time
        tokens, S, logits, cache, row_len = self._gen_setup(
            tokens, max_new_tokens, attention_mask)

        rng = jax.random.PRNGKey(seed)
        first = self._sample(logits, rng, temperature, top_k)
        n_steps = max_new_tokens - 1
        if n_steps <= 0:
            return np.concatenate([np.asarray(tokens),
                                   np.asarray(first)[:, None]], axis=1)

        # same key stream as generate(): the scan carries the ORIGINAL key
        # and splits per step, so sampled outputs match token-for-token
        args = (self.params, cache, first, jnp.asarray(S, jnp.int32),
                row_len, rng)
        # the compiled executable is shape-specialized: key on the abstract
        # shapes/dtypes of every traced arg (batch size, cache length, ...)
        # or a later call with a different batch hits a stale executable
        # and fails with an aval mismatch instead of recompiling
        avals = jax.tree_util.tree_map(
            lambda x: (x.shape, str(x.dtype)) if hasattr(x, "shape") else x,
            (cache, first, row_len))
        key = ("gen", n_steps, temperature, top_k,
               jax.tree_util.tree_structure(avals), str(avals))
        if not hasattr(self, "_gen_cache"):
            self._gen_cache = {}
        if key not in self._gen_cache:
            # AOT-compile so the per-token metric below never includes the
            # seconds-long XLA compile of the whole scan program
            t0 = time.perf_counter()
            self._gen_cache[key] = jax.jit(
                partial(self._generate_scan_fn, n_steps=n_steps,
                        temperature=temperature, top_k=top_k),
                donate_argnums=(1,)).lower(*args).compile()
            self.latency_ms["fused_generate_compile"] = \
                (time.perf_counter() - t0) * 1e3
        t0 = time.perf_counter()
        toks = np.asarray(self._gen_cache[key](*args))   # blocks
        self.latency_ms["decode_per_token_fused"] = \
            (time.perf_counter() - t0) * 1e3 / n_steps
        return np.concatenate([np.asarray(tokens),
                               np.asarray(first)[:, None], toks.T], axis=1)
