"""Draft proposers for speculative decoding inside continuous batching.

The serving scheduler (inference/serving.py, ``spec_decode=True`` /
``DS_SPEC_DECODE=on``) asks a DRAFTER for ``k`` candidate tokens per
active slot each step, then verifies all ``k+1`` positions in one
engine program (``InferenceEngine.verify_slots``) and accepts the
longest surviving prefix — greedy-target agreement for temperature=0
slots, per-position rejection sampling for sampled slots
(docs/SAMPLING.md) — so the drafter affects LATENCY only, never the
output distribution (docs/SPECULATIVE.md).

The drafting interface is one duck-typed method::

    propose(context: np.ndarray[int], k: int) -> np.ndarray[int32, (k,)]

``context`` is the slot's prompt + everything generated so far
(including the pending token the verify chunk starts with); the return
is exactly ``k`` tokens — static shape, so the verify program never
retraces. Anything with that method plugs in via
``ServingEngine(spec_draft=...)``.

Two drafters ship:

- :class:`NGramDraft` (default, ``DS_SPEC_DRAFT=ngram``) — prompt-lookup
  decoding (Saxena 2023; the technique behind vLLM's
  ``speculative_model="[ngram]"``): match the slot's trailing n-gram
  against its OWN earlier context and propose the continuation of the
  most recent earlier occurrence. Zero model cost, host-side numpy
  only, and strong on the shared-suffix traffic serving actually sees
  (quoting, code completion, templated answers, greedy loops).
- :class:`ModelDraft` (``spec_draft=<draft InferenceEngine>``) — the
  classic small-draft-model path (Leviathan et al., ICML 2023), the
  same economics as the static ``generate_speculative`` but behind the
  serving interface. Costs k draft forwards per slot per step; worth it
  only when the draft is much smaller than the target.
"""

from typing import Any, Optional

import numpy as np

from deepspeed_tpu.utils.env import resolve_flag


def resolve_spec_decode(flag: Optional[bool] = None) -> bool:
    """Resolve the speculative-serving switch.

    Explicit argument wins, else the ``DS_SPEC_DECODE`` env var
    (``on``/``off``, also ``1``/``0``/``true``/``false``), else OFF —
    plain one-token decode stays the behavioral bit-reference."""
    return resolve_flag("DS_SPEC_DECODE", flag)


def resolve_spec_draft(spec: Optional[str] = None) -> str:
    """Resolve the drafter NAME: explicit argument, else
    ``DS_SPEC_DRAFT``, else ``"ngram"`` (the no-second-model default)."""
    if spec is None:
        spec = str(resolve_flag("DS_SPEC_DRAFT")).strip().lower()
    if spec != "ngram":
        raise ValueError(
            f"DS_SPEC_DRAFT={spec!r}: 'ngram' is the only named drafter "
            f"(pass a draft InferenceEngine or a propose()-bearing "
            f"object as spec_draft= for the model path)")
    return spec


def resolve_spec_k(k: Optional[int] = None) -> int:
    """Draft chunk length: explicit argument, else ``DS_SPEC_K``, else
    4 (docs/SPECULATIVE.md discusses tuning)."""
    k = int(resolve_flag("DS_SPEC_K", k))
    if k < 1:
        raise ValueError(f"spec_k={k}: need at least one draft token")
    return k


class NGramDraft:
    """Prompt-lookup n-gram drafter: propose the continuation of the
    most recent earlier occurrence of the context's trailing n-gram,
    longest ``n`` first (``max_ngram`` down to ``min_ngram``). No match
    anywhere falls back to repeating the last token — still a valid
    proposal (the verifier rejects wrong tokens for free, and repeat
    runs are common in greedy decoding)."""

    name = "ngram"

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(f"need 1 <= min_ngram <= max_ngram, got "
                             f"({min_ngram}, {max_ngram})")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, context, k: int) -> np.ndarray:
        ctx = np.asarray(context, np.int64).ravel()
        if ctx.size == 0:
            return np.zeros((k,), np.int32)
        for n in range(min(self.max_ngram, ctx.size - 1),
                       self.min_ngram - 1, -1):
            # candidate starts exclude the trailing n-gram itself
            L = ctx.size - n
            if L <= 0:
                continue
            pat = ctx[-n:]
            win = np.lib.stride_tricks.sliding_window_view(ctx, n)[:L]
            hits = np.flatnonzero((win == pat).all(axis=1))
            if hits.size:
                s = int(hits[-1])            # most recent occurrence
                cont = ctx[s + n:s + n + k]
                out = np.empty((k,), np.int64)
                out[:cont.size] = cont
                out[cont.size:] = cont[-1] if cont.size else ctx[-1]
                return out.astype(np.int32)
        return np.full((k,), ctx[-1], np.int32)


class ModelDraft:
    """Draft-model proposer over a second :class:`InferenceEngine`:
    greedy k-token continuation of a fixed-width, left-padded context
    window. The fixed window keeps the draft's prefill/decode programs
    shape-stable across calls (one compile, like every other serving
    program); the cost is re-prefilling the window each proposal — the
    simple-and-correct baseline, acceptable when the draft is tiny
    relative to the target."""

    name = "model"

    def __init__(self, engine, window: int = 64):
        if getattr(engine, "is_encoder", False):
            raise ValueError("draft model must be a causal decoder")
        self.engine = engine
        self.window = int(window)

    def propose(self, context, k: int) -> np.ndarray:
        ctx = np.asarray(context, np.int32).ravel()[-self.window:]
        W = self.window
        if W + k > self.engine.max_seq_len:
            raise ValueError(
                f"draft window {W} + k {k} exceeds the draft engine's "
                f"max_seq_len {self.engine.max_seq_len}")
        toks = np.zeros((1, W), np.int32)
        toks[0, W - ctx.size:] = ctx
        mask = np.zeros((1, W), np.float32)
        mask[0, W - ctx.size:] = 1.0
        out = self.engine.generate(toks, max_new_tokens=k,
                                   attention_mask=mask)
        return np.asarray(out[0, W:W + k], np.int32)


def make_draft(spec: Any = None) -> Any:
    """Build the drafter from whatever ``ServingEngine(spec_draft=)``
    was given: None/str resolve by name (env ``DS_SPEC_DRAFT``), a
    ``propose()``-bearing object is used as-is, a draft
    :class:`InferenceEngine` (anything with ``generate``) is wrapped in
    :class:`ModelDraft`."""
    if spec is None or isinstance(spec, str):
        resolve_spec_draft(spec)      # "ngram" is the only named drafter
        return NGramDraft()
    if hasattr(spec, "propose"):
        return spec
    if hasattr(spec, "generate"):
        return ModelDraft(spec)
    raise ValueError(
        f"spec_draft={spec!r}: expected 'ngram', a draft "
        f"InferenceEngine, or an object with propose(context, k)")
