"""Multi-tenant LoRA adapter serving: registry + paged adapter pool.

One base model, many tenants: ``runtime/lora.py`` trains and exports
rank-r adapters, but merging them into the base (``merge_lora``) means
one fleet per tenant. S-LoRA (Sheng et al., 2023) and Punica (Chen et
al., 2023) showed that thousands of UNMERGED adapters can share one
base if (a) adapter weights live in a paged device pool, and (b) the
decode program applies them with gathered low-rank matmuls indexed by a
per-slot adapter table — traced data, never a jit static, so one
compiled program serves any mix of adapters and base-only slots.

This module is the host-side half of that design (the gathered matmul
lives in ``models/gpt._dense`` + the engine's ``_l`` program twins):

- **Registry**: ``register(adapter_id, source)`` parses the
  ``runtime/lora.py`` adapter-only export (an ``.npz`` path or the
  ``adapter_state_dict`` mapping), validates every leaf against the
  base kernels, folds ``lora_scale`` into B once in fp32, and stages
  the result host-side in rank-block chunks. Registration touches no
  device memory — thousands of tenants can register against a pool
  that holds only the hot few.
- **Paged pool**: per-target device pools ``a[t] [L, NB, in_t, rb]`` /
  ``b[t] [L, NB, rb, out_t]`` paged over the RANK axis: an adapter of
  rank r occupies ``ceil(r / rank_block)`` blocks recorded in its block
  row. The allocator reuses ``paged_cache.py`` idioms verbatim: block 0
  is a permanent all-zeros trash block (a base-only slot's table row is
  all zeros, so its gathered contribution is exactly ``+0.0`` — bit
  parity with the pre-subsystem stream), a LIFO free list, per-adapter
  refcounts, and LRU eviction of refcount-zero residents when the pool
  fills. Loads go through ONE jitted scatter program (traced dst, all
  targets as a pytree) warmed at construction so a mid-run adapter
  load never compiles.
- **Degradation**: the ``cache.adapter_load`` fault site fires before
  any pool state moves. ``cache_exhausted`` (and a genuinely full
  pool, and an unregistered id) raise :class:`AdapterLoadError`;
  ``device_error`` raises the usual retryable error. The serving
  engine maps both onto a structured per-request ``error`` terminal
  state — the batch keeps serving, never wrong tokens.

docs/ADAPTERS.md has the full contract, including the interplay
matrix with spec-decode / int8 KV / the prefix cache.
"""

import math
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.lora import DEFAULT_TARGETS
from deepspeed_tpu.utils import faults as faults_lib
from deepspeed_tpu.utils.env import resolve_flag

__all__ = ["AdapterLoadError", "AdapterPool", "resolve_lora_serve"]


class AdapterLoadError(RuntimeError):
    """An adapter could not be made pool-resident (unregistered id,
    pool exhausted with every resident adapter pinned, or an injected
    ``cache.adapter_load`` exhaustion). The serving engine degrades the
    owning request to the structured ``error`` terminal state."""


def resolve_lora_serve(override=None) -> bool:
    """``DS_LORA_SERVE``: explicit argument wins, then env, then the
    declared off-default (base-only serving is the bit-reference)."""
    return resolve_flag("DS_LORA_SERVE", override)


def _load_blocks_fn(a_pool, b_pool, a_chunk, b_chunk, dst):
    """Write one rank-block of every target into pool slot ``dst``.
    ``dst`` is traced data, so one compiled program serves every load."""
    a_pool = {t: a_pool[t].at[:, dst].set(
        a_chunk[t].astype(a_pool[t].dtype)) for t in a_pool}
    b_pool = {t: b_pool[t].at[:, dst].set(
        b_chunk[t].astype(b_pool[t].dtype)) for t in b_pool}
    return a_pool, b_pool


_load_blocks = jax.jit(_load_blocks_fn, donate_argnums=(0, 1))


class AdapterPool:
    """Adapter registry + fixed-size paged device pool (module
    docstring has the design; docs/ADAPTERS.md the contract).

    - ``engine``: the :class:`InferenceEngine` whose base kernels size
      the per-target pools (and whose mesh places them).
    - ``pool_mb`` / ``pool_blocks``: pool capacity as a MiB budget
      (``DS_LORA_POOL_MB`` default) or an explicit block count
      (override wins; tests use it to force eviction).
    - ``max_rank`` / ``rank_block``: largest accepted adapter rank and
      the rank granularity of one block (``DS_LORA_MAX_RANK`` /
      ``DS_LORA_RANK_BLOCK``). Together they fix the STATIC width of
      every per-slot adapter-table row: ``ceil(max_rank / rank_block)``.
    - ``faults`` / ``tracer`` / ``hooks``: the chaos injector for the
      ``cache.adapter_load`` site, an optional trace-event sink, and
      optional ``{"on_hit","on_load","on_evict"}`` counter callbacks
      (the serving engine wires its ``serving_adapter_*`` counters in).
    """

    def __init__(self, engine, *, pool_mb: Optional[float] = None,
                 pool_blocks: Optional[int] = None,
                 max_rank: Optional[int] = None,
                 rank_block: Optional[int] = None,
                 faults: Optional[faults_lib.FaultInjector] = None,
                 tracer=None,
                 hooks: Optional[Mapping[str, Callable]] = None):
        self.engine = engine
        self.faults = faults if faults is not None else faults_lib.active()
        self.tracer = tracer
        self.hooks = dict(hooks or {})
        self.max_rank = int(resolve_flag("DS_LORA_MAX_RANK", max_rank))
        self.rank_block = int(resolve_flag("DS_LORA_RANK_BLOCK", rank_block))
        if self.max_rank < 1 or self.rank_block < 1:
            raise ValueError("max_rank and rank_block must be >= 1")
        # static per-slot adapter-table width (row of pool block ids,
        # zero-padded; the all-zeros row is the base-only slot)
        self.blocks_per_adapter = math.ceil(self.max_rank / self.rank_block)

        # per-target shapes off the base kernels (int8-served bases
        # carry "q" with the kernel's shape); targets the model dialect
        # lacks (mlp_gate on gelu) are simply absent from the pool
        block = engine.params["block"]
        self._shapes: Dict[str, tuple] = {}
        for t in DEFAULT_TARGETS:
            entry = block.get(t)
            if not isinstance(entry, dict):
                continue
            kern = entry.get("kernel", entry.get("q"))
            if kern is None:
                continue
            self._shapes[t] = tuple(kern.shape)   # (L, in, out)
        if not self._shapes:
            raise ValueError("base model has no adaptable dense targets")
        self.n_layers = next(iter(self._shapes.values()))[0]
        self.dtype = engine.dtype
        itemsize = jnp.dtype(self.dtype).itemsize
        rb = self.rank_block
        self._block_bytes = sum(
            (din * rb + rb * dout) * L * itemsize
            for (L, din, dout) in self._shapes.values())

        if pool_blocks is None:
            budget = resolve_flag("DS_LORA_POOL_MB", pool_mb) * (1 << 20)
            pool_blocks = max(self.blocks_per_adapter,
                              int(budget // self._block_bytes))
        if pool_blocks < self.blocks_per_adapter:
            raise ValueError(
                f"adapter pool of {pool_blocks} blocks cannot hold one "
                f"max-rank adapter ({self.blocks_per_adapter} blocks)")
        # block 0 is the permanent all-zeros trash block (never
        # allocated): base-only table rows gather exact zeros from it
        self.num_blocks = int(pool_blocks) + 1
        self.a_pool = {t: jnp.zeros((L, self.num_blocks, din, rb),
                                    self.dtype)
                       for t, (L, din, dout) in self._shapes.items()}
        self.b_pool = {t: jnp.zeros((L, self.num_blocks, rb, dout),
                                    self.dtype)
                       for t, (L, din, dout) in self._shapes.items()}
        mesh = getattr(engine, "mesh", None)
        if mesh is not None:
            pool_sh = NamedSharding(mesh, PartitionSpec())
            self.a_pool = {t: jax.device_put(v, pool_sh)
                           for t, v in self.a_pool.items()}
            self.b_pool = {t: jax.device_put(v, pool_sh)
                           for t, v in self.b_pool.items()}

        # allocator state, paged_cache.py idioms: LIFO free list (pop()
        # yields ascending ids), refcounts, LRU clock over residents
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._staged: Dict[str, List[Dict[str, Dict[str, np.ndarray]]]] = {}
        self._rank: Dict[str, int] = {}
        self._blocks: Dict[str, List[int]] = {}    # resident -> block ids
        self._refcount: Dict[str, int] = {}
        self._last_used: Dict[str, int] = {}
        self._tick = 0
        self.hits = 0
        self.loads = 0
        self.evictions = 0
        self._warm_load()

    # -- construction helpers -----------------------------------------
    def _zero_chunks(self):
        rb = self.rank_block
        a = {t: np.zeros((L, din, rb), np.float32)
             for t, (L, din, dout) in self._shapes.items()}
        b = {t: np.zeros((L, rb, dout), np.float32)
             for t, (L, din, dout) in self._shapes.items()}
        return a, b

    def _warm_load(self) -> None:
        """Compile the scatter program up front (a zero-write into the
        trash block) so a mid-run adapter load never compiles — the
        warm_cow/warm_host_tier precedent."""
        a, b = self._zero_chunks()
        self.a_pool, self.b_pool = _load_blocks(
            self.a_pool, self.b_pool, a, b, 0)

    # -- registry ------------------------------------------------------
    def register(self, adapter_id: str,
                 source: Union[str, Mapping[str, np.ndarray]]) -> None:
        """Stage ``source`` (an ``.npz`` path or an
        ``adapter_state_dict`` mapping, both the ``runtime/lora.py``
        export format) host-side under ``adapter_id``. Validates every
        leaf against the base kernels and folds ``lora_scale`` into B
        in fp32. No device memory moves until :meth:`acquire`."""
        if isinstance(source, str):
            with np.load(source) as data:
                flat = {k: np.asarray(data[k]) for k in data.files}
        else:
            flat = {k: np.asarray(v) for k, v in source.items()}
        per_target: Dict[str, Dict[str, np.ndarray]] = {}
        for key, val in flat.items():
            parts = key.split("/")
            if len(parts) != 3 or parts[0] != "block":
                raise ValueError(
                    f"adapter {adapter_id!r}: unexpected export key "
                    f"{key!r} (want 'block/<target>/lora_*')")
            _, target, leaf = parts
            if target not in self._shapes:
                raise ValueError(
                    f"adapter {adapter_id!r} adapts {target!r}, which "
                    f"the base model does not expose")
            per_target.setdefault(target, {})[leaf] = val
        if not per_target:
            raise ValueError(f"adapter {adapter_id!r}: empty export")

        rank = None
        for t, leaves in per_target.items():
            missing = {"lora_a", "lora_b", "lora_scale"} - set(leaves)
            if missing:
                raise ValueError(
                    f"adapter {adapter_id!r}/{t}: missing {sorted(missing)}")
            L, din, dout = self._shapes[t]
            a, b = leaves["lora_a"], leaves["lora_b"]
            r = a.shape[-1]
            if a.shape != (L, din, r) or b.shape != (L, r, dout):
                raise ValueError(
                    f"adapter {adapter_id!r}/{t}: shapes A{a.shape} "
                    f"B{b.shape} do not match base ({L}, {din}, {dout})")
            if rank is None:
                rank = r
            elif r != rank:
                raise ValueError(
                    f"adapter {adapter_id!r}: mixed ranks {rank} vs {r}")
        if rank > self.max_rank:
            raise ValueError(
                f"adapter {adapter_id!r} rank {rank} exceeds the pool's "
                f"max_rank {self.max_rank} (DS_LORA_MAX_RANK)")

        # fold scale into B once (fp32), chunk both factors into
        # rank-blocks zero-padded to rank_block; unadapted targets get
        # zero chunks so their gathered contribution is exactly +0.0
        rb = self.rank_block
        nb = math.ceil(rank / rb)
        chunks = []
        for j in range(nb):
            a_c, b_c = self._zero_chunks()
            lo, hi = j * rb, min((j + 1) * rb, rank)
            for t, leaves in per_target.items():
                scale = leaves["lora_scale"].astype(np.float32)
                a_c[t][:, :, :hi - lo] = (
                    leaves["lora_a"][:, :, lo:hi].astype(np.float32))
                b_c[t][:, :hi - lo, :] = (
                    leaves["lora_b"][:, lo:hi, :].astype(np.float32)
                    * scale[:, None, None])
            chunks.append({"a": a_c, "b": b_c})
        self._staged[adapter_id] = chunks
        self._rank[adapter_id] = int(rank)

    def registered(self) -> List[str]:
        return sorted(self._staged)

    # -- residency -----------------------------------------------------
    @property
    def active_adapters(self) -> int:
        return len(self._blocks)

    @property
    def pool_bytes(self) -> int:
        return self._block_bytes * self.num_blocks

    def resident(self, adapter_id: str) -> bool:
        return adapter_id in self._blocks

    def _evict_one(self) -> bool:
        """Evict the least-recently-used refcount-zero resident,
        returning its blocks to the free list. False when every
        resident is pinned by an in-flight request."""
        victims = [aid for aid, rc in self._refcount.items() if rc == 0]
        if not victims:
            return False
        victim = min(victims, key=lambda aid: self._last_used[aid])
        for bid in self._blocks.pop(victim):
            self._free.append(bid)
        del self._refcount[victim]
        del self._last_used[victim]
        self.evictions += 1
        hook = self.hooks.get("on_evict")
        if hook is not None:
            hook()
        return True

    def _pop_free(self) -> int:
        if not self._free:
            if not self._evict_one():
                raise AdapterLoadError(
                    "adapter pool exhausted: every resident adapter is "
                    "pinned by an in-flight request")
        return self._free.pop()

    def acquire(self, adapter_id: str) -> np.ndarray:
        """Pin ``adapter_id`` for one request and return its block-table
        row (``[blocks_per_adapter] int32``, zero-padded). Loads the
        adapter into the pool on a miss — the ``cache.adapter_load``
        fault site fires BEFORE any pool state moves, so a degraded
        load leaves the pool untouched. Raises
        :class:`AdapterLoadError` (or the injector's retryable error)
        on failure; the caller owns one :meth:`release`."""
        if adapter_id not in self._staged:
            raise AdapterLoadError(
                f"adapter {adapter_id!r} is not registered")
        self._tick += 1
        if adapter_id in self._blocks:
            self._refcount[adapter_id] += 1
            self._last_used[adapter_id] = self._tick
            self.hits += 1
            hook = self.hooks.get("on_hit")
            if hook is not None:
                hook()
            return self._row(adapter_id)
        fault = self.faults.fire("cache.adapter_load")
        if fault is not None and fault.kind == "cache_exhausted":
            raise AdapterLoadError(
                f"injected adapter-pool exhaustion loading {adapter_id!r}")
        chunks = self._staged[adapter_id]
        blocks: List[int] = []
        try:
            for _ in chunks:
                blocks.append(self._pop_free())
        except AdapterLoadError:
            self._free.extend(reversed(blocks))
            raise
        for bid, chunk in zip(blocks, chunks):
            self.a_pool, self.b_pool = _load_blocks(
                self.a_pool, self.b_pool, chunk["a"], chunk["b"], bid)
        self._blocks[adapter_id] = blocks
        self._refcount[adapter_id] = 1
        self._last_used[adapter_id] = self._tick
        self.loads += 1
        hook = self.hooks.get("on_load")
        if hook is not None:
            hook()
        if self.tracer is not None:
            self.tracer.event(
                "adapter_load", adapter=adapter_id,
                rank=self._rank[adapter_id], blocks=len(blocks),
                resident=len(self._blocks))
        return self._row(adapter_id)

    def release(self, adapter_id: str) -> None:
        """Drop one pin. Blocks stay resident (an LRU-evictable warm
        entry) until the pool needs the space."""
        rc = self._refcount.get(adapter_id)
        if rc is None or rc <= 0:
            raise ValueError(
                f"release of non-acquired adapter {adapter_id!r}")
        self._refcount[adapter_id] = rc - 1

    def _row(self, adapter_id: str) -> np.ndarray:
        row = np.zeros((self.blocks_per_adapter,), np.int32)
        blocks = self._blocks[adapter_id]
        row[:len(blocks)] = blocks
        return row

    # -- program plumbing ---------------------------------------------
    def lora_args(self, rows) -> tuple:
        """Package the pools + a slot table for the engine's ``lora=``
        kwarg: ``(a_pool, b_pool, rows)`` with ``rows`` ``[B, NBa]``
        (decode/verify) or ``[NBa]`` (one prefill slot) — traced data,
        so any adapter mix reuses the same compiled program."""
        return (self.a_pool, self.b_pool, jnp.asarray(rows, jnp.int32))

    def stats(self) -> Dict[str, int]:
        return {
            "registered": len(self._staged),
            "resident": len(self._blocks),
            "pool_blocks": self.num_blocks - 1,
            "free_blocks": len(self._free),
            "pool_bytes": self.pool_bytes,
            "hits": self.hits,
            "loads": self.loads,
            "evictions": self.evictions,
        }
