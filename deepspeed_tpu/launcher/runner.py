"""Launcher runner — multi-host TPU job entry.

Capability match for the reference's runner
(ref: deepspeed/launcher/runner.py:313 main, fetch_hostfile :153,
parse_resource_filter :194): parse a hostfile (``host slots=N``), apply
``--include``/``--exclude`` filters, build the encoded world-info, and
launch one worker per host — locally for single host, over pdsh/ssh/mpi
for pods.

TPU differences: the per-host worker is ONE python process driving all
local chips (jax's process-per-host model), not one per accelerator, so
"slots" count chips for bookkeeping/filters while the spawn count per
host is 1. Rendezvous uses ``jax.distributed.initialize``'s coordinator
(env: DSTPU_COORDINATOR, DSTPU_NUM_PROCESSES, DSTPU_PROCESS_ID) in
place of torch's MASTER_ADDR/RANK env rendezvous.
"""

import argparse
import base64
import collections
import json
import os
import shutil
import subprocess
import sys
from copy import deepcopy
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["PYTHONPATH", "PATH", "LD_LIBRARY_PATH", "TPU_", "JAX_",
               "XLA_", "LIBTPU_"]
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"


def fetch_hostfile(hostfile_path: str) -> Optional[Dict[str, int]]:
    """Parse ``hostname slots=N`` lines (ref: runner.py:153)."""
    if not os.path.isfile(hostfile_path):
        logger.warning("Unable to find hostfile, will proceed with training "
                       "with local resources only.")
        return None
    resource_pool: Dict[str, int] = collections.OrderedDict()
    with open(hostfile_path, "r") as fd:
        for line in fd.readlines():
            line = line.strip()
            if line == "" or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError as err:
                logger.error("Hostfile is not formatted correctly, unable "
                             "to proceed with training.")
                raise err
            if hostname in resource_pool:
                raise ValueError(f"host {hostname} is already defined")
            resource_pool[hostname] = slot_count
    return resource_pool


def parse_resource_filter(host_info: Dict[str, List[int]],
                          include_str: str = "",
                          exclude_str: str = "") -> Dict[str, List[int]]:
    """Filter {host: [slot ids]} by NODE_SPEC[@NODE_SPEC...] strings,
    NODE_SPEC = NAME[:SLOT[,SLOT...]] (ref: runner.py:194)."""
    NODE_SEP, SLOT_LIST_START, SLOT_SEP = "@", ":", ","

    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive.")
    if not include_str and not exclude_str:
        return host_info

    filtered_hosts: Dict[str, List[int]] = dict()
    if include_str:
        parse_str = include_str
    else:
        filtered_hosts = deepcopy(host_info)
        parse_str = exclude_str

    for node_config in parse_str.split(NODE_SEP):
        if SLOT_LIST_START in node_config:
            hostname, slots = node_config.split(SLOT_LIST_START)
            slots = [int(x) for x in slots.split(SLOT_SEP)]
            if hostname not in host_info:
                raise ValueError(f"Hostname '{hostname}' not found in hostfile")
            for slot in slots:
                if slot not in host_info[hostname]:
                    raise ValueError(
                        f"No slot '{slot}' specified on host '{hostname}'")
            if include_str:
                filtered_hosts[hostname] = slots
            else:
                for slot in slots:
                    filtered_hosts[hostname].remove(slot)
        else:
            hostname = node_config
            if hostname not in host_info:
                raise ValueError(f"Hostname '{hostname}' not found in hostfile")
            if include_str:
                filtered_hosts[hostname] = host_info[hostname]
            else:
                filtered_hosts[hostname] = []

    # prune empty hosts, preserve order
    return collections.OrderedDict(
        (h, s) for h, s in filtered_hosts.items() if s)


def parse_inclusion_exclusion(resource_pool: Dict[str, int],
                              inclusion: str,
                              exclusion: str) -> Dict[str, List[int]]:
    """slots-count pool -> filtered {host: [slot ids]}
    (ref: runner.py:300)."""
    active_resources = collections.OrderedDict(
        (host, list(range(slots))) for host, slots in resource_pool.items())
    return parse_resource_filter(active_resources, include_str=inclusion,
                                 exclude_str=exclusion)


def encode_world_info(world_info: Dict[str, List[int]]) -> str:
    """base64(json) world info handed to per-host launchers
    (ref: runner.py:292)."""
    return base64.urlsafe_b64encode(
        json.dumps(world_info).encode()).decode()


def decode_world_info(encoded: str) -> Dict[str, List[int]]:
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


class MultiNodeRunner:
    """(ref: launcher/multinode_runner.py:15) builds the per-pod launch
    command; subclasses differ in transport."""

    def __init__(self, args, world_info_base64: str):
        self.args = args
        self.world_info_base64 = world_info_base64
        self.user_arguments = args.user_args
        self.user_script = args.user_script
        self.exports: Dict[str, str] = {}

    def add_export(self, key: str, var: str) -> None:
        self.exports[key.strip()] = var.strip()

    def backend_exists(self) -> bool:
        raise NotImplementedError

    def get_cmd(self, environment, active_resources) -> List[str]:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return self.__class__.__name__

    def _launcher_args(self, active_resources) -> List[str]:
        first_host = next(iter(active_resources.keys()))
        return [
            "--world_info", self.world_info_base64,
            "--master_addr", self.args.master_addr or first_host,
            "--master_port", str(self.args.master_port),
        ]


class PDSHRunner(MultiNodeRunner):
    """pdsh transport (ref: multinode_runner.py:45)."""

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources) -> List[str]:
        import shlex
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())
        exports = "".join(f"export {k}={shlex.quote(v)}; "
                          for k, v in self.exports.items())
        # each host runs the per-host launcher; node rank is resolved by
        # the launcher from its own hostname (%h pdsh substitution)
        cmd = [
            "pdsh", "-S", "-f", "1024", "-w", active_workers,
            exports + f"cd {shlex.quote(os.path.abspath('.'))}; "
            f"{sys.executable} -m deepspeed_tpu.launcher.launch "
            + " ".join(self._launcher_args(active_resources))
            + f" --hostname %h {shlex.quote(self.user_script)} "
            + " ".join(shlex.quote(a) for a in self.user_arguments),
        ]
        return cmd


class SSHRunner(MultiNodeRunner):
    """Plain-ssh transport: one ssh per host, in parallel, joined by
    ``wait`` so the launch fails if any node fails. The third transport
    slot the reference fills with MVAPICH's mpirun_rsh
    (ref: launcher/multinode_runner.py:156) — MVAPICH itself is an
    InfiniBand-tuned MPI with no TPU-pod analog (docs/PARITY.md), but
    the capability it provides there (launch without pdsh or an MPI
    install, rsh/ssh fan-out) is exactly this runner."""

    def backend_exists(self) -> bool:
        return shutil.which("ssh") is not None

    def get_cmd(self, environment, active_resources) -> List[str]:
        import shlex
        exports = "".join(f"export {k}={shlex.quote(v)}; "
                          for k, v in self.exports.items())
        launcher_args = " ".join(self._launcher_args(active_resources))
        user = " ".join([shlex.quote(self.user_script)]
                        + [shlex.quote(a) for a in self.user_arguments])
        per_host = []
        for host in active_resources:
            remote = (exports + f"cd {shlex.quote(os.path.abspath('.'))}; "
                      f"{sys.executable} -m deepspeed_tpu.launcher.launch "
                      f"{launcher_args} --hostname {host} {user}")
            per_host.append(f"ssh -o StrictHostKeyChecking=no "
                            f"{shlex.quote(host)} {shlex.quote(remote)} &")
        # `wait -n`-free portable join: wait collects every child; the
        # subshell's exit code is the last wait's, so check each pid
        script = "pids=(); " + " ".join(
            p + " pids+=($!);" for p in per_host) + \
            " rc=0; for p in ${pids[@]}; do wait $p || rc=$?; done; exit $rc"
        return ["bash", "-c", script]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun transport (ref: multinode_runner.py:101): one rank per
    host; jax.distributed picks up OMPI env."""

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources) -> List[str]:
        total_hosts = len(active_resources)
        hosts = ",".join(f"{h}:1" for h in active_resources)
        export_args = []
        for k, v in self.exports.items():
            export_args += ["-x", f"{k}={v}"]
        return [
            "mpirun", "-n", str(total_hosts), "--host", hosts,
            "--mca", "btl", "^openib",
        ] + export_args + [
            sys.executable, "-m", "deepspeed_tpu.launcher.launch",
        ] + self._launcher_args(active_resources) + [
            self.user_script,
        ] + list(self.user_arguments)


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher (ref: bin/deepspeed)")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE)
    parser.add_argument("-i", "--include", type=str, default="")
    parser.add_argument("-e", "--exclude", type=str, default="")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_chips", "--num_gpus", dest="num_chips",
                        type=int, default=-1)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default="pdsh",
                        choices=["pdsh", "openmpi", "ssh"])
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)
    from_hostfile = resource_pool is not None

    if not resource_pool:
        # single host: this machine, all local chips as one worker
        resource_pool = {"localhost": max(args.num_chips, 1)}
    if args.num_nodes > 0:
        resource_pool = collections.OrderedDict(
            list(resource_pool.items())[:args.num_nodes])

    active_resources = parse_inclusion_exclusion(
        resource_pool, args.include, args.exclude)
    if not active_resources:
        raise RuntimeError("no resources left after include/exclude filters")
    world_info = encode_world_info(active_resources)

    # any hostfile => remote dispatch, even for one host (the host may
    # not be this machine); local exec only without a hostfile
    multi_node = args.force_multi or from_hostfile or \
        len(active_resources) > 1
    env = os.environ.copy()

    if not multi_node:
        cmd = [
            sys.executable, "-m", "deepspeed_tpu.launcher.launch",
            "--world_info", world_info,
            "--master_addr", args.master_addr or "127.0.0.1",
            "--master_port", str(args.master_port),
            "--hostname", "localhost",
            args.user_script,
        ] + list(args.user_args)
    else:
        runner_cls = {"pdsh": PDSHRunner, "openmpi": OpenMPIRunner,
                      "ssh": SSHRunner}[args.launcher]
        runner = runner_cls(args, world_info)
        if not runner.backend_exists():
            raise RuntimeError(f"launcher backend '{args.launcher}' not found")
        # propagate relevant env (ref: runner.py:389 EXPORT_ENVS +
        # .deepspeed_env file)
        for key, val in env.items():
            if any(key.startswith(p) for p in EXPORT_ENVS):
                runner.add_export(key, val)
        env_file = os.path.join(os.path.expanduser("~"),
                                DEEPSPEED_ENVIRONMENT_NAME)
        if os.path.isfile(env_file):
            with open(env_file) as f:
                for line in f:
                    if "=" in line:
                        k, v = line.strip().split("=", 1)
                        runner.add_export(k, v)
        cmd = runner.get_cmd(env, active_resources)

    logger.info(f"cmd = {' '.join(cmd)}")
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    if result.returncode != 0:
        sys.exit(result.returncode)


if __name__ == "__main__":
    main()
