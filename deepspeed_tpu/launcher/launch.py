"""Per-host launcher.

Capability match for the reference's per-node launcher
(ref: deepspeed/launcher/launch.py:90 main, sigkill_handler :176). The
reference spawns one subprocess per local GPU with RANK/LOCAL_RANK env;
on TPU each host runs ONE process that owns all local chips
(jax.distributed process-per-host), so this launcher resolves the
host's process index from the world info, exports the coordinator env
consumed by ``deepspeed_tpu.utils.distributed.init_distributed``, and
executes the training script — killing the child tree on SIGINT/SIGTERM
like the reference's sigkill handler.
"""

import argparse
import os
import signal
import socket
import subprocess
import sys
from typing import List

from deepspeed_tpu.launcher.runner import decode_world_info
from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--world_info", type=str, required=True)
    parser.add_argument("--master_addr", type=str, required=True)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--hostname", type=str, default="")
    parser.add_argument("--procs_per_node", type=int, default=1,
                        help="1 on TPU (process-per-host); >1 only for "
                        "CPU-emulation testing")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def resolve_node_rank(world_info: dict, hostname: str) -> int:
    hosts = list(world_info.keys())
    if hostname in hosts:
        return hosts.index(hostname)
    fqdn = socket.gethostname()
    for cand in (fqdn, fqdn.split(".")[0]):
        if cand in hosts:
            return hosts.index(cand)
    if len(hosts) == 1:
        return 0
    raise RuntimeError(f"host '{hostname or fqdn}' not in world info {hosts}")


def build_child_env(base_env: dict, master_addr: str, master_port: int,
                    num_processes: int, process_id: int,
                    local_chips: List[int]) -> dict:
    env = dict(base_env)
    # consumed by utils/distributed.py init_distributed →
    # jax.distributed.initialize
    env["DSTPU_COORDINATOR"] = f"{master_addr}:{master_port}"
    env["DSTPU_NUM_PROCESSES"] = str(num_processes)
    env["DSTPU_PROCESS_ID"] = str(process_id)
    # reference-compatible aliases so user scripts can read familiar keys
    env["RANK"] = str(process_id)
    env["WORLD_SIZE"] = str(num_processes)
    env["LOCAL_RANK"] = "0"
    env["MASTER_ADDR"] = master_addr
    env["MASTER_PORT"] = str(master_port)
    env["DSTPU_LOCAL_CHIPS"] = ",".join(str(c) for c in local_chips)
    return env


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    node_rank = resolve_node_rank(world_info, args.hostname)
    num_nodes = len(world_info)
    local_chips = list(world_info.values())[node_rank]
    logger.info(f"node_rank={node_rank}/{num_nodes}, "
                f"local chips={local_chips}")

    procs = []
    for local_proc in range(args.procs_per_node):
        process_id = node_rank * args.procs_per_node + local_proc
        env = build_child_env(
            os.environ.copy(), args.master_addr, args.master_port,
            num_processes=num_nodes * args.procs_per_node,
            process_id=process_id, local_chips=local_chips)
        cmd = [sys.executable, args.user_script] + list(args.user_args)
        procs.append(subprocess.Popen(cmd, env=env))

    def sigkill_handler(signum, frame):
        # (ref: launch.py:176) take the whole tree down
        for p in procs:
            logger.info(f"killing subprocess {p.pid}")
            try:
                p.kill()
            except OSError:
                pass
        sys.exit(1)

    signal.signal(signal.SIGINT, sigkill_handler)
    signal.signal(signal.SIGTERM, sigkill_handler)

    exit_code = 0
    for p in procs:
        p.wait()
        if p.returncode != 0 and exit_code == 0:
            exit_code = p.returncode
    # propagate the first failing exit code (ref: launch.py:176,
    # runner.py:458)
    sys.exit(exit_code)


if __name__ == "__main__":
    main()
