from deepspeed_tpu.launcher.runner import (
    fetch_hostfile, parse_inclusion_exclusion, parse_resource_filter,
    encode_world_info, decode_world_info)

__all__ = ["fetch_hostfile", "parse_inclusion_exclusion",
           "parse_resource_filter", "encode_world_info",
           "decode_world_info"]
