"""Environment report — ``ds_report`` analog.

Capability match for the reference's env report
(ref: deepspeed/env_report.py + bin/ds_report): prints framework
versions, platform/device inventory, HBM capacity, and a feature
compatibility table (which optional subsystems are usable in this
environment) instead of the reference's CUDA-op build matrix.
"""

import importlib
import os
import platform
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def _version(mod_name: str) -> str:
    try:
        mod = importlib.import_module(mod_name)
        return getattr(mod, "__version__", "unknown")
    except ImportError:
        return "not installed"


def _feature_rows():
    """(name, available, note) for every optional subsystem."""
    rows = []
    import jax
    platform_name = jax.default_backend()
    on_tpu = platform_name == "tpu"
    rows.append(("tpu backend", on_tpu, f"backend={platform_name}"))

    from deepspeed_tpu.ops.op_builder import AsyncIOBuilder, CPUAdamBuilder
    for label, builder in (("async_io (C++ aio pool)", AsyncIOBuilder),
                           ("cpu_adam (host offload)", CPUAdamBuilder)):
        try:
            b = builder()
            ok = b.is_compatible()
            note = "builds on demand" if ok else "toolchain/libaio missing"
            if ok:
                b.load()
                note = "built"
        except Exception as e:
            ok, note = False, f"{type(e).__name__}: {e}"
        rows.append((label, ok, note))

    try:
        import jax.experimental.pallas  # noqa: F401
        rows.append(("pallas kernels", True, "flash/block-sparse attention"))
    except ImportError:
        rows.append(("pallas kernels", False, "pallas unavailable"))

    multi = False
    try:
        multi = jax.process_count() > 1
    except Exception:  # dslint: disable=DS006 — best-effort report probe
        pass
    rows.append(("multi-host runtime", multi,
                 f"{jax.process_count() if multi else 1} process(es)"))
    return rows


def main():
    import jax
    import deepspeed_tpu

    lines = ["-" * 70, "DeepSpeed-TPU environment report", "-" * 70]
    lines.append(f"deepspeed_tpu ........ {deepspeed_tpu.__version__}")
    lines.append(f"python ............... {sys.version.split()[0]} "
                 f"({platform.platform()})")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        lines.append(f"{mod:<21}{'':.<1} {_version(mod)}")
    lines.append("-" * 70)

    devs = jax.devices()
    lines.append(f"devices: {len(devs)} x {devs[0].device_kind} "
                 f"(process {jax.process_index()}/{jax.process_count()})")
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            lines.append(f"HBM per device: {stats['bytes_limit'] / 1e9:.1f} GB")
    except Exception:  # dslint: disable=DS006 — best-effort report probe
        pass
    cache = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache:
        lines.append(f"compilation cache: {cache}")
    lines.append("-" * 70)

    for name, ok, note in _feature_rows():
        status = GREEN_OK if ok else RED_NO
        lines.append(f"{name:<28} {status}  {note}")
    lines.append("-" * 70)
    print("\n".join(lines))
    return lines


if __name__ == "__main__":
    main()
