"""FLOPS profiler — XLA cost analysis + measured wall time.

Capability match for the reference's ``FlopsProfiler``
(ref: deepspeed/profiling/flops_profiler/profiler.py:164). The
reference monkey-patches ``torch.nn.functional`` (wrapFunc :1108) and
hangs fwd hooks on every module to count MACs per op; under XLA none of
that is needed — the compiler already knows the exact FLOP count of the
optimized program. We read it from ``compiled.cost_analysis()``
(flops, bytes accessed) and pair it with measured execution time for
achieved-TFLOPS and MFU.

Per-module breakdown: jax has no module tree, so callers may pass a
``submodules`` dict of named jittable sub-functions (e.g. one
transformer block, the embed, the head) — each is cost-analyzed
separately, mirroring the reference's depth-aggregated module profile
(ref: profiler.py:573 print_model_aggregated_profile).
"""

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

# the chip-peak table and the analytic per-token formulas now live in
# telemetry/costs.py so serving-side attribution and this training-side
# profiler can never disagree; the old names stay importable from here.
from deepspeed_tpu.telemetry.costs import (PEAK_FLOPS as _PEAK_FLOPS,
                                           attn_flops,
                                           device_peak_flops,
                                           infer_flops,
                                           model_flops_per_token,
                                           weight_bytes)
from deepspeed_tpu.utils.logging import logger


def analytic_model_profile(cfg, seq_len: Optional[int] = None,
                           param_itemsize: int = 2) -> Dict[str, Any]:
    """Closed-form per-token profile of a :class:`GPTConfig` — no
    compilation, no device. The per-layer counts route through the
    ``telemetry/costs.py`` helpers (the single FLOPs formula source of
    truth over ``models/gpt.py``'s param counts), so a number printed
    here matches what the serving cost accountant charges per dispatch.
    """
    from deepspeed_tpu.models.gpt import (kv_bytes_per_token, num_params,
                                          train_flops_per_token)
    s = int(seq_len if seq_len is not None else cfg.max_seq_len)
    fwd_tok = model_flops_per_token(cfg)
    return {
        "params": int(num_params(cfg)),
        "seq_len": s,
        "fwd_flops_per_token": fwd_tok,
        "fwd_attn_flops_seq": attn_flops(cfg, s, 0),
        "fwd_flops_seq": infer_flops(cfg, s, 0),
        "train_flops_per_token": int(train_flops_per_token(cfg, s)),
        "kv_bytes_per_token": int(kv_bytes_per_token(cfg)),
        "weight_bytes": weight_bytes(cfg, param_itemsize),
    }


def _num_to_string(num: float, units=None, precision: int = 2) -> str:
    """1.23e9 -> '1.23 G' (ref: profiler.py num_to_string helpers)."""
    if units is None:
        for cut, unit in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
            if abs(num) >= cut:
                return f"{num / cut:.{precision}f} {unit}"
        return f"{num:.{precision}f} "
    scale = {"T": 1e12, "G": 1e9, "M": 1e6, "K": 1e3, "": 1.0}[units]
    return f"{num / scale:.{precision}f} {units}"


def analyze_fn(fn: Callable, *args,
               static_argnums=(), runs: int = 3,
               **kwargs) -> Dict[str, Any]:
    """Compile ``fn(*args)`` and return
    {flops, bytes_accessed, peak_bytes, duration_s, tflops_achieved,
    mfu, arithmetic_intensity}. ``fn`` may already be jitted (the
    lower/compile hits the jit cache)."""
    jfn = fn if hasattr(fn, "lower") else jax.jit(fn, static_argnums=static_argnums)
    static = analyze_compiled(jfn, *args, **kwargs)
    flops = static["flops"]
    bytes_accessed = static["bytes_accessed"]
    compiled = jfn.lower(*args, **kwargs).compile()  # jit-cache hit
    try:
        mem = compiled.memory_analysis()
        peak_bytes = int(getattr(mem, "temp_size_in_bytes", 0) +
                         getattr(mem, "output_size_in_bytes", 0))
    except Exception:  # pragma: no cover - backend-dependent
        peak_bytes = 0

    # measured duration: best of `runs` (first call may add dispatch noise)
    out = compiled(*args, **kwargs)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        out = compiled(*args, **kwargs)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)

    peak = device_peak_flops()
    achieved = flops / best if best > 0 else 0.0
    return {
        "flops": flops,
        "macs": flops / 2.0,
        "bytes_accessed": bytes_accessed,
        "peak_bytes": peak_bytes,
        "duration_s": best,
        "tflops_achieved": achieved / 1e12,
        "mfu": (achieved / peak) if peak else None,
        "arithmetic_intensity": (flops / bytes_accessed)
        if bytes_accessed else None,
    }


def analyze_compiled(jfn, *args, **kwargs) -> Dict[str, float]:
    """Static cost analysis only — never executes (safe for programs
    with donated buffers, like the engine's train step)."""
    compiled = jfn.lower(*args, **kwargs).compile()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, list):
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0))
    return {"flops": flops, "macs": flops / 2.0,
            "bytes_accessed": float(cost.get("bytes accessed", 0.0))}


def _count_params(tree) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "size"))


class FlopsProfiler:
    """Reference-shaped profiler driven by XLA cost analysis.

    Usage::

        prof = FlopsProfiler(loss_fn, params)
        prof.start_profile()
        prof.profile(batch, rng)       # compiles + measures
        prof.print_model_profile()
        prof.end_profile()

    ``submodules``: optional {name: (fn, args_tuple)} for a per-component
    table (the reference's per-module tree, profiler.py:392).
    """

    def __init__(self, model: Callable, params=None,
                 submodules: Optional[Dict[str, Tuple[Callable, tuple]]] = None):
        self.model = model
        self.params = params
        self.submodules = submodules or {}
        self.started = False
        self._profile: Dict[str, Any] = {}
        self._sub_profiles: Dict[str, Dict[str, Any]] = {}

    # -- reference API -------------------------------------------------

    def start_profile(self, ignore_list=None) -> None:
        del ignore_list  # reference arg; no hooks to install under XLA
        self.started = True
        self._profile = {}
        self._sub_profiles = {}

    def stop_profile(self) -> None:
        self.started = False

    def reset_profile(self) -> None:
        self._profile = {}
        self._sub_profiles = {}

    def end_profile(self) -> None:
        self.stop_profile()
        self.reset_profile()

    def profile(self, *args, **kwargs) -> Dict[str, Any]:
        """Cost-analyze model(params, *args) (or model(*args) when no
        params were given)."""
        call_args = ((self.params,) + args) if self.params is not None else args
        self._profile = analyze_fn(self.model, *call_args, **kwargs)
        for name, (fn, sub_args) in self.submodules.items():
            self._sub_profiles[name] = analyze_fn(fn, *sub_args)
        return self._profile

    def get_total_flops(self, as_string: bool = False):
        v = self._profile.get("flops", 0.0)
        return _num_to_string(v) + "FLOPS" if as_string else v

    def get_total_macs(self, as_string: bool = False):
        v = self._profile.get("macs", 0.0)
        return _num_to_string(v) + "MACs" if as_string else v

    def get_total_duration(self, as_string: bool = False):
        v = self._profile.get("duration_s", 0.0)
        return f"{v * 1e3:.2f} ms" if as_string else v

    def get_total_params(self, as_string: bool = False):
        v = _count_params(self.params) if self.params is not None else 0
        return _num_to_string(v) + "params" if as_string else v

    # -- printing ------------------------------------------------------

    def print_model_profile(self, profile_step: int = 1,
                            module_depth: int = -1, top_modules: int = 1,
                            detailed: bool = True,
                            output_file: Optional[str] = None) -> None:
        """(ref: profiler.py:392) one summary block + optional
        per-submodule table."""
        p = self._profile
        if not p:
            logger.warning("FlopsProfiler: call profile() first")
            return
        lines = [
            "", "-" * 72,
            "DeepSpeed-TPU Flops Profiler",
            "-" * 72,
            f"profile step:                   {profile_step}",
            f"params:                         {self.get_total_params(True)}",
            f"fwd(+bwd+step) flops:           {self.get_total_flops(True)}",
            f"fwd(+bwd+step) MACs:            {self.get_total_macs(True)}",
            f"bytes accessed (HBM):           {_num_to_string(p['bytes_accessed'])}B",
            f"arithmetic intensity:           "
            f"{p['arithmetic_intensity'] and round(p['arithmetic_intensity'], 1)} flops/byte",
            f"measured latency:               {self.get_total_duration(True)}",
            f"achieved throughput:            {p['tflops_achieved']:.2f} TFLOPS",
        ]
        if p.get("mfu") is not None:
            lines.append(f"model flops utilization (MFU):  {p['mfu'] * 100:.1f}%")
        if detailed and self._sub_profiles:
            lines.append("-" * 72)
            lines.append(f"{'submodule':<28}{'flops':>14}{'latency':>12}{'share':>10}")
            total = max(p["flops"], 1.0)
            # the detailed table lists every submodule; top_modules only
            # limits print_model_aggregated_profile (as in the reference)
            ranked = sorted(self._sub_profiles.items(),
                            key=lambda kv: -kv[1]["flops"])
            for name, sp in ranked:
                lines.append(
                    f"{name:<28}{_num_to_string(sp['flops']):>13} "
                    f"{sp['duration_s'] * 1e3:>10.2f}ms"
                    f"{sp['flops'] / total * 100:>9.1f}%")
        lines.append("-" * 72)
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(text + "\n")
        else:
            logger.info(text)

    def print_model_aggregated_profile(self, module_depth: int = -1,
                                       top_modules: int = 1) -> None:
        """(ref: profiler.py:573) top-k submodules by flops."""
        if not self._sub_profiles:
            logger.warning("FlopsProfiler: no submodules registered")
            return
        ranked = sorted(self._sub_profiles.items(),
                        key=lambda kv: -kv[1]["flops"])[:top_modules]
        for name, sp in ranked:
            logger.info(f"{name}: {_num_to_string(sp['flops'])}FLOPS, "
                        f"{sp['duration_s'] * 1e3:.2f} ms")


def get_model_profile(model: Callable, args=(), kwargs=None,
                      print_profile: bool = True, detailed: bool = True,
                      warm_up: int = 1, as_string: bool = True,
                      output_file: Optional[str] = None,
                      ignore_modules=None):
    """One-shot convenience (ref: profiler.py:1185 get_model_profile):
    returns (flops, macs, params) of ``model(*args)``."""
    del warm_up, ignore_modules
    kwargs = kwargs or {}
    prof = FlopsProfiler(model)
    prof.start_profile()
    prof.profile(*args, **kwargs)
    if print_profile:
        prof.print_model_profile(detailed=detailed, output_file=output_file)
    flops = prof.get_total_flops(as_string)
    macs = prof.get_total_macs(as_string)
    params = _count_params(args[0]) if args else 0
    if as_string:
        params = _num_to_string(params) + "params"
    prof.end_profile()
    return flops, macs, params
