from deepspeed_tpu.profiling.flops_profiler.profiler import (
    FlopsProfiler, analyze_compiled, analyze_fn, get_model_profile,
    device_peak_flops)

__all__ = ["FlopsProfiler", "analyze_compiled", "analyze_fn",
           "get_model_profile", "device_peak_flops"]
