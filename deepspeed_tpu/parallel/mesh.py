"""Device mesh construction — the TPU replacement for process groups.

The reference manages many torch.distributed process groups
(ref: deepspeed/utils/groups.py:305 _clone_world_group, :321
_get_data_parallel_group, expert groups at :107/:160/:206). On TPU all of
that collapses into ONE ``jax.sharding.Mesh`` with named axes; "groups"
become axis names and collectives become XLA ops over those axes.

Axis layout (major to minor): ``('pipe', 'data', 'fsdp', 'sequence', 'model')``.
- ``data``   replicated-param data parallelism (ZeRO-0/1/2)
- ``fsdp``   parameter-sharding data parallelism (ZeRO-3); merged with
             ``data`` for the optimizer-state partitioning so dp degree =
             data*fsdp
- ``model``  tensor parallelism — innermost so TP collectives ride the
             fastest ICI links
- ``sequence`` ring/all-to-all sequence parallelism (DeepSpeed has no SP at
             v0.6.4; first-class here)
- ``expert`` expert parallelism reuses the (data x fsdp) axes via
             ``expert_sharding`` helpers rather than occupying mesh slots
             (GShard-style: experts sharded over dp ranks).
"""

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.utils.logging import logger

# canonical axis order, major -> minor
MESH_AXES = ("pipe", "data", "fsdp", "sequence", "model")

# axes over which a batch is split
BATCH_AXES = ("data", "fsdp")


@dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism degrees; -1 data means 'use remaining devices'."""
    pipe: int = 1
    data: int = -1
    fsdp: int = 1
    sequence: int = 1
    model: int = 1

    def resolve(self, n_devices: int) -> Tuple[int, ...]:
        fixed = self.pipe * self.fsdp * self.sequence * self.model
        data = self.data
        if data == -1:
            assert n_devices % fixed == 0, (
                f"devices {n_devices} not divisible by pipe*fsdp*seq*model={fixed}")
            data = n_devices // fixed
        total = fixed * data
        assert total == n_devices, (
            f"mesh {self} requires {total} devices, have {n_devices}")
        return (self.pipe, data, self.fsdp, self.sequence, self.model)


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build the framework mesh over the given (default: all) devices."""
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    dims = spec.resolve(len(devices))
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(dims, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(dims)
    return Mesh(dev_array, MESH_AXES)


def mesh_from_config(cfg, devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh from a DeepSpeedConfig's MeshConfig.

    ZeRO stage 3 moves the data-parallel degree onto the ``fsdp`` axis so
    parameter sharding happens over it; stages 0-2 keep it on ``data``.
    """
    m = cfg.mesh
    n = len(devices if devices is not None else jax.devices())
    fixed = (m.pipeline_parallel_size * m.tensor_parallel_size *
             m.sequence_parallel_size)
    assert n % fixed == 0, f"{n} devices not divisible by pp*tp*sp={fixed}"
    dp_total = n // fixed
    if cfg.zero.stage == 3:
        # replica_parallel_size splits dp into outer 'data' replicas
        # (the DCN-crossing axis dcn_compressed rides) x inner 'fsdp'
        # param shards (PERF.md "Compressed DCN x ZeRO-fsdp")
        rep = m.replica_parallel_size
        assert dp_total % rep == 0, (
            f"replica_parallel_size={rep} does not divide the dp degree "
            f"{dp_total}")
        spec = MeshSpec(pipe=m.pipeline_parallel_size, data=rep,
                        fsdp=dp_total // rep,
                        sequence=m.sequence_parallel_size,
                        model=m.tensor_parallel_size)
    else:
        if m.replica_parallel_size > 1:
            raise ValueError(
                f"replica_parallel_size={m.replica_parallel_size} requires "
                f"zero.stage=3 (it splits dp into data replicas x fsdp "
                f"shards); stage {cfg.zero.stage} has no fsdp axis and "
                f"would silently ignore it")
        spec = MeshSpec(pipe=m.pipeline_parallel_size, data=dp_total, fsdp=1,
                        sequence=m.sequence_parallel_size,
                        model=m.tensor_parallel_size)
    mesh = make_mesh(spec, devices)
    logger.info(f"mesh axes {dict(zip(mesh.axis_names, mesh.devices.shape))}")
    return mesh


def dp_world_size(mesh: Mesh) -> int:
    """Total data-parallel degree (data x fsdp axes)."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    return shape.get("data", 1) * shape.get("fsdp", 1)


def axis_size(mesh: Mesh, axis: str) -> int:
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    return shape.get(axis, 1)


def batch_sharding(mesh: Mesh):
    """Sharding fn for batch pytrees: leading dim over the dp axes, and —
    when sequence parallelism is on — dim 1 (tokens) over 'sequence'."""
    seq = axis_size(mesh, "sequence")

    def shard_leaf(leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        # token dim joins 'sequence' only when divisible (e.g. the +1-shifted
        # LM input of length S+1 stays batch-sharded; the model's internal
        # slice gets resharded by the ring attention's shard_map)
        if seq > 1 and len(shape) >= 2 and shape[1] % seq == 0:
            return NamedSharding(mesh, P(BATCH_AXES, "sequence"))
        return NamedSharding(mesh, P(BATCH_AXES))

    return shard_leaf


def batch_pspec() -> P:
    return P(BATCH_AXES)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
