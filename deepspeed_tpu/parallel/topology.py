"""Cartesian process/device topology — pure math, no devices required.

TPU-native analog of the reference's topology module
(ref: deepspeed/runtime/pipe/topology.py:12 ProcessTopology,
:235 PipeDataParallelTopology, :246 PipeModelDataParallelTopology,
:252 PipelineParallelGrid). On TPU the runtime realization is a
``jax.sharding.Mesh``, but the coordinate math (rank <-> axis coordinates,
peer lists along an axis) is identical and is used by the pipeline schedule,
checkpoint naming, and tests.
"""

from collections import namedtuple
from itertools import product
from typing import Dict, List


class ProcessTopology:
    """Maps n-dimensional cartesian coordinates to linear indices.

    Axis order is major to minor: the LAST axis varies fastest
    (ref: topology.py:12-24).
    """

    def __init__(self, axes: List[str], dims: List[int]):
        self.axes = list(axes)
        self.dims = list(dims)
        assert len(self.axes) == len(self.dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self.mapping: Dict = {}
        ranges = [range(d) for d in self.dims]
        for global_rank, coord in enumerate(product(*ranges)):
            key = dict(zip(self.axes, coord))
            self.mapping[self.ProcessCoord(**key)] = global_rank

    def get_rank(self, **coord_kwargs) -> int:
        if len(coord_kwargs) != len(self.axes):
            raise ValueError("get_rank() does not support slices, use filter_match()")
        key = self.ProcessCoord(**coord_kwargs)
        assert key in self.mapping, f"key {coord_kwargs} invalid"
        return self.mapping[key]

    def get_axis_names(self) -> List[str]:
        return self.axes

    def get_rank_repr(self, rank: int, omit_axes=("data",), inner_sep="_",
                      outer_sep="-") -> str:
        """Canonical checkpoint-path name for a rank (ref: topology.py:80)."""
        omit_axes = list(omit_axes)
        axes = [a for a in self.get_axis_names() if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis: str) -> int:
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank: int):
        for coord, idx in self.mapping.items():
            if idx == rank:
                return coord
        raise ValueError(f"rank {rank} not found in topology")

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """All peer groups along ``axis`` (ref: topology.py:137)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for coord in product(*ranges):
            other_keys = dict(zip(other_axes, coord))
            sub = [self.get_rank(**other_keys, **{axis: i})
                   for i in range(self.get_dim(axis))]
            lists.append(sub)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        """Ranks whose coordinates match all filters (ref: topology.py:169)."""
        def _filter_helper(x):
            for key, val in filter_kwargs.items():
                if getattr(x, key) != val:
                    return False
            return True
        coords = filter(_filter_helper, self.mapping.keys())
        return [self.mapping[coord] for coord in coords]

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return [self.mapping[k] for k in self.mapping
                if getattr(k, axis) == idx]

    def world_size(self) -> int:
        size = 1
        for d in self.dims:
            size *= d
        return size

    def __str__(self):
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """Hybrid pipeline+data topology; pipe-adjacent ranks are mapped close
    together so p2p rides ICI neighbors (ref: topology.py:235)."""

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """3D pipe/data/model topology (ref: topology.py:246)."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Axis-rank bookkeeping for one process in a topology
    (ref: topology.py:252 PipelineParallelGrid). Device-free: on TPU the
    collectives ride the Mesh; this class answers "who am I / who are my
    peers" questions for the scheduler and checkpoint layer.
    """

    def __init__(self, topology: ProcessTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        self.world_size = topology.world_size()

        self.data_parallel_size = max(1, topology.get_dim("data"))
        self.pipe_parallel_size = max(1, topology.get_dim("pipe"))
        self.model_parallel_size = max(1, topology.get_dim("model"))
        self.slice_parallel_size = self.model_parallel_size
        assert self.world_size == (self.data_parallel_size * self.pipe_parallel_size *
                                   self.model_parallel_size)

        coord = topology.get_coord(global_rank)
        self.stage_id = getattr(coord, "pipe", 0) if "pipe" in topology.axes else 0
        self.data_parallel_id = getattr(coord, "data", 0) if "data" in topology.axes else 0
        self.model_parallel_id = getattr(coord, "model", 0) if "model" in topology.axes else 0

        if "pipe" in topology.axes:
            self.p2p_groups = self._build_p2p_groups()
        else:
            self.p2p_groups = []

    def _build_p2p_groups(self) -> List[List[int]]:
        """Ring groups of pipe-adjacent ranks (ref: topology.py:301)."""
        comm_lists = self._topo.get_axis_comm_lists("pipe")
        groups = []
        for l in comm_lists:
            assert len(l) >= 1
            for idx in range(len(l)):
                groups.append(sorted([l[idx], l[(idx + 1) % len(l)]]))
        return [list(g) for g in groups]

    def get_stage_id(self) -> int:
        return self.stage_id

    def get_data_parallel_id(self) -> int:
        return self.data_parallel_id

    def get_pipe_parallel_rank(self) -> int:
        return self.stage_id

    def get_data_parallel_rank(self) -> int:
        return self.data_parallel_id

    def get_model_parallel_rank(self) -> int:
        return self.model_parallel_id

    def get_global_rank(self) -> int:
        return self.global_rank

    def get_data_parallel_world_size(self) -> int:
        return self.data_parallel_size

    def get_pipe_parallel_world_size(self) -> int:
        return self.pipe_parallel_size

    def get_model_parallel_world_size(self) -> int:
        return self.model_parallel_size

    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    def is_last_stage(self) -> bool:
        return self.stage_id == self.pipe_parallel_size - 1

    def stage_to_global(self, stage_id: int, **kwargs) -> int:
        """Global rank of ``stage_id`` with my other coordinates
        (ref: topology.py:432)."""
        me = self._topo.get_coord(self.global_rank)
        transform = me._replace(pipe=stage_id, **kwargs)._asdict()
        return self._topo.get_rank(**transform)
