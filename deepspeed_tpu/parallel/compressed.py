"""Communication-compressed collectives (1-bit, error-feedback).

Capability analog of the reference's compressed allreduce backends
(ref: deepspeed/runtime/comm/nccl.py:52 NcclBackend.compressed_allreduce,
runtime/comm/mpi.py MpiBackend, cupy bit packing in
runtime/compression/cupy.py). Intended for DCN links between TPU slices —
over ICI plain XLA collectives win (SURVEY §2.3).

Algorithm (error-feedback signSGD compression, as in 1-bit Adam):
  1. corrected = x + error                (error feedback)
  2. scale = ||corrected||_1 / n          (per-tensor magnitude)
  3. compressed = sign(corrected) * scale
  4. new_error = corrected - compressed   (kept locally)
  5. allreduce(compressed) — executed as all_gather of PACKED sign bits
     (uint8, 8 signs/byte = 32x volume reduction vs fp32) + scalar scales,
     then a local unpack-and-average. A second error-feedback stage on the
     server-side average (ref nccl.py's two-stage scheme) is folded into
     the worker error because TPU all_gather is symmetric.
"""

from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from deepspeed_tpu.utils.jax_compat import shard_map

PyTree = Any


def _pack_signs(x: jnp.ndarray) -> jnp.ndarray:
    """flat float [n] -> uint8 [ceil(n/8)] of sign bits (1 = non-negative)."""
    n = x.shape[0]
    pad = (-n) % 8
    bits = (x >= 0).astype(jnp.uint8)
    bits = jnp.pad(bits, (0, pad))
    bits = bits.reshape(-1, 8)
    weights = (2 ** jnp.arange(8, dtype=jnp.uint8))
    return jnp.sum(bits * weights, axis=1).astype(jnp.uint8)


def _unpack_signs(packed: jnp.ndarray, n: int) -> jnp.ndarray:
    """uint8 [m] -> float [n] of +-1."""
    weights = (2 ** jnp.arange(8, dtype=jnp.uint8))
    bits = (packed[:, None] & weights[None, :]) > 0
    signs = jnp.where(bits, 1.0, -1.0).astype(jnp.float32)
    return signs.reshape(-1)[:n]


def compress(x: jnp.ndarray, error: jnp.ndarray
             ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """-> (packed_bits uint8, scale f32 scalar, new_error)."""
    corrected = x.astype(jnp.float32) + error
    flat = corrected.reshape(-1)
    n = flat.shape[0]
    scale = jnp.sum(jnp.abs(flat)) / n
    packed = _pack_signs(flat)
    compressed = _unpack_signs(packed, n).reshape(x.shape) * scale
    new_error = corrected - compressed
    return packed, scale, new_error


def decompress(packed: jnp.ndarray, scale: jnp.ndarray, n: int,
               shape) -> jnp.ndarray:
    return (_unpack_signs(packed, n) * scale).reshape(shape)


def compressed_allreduce_local(x: jnp.ndarray, error: jnp.ndarray,
                               axis: str) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Inside shard_map over ``axis``: error-feedback 1-bit mean-allreduce.

    Returns (averaged tensor, new local error). The wire payload is the
    packed uint8 sign array + one f32 scale per rank.
    """
    packed, scale, new_error = compress(x, error)
    n = int(np.prod(x.shape))
    # all_gather the compressed payloads (tiled=False -> leading rank dim)
    all_packed = jax.lax.all_gather(packed, axis)          # [R, m] uint8
    all_scales = jax.lax.all_gather(scale, axis)           # [R]
    R = all_packed.shape[0]

    def one(i, acc):
        contrib = decompress(all_packed[i], all_scales[i], n, x.shape)
        return acc + contrib

    total = jax.lax.fori_loop(0, R, one, jnp.zeros(x.shape, jnp.float32))
    return total / R, new_error


def compressed_allreduce(tree: PyTree, error_tree: PyTree, mesh: Mesh,
                         axis: str = "data") -> Tuple[PyTree, PyTree]:
    """Standalone compressed mean-allreduce of a replicated pytree: each
    rank contributes its local values; result is identical on all ranks.
    (For testing / host-level use; the training path calls
    compressed_allreduce_local inside its shard_map.)"""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    err_leaves = jax.tree_util.tree_leaves(error_tree)

    def inner(*flat):
        k = len(flat) // 2
        outs, errs = [], []
        for x, e in zip(flat[:k], flat[k:]):
            o, ne = compressed_allreduce_local(x, e, axis)
            outs.append(o)
            errs.append(ne)
        return tuple(outs) + tuple(errs)

    specs = tuple(P() for _ in range(2 * len(leaves)))
    fn = jax.jit(shard_map(
        inner, mesh=mesh, in_specs=specs, out_specs=specs,
        axis_names={axis}, check_vma=False))
    out = fn(*leaves, *err_leaves)
    k = len(leaves)
    return (jax.tree_util.tree_unflatten(treedef, out[:k]),
            jax.tree_util.tree_unflatten(treedef, out[k:]))


def compression_ratio(shape, dtype=jnp.float32) -> float:
    """Wire bytes full-precision / wire bytes compressed."""
    n = int(np.prod(shape))
    full = n * jnp.dtype(dtype).itemsize
    packed = (n + 7) // 8 + 4
    return full / packed
