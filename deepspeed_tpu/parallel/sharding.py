"""Sharding-spec inference: ZeRO stages and tensor parallelism as PartitionSpecs.

This is where the reference's ZeRO machinery dissolves into XLA sharding:
- stage 1/2 (ref: deepspeed/runtime/zero/stage_1_and_2.py:91
  DeepSpeedZeroOptimizer — flatten/partition/hook/bucket machinery) becomes
  "optimizer state pytree is sharded over the dp axes"; XLA emits the
  reduce-scatter of grads and the allgather of updated params that the
  reference hand-rolls (average_tensor :879, all_gather_dp_groups :1754).
- stage 3 (ref: deepspeed/runtime/zero/stage3.py:226, partition_parameters.py:548
  zero.Init) becomes "params are sharded over the fsdp axis"; XLA's SPMD
  partitioner inserts the per-layer allgather/ reduce-scatter the reference
  drives manually through module hooks and the PartitionedParameterCoordinator.
- tensor parallelism (delegated to Megatron `mpu` in the reference,
  SURVEY §2.2) is first-class here via regex partition rules.
"""

import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.tree import tree_path_str

PyTree = Any

# ---------------------------------------------------------------------------
# path utilities
# ---------------------------------------------------------------------------


def _path_str(path) -> str:
    """Render a jax tree path as 'a/b/c'."""
    return tree_path_str(path, sep="/")


# ---------------------------------------------------------------------------
# tensor-parallel partition rules
# ---------------------------------------------------------------------------

class PartitionRule:
    """(regex over param path) -> PartitionSpec template.

    Spec entries may name mesh axes or None. e.g.
    ``("attn/qkv/kernel", P(None, "model"))`` column-shards a QKV projection.
    """

    def __init__(self, pattern: str, spec: P):
        self.pattern = re.compile(pattern)
        self.spec = spec

    def matches(self, path: str) -> bool:
        return self.pattern.search(path) is not None


def _rule_spec_for(path: str, shape: Tuple[int, ...],
                   rules: Sequence[PartitionRule]) -> Optional[P]:
    for rule in rules:
        if rule.matches(path):
            spec = list(rule.spec)
            # pad/truncate to rank
            if len(spec) < len(shape):
                spec = [None] * (len(shape) - len(spec)) + spec
            return P(*spec[:len(shape)])
    return None


# ---------------------------------------------------------------------------
# ZeRO-3 fsdp sharding
# ---------------------------------------------------------------------------


def _add_fsdp_axis(spec: P, shape: Tuple[int, ...], fsdp_size: int,
                   min_size: int) -> P:
    """Shard the largest free, divisible dim over 'fsdp' (FSDP-style).

    Mirrors the capability of zero.Init's flat partitioning
    (ref: partition_parameters.py:892 partition) without the flattening:
    XLA handles non-even layouts; we only require divisibility to keep
    layouts collective-friendly.
    """
    if fsdp_size <= 1:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    used_axes = set()
    for e in entries:
        if e is None:
            continue
        for a in (e if isinstance(e, tuple) else (e,)):
            used_axes.add(a)
    if "fsdp" in used_axes:
        return P(*entries)
    # pick largest divisible unused dim
    best, best_dim = -1, -1
    for i, d in enumerate(shape):
        if entries[i] is None and d % fsdp_size == 0 and d >= min_size and d > best_dim:
            best, best_dim = i, d
    if best < 0:
        return P(*entries)  # too small / indivisible -> stays replicated ("persistent param")
    entries[best] = "fsdp"
    return P(*entries)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def param_specs(params: PyTree,
                mesh: Mesh,
                zero_stage: int = 0,
                rules: Optional[Sequence[PartitionRule]] = None,
                min_shard_size: int = 1024) -> PyTree:
    """PartitionSpec pytree for model parameters.

    - TP rules applied first (model/sequence axes).
    - If zero_stage == 3, additionally shard over 'fsdp'.
    """
    rules = rules or []
    fsdp_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get("fsdp", 1)

    def spec_for(path, leaf):
        pstr = _path_str(path)
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0:
            return P()
        spec = _rule_spec_for(pstr, shape, rules) or P(*([None] * len(shape)))
        if zero_stage == 3:
            spec = _add_fsdp_axis(spec, shape, fsdp_size, min_shard_size)
        return spec

    return jax.tree_util.tree_map_with_path(spec_for, params)


def opt_state_specs(opt_state: PyTree,
                    params_spec_tree: PyTree,
                    params: PyTree,
                    mesh: Mesh,
                    zero_stage: int = 0,
                    min_shard_size: int = 1024) -> PyTree:
    """PartitionSpec pytree for optimizer state.

    ZeRO stage >= 1: any optimizer-state leaf shaped like a parameter
    (momentum, variance, master copy) gets the param's spec PLUS dp-axis
    sharding over 'data' (stage 1/2) — the TPU realization of the
    reference's optimizer-state partitioning (stage_1_and_2.py:546).
    Scalar leaves (step counts, loss-scale) stay replicated.
    """
    shape_to_spec: Dict[Tuple[int, ...], P] = {}
    for leaf, spec in zip(
            jax.tree_util.tree_leaves(params),
            jax.tree_util.tree_leaves(params_spec_tree,
                                      is_leaf=lambda x: isinstance(x, P))):
        shape_to_spec.setdefault(tuple(leaf.shape), spec)

    data_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get("data", 1)

    def spec_for(leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0:
            return P()
        base = shape_to_spec.get(shape)
        if base is None:
            return P(*([None] * len(shape)))
        if zero_stage >= 1:
            # shard over 'data' too (on top of fsdp/model placement)
            return _add_axis(base, shape, "data", data_size, min_shard_size,
                             mesh_shape=dict(zip(mesh.axis_names,
                                                 mesh.devices.shape)))
        return base

    return jax.tree_util.tree_map(spec_for, opt_state)


def _add_axis(spec: P, shape: Tuple[int, ...], axis: str, axis_size: int,
              min_size: int, mesh_shape: Optional[Dict[str, int]] = None) -> P:
    if axis_size <= 1:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    used = set()
    for e in entries:
        if e is None:
            continue
        for a in (e if isinstance(e, tuple) else (e,)):
            used.add(a)
    if axis in used:
        return P(*entries)
    best, best_dim = -1, -1
    for i, d in enumerate(shape):
        free = entries[i] is None
        if not free:
            continue
        if d % axis_size == 0 and d >= min_size and d > best_dim:
            best, best_dim = i, d
    if best < 0:
        # try stacking onto an existing sharded dim — only if the dim stays
        # divisible by the combined shard product
        mesh_shape = mesh_shape or {}
        for i, d in enumerate(shape):
            e = entries[i]
            if e is None:
                continue
            cur = e if isinstance(e, tuple) else (e,)
            existing = 1
            for a in cur:
                existing *= mesh_shape.get(a, 1)
            if d % (existing * axis_size) == 0:
                entries[i] = tuple(cur) + (axis,)
                return P(*entries)
        return P(*entries)
    entries[best] = axis
    return P(*entries)


def to_named(specs: PyTree, mesh: Mesh) -> PyTree:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs, is_leaf=lambda x: isinstance(x, P))


# common TP rule sets -------------------------------------------------------

def megatron_rules() -> List[PartitionRule]:
    """Megatron-style TP rules for the models in deepspeed_tpu.models:
    column-parallel QKV & MLP-in, row-parallel attn-out & MLP-out,
    vocab-parallel embedding.
    """
    return [
        PartitionRule(r"(qkv|query|key|value|wqkv)/kernel", P(None, "model")),
        PartitionRule(r"(attn_out|out_proj|wo)/kernel", P("model", None)),
        PartitionRule(r"(mlp_in|mlp_gate|fc_in|wi|up_proj|gate_proj)/kernel",
                      P(None, "model")),
        PartitionRule(r"(mlp_out|fc_out|wo_mlp|down_proj)/kernel", P("model", None)),
        PartitionRule(r"(embed|wte|word_embeddings)/embedding", P("model", None)),
        PartitionRule(r"(qkv|query|key|value|wqkv|mlp_in|mlp_gate|fc_in|wi)/bias",
                      P("model")),
    ]
