"""Deterministic fault injection — the chaos substrate for the serving
and checkpoint robustness layers.

Production serving dies in ways unit tests never exercise: a cache-
exhaustion storm mid-decode, a device that throws once and recovers, a
decode step that silently takes 100x its budget, a host that crashes
between writing checkpoint state and updating the ``latest`` pointer.
This module makes every one of those failure modes a *scheduled,
reproducible event*: a :class:`FaultInjector` carries an ordered set of
:class:`Fault` specs, each bound to a named **site** (a point in the
code that calls :func:`FaultInjector.fire`) and a **visit index** at
which it triggers. Same spec + same seed → the identical failure
sequence, so chaos tests assert exact outcomes (token parity, which tag
``load_checkpoint`` lands on) instead of "it didn't crash".

Sites currently instrumented:

====================== =====================================================
``serving.decode``     before each batched decode-slots dispatch
``serving.prefill``    before each prefill-chunk dispatch
``cache.ensure``       inside ``PagedKVCache.ensure_capacity`` (growth)
``cache.allocate``     inside ``PagedKVCache.allocate`` (admission)
``cache.match``        before the prefix-index lookup in ``allocate``;
                       ``cache_exhausted`` degrades the request to a
                       cold miss (served correctly, no sharing)
``cache.cow``          before the copy-on-write block copy (and before
                       ANY bookkeeping mutates); ``cache_exhausted``
                       raises CacheExhausted — the admission retries
``cache.quantize``     inside the engine's paged public wrappers when
                       ``kv_quant=int8``, after the ``engine.*`` site
                       and still BEFORE the device dispatch — donated
                       pool/scale buffers are untouched, so the
                       serving retry replays the step safely
``cache.spill``        before a spill batch's gather dispatch in the
                       host-tier spill daemon (``spill_tick``);
                       ``cache_exhausted`` skips the batch — blocks
                       stay device-resident behind exponential backoff
``cache.restore``      before a host→device block restore on a prefix
                       match; ``cache_exhausted`` truncates the match
                       there (the tail re-prefills; the host entry
                       survives for a later retry)
``cache.host_corrupt`` at restore time, AFTER ``cache.restore``
                       passed; ``cache_exhausted`` flips a real byte of
                       the stored block so the CRC32 check itself
                       drives the degrade path (chain discarded,
                       cold-miss re-prefill — never wrong tokens)
``cache.adapter_load`` before a LoRA adapter's pool load at admission
                       (``AdapterPool.acquire``), BEFORE any pool
                       state moves; ``device_error``/``cache_exhausted``
                       degrade that request to a structured ``error``
                       terminal state — the batch keeps serving, never
                       wrong tokens — while ``crash`` kills the replica
                       (the router drains it) (docs/ADAPTERS.md)
``engine.decode``      ``InferenceEngine.decode_slots`` public wrapper
``engine.verify``      ``InferenceEngine.verify_slots`` public wrapper
                       (speculative verify); the scheduler degrades the
                       step to plain one-token decode, never retries
``serving.spec_draft`` before the per-slot draft proposals each
                       speculative step; same degrade-to-plain contract
``serving.horizon``    before the fused multi-step decode dispatch each
                       horizon step, BEFORE any capacity or slot state
                       moves; the scheduler degrades the step to N=1
                       single-step decode — never retried, never a
                       dropped token (docs/MULTISTEP.md)
``checkpoint.pre_commit``  after state write, BEFORE the tag dir commit
``checkpoint.commit``  after the tag dir commit, BEFORE ``latest`` update
``router.dispatch``    after the router picks a target replica, BEFORE
                       the request is submitted to it — a retry re-picks
                       against untouched replicas
``router.step``        before each per-replica step in the router's
                       round-robin loop; ``crash`` kills that replica
                       (its in-flight work drains onto survivors)
``router.drain``       at the start of a dead replica's drain, BEFORE
                       any snapshot/redistribution state moves
``router.migrate_gather``  before the source replica gathers a finished
                       prefill's KV blocks into host DRAM for a
                       replica-to-replica migration; any failure falls
                       back to cold re-prefill on the decode side
``router.migrate_scatter``  before the destination replica lands the
                       migrated blocks free-list-only into its own
                       pool; failure (including capacity refusal)
                       discards the partial landing and falls back cold
``router.migrate_corrupt``  after the gather passed, before the landing
                       fetch; ``cache_exhausted`` flips a real stored
                       byte so the genuine per-array CRC32 verify
                       drives the fallback — never wrong tokens
====================== =====================================================

Fault kinds and what firing does:

- ``device_error`` — raises :class:`TransientDeviceError` (the serving
  engine retries with exponential backoff + deterministic jitter);
- ``crash`` — raises :class:`InjectedCrash` (simulated process death:
  the exception unwinds past the save path exactly where ``kill -9``
  would cut it);
- ``slow`` — sleeps ``param`` seconds inside the caller's timed region
  (drives the step watchdog); a hung step is a ``slow`` fault whose
  param exceeds the step budget;
- ``cache_exhausted`` — returned to the site, which raises its own
  domain exception (:class:`~deepspeed_tpu.inference.paged_cache.
  CacheExhausted`) so the scheduler's eviction path runs for real.

The ambient injector is either :func:`install`-ed programmatically
(tests use the :func:`injected` context manager) or parsed once from
``DS_FAULTS`` / ``DS_FAULT_SEED``::

    DS_FAULTS="serving.decode:device_error@3;checkpoint.commit:crash@0"
    DS_FAULT_SEED=0

Entry grammar: ``site:kind@step[*count][~param]`` joined by ``;`` —
fire ``kind`` at ``site`` on visits ``[step, step+count)`` with float
``param`` (sleep seconds for ``slow``).
"""

import time
import warnings
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class FaultError(Exception):
    """Base class for every injected failure."""


class UnknownFaultSiteWarning(UserWarning):
    """A fault spec names a site no code path ever fires — almost
    always a typo (``serving.prefil``): the chaos config would silently
    inject nothing. Tests running with warnings-as-errors fail loudly."""


class TransientDeviceError(FaultError):
    """A device dispatch failed in a retryable way (injected analog of a
    one-off XLA/runtime error; the serving engine's backoff handles it)."""


class InjectedCrash(FaultError):
    """Simulated process death: raised where the process would die, so
    everything after the site (e.g. the ``latest`` pointer update) never
    happens — the crash-consistency scenario checkpoint tests drive."""


@dataclass(frozen=True)
class Fault:
    """One scheduled failure: fire ``kind`` at ``site`` on visit
    indices ``[step, step + count)``. ``param`` is kind-specific
    (sleep seconds for ``slow``)."""
    site: str
    kind: str
    step: int = 0
    count: int = 1
    param: float = 0.0

    def matches(self, visit: int) -> bool:
        return self.step <= visit < self.step + self.count


KINDS = ("device_error", "crash", "slow", "cache_exhausted")

# every site some shipped code path fires (the module-docstring table);
# subsystems adding sites register them so parse_spec can flag typos
KNOWN_SITES = {
    "serving.decode", "serving.prefill", "serving.spec_draft",
    "serving.horizon",
    "engine.prefill", "engine.decode", "engine.verify",
    "cache.allocate", "cache.ensure", "cache.match", "cache.cow",
    "cache.quantize", "cache.spill", "cache.restore", "cache.host_corrupt",
    "cache.adapter_load",
    "checkpoint.pre_commit", "checkpoint.commit",
    "router.dispatch", "router.step", "router.drain",
    "router.migrate_gather", "router.migrate_scatter",
    "router.migrate_corrupt",
}

_warned_sites: set = set()


def register_site(site: str) -> None:
    """Declare ``site`` as a real fire point (plugins/tests adding
    their own sites keep :func:`parse_spec` quiet about them)."""
    KNOWN_SITES.add(site)


def parse_spec(spec: str) -> List[Fault]:
    """Parse the ``DS_FAULTS`` grammar (see module docstring). A spec
    naming a site nothing ever fires warns ONCE per site
    (:class:`UnknownFaultSiteWarning`) — a typo'd chaos config should
    fail loudly in tests, not silently inject nothing."""
    faults: List[Fault] = []
    for entry in spec.replace(",", ";").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        try:
            site, rest = entry.split(":", 1)
            kind, rest = rest.split("@", 1)
            param = 0.0
            count = 1
            if "~" in rest:
                rest, p = rest.split("~", 1)
                param = float(p)
            if "*" in rest:
                rest, c = rest.split("*", 1)
                count = int(c)
            step = int(rest)
        except ValueError as e:
            raise ValueError(
                f"bad fault spec entry {entry!r} (want "
                f"site:kind@step[*count][~param]): {e}") from e
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} in {entry!r} "
                             f"(known: {', '.join(KINDS)})")
        site = site.strip()
        if site not in KNOWN_SITES and site not in _warned_sites:
            _warned_sites.add(site)
            warnings.warn(
                f"fault spec names unknown site {site!r} — no "
                f"instrumented code path fires it, so this entry "
                f"injects nothing (known sites: "
                f"{', '.join(sorted(KNOWN_SITES))})",
                UnknownFaultSiteWarning, stacklevel=2)
        faults.append(Fault(site=site, kind=kind.strip(),
                            step=step, count=count, param=param))
    return faults


class FaultInjector:
    """Deterministic, seedable fault scheduler.

    ``visit(site)`` increments the site's visit counter and returns the
    matching :class:`Fault` (or None); ``fire(site)`` additionally acts
    on the generic kinds (raise / sleep) and returns domain-specific
    kinds (``cache_exhausted``) for the site to interpret. ``fired``
    logs every triggered fault as ``(site, kind, visit)`` so tests can
    assert the chaos actually happened.

    ``rng`` is a seeded generator shared with the serving engine's
    retry jitter: one seed pins the whole failure-and-recovery timeline.
    """

    def __init__(self, faults: Sequence[Fault] = (), seed: int = 0):
        self.faults: List[Fault] = list(faults)
        self.seed = int(seed)
        self.rng = np.random.default_rng(self.seed)
        self.visits: Dict[str, int] = {}
        self.fired: List[Tuple[str, str, int]] = []
        # observers notified on every fired fault (before the kind
        # acts, so a raise still reaches them): telemetry tracers tag
        # chaos events into the request-lifecycle timeline here
        self._listeners: List = []

    def add_listener(self, cb) -> None:
        """Register ``cb(site, kind, visit)``, called on every fired
        fault (including ones that then raise)."""
        self._listeners.append(cb)

    def remove_listener(self, cb) -> None:
        if cb in self._listeners:
            self._listeners.remove(cb)

    @classmethod
    def from_env(cls, env=None) -> "FaultInjector":
        # ambient chaos config; tests pin it via install()/injected().
        # resolve_flag carries the declared defaults ("" / seed 0) and
        # honors the explicit env mapping chaos tests pass in
        from deepspeed_tpu.utils.env import resolve_flag
        spec = resolve_flag("DS_FAULTS", env=env)
        seed = resolve_flag("DS_FAULT_SEED", env=env)
        return cls(parse_spec(spec), seed=seed)

    # -- scheduling ----------------------------------------------------
    def visit(self, site: str) -> Optional[Fault]:
        n = self.visits.get(site, 0)
        self.visits[site] = n + 1
        if not self.faults:
            return None
        for f in self.faults:
            if f.site == site and f.matches(n):
                self.fired.append((site, f.kind, n))
                for cb in self._listeners:
                    cb(site, f.kind, n)
                return f
        return None

    def fire(self, site: str) -> Optional[Fault]:
        """Visit ``site`` and act on the matched fault: raise the
        generic kinds, sleep for ``slow``, return the rest."""
        f = self.visit(site)
        if f is None:
            return None
        n = self.visits[site] - 1
        if f.kind == "device_error":
            raise TransientDeviceError(
                f"injected device error at {site} (visit {n})")
        if f.kind == "crash":
            raise InjectedCrash(f"injected crash at {site} (visit {n})")
        if f.kind == "slow":
            time.sleep(f.param)
        return f

    def jitter(self, scale: float) -> float:
        """Deterministic backoff jitter in ``[0, scale)``."""
        return float(self.rng.uniform(0.0, scale))

    def reset(self) -> None:
        """Rewind visit counters and the rng — same timeline replays."""
        self.visits.clear()
        self.fired.clear()
        self.rng = np.random.default_rng(self.seed)


# -- ambient injector --------------------------------------------------
_active: Optional[FaultInjector] = None


def active() -> FaultInjector:
    """The ambient injector: installed one, else env-derived (parsed
    once; an empty ``DS_FAULTS`` yields a no-op injector)."""
    global _active
    if _active is None:
        _active = FaultInjector.from_env()
    return _active


def install(injector: Optional[FaultInjector]) -> Optional[FaultInjector]:
    """Install ``injector`` as the ambient one (None re-derives from the
    env on next use). Returns the previous injector for restore."""
    global _active
    prev = _active
    _active = injector
    return prev


def maybe_fire(site: str) -> Optional[Fault]:
    """Module-level site hook: fire against the ambient injector. The
    no-fault fast path is one dict get + compare."""
    return active().fire(site)


@contextmanager
def injected(*faults: Fault, seed: int = 0):
    """Install a fresh injector for the block (tests)::

        with faults.injected(Fault("serving.decode", "device_error",
                                   step=3)) as inj:
            srv.run(reqs)
        assert inj.fired
    """
    inj = FaultInjector(faults, seed=seed)
    prev = install(inj)
    try:
        yield inj
    finally:
        install(prev)
