"""Analytic HBM estimator + compile-memory guard.

Why analytic, not XLA cost analysis: on this rig the *compile itself* is
the hazard — borderline-HBM programs (est. within ~1GB of the 16GB v5e)
send the remote compile service into a multi-ten-minute memory-fitting
grind that has twice wedged the whole backend (PERF.md "variants probed
and REJECTED"). A guard that needs to compile to measure would trigger
the failure it exists to prevent, so we estimate peak bytes from the
model/config shape alone and refuse to compile anything too close to
device HBM.

Reference analog: the autotuner prunes configs by an activation+state
memory model *before* launching them
(ref: deepspeed/autotuning/autotuner.py:396 mem-per-GPU pruning;
ref: deepspeed/runtime/zero/stage3.py memory estimators
``estimate_zero3_model_states_mem_needs``).

Calibration (measured on the 16GB v5e, PERF.md):
- gpt2-1.5B b16 full-remat + chunked CE: compiles ~2min, runs (the
  headline). Estimate must stay SAFE.
- same + flash_only remat (saves ~2.6GB flash residuals), or b24/b32, or
  selective remat at b4+ (5.9GB saved acts at b4): compile grind / OOM.
  Estimates must be REFUSED.
- gpt2-medium selective b8/b16 + chunked CE: comfortable. SAFE.
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

GiB = 1024 ** 3

# default distance-to-HBM below which we refuse to compile (GiB). The
# known-good 1.5B headline estimates ~14.4GB on 16GB — refusing anything
# estimated past (HBM - 1.2GiB) keeps it runnable while rejecting every
# config that has actually wedged the rig.
DEFAULT_HEADROOM_GIB = 1.2

# allocator/fragmentation + small-buffer slack added to every estimate
FUDGE_BYTES = int(0.25 * GiB)

KNOWN_HBM = {  # by device_kind substring (lowercased)
    "v5 lite": 16 * GiB,
    "v5e": 16 * GiB,
    "v5p": 95 * GiB,
    "v4": 32 * GiB,
    "v6": 32 * GiB,
}


class MemoryGuardError(RuntimeError):
    """Raised when a config's estimated peak HBM is too close to device
    capacity to compile safely."""


@dataclass
class MemoryEstimate:
    contributions: Dict[str, int] = field(default_factory=dict)

    @property
    def total(self) -> int:
        return sum(self.contributions.values())

    def summary(self) -> str:
        parts = ", ".join(f"{k}={v / GiB:.2f}GiB"
                          for k, v in self.contributions.items())
        return f"{self.total / GiB:.2f}GiB ({parts})"


def _dtype_bytes(precision: str) -> int:
    return {"bf16": 2, "fp16": 2, "fp32": 4}[precision]


def state_bytes(n_params: int, precision: str = "bf16",
                memory_efficient: bool = False,
                optimizer: str = "adamw") -> Dict[str, int]:
    """Persistent training-state bytes: params + optimizer moments
    [+ fp32 masters]. Shared by the full estimator and the engine's
    HBM-headroom warning so the two can't drift."""
    pb = _dtype_bytes(precision)
    if precision == "fp32":
        opt = 8 * n_params                       # fp32 m+v
    elif memory_efficient:
        opt = 4 * n_params                       # bf16 m+v (SR updates)
    else:
        opt = 12 * n_params                      # fp32 master + m + v
    if optimizer == "adagrad":
        opt = opt * 2 // 3                       # single moment
    return {"params": n_params * pb, "optimizer": opt}


def estimate_train_bytes(
    *,
    n_params: int,
    n_layers: int,
    d_model: int,
    ffn_dim: int,
    qkv_dim: int,
    n_heads: int,
    vocab_size: int,
    batch: int,
    seq: int,
    precision: str = "bf16",
    memory_efficient: bool = False,
    remat: bool = True,
    remat_policy: str = "full",
    loss_chunk: int = 0,
    optimizer: str = "adamw",
) -> MemoryEstimate:
    """Peak training HBM for one data-parallel shard of a GPT-style model.

    Peak model: persistent state (params + optimizer moments [+ masters])
    plus max(gradients, live activations) — under reverse-mode scan the
    gradient buffer fills as the saved activations drain, so they mostly
    don't coexist at full size — plus the loss-path working set and an
    allocator fudge.

    Activation widths (units of d_model per token per layer, bf16) by
    remat policy, counted from what each policy saves for backward:
    - none:       ln1+ln2 (2) + qkv + flash o (1) + attn out (1) +
                  gelu in+out (2*ffn/d) + mlp out (1)
    - selective:  qkv + flash o (1) + gelu in (ffn/d) + mlp out (1)
                  [measured 9.38*d at 1.5B — PERF.md b4-selective 5.9GB]
    - full:       layer-boundary hidden only (1)
    - flash_only: boundary (1) + packed flash o residual (1)
                  [measured +2.6GB at 1.5B b16 — PERF.md]
    full/flash_only additionally pay ONE layer's un-rematted working set
    (transient, not *L) during the per-layer recompute.
    """
    est = MemoryEstimate()
    pb = _dtype_bytes(precision)

    # --- persistent training state -----------------------------------
    est.contributions.update(state_bytes(n_params, precision,
                                         memory_efficient, optimizer))

    grad_bytes = n_params * pb                   # accumulator or transient

    # --- activations --------------------------------------------------
    tokens = batch * seq
    ffn_w = ffn_dim / d_model
    qkv_w = qkv_dim / d_model
    none_width = 2 + qkv_w + 1 + 1 + 2 * ffn_w + 1
    if not remat:
        width, transient = none_width, 0.0
    elif remat_policy == "selective":
        width, transient = qkv_w + 1 + ffn_w + 1, 0.0
    elif remat_policy == "flash_only":
        width, transient = 2.0, none_width
    else:
        # 'full' — and 'offload_flash', whose saved residuals live in
        # pinned HOST memory, so device HBM matches full remat
        width, transient = 1.0, none_width
    act_bytes = int(tokens * n_layers * width * d_model * 2)
    act_bytes += int(tokens * transient * d_model * 2)   # one-layer recompute
    act_bytes += tokens * n_layers * n_heads * 4         # flash lse (fp32)
    # grads fill while saved activations drain: peak is the larger one
    est.contributions["grads_or_acts"] = max(grad_bytes, act_bytes)

    # --- loss path ----------------------------------------------------
    # one cost model for both paths: rows processed at once x fp32
    # (logits + softmax + bwd residual). Dense is simply chunk=inf —
    # using a SMALLER per-row factor for dense (as r3 did: 8 vs 12)
    # breaks the monotonicity the guard's safety rests on in the
    # clamped regime chunk >= tokens, where the two programs coincide
    # (hypothesis counterexample: b1/s256/chunk2048)
    rows = min(loss_chunk, tokens) if loss_chunk else tokens
    est.contributions["loss"] = rows * vocab_size * 12

    est.contributions["fudge"] = FUDGE_BYTES
    return est


def estimate_gpt_train_bytes(cfg, batch: int, seq: Optional[int] = None,
                             **kw) -> MemoryEstimate:
    """Convenience wrapper mapping a models.gpt.GPTConfig."""
    from deepspeed_tpu.models import gpt
    return estimate_train_bytes(
        n_params=gpt.num_params(cfg), n_layers=cfg.n_layers,
        d_model=cfg.d_model, ffn_dim=cfg.ffn_dim, qkv_dim=cfg.qkv_dim,
        n_heads=cfg.n_heads, vocab_size=cfg.vocab_size,
        batch=batch, seq=seq or cfg.max_seq_len,
        remat=cfg.remat, remat_policy=cfg.remat_policy,
        loss_chunk=cfg.loss_chunk, **kw)


def estimate_bert_train_bytes(cfg, batch: int, seq: Optional[int] = None,
                              **kw) -> MemoryEstimate:
    """Convenience wrapper mapping a models.bert.BertConfig. The encoder
    layer is the classic post/pre-LN transformer (ffn = 4d, fused qkv =
    3d); bidirectional attention changes flops, not live bytes, so the
    GPT activation-width model carries over unchanged."""
    from deepspeed_tpu.models import bert
    return estimate_train_bytes(
        n_params=bert.num_params(cfg), n_layers=cfg.n_layers,
        d_model=cfg.d_model, ffn_dim=4 * cfg.d_model,
        qkv_dim=3 * cfg.d_model, n_heads=cfg.n_heads,
        vocab_size=cfg.vocab_size, batch=batch,
        seq=seq or cfg.max_seq_len, remat=cfg.remat,
        remat_policy=cfg.remat_policy, loss_chunk=cfg.loss_chunk, **kw)


def estimate_moe_train_bytes(cfg, batch: int, seq: Optional[int] = None,
                             **kw) -> MemoryEstimate:
    """models.moe_gpt.MoEGPTConfig variant: the dense-GPT estimate (with
    the MoE param count — experts dominate) plus the gating/dispatch
    working set of ONE layer (transient under the moe remat policy):
    fp32 combine weights + dispatch mask [B, S, E, C] and the dispatched
    expert activations [E, C_total, d..ffn]."""
    from deepspeed_tpu.models import moe_gpt
    from deepspeed_tpu.moe.sharded_moe import _capacity
    seq = seq or cfg.max_seq_len
    est = estimate_train_bytes(
        n_params=moe_gpt.num_params(cfg), n_layers=cfg.n_layers,
        d_model=cfg.d_model, ffn_dim=cfg.ffn_dim, qkv_dim=cfg.qkv_dim,
        n_heads=cfg.n_heads, vocab_size=cfg.vocab_size, batch=batch,
        seq=seq, remat=cfg.remat, remat_policy=cfg.remat_policy,
        loss_chunk=cfg.loss_chunk, **kw)
    E = cfg.num_experts
    cf = cfg.capacity_factor * (2 if cfg.moe_k == 2 else 1)
    C = _capacity(seq, E, cf, cfg.min_capacity)
    dispatch = batch * seq * E * C * 5            # fp32 combine + bool mask
    expert_act = E * C * batch * (cfg.d_model + cfg.ffn_dim) * 2
    est.contributions["moe_dispatch"] = dispatch + expert_act
    return est


def estimate_infer_bytes(cfg, batch: int,
                         max_seq: Optional[int] = None) -> MemoryEstimate:
    """Inference working set for a models.gpt config: bf16 params, the
    preallocated [L, B, S_max, Hkv, Dh] KV cache pair, one fp32 logits
    row per sequence, and the prefill activation transient."""
    from deepspeed_tpu.models import gpt
    est = MemoryEstimate()
    max_seq = max_seq or cfg.max_seq_len
    pb = 2                                        # bf16 serving
    est.contributions["params"] = gpt.num_params(cfg) * pb
    est.contributions["kv_cache"] = (
        2 * cfg.n_layers * batch * max_seq * cfg.kv_heads
        * cfg.head_dim * pb)
    est.contributions["logits"] = batch * cfg.vocab_size * 4
    # prefill holds one layer's qkv/ffn working set across the prompt
    est.contributions["prefill"] = int(
        batch * max_seq * (cfg.qkv_dim + cfg.ffn_dim + 2 * cfg.d_model) * pb)
    est.contributions["fudge"] = FUDGE_BYTES
    return est


def device_hbm_bytes(device: Any = None) -> Optional[int]:
    """Device HBM capacity, via memory_stats when the backend exposes it,
    else the known-capacity table. None for CPU/unknown (no guard)."""
    if device is None:
        import jax
        devices = jax.devices()
        if not devices:
            return None
        device = devices[0]
    if device.platform == "cpu":
        return None
    try:
        stats = device.memory_stats() or {}
        if stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:  # dslint: disable=DS006 — probe falls through to the known-HBM table
        pass
    kind = (device.device_kind or "").lower()
    for k, v in KNOWN_HBM.items():
        if k in kind:
            return v
    return None


def check_compile_safe(est: MemoryEstimate, hbm_bytes: Optional[int],
                       headroom_gib: float = DEFAULT_HEADROOM_GIB):
    """Returns (ok, message). ok=True when the estimate clears the
    headroom or HBM capacity is unknown (nothing to guard against)."""
    if hbm_bytes is None:
        return True, "device HBM unknown — guard inactive"
    limit = hbm_bytes - int(headroom_gib * GiB)
    msg = (f"estimated peak {est.total / GiB:.2f}GiB vs limit "
           f"{limit / GiB:.2f}GiB (HBM {hbm_bytes / GiB:.0f}GiB - "
           f"{headroom_gib}GiB compile headroom): {est.summary()}")
    return est.total <= limit, msg


def _guard(est: MemoryEstimate, device, headroom_gib) -> str:
    ok, msg = check_compile_safe(est, device_hbm_bytes(device), headroom_gib)
    if not ok:
        raise MemoryGuardError(
            f"refusing to compile: {msg}. Borderline-HBM compiles wedge "
            f"this backend (PERF.md); shrink batch/model or use "
            f"remat_policy='full' + loss_chunk.")
    return msg


def guard_gpt_config(cfg, batch: int, seq: Optional[int] = None,
                     device: Any = None,
                     headroom_gib: float = DEFAULT_HEADROOM_GIB,
                     **estimate_kw) -> str:
    """Raise MemoryGuardError if compiling this training config risks the
    borderline-HBM compile grind; returns the decision message otherwise."""
    return _guard(estimate_gpt_train_bytes(cfg, batch, seq, **estimate_kw),
                  device, headroom_gib)


def guard_bert_config(cfg, batch: int, seq: Optional[int] = None,
                      device: Any = None,
                      headroom_gib: float = DEFAULT_HEADROOM_GIB,
                      **estimate_kw) -> str:
    """Encoder (BERT) variant of :func:`guard_gpt_config`."""
    return _guard(estimate_bert_train_bytes(cfg, batch, seq, **estimate_kw),
                  device, headroom_gib)


def guard_moe_config(cfg, batch: int, seq: Optional[int] = None,
                     device: Any = None,
                     headroom_gib: float = DEFAULT_HEADROOM_GIB,
                     **estimate_kw) -> str:
    """MoE-GPT variant of :func:`guard_gpt_config` (adds the dispatch
    working set on top of the dense estimate)."""
    return _guard(estimate_moe_train_bytes(cfg, batch, seq, **estimate_kw),
                  device, headroom_gib)


def guard_infer_config(cfg, batch: int, max_seq: Optional[int] = None,
                       device: Any = None,
                       headroom_gib: float = DEFAULT_HEADROOM_GIB) -> str:
    """Inference variant: params + KV cache + logits + prefill transient."""
    return _guard(estimate_infer_bytes(cfg, batch, max_seq),
                  device, headroom_gib)
