"""Version bridge for jax APIs that moved between 0.4.x and 0.5+.

The codebase targets the 0.5+ spellings; this module maps them onto
what an older installed jax actually provides so the same source runs
on both. Keep every bridge here (one import site to delete when the
floor moves past 0.5).
"""

import jax


def axis_size(axis_name):
    """``jax.lax.axis_size`` (0.5+); 0.4.x gets it from ``psum(1, axis)``,
    which the tracer folds to the same static int inside a manual region."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=True):
    """``jax.shard_map`` with the 0.5+ keyword surface.

    On 0.4.x this lowers to ``jax.experimental.shard_map.shard_map``:
    ``axis_names`` (the MANUAL axes) becomes its complement ``auto``,
    and ``check_vma`` maps to the old ``check_rep``.
    """
    if hasattr(jax, "shard_map"):
        kw = {} if axis_names is None else {"axis_names": axis_names}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    # a size-1 axis splits nothing: treating it as manual instead of
    # auto is an identity, so only size>1 auto axes are truly partial
    auto = (frozenset() if axis_names is None
            else frozenset(a for a in mesh.axis_names
                           if a not in axis_names and mesh.shape[a] > 1))
    if auto:
        # 0.4.x ``auto=`` (partial-manual) is experimental enough that the
        # XLA lowering can abort the whole process — refuse cleanly instead
        raise NotImplementedError(
            f"shard_map over a subset of mesh axes (manual {set(axis_names)} "
            f"of {set(mesh.axis_names)}) needs jax>=0.5; this jax "
            f"{jax.__version__} only supports full-manual shard_map")
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)
