"""Compile-count regression guard.

The serving engine's perf story rests on a compile contract: steady
state is exactly TWO compiled programs (`_prefill_slot`, `_decode_slots`)
and ZERO recompiles across admission, eviction and requeue.  Nothing in
the code *structurally* prevents a refactor from silently breaking that
— a dynamic shape, a fresh lambda, a python int leaking into a traced
position all recompile quietly and only show up as a latency cliff on
the rig.  ``CompileWatch`` turns the contract into an executable assert.

Counting strategy, in preference order:

1. ``jax.monitoring`` duration events.  Every XLA compilation fires
   ``/jax/core/compile/backend_compile_duration`` exactly once, so a
   registered listener counts real backend compiles — including eager-op
   programs that no jit cache ever sees (the failure mode PR 2's
   per-slot ``logits[i:i+1]`` slice would have been).
2. For jax builds without ``jax.monitoring`` (or with the event renamed)
   a jit-wrapper fallback: ``CompileWatch.wrap(fn)`` snapshots
   ``fn._cache_size()`` deltas for explicitly registered jitted
   callables.  Narrower — it only sees tracing-cache growth of wrapped
   functions — but it keeps the guard meaningful on old jax.

Usage::

    with CompileWatch(max_compiles=0) as w:
        engine.step(); engine.step()
    # raises RecompileError on exit if anything compiled

    w = CompileWatch()
    with w:
        run_workload()
    assert w.compiles <= 2

The watch only *asserts on clean exit* — an exception inside the body
propagates untouched (masking the original failure with a compile-count
complaint would be strictly worse).
"""

import threading
from typing import List, Optional

import jax

# what counts as a jit entry point (wrapper chains, compile-event stem)
# is shared with tools/dslint via jit_registry so the runtime watch and
# the static lint police the same callable set
from deepspeed_tpu.utils.jit_registry import (COMPILE_EVENT_STEM,
                                              is_compile_event)
from deepspeed_tpu.utils.jit_registry import cache_size as _registry_cache_size

_COMPILE_EVENT_STEM = COMPILE_EVENT_STEM  # back-compat alias


class RecompileError(AssertionError):
    """Raised when a CompileWatch block compiled more than allowed."""


def _monitoring_api():
    """(register, unregister) for duration listeners, or None."""
    mon = getattr(jax, "monitoring", None)
    reg = getattr(mon, "register_event_duration_secs_listener", None)
    if reg is None:
        return None
    try:
        from jax._src import monitoring as _mon_impl
        unreg = getattr(
            _mon_impl, "_unregister_event_duration_listener_by_callback",
            None)
    except Exception:  # dslint: disable=DS006 — private API probe; fallback below
        unreg = None
    return reg, unreg


class CompileWatch:
    """Count XLA compilations inside a ``with`` block and (optionally)
    assert a ceiling.

    Args:
      max_compiles: raise :class:`RecompileError` on clean exit when
        more than this many compilations happened inside the block.
        ``None`` (default) means count only, never raise.
      label: prefix for the error message — name the contract being
        enforced (e.g. ``"serving steady state"``).
    """

    def __init__(self, max_compiles: Optional[int] = None,
                 label: str = "CompileWatch"):
        self.max_compiles = max_compiles
        self.label = label
        self.compiles = 0
        self.events: List[str] = []
        self._lock = threading.Lock()
        self._armed = False
        self._listener = None
        self._unreg = None
        self._wrapped = []  # (jitted_fn, cache_size_at_enter)

    # -- jit-wrapper fallback -------------------------------------------

    def wrap(self, jitted_fn):
        """Register a jitted callable for the cache-size fallback and
        return it unchanged.

        Harmless (and free) when event monitoring is active; on jax
        builds without ``jax.monitoring`` the watch counts
        ``_cache_size()`` growth of every wrapped function instead.
        """
        if hasattr(jitted_fn, "_cache_size"):
            self._wrapped.append(jitted_fn)
        return jitted_fn

    @property
    def monitored(self) -> bool:
        """True when real event-based counting is active."""
        return self._listener is not None

    # -- context manager ------------------------------------------------

    def __enter__(self):
        self.compiles = 0
        self.events = []
        self._armed = True
        api = _monitoring_api()
        if api is not None:
            reg, self._unreg = api

            def _on_event(event, duration=None, **kw):
                if not is_compile_event(event):
                    return
                with self._lock:
                    if self._armed:
                        self.compiles += 1
                        self.events.append(event)

            self._listener = _on_event
            reg(_on_event)
        self._wrap_base = [(f, f._cache_size()) for f in self._wrapped]
        return self

    def __exit__(self, exc_type, exc, tb):
        with self._lock:
            self._armed = False
        if self._listener is not None and self._unreg is not None:
            try:
                self._unreg(self._listener)
            except Exception:  # dslint: disable=DS006 — private unregister API; the disarm flag above already silences the listener
                pass
        if self._listener is None:
            # fallback: tracing-cache growth of registered callables
            self.compiles = sum(
                max(0, f._cache_size() - base) for f, base in self._wrap_base)
        if exc_type is not None:
            return False  # never mask the body's own failure
        if self.max_compiles is not None and self.compiles > self.max_compiles:
            raise RecompileError(
                f"{self.label}: {self.compiles} compilation(s) inside the "
                f"watched block (allowed {self.max_compiles}). Events: "
                f"{self.events or '(cache-size fallback)'} — a traced shape, "
                f"python value in a traced position, or fresh callable is "
                f"defeating the compile cache.")
        return False


def cache_size(jitted_fn) -> Optional[int]:
    """Number of compiled programs held by a jitted callable, or None
    when the jax build doesn't expose it.  Use to pin 'exactly N
    programs' (cache sizes) alongside CompileWatch's 'zero new
    compiles' (cache deltas).  (Implementation lives in
    :mod:`~deepspeed_tpu.utils.jit_registry`, the shared jit-entry-point
    definition; this re-export keeps the historical import path.)"""
    return _registry_cache_size(jitted_fn)
