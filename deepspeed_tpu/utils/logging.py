"""Rank-aware logging.

TPU-native equivalent of the reference's logger factory and rank-filtered
helpers (ref: deepspeed/utils/logging.py:16 LoggerFactory, :49 log_dist,
:72 print_json_dist). On TPU there are no torch.distributed ranks; we use
``jax.process_index()`` when the distributed runtime is initialized and fall
back to rank 0 in single-process mode.
"""

import json
import logging
import os
import sys
from typing import List, Optional

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class LoggerFactory:
    @staticmethod
    def create_logger(name: str = "deepspeed_tpu", level=logging.INFO) -> logging.Logger:
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d:%(funcName)s] %(message)s")
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            ch = logging.StreamHandler(stream=sys.stdout)
            ch.setLevel(level)
            ch.setFormatter(formatter)
            logger_.addHandler(ch)
        return logger_


logger = LoggerFactory.create_logger(
    level=log_levels.get(os.environ.get("DSTPU_LOG_LEVEL", "info"), logging.INFO))  # dslint: disable=DS005 — log level must exist before config loads


def _get_rank() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return int(os.environ.get("DSTPU_PROCESS_ID", "0"))  # dslint: disable=DS005 — pre-init rank fallback


def log_dist(message: str, ranks: Optional[List[int]] = None, level=logging.INFO) -> None:
    """Log ``message`` only on the given process ranks (-1 or None = all)."""
    rank = _get_rank()
    if ranks is None or len(ranks) == 0 or -1 in ranks or rank in ranks:
        logger.log(level, f"[Rank {rank}] {message}")


def print_json_dist(message: dict, ranks: Optional[List[int]] = None,
                    path: Optional[str] = None) -> None:
    """Dump a json payload on the given ranks, optionally to a file."""
    rank = _get_rank()
    if ranks is None or len(ranks) == 0 or -1 in ranks or rank in ranks:
        message["rank"] = rank
        if path is None:
            print(json.dumps(message))
        else:
            with open(path, "w") as f:
                json.dump(message, f)
                f.flush()


def should_log_le(max_log_level_str: str) -> bool:
    if not isinstance(max_log_level_str, str):
        raise ValueError("max_log_level_str must be a string")
    max_log_level_str = max_log_level_str.lower()
    if max_log_level_str not in log_levels:
        raise ValueError(f"{max_log_level_str} is not one of the logging levels")
    return logger.getEffectiveLevel() <= log_levels[max_log_level_str]
