"""Multi-host distributed bootstrap.

Capability analog of the reference's init_distributed
(ref: deepspeed/utils/distributed.py:12 init_distributed, :56 mpi_discovery).
On TPU pods there is no NCCL rendezvous: `jax.distributed.initialize` joins
the JAX runtime across hosts (GCE metadata auto-discovery on Cloud TPU, or
env/args for manual setups), after which `jax.devices()` spans the pod and
ONE global mesh replaces all process groups.
"""
# dslint: disable-file=DS005 — process bootstrap IS the env layer here:
# rendezvous variables (MPI vars, MASTER_ADDR, DSTPU_*) are set by the
# launcher/scheduler and are this module's input contract, not config.

import os
from typing import Optional

from deepspeed_tpu.utils.logging import logger

_initialized = False


def init_distributed(dist_backend: str = "xla",
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     auto_mpi_discovery: bool = True,
                     timeout: Optional[int] = None,
                     init_method: Optional[str] = None) -> bool:
    """Join the multi-host runtime. Safe to call multiple times.

    Resolution order (mirrors the reference's env:// + MPI discovery):
      1. explicit args,
      2. OMPI_* env (MPI launches, ref mpi_discovery :56),
      3. DSTPU_* / standard env vars,
      4. single-process fallback (no-op).
    """
    global _initialized
    if _initialized:
        return True
    del dist_backend, init_method  # XLA collectives only; kept for API parity

    import jax

    if coordinator_address is None:
        if "OMPI_COMM_WORLD_SIZE" in os.environ and auto_mpi_discovery:
            num_processes = int(os.environ["OMPI_COMM_WORLD_SIZE"])
            process_id = int(os.environ["OMPI_COMM_WORLD_RANK"])
            coordinator_address = os.environ.get("MASTER_ADDR", "127.0.0.1") + \
                ":" + os.environ.get("MASTER_PORT", "29500")
        elif "DSTPU_COORDINATOR" in os.environ:
            coordinator_address = os.environ["DSTPU_COORDINATOR"]
            num_processes = int(os.environ.get("DSTPU_NUM_PROCESSES", "1"))
            process_id = int(os.environ.get("DSTPU_PROCESS_ID", "0"))

    try:
        if coordinator_address is not None:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id)
        elif os.environ.get("TPU_WORKER_HOSTNAMES") or \
                os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
            # Cloud TPU pod: args auto-discovered from metadata
            jax.distributed.initialize()
        else:
            logger.info("single-process mode (no coordinator configured)")
            _initialized = True
            return True
    except Exception as e:  # already initialized or single-host
        logger.warning(f"jax.distributed.initialize skipped: {e}")
    _initialized = True
    logger.info(
        f"distributed runtime up: process {get_rank()}/{get_world_size()} "
        f"with {len(jax.local_devices())} local / "
        f"{len(jax.devices())} global devices")
    return True


def get_rank() -> int:
    import jax
    return jax.process_index()


def get_world_size() -> int:
    import jax
    return jax.process_count()


def get_local_rank() -> int:
    return int(os.environ.get("DSTPU_LOCAL_RANK", "0"))


def barrier():
    """Host-level barrier via a trivial global psum."""
    import jax
    import jax.numpy as jnp
    jax.block_until_ready(
        jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
            jnp.ones((jax.local_device_count(),))))
