"""Mesh-axis accessors — the dissolution of process groups.

The reference maintains dictionaries of torch process groups
(ref: deepspeed/utils/groups.py:305 _clone_world_group, :321
_get_data_parallel_group, expert groups :107/:160/:206). On TPU a single
named-axis Mesh subsumes them; this module provides the same *query*
surface (sizes/ranks per parallel dimension) against a registered mesh so
user code migrating from the reference keeps its call sites.
"""

from typing import Optional

import jax
from jax.sharding import Mesh

from deepspeed_tpu.parallel import mesh as mesh_lib

_MESH: Optional[Mesh] = None


def set_mesh(mesh: Mesh) -> None:
    global _MESH
    _MESH = mesh


def get_mesh() -> Mesh:
    assert _MESH is not None, \
        "no mesh registered — deepspeed_tpu.initialize() does this"
    return _MESH


def _axis(axis: str) -> int:
    return mesh_lib.axis_size(get_mesh(), axis)


# --- world ---------------------------------------------------------------

def get_world_size() -> int:
    return int(get_mesh().devices.size)


def get_global_rank() -> int:
    return jax.process_index()


# --- data parallel (ref :321) -------------------------------------------

def get_data_parallel_world_size() -> int:
    return mesh_lib.dp_world_size(get_mesh())


def get_data_parallel_group() -> tuple:
    """On TPU the "group" IS the axis names."""
    return ("data", "fsdp")


# --- model parallel ------------------------------------------------------

def get_model_parallel_world_size() -> int:
    return _axis("model")


def get_model_parallel_group() -> tuple:
    return ("model",)


# --- pipeline ------------------------------------------------------------

def get_pipe_parallel_world_size() -> int:
    return _axis("pipe")


# --- sequence ------------------------------------------------------------

def get_sequence_parallel_world_size() -> int:
    return _axis("sequence")


# --- expert parallel (ref :107/:160/:206) --------------------------------

def get_expert_parallel_world_size(num_experts: Optional[int] = None) -> int:
    """Experts shard over the dp axes; the EP degree is min(dp, experts)."""
    dp = get_data_parallel_world_size()
    if num_experts is None:
        return dp
    return min(dp, num_experts)


def get_expert_data_parallel_world_size(num_experts: int) -> int:
    dp = get_data_parallel_world_size()
    ep = get_expert_parallel_world_size(num_experts)
    return max(1, dp // ep)
