"""Training metrics monitor — TensorBoard scalars analog.

Capability match for the reference's engine-owned SummaryWriter
(ref: deepspeed/runtime/engine.py:470-517 _get_tensorboard_summary_writer,
loss/lr/loss-scale scalars :1656-1666, :1889-1917). Writes through
every available backend:

* TensorBoard event files when a writer implementation is importable
  (torch.utils.tensorboard or tensorboardX — optional in this image),
* always a CSV + JSONL mirror (self-contained, greppable, and what
  bench tooling parses), matching the reference's later csv_monitor.

Rank-0-only, like the reference.
"""

import atexit
import csv
import json
import os
from collections.abc import Mapping as MappingABC
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger


def _try_tensorboard_writer(log_dir: str):
    try:
        from torch.utils.tensorboard import SummaryWriter
        return SummaryWriter(log_dir=log_dir)
    except Exception:  # dslint: disable=DS006 — optional tensorboard backend probe
        pass
    try:
        from tensorboardX import SummaryWriter
        return SummaryWriter(log_dir=log_dir)
    except Exception:  # dslint: disable=DS006 — optional tensorboard backend probe (tensorboardX fallback)
        return None


class Monitor:
    """scalar sink: ``write_scalars([(tag, value, step), ...])``."""

    def __init__(self, output_path: str = "runs",
                 job_name: str = "deepspeed_tpu",
                 enabled: bool = True, rank: Optional[int] = None):
        if rank is None:
            try:
                import jax
                rank = jax.process_index()
            except Exception:
                rank = 0
        self.enabled = enabled and rank == 0
        self.log_dir = os.path.join(os.path.expanduser(output_path), job_name)
        self._tb = None
        self._csv_path = None
        self._jsonl_path = None
        self._csv_known_tags: List[str] = []
        if self.enabled:
            os.makedirs(self.log_dir, exist_ok=True)
            self._tb = _try_tensorboard_writer(self.log_dir)
            if self._tb is None:
                logger.info("no tensorboard writer available; "
                            "scalars go to csv/jsonl only")
            self._csv_path = os.path.join(self.log_dir, "scalars.csv")
            self._jsonl_path = os.path.join(self.log_dir, "scalars.jsonl")
            # resume: adopt the existing header so appends don't inject
            # a second header row mid-file
            if os.path.exists(self._csv_path):
                with open(self._csv_path) as f:
                    first = f.readline().strip()
                if first.startswith("step,"):
                    self._csv_known_tags = first.split(",")[1:]
            # TB writers buffer; make sure the tail is flushed on exit
            atexit.register(self.close)

    @classmethod
    def from_config(cls, tb_config) -> "Monitor":
        """tb_config: TensorboardConfig (runtime/config.py)."""
        return cls(output_path=tb_config.output_path or "runs",
                   job_name=tb_config.job_name,
                   enabled=tb_config.enabled)

    def write_scalars(self,
                      scalars: List[Tuple[str, float, int]]) -> None:
        """``(tag, value, step)`` tuples. A value may also be a
        histogram summary mapping (p50/p95/p99/... as emitted by
        ``telemetry.MetricsRegistry.to_scalars``): it expands into
        ``tag/p50`` style sub-scalars, so serving latency digests and
        training losses share this one sink."""
        if not self.enabled or not scalars:
            return
        scalars = self._expand_summaries(scalars)
        if not scalars:
            return
        if self._tb is not None:
            for tag, value, step in scalars:
                self._tb.add_scalar(tag, float(value), int(step))
        with open(self._jsonl_path, "a") as f:
            for tag, value, step in scalars:
                f.write(json.dumps({"tag": tag, "value": float(value),
                                    "step": int(step)}) + "\n")
        self._write_csv_row(scalars)

    @staticmethod
    def _expand_summaries(scalars) -> List[Tuple[str, float, int]]:
        flat: List[Tuple[str, float, int]] = []
        for tag, value, step in scalars:
            if isinstance(value, MappingABC):
                flat.extend((f"{tag}/{k}", float(v), step)
                            for k, v in value.items())
            else:
                flat.append((tag, float(value), step))
        return flat

    def _write_csv_row(self, scalars) -> None:
        tags = [t for t, _, _ in scalars]
        step = scalars[0][2]
        new_header = tags != self._csv_known_tags or \
            not os.path.exists(self._csv_path)
        with open(self._csv_path, "a", newline="") as f:
            w = csv.writer(f)
            if new_header:
                w.writerow(["step"] + tags)
                self._csv_known_tags = list(tags)
            w.writerow([step] + [float(v) for _, v, _ in scalars])

    def flush(self) -> None:
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
            self._tb = None


class NoopMonitor:
    enabled = False

    def write_scalars(self, scalars) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass
