import os


def honor_platform_request() -> None:
    """Apply an explicit ``JAX_PLATFORMS`` env request through jax.config.

    Some environments pre-import jax from a sitecustomize with another
    platform pinned; setting the env var afterwards is silently ignored
    and a dead accelerator tunnel can then hang ``jax.devices()`` forever.
    Call this before first device use (bench.py and the examples do)."""
    want = os.environ.get("JAX_PLATFORMS", "")
    if want:
        import jax
        jax.config.update("jax_platforms", want)
