import os


def honor_platform_request() -> None:
    """Apply an explicit ``JAX_PLATFORMS`` env request through jax.config.

    Some environments pre-import jax from a sitecustomize with another
    platform pinned; setting the env var afterwards is silently ignored
    and a dead accelerator tunnel can then hang ``jax.devices()`` forever.
    Call this before first device use (bench.py and the examples do)."""
    want = os.environ.get("JAX_PLATFORMS", "")  # dslint: disable=DS005 — mirrors jax's own env contract
    if want:
        import jax
        jax.config.update("jax_platforms", want)


def on_tpu() -> bool:
    """Whether device 0 is a TPU — the single source of truth for flash
    eligibility and other hardware gates (models/gpt.py, ops ring).

    Forced-CPU contexts short-circuit WITHOUT touching jax.devices():
    the session's accelerator plugin initializes the remote backend even
    when the platform priority list starts with cpu, and a wedged tunnel
    then hangs the probe (observed r4: backend init hung under
    JAX_PLATFORMS=cpu)."""
    import os
    import jax
    plats = (getattr(jax.config, "jax_platforms", None)
             or os.environ.get("JAX_PLATFORMS", ""))  # dslint: disable=DS005 — mirrors jax's own env contract
    if plats and plats.split(",")[0].strip() == "cpu":
        return False
    try:
        d = jax.devices()[0]
        return "tpu" in (d.platform + d.device_kind).lower()
    except Exception:
        return False
