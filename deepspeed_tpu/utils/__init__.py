import os


def honor_platform_request() -> None:
    """Apply an explicit ``JAX_PLATFORMS`` env request through jax.config.

    Some environments pre-import jax from a sitecustomize with another
    platform pinned; setting the env var afterwards is silently ignored
    and a dead accelerator tunnel can then hang ``jax.devices()`` forever.
    Call this before first device use (bench.py and the examples do)."""
    want = os.environ.get("JAX_PLATFORMS", "")
    if want:
        import jax
        jax.config.update("jax_platforms", want)


def on_tpu() -> bool:
    """Whether device 0 is a TPU — the single source of truth for flash
    eligibility and other hardware gates (models/gpt.py, ops ring)."""
    try:
        import jax
        d = jax.devices()[0]
        return "tpu" in (d.platform + d.device_kind).lower()
    except Exception:
        return False
