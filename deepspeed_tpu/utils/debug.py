"""Debug helpers: parameter/module name mapping for prints and probes.

Capability analog of the reference's debug module
(ref: deepspeed/utils/debug.py:144 LoC —
debug_extract_module_and_param_names called at runtime/engine.py:218,
plus rank-gated param printers used while bringing up ZeRO). The torch
version walks nn.Module attributes; the pytree-native version walks
key paths.
"""

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

PyTree = Any


def param_names(tree: PyTree) -> Dict[str, Any]:
    """Flat {'a/b/c': leaf} mapping of a parameter pytree (the
    param->name map the reference builds at engine init,
    ref utils/debug.py debug_extract_module_and_param_names)."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out["/".join(_key_str(k) for k in path)] = leaf
    return out


def _key_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    return str(k)


def module_summary(tree: PyTree, max_rows: int = 0) -> str:
    """Human-readable table: name, shape, dtype, #params, sharding."""
    rows: List[Tuple[str, str, str, int, str]] = []
    for name, leaf in param_names(tree).items():
        arr = np.asarray(jax.eval_shape(lambda: leaf)) \
            if not hasattr(leaf, "shape") else leaf
        sh = getattr(leaf, "sharding", None)
        spec = getattr(sh, "spec", "") if sh is not None else ""
        rows.append((name, str(tuple(arr.shape)), str(arr.dtype),
                     int(np.prod(arr.shape)) if arr.shape else 1,
                     str(spec)))
    if max_rows:
        rows = rows[:max_rows]
    total = sum(r[3] for r in rows)
    w = max((len(r[0]) for r in rows), default=4)
    lines = [f"{'name':<{w}}  shape            dtype     params      spec"]
    for name, shape, dtype, n, spec in rows:
        lines.append(f"{name:<{w}}  {shape:<15}  {dtype:<8}  {n:>10,}  {spec}")
    lines.append(f"total parameters: {total:,}")
    return "\n".join(lines)


def debug_param(tree: PyTree, name: str,
                summarize: int = 3) -> Optional[str]:
    """One-leaf probe: stats + corner values (the rank-gated
    print_ helpers' role in the reference's debug module)."""
    leaf = param_names(tree).get(name)
    if leaf is None:
        return None
    a = np.asarray(leaf, np.float32)
    head = a.ravel()[:summarize]
    return (f"{name}: shape={tuple(a.shape)} dtype={a.dtype} "
            f"mean={a.mean():.3e} std={a.std():.3e} "
            f"absmax={np.abs(a).max():.3e} head={head.tolist()}")
