"""Pytree path helpers shared across the framework."""


def tree_path_str(path, sep: str = ".") -> str:
    """Render a jax tree-path (tuple of DictKey/SequenceKey/GetAttrKey/
    FlattenedIndexKey entries) as a ``sep``-joined string."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return sep.join(parts)
