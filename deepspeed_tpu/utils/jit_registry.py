"""The shared definition of "what counts as a jit entry point".

Two independent guards police the compile contract and used to disagree
about the set of callables it covers:

- ``utils/compile_guard.py`` (CompileWatch) counts *runtime* compiles by
  listening for the ``backend_compile`` monitoring event, falling back
  to ``_cache_size()`` deltas of explicitly registered jitted callables;
- ``tools/dslint`` (DS002/DS003, and the v2 interprocedural DS011/DS012)
  pattern-matches jit wrapper *syntax* in the AST.

When one side learns a new spelling (``pjit``, ``functools.partial(
jax.jit, ...)``) and the other doesn't, a callable is watched at runtime
but invisible to the lint — or vice versa. This module is the single
source of truth both import: the wrapper name-chains, the donation/
static keyword names, and the monitoring-event stem. It is deliberately
**pure stdlib** (no jax import): dslint loads it straight from the file
path (``tools/dslint/symbols.py``) so linting never imports the code
under analysis.
"""

from typing import Optional, Sequence, Tuple

# Dotted-name chains that wrap a python callable into an XLA-compiled
# entry point. Matched against ``ast`` attribute chains by dslint and
# usable for runtime predicates. ("jit",)/( "pjit",) cover
# ``from jax import jit`` style imports used in older layers.
JIT_WRAPPER_CHAINS: Tuple[Tuple[str, ...], ...] = (
    ("jax", "jit"), ("jit",),
    ("jax", "pjit"), ("pjit",),
    ("jax", "experimental", "pjit", "pjit"),
)

# Keyword names on the wrapper call that change the entry point's
# aliasing/caching contract. DS003/DS011 read DONATE_KWARGS; DS002/DS004
# read STATIC_KWARGS; CompileWatch doesn't care but the names live here
# so a future spelling lands in both tools at once.
DONATE_KWARGS: Tuple[str, ...] = ("donate_argnums", "donate_argnames")
STATIC_KWARGS: Tuple[str, ...] = ("static_argnums", "static_argnames")

# Substring (not equality) of the jax.monitoring duration event every
# XLA compilation fires: jax has moved the event between
# /jax/core/compile/backend_compile_duration and sibling names across
# releases; every variant keeps this stem.
COMPILE_EVENT_STEM = "backend_compile"


def is_jit_chain(chain: Sequence[str]) -> bool:
    """True when ``chain`` (a dotted-name list like ``["jax", "jit"]``)
    spells a jit wrapper."""
    return tuple(chain) in JIT_WRAPPER_CHAINS


def is_compile_event(event_name: str) -> bool:
    """True when a jax.monitoring duration event records a backend
    compilation (the thing CompileWatch counts)."""
    return COMPILE_EVENT_STEM in event_name


def cache_size(jitted_fn) -> Optional[int]:
    """Number of compiled programs held by a jitted callable, or None
    when the jax build doesn't expose it. Use to pin 'exactly N
    programs' (cache sizes) alongside CompileWatch's 'zero new
    compiles' (cache deltas)."""
    probe = getattr(jitted_fn, "_cache_size", None)
    if probe is None:
        return None
    return int(probe())


# Serving-side program catalog: every jitted entry point the paged
# engine dispatches in steady state, by family stem and precision/LoRA
# twin suffix ("" fp, "_q" int8 KV, "_l" LoRA, "_ql" both). The cost
# registry (telemetry/costs.py) walks this table to probe
# ``cost_analysis()``/``memory_analysis()`` per program, and the
# per-dispatch accountant keys its charges on the same program ids —
# one table so the two planes can never disagree about what exists.
# ``cow_blocks`` and the host-tier transfer programs have no LoRA
# variant (they move cache bytes, not weights).
ENGINE_PROGRAM_FAMILIES: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("prefill_slot", ("", "_q", "_l", "_ql")),
    ("decode_slots", ("", "_q", "_l", "_ql")),
    ("decode_horizon", ("", "_q", "_l", "_ql")),
    ("verify_slots", ("", "_q", "_l", "_ql")),
    ("cow_blocks", ("", "_q")),
    ("gather_blocks", ("", "_q")),
    ("scatter_block", ("", "_q")),
)

# Declared per-feature twin deltas: what a feature suffix is ALLOWED to
# change relative to the base program. dslint's DS015 normalizes each
# twin's AST modulo this spec and flags any other divergence, so an edit
# to ``_decode_slots_fn`` that misses ``_decode_slots_q_fn`` is a lint
# error instead of a silent parity bug. Suffix characters compose:
# ``_ql`` owns the union of the "q" and "l" deltas.
#
#   params : extra positional parameters the twin's signature may add
#   names  : local/parameter names the feature owns — any statement or
#            tuple/call element mentioning ONLY these is feature-owned
#            and stripped before comparison (q: the requantize block's
#            scale sidecars; l: the gathered-einsum LoRA block)
#   kwargs : call keywords the twin may thread through (``k_scale=``,
#            ``lora_ops=``) that the base never passes
TWIN_DELTAS = {
    "q": {
        "params": ("k_scale", "v_scale", "ks_blk", "vs_blk"),
        "names": ("k_scale", "v_scale", "ks_blk", "vs_blk",
                  "ksp", "vsp", "kss", "vss"),
        "kwargs": ("k_scale", "v_scale"),
    },
    "l": {
        "params": ("lora_a", "lora_b", "ablocks", "ablock_row"),
        "names": ("lora_a", "lora_b", "ablocks", "ablock_row",
                  "la", "lb", "lora", "lora_ops"),
        "kwargs": ("lora", "lora_ops"),
    },
}


# program family stem -> dispatch class the accountant rolls it into
DISPATCH_CLASSES: Tuple[str, ...] = (
    "prefill", "decode", "verify", "cow", "spill")
_FAMILY_CLASS = {
    "prefill_slot": "prefill",
    "decode_slots": "decode",
    "decode_horizon": "decode",
    "verify_slots": "verify",
    "cow_blocks": "cow",
    "gather_blocks": "spill",
    "scatter_block": "spill",
}


def engine_programs() -> Tuple[Tuple[str, str, str], ...]:
    """``(program_id, engine_attr, dispatch_class)`` for every serving
    program: ``("decode_slots_ql", "_decode_slots_ql", "decode")``."""
    out = []
    for stem, suffixes in ENGINE_PROGRAM_FAMILIES:
        for suf in suffixes:
            out.append((stem + suf, "_" + stem + suf, _FAMILY_CLASS[stem]))
    return tuple(out)


def dispatch_class(program_id: str) -> str:
    """Dispatch class for a program id (``decode_horizon_q`` →
    ``decode``); raises ``KeyError`` on an unknown id."""
    for stem, suffixes in ENGINE_PROGRAM_FAMILIES:
        for suf in suffixes:
            if program_id == stem + suf:
                return _FAMILY_CLASS[stem]
    raise KeyError(f"unknown engine program id: {program_id!r}")
