"""Runtime trace capture + hot-path annotation.

TPU analog of the reference's NVTX instrumentation
(ref: deepspeed/utils/nvtx.py:4 instrument_w_nvtx, applied across
zero/coordinator hot paths) and its pointer to torch.profiler
(ref docs/_tutorials/pytorch-profiler.md). On TPU the equivalents are:

- ``jax.named_scope`` — names traced ops so they appear as annotated
  regions in the compiled program's XPlane timeline (device side),
- ``jax.profiler.TraceAnnotation`` — host-side trace ranges,
- ``jax.profiler.trace`` — XPlane/TensorBoard trace capture of a window
  of steps (view with ``tensorboard --logdir`` or xprof).

Usage::

    from deepspeed_tpu.utils import trace

    @trace.instrument()           # device scope when traced, host range
    def hot_path(...): ...

    with trace.capture("/tmp/tb"):   # one XPlane capture window
        engine.train_batch(batch)

or let the engine drive it: ``engine.start_trace(log_dir, steps=3)``
captures the next 3 train_batch calls.
"""

import contextlib
import functools
from typing import Optional

import jax


def instrument(name: Optional[str] = None):
    """Decorator naming a function in both device (named_scope) and host
    (TraceAnnotation) timelines — the instrument_w_nvtx analog."""

    def deco(fn):
        scope = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with jax.named_scope(scope), \
                    jax.profiler.TraceAnnotation(scope):
                return fn(*args, **kwargs)

        return wrapped

    return deco


def annotation(name: str):
    """Host-side trace range context manager (NVTX push/pop analog)."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def capture(log_dir: str):
    """Capture an XPlane trace of the enclosed block into ``log_dir``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
