"""Wall-clock and throughput timers.

TPU-native equivalent of the reference's cuda-event timers
(ref: deepspeed/utils/timer.py:34 SynchronizedWallClockTimer,
:134 ThroughputTimer). CUDA events do not exist on TPU; synchronization is a
``jax.block_until_ready`` / ``jax.effects_barrier`` on the device stream, and
otherwise identical trim-mean throughput accounting is kept.
"""

import time
from collections import OrderedDict
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist

try:
    import psutil
    PSUTIL_AVAILABLE = True
except ImportError:  # pragma: no cover
    PSUTIL_AVAILABLE = False

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


def _device_sync():
    """Block until all dispatched device work is complete."""
    try:
        import jax
        jax.effects_barrier()
        # touch a trivial computation to flush the async dispatch queue
        jax.device_put(0.0).block_until_ready()
    except Exception:  # dslint: disable=DS006 — best-effort queue flush; timers must not crash training
        pass


class SynchronizedWallClockTimer:
    """Group of named timers with optional device synchronization."""

    class Timer:
        def __init__(self, name: str):
            self.name_ = name
            self.started_ = False
            self.start_time = 0.0
            self.elapsed_records: List[float] = []

        def start(self, sync: bool = False):
            assert not self.started_, f"{self.name_} timer has already been started"
            if sync:
                _device_sync()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset: bool = False, record: bool = True, sync: bool = False):
            assert self.started_, f"{self.name_} timer is not started"
            if sync:
                _device_sync()
            elapsed = time.time() - self.start_time
            if record:
                self.elapsed_records.append(elapsed)
            self.started_ = False

        def reset(self):
            self.started_ = False
            self.elapsed_records = []

        def elapsed(self, reset: bool = True) -> float:
            """Total elapsed seconds recorded so far."""
            total = sum(self.elapsed_records)
            if self.started_:
                total += time.time() - self.start_time
            if reset:
                self.reset()
            return total

        def mean(self) -> float:
            if not self.elapsed_records:
                return 0.0
            return sum(self.elapsed_records) / len(self.elapsed_records)

    def __init__(self):
        self.timers: "OrderedDict[str, SynchronizedWallClockTimer.Timer]" = OrderedDict()

    def __call__(self, name: str) -> "SynchronizedWallClockTimer.Timer":
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    def get_timers(self):
        return self.timers

    @staticmethod
    def memory_usage() -> str:
        if not PSUTIL_AVAILABLE:
            return "psutil unavailable"
        vm = psutil.virtual_memory()
        return (f"host mem: used={vm.used / 2**30:.2f}GB "
                f"avail={vm.available / 2**30:.2f}GB ({vm.percent}%)")

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False, ranks: Optional[List[int]] = None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed_time:.2f}"
        if memory_breakdown:
            string += f" | {self.memory_usage()}"
        log_dist(string, ranks=ranks or [0])

    def means(self, names: List[str]) -> Dict[str, float]:
        return {n: self.timers[n].mean() for n in names if n in self.timers}


class NoopTimer:
    """Disabled-timer stand-in so call sites need no branching."""

    class Timer:
        def start(self, **kw):
            ...

        def stop(self, **kw):
            ...

        def reset(self):
            ...

        def elapsed(self, **kw):
            return 0.0

        def mean(self):
            return 0.0

    def __call__(self, name):
        return self.Timer()

    def get_timers(self):
        return {}

    def log(self, *a, **kw):
        ...

    def means(self, *a, **kw):
        return {}


class ThroughputTimer:
    """Samples/sec meter with warm-up skip (ref: utils/timer.py:134)."""

    def __init__(self, batch_size: int, start_step: int = 2,
                 steps_per_output: int = 50, monitor_memory: bool = False,
                 logging_fn=None):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or (lambda msg: log_dist(msg, ranks=[0]))
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            _device_sync()
            self.start_time = time.time()

    def stop(self, global_step: bool = False, report_speed: bool = True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0:
            _device_sync()
            self.end_time = time.time()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            self.start_time = 0.0
            if global_step:
                if report_speed and self.global_step_count % self.steps_per_output == 0:
                    self.logging(
                        f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                        f"global_step={self.global_step_count}, "
                        f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.2f}, "
                        f"CurrSamplesPerSec={self.batch_size / self.step_elapsed_time:.2f}")
                self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self) -> float:
        if self.global_step_count > self.start_step and self.total_elapsed_time > 0:
            samples = self.batch_size * (self.global_step_count - self.start_step)
            return samples / self.total_elapsed_time
        return float("-inf")


def trim_mean(data: List[float], trim_percent: float) -> float:
    """Mean of data with the top/bottom ``trim_percent`` trimmed."""
    assert 0.0 <= trim_percent <= 1.0
    n = len(data)
    if n == 0:
        return 0.0
    data = sorted(data)
    trim = int(n * trim_percent)
    trimmed = data[trim:n - trim] or data
    return sum(trimmed) / len(trimmed)
