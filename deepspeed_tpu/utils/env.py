# dslint: disable-file=DS005 — this IS the sanctioned env layer: every
# DS_* knob resolves here (DS013), so the ambient read is the point
"""Central registry + resolver for every ``DS_*`` environment switch.

Before this module each subsystem carried its own copy of the same
resolve-a-knob ritual — read ``os.environ``, strip/lower, accept the
same five spellings of off and four of on, raise ``ValueError`` on
garbage — a dozen near-identical blocks whose inevitable drift was
invisible (``resolve_telemetry`` silently coerced garbage to off while
its siblings raised). Now there is ONE parser and ONE table:

- :data:`FLAGS` declares every knob: name, type, default, choices and
  a one-line help string. The declared default IS the bit-reference
  off-state — the serving stack's contract that every feature switch
  defaults to the behavior the parity tests pin (dslint DS013 checks
  this mechanically by parsing this table).
- :func:`resolve_flag` is the only place environment state is read.
  Subsystem ``resolve_*`` helpers stay as the public API (explicit
  argument wins, then env, then default) but delegate parsing here.

dslint's DS013 rule flags any literal ``DS_*`` env read elsewhere under
``deepspeed_tpu/`` and any ``resolve_flag`` call naming a flag this
table doesn't declare, so adding a knob without declaring it — or
declaring it default-on — fails the lint, not a code review.
"""

import os
from dataclasses import dataclass, field
from typing import Dict, Mapping, Optional, Tuple

__all__ = ["Flag", "FLAGS", "resolve_flag", "flag_names",
           "MAX_DECODE_HORIZON", "resolve_decode_horizon"]

# the shared bool grammar every DS_* switch accepts; "" (unset) is off
TRUE_WORDS = ("on", "1", "true", "yes")
FALSE_WORDS = ("", "off", "0", "false", "no")


@dataclass(frozen=True)
class Flag:
    """One declared environment knob.

    ``kind`` selects the parser: ``bool`` (the on/off grammar above),
    ``int``, ``float``, ``str`` (returned verbatim, stripped), or
    ``choice`` (normalized via ``aliases`` then validated against
    ``choices``). ``default`` is returned when the variable is unset or
    empty — by contract the bit-reference off-state for feature
    switches. ``aliases`` maps accepted spellings onto canonical choice
    values (``"on" -> "int8"`` for DS_KV_QUANT).
    """
    name: str
    kind: str
    default: object
    help: str
    choices: Tuple[str, ...] = ()
    aliases: Mapping[str, str] = field(default_factory=dict)


def _mk(name, kind, default, help, **kw) -> Tuple[str, Flag]:
    return name, Flag(name=name, kind=kind, default=default, help=help, **kw)


# The registry. Feature switches (kind=bool) MUST default False — the
# off-state is the behavioral bit-reference (docs/LINT.md DS013).
FLAGS: Dict[str, Flag] = dict([
    _mk("DS_TELEMETRY", "bool", False,
        "metrics/tracer/breakdown plane on the serving engine; off is "
        "the no-op bit-reference (docs/OBSERVABILITY.md)"),
    _mk("DS_PREFIX_CACHE", "bool", False,
        "shared-prefix KV cache with refcounted blocks + COW; off is "
        "the refcount-free allocator bit-reference (docs/PREFIX_CACHE.md)"),
    _mk("DS_SPEC_DECODE", "bool", False,
        "speculative serving (draft + k+1 verify per slot); off is the "
        "plain one-token-decode bit-reference (docs/SPECULATIVE.md)"),
    _mk("DS_SPEC_DRAFT", "str", "ngram",
        "named drafter for speculative serving; 'ngram' (prompt-lookup) "
        "is the only named one — model drafters pass an object"),
    _mk("DS_SPEC_K", "int", 4,
        "draft chunk length per speculative step (docs/SPECULATIVE.md)"),
    _mk("DS_KV_QUANT", "choice", "off",
        "paged KV-cache block quantization; off is the bf16/fp32 pool "
        "bit-reference (docs/KV_QUANT.md)",
        choices=("off", "int8"),
        aliases={"0": "off", "false": "off", "no": "off", "none": "off",
                 "on": "int8", "1": "int8", "true": "int8", "yes": "int8"}),
    _mk("DS_KV_HOST_TIER", "bool", False,
        "host-DRAM second tier for spilled KV blocks; off is the "
        "device-only cache bit-reference (docs/KV_TIERING.md)"),
    _mk("DS_KV_HOST_BUDGET_MB", "float", 256.0,
        "host-tier byte budget in MiB (bounded so leaks surface)"),
    _mk("DS_PAGED_DECODE_IMPL", "str", None,
        "paged-decode kernel override ('pallas'/'gather'); unset picks "
        "the platform default (pallas on TPU, gather elsewhere)"),
    _mk("DS_FLASH_WINDOW_IMPL", "str", "banded",
        "windowed flash-attention implementation ('banded'/'masked'); "
        "the PARITY.md quarantine switch"),
    _mk("DS_INT8_FUSED", "bool", False,
        "route int8 dense entries through the Pallas fused "
        "dequant-matmul kernel (TPU-only experiment; models/gpt.py)"),
    _mk("DS_LORA_SERVE", "bool", False,
        "multi-tenant LoRA adapter serving (paged adapter pool + "
        "heterogeneous-adapter batched decode); off is the base-only "
        "bit-reference (docs/ADAPTERS.md)"),
    _mk("DS_LORA_POOL_MB", "float", 16.0,
        "device adapter-pool byte budget in MiB (sizes the paged "
        "rank-block pool; docs/ADAPTERS.md)"),
    _mk("DS_LORA_MAX_RANK", "int", 16,
        "largest adapter rank the pool accepts; fixes the static "
        "per-slot adapter-table width ceil(max_rank/rank_block)"),
    _mk("DS_LORA_RANK_BLOCK", "int", 8,
        "rank granularity of one adapter-pool block (an adapter "
        "occupies ceil(rank/rank_block) blocks)"),
    _mk("DS_DECODE_HORIZON", "int", 1,
        "decode iterations fused into one compiled program per dispatch "
        "(the serving horizon N); 1 is the one-token-per-step "
        "bit-reference, capped at 32 (docs/MULTISTEP.md)"),
    _mk("DS_FAULTS", "str", "",
        "ambient chaos spec 'site:kind@step[*count][~param];...' "
        "(docs/ROBUSTNESS.md); empty injects nothing"),
    _mk("DS_FAULT_SEED", "int", 0,
        "seed for the ambient FaultInjector's backoff-jitter rng"),
    _mk("DS_COST_ACCOUNTING", "bool", False,
        "per-dispatch analytic cost accounting (FLOPs/HBM bytes/KV "
        "block-seconds per request and tenant) without full telemetry; "
        "DS_TELEMETRY=on implies it (docs/OBSERVABILITY.md)"),
    _mk("DS_FLIGHT_RECORDER", "bool", False,
        "bounded flight recorder: on DegradedError/watchdog/breaker "
        "trips write a CRC-stamped postmortem JSON artifact "
        "(tools/postmortem.py reads it; docs/OBSERVABILITY.md)"),
    _mk("DS_FLIGHT_DIR", "str", "",
        "directory for flight-recorder postmortem artifacts; empty "
        "means the platform tempdir under ds_flight/"),
])


# ceiling on the fused-decode horizon: the scan body is cheap to grow,
# but every distinct N is its own compiled program and the serving
# harvest buffers N tokens per slot — cap it where the host-amortization
# curve has long flattened (docs/MULTISTEP.md)
MAX_DECODE_HORIZON = 32


def resolve_decode_horizon(value=None) -> int:
    """Resolve the fused-decode horizon N: explicit ``value`` wins, then
    ``DS_DECODE_HORIZON``, then 1 (the one-token-per-dispatch
    bit-reference). Validates 1 <= N <= :data:`MAX_DECODE_HORIZON`."""
    n = resolve_flag("DS_DECODE_HORIZON", value)
    if not 1 <= int(n) <= MAX_DECODE_HORIZON:
        raise ValueError(
            f"DS_DECODE_HORIZON={n!r}: expected an integer in "
            f"[1, {MAX_DECODE_HORIZON}]")
    return int(n)


def flag_names() -> Tuple[str, ...]:
    """Every declared DS_* knob, sorted (env_report / docs use this)."""
    return tuple(sorted(FLAGS))


def _parse(flag: Flag, raw: str):
    v = raw.strip()
    if flag.kind != "str":
        v = v.lower()
    if v == "":
        return flag.default
    if flag.kind == "bool":
        if v in FALSE_WORDS:
            return False
        if v in TRUE_WORDS:
            return True
        # ValueError, not assert: validates user env input, survives -O
        raise ValueError(f"{flag.name}={raw!r}: expected 'on' or 'off'")
    if flag.kind == "int":
        try:
            return int(v)
        except ValueError:
            raise ValueError(f"{flag.name}={raw!r}: expected an integer")
    if flag.kind == "float":
        try:
            return float(v)
        except ValueError:
            raise ValueError(f"{flag.name}={raw!r}: expected a number")
    if flag.kind == "choice":
        v = flag.aliases.get(v, v)
        if v not in flag.choices:
            raise ValueError(f"{flag.name}={raw!r}: expected "
                             + " or ".join(f"'{c}'"
                                           for c in reversed(flag.choices)))
        return v
    return v  # kind == "str": verbatim (stripped)


def resolve_flag(name: str, override=None, env: Optional[Mapping] = None):
    """Resolve the declared knob ``name``: explicit ``override`` wins,
    else the environment (``env`` mapping, default ``os.environ``),
    else the declared default.

    Overrides go through the same normalization as env strings when
    they are strings; non-string overrides pass through the kind's
    coercion (``bool``/``int``/``float``; ``True``/``False`` map onto a
    choice flag's on/off aliases so ``resolve_kv_quant(True)`` keeps
    meaning int8). Unknown names raise ``KeyError`` — declare the flag
    in :data:`FLAGS` first (dslint DS013 enforces the same statically).
    """
    flag = FLAGS.get(name)
    if flag is None:
        raise KeyError(f"undeclared env flag {name!r} — add it to "
                       f"deepspeed_tpu.utils.env.FLAGS")
    if override is not None:
        if isinstance(override, str):
            return _parse(flag, override)
        if flag.kind == "bool":
            return bool(override)
        if flag.kind == "int":
            return int(override)
        if flag.kind == "float":
            return float(override)
        if flag.kind == "choice" and isinstance(override, bool):
            return _parse(flag, "on" if override else "off")
        return override
    env = os.environ if env is None else env
    return _parse(flag, env.get(name, ""))
