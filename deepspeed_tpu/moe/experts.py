"""Stacked expert FFNs (ref: deepspeed/moe/experts.py:9 Experts).

The reference deep-copies an nn.Module per local expert; here all E experts
are ONE stacked pytree [E, ...] so the expert computation is a single
batched einsum on the MXU and the expert dim's sharding drives the
all-to-all."""

from typing import Dict

import jax
import jax.numpy as jnp


def init_ffn_experts(rng, num_experts: int, d_model: int, d_ff: int) -> Dict:
    k1, k2 = jax.random.split(rng)
    init = jax.nn.initializers.normal(0.02)
    return {
        "wi": {"kernel": init(k1, (num_experts, d_model, d_ff), jnp.float32),
               "bias": jnp.zeros((num_experts, d_ff), jnp.float32)},
        "wo": {"kernel": init(k2, (num_experts, d_ff, d_model), jnp.float32),
               "bias": jnp.zeros((num_experts, d_model), jnp.float32)},
    }


def ffn_expert_fn(params: Dict, tokens: jnp.ndarray) -> jnp.ndarray:
    """tokens: [E, T, d] -> [E, T, d]; one fused einsum per projection.

    Two expert dialects, keyed by the params tree: the GPT-2 style
    (gelu, biased wi/wo) and the llama/mixtral style (a "wg" gate stack
    present -> silu(t@wg) * (t@wi) @ wo, biases optional)."""
    dtype = tokens.dtype

    def dense(t, p):
        from deepspeed_tpu.models.gpt import _kernel_of
        y = jnp.einsum("etd,edf->etf", t, _kernel_of(p, dtype))
        b = p.get("bias")
        return y if b is None else y + b.astype(dtype)[:, None, :]

    h = dense(tokens, params["wi"])
    if "wg" in params:
        h = jax.nn.silu(dense(tokens, params["wg"])) * h
    else:
        h = jax.nn.gelu(h, approximate=True)
    return dense(h, params["wo"])
