"""GShard-style sharded Mixture of Experts.

Capability analog of the reference MoE layer
(ref: deepspeed/moe/sharded_moe.py — MOELayer :432, TopKGate :344,
top1gating :170, top2gating :271, _AllToAll :84). TPU-native design:

- tokens are arranged [groups, tokens_per_group, d] with the group dim
  sharded over the data axes; expert weights are stacked [E, ...] and
  sharded over the SAME axes (expert-data parallelism, ref
  utils/groups.py:107) — the dispatch/combine einsums then force XLA to
  emit the all-to-all over ICI that the reference performs with the
  explicit _AllToAll autograd function;
- gating is pure jnp with static shapes: capacity-bounded one-hot dispatch
  tensors, cumsum-based position assignment, load-balance auxiliary loss;
- everything differentiates through jax.grad — no custom autograd.
"""

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any


class GateOutput(NamedTuple):
    l_aux: jnp.ndarray          # load-balance loss (scalar)
    combine: jnp.ndarray        # [G, S, E, C] float — combine weights
    dispatch: jnp.ndarray       # [G, S, E, C] bool  — dispatch mask
    exp_counts: jnp.ndarray     # [E] tokens routed per expert (pre-drop)


def _one_hot(x, num_classes, dtype=jnp.float32):
    return jax.nn.one_hot(x, num_classes, dtype=dtype)


def _capacity(tokens_per_group: int, num_experts: int,
              capacity_factor: float, min_capacity: int) -> int:
    cap = int(np.ceil(tokens_per_group / num_experts * capacity_factor))
    return max(cap, min_capacity)


def top1gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               rng: Optional[jax.Array] = None,
               noisy_gate_policy: Optional[str] = None,
               drop_tokens: bool = True,
               used_token_mask: Optional[jnp.ndarray] = None) -> GateOutput:
    """Top-1 gating (ref: sharded_moe.py:170).

    logits: [G, S, E]. Capacity C = ceil(S/E * cf). Tokens beyond an
    expert's capacity are dropped (their combine weights are zero), with
    optional RSample noise on routing (noisy_gate_policy='RSample').
    """
    G, S, E = logits.shape
    if noisy_gate_policy == "RSample":
        assert rng is not None
        logits_w_noise = logits + jax.random.normal(rng, logits.shape)
    else:
        logits_w_noise = logits

    gates = jax.nn.softmax(logits, axis=-1)                   # [G,S,E]
    index1 = jnp.argmax(logits_w_noise, axis=-1)              # [G,S]
    mask1 = _one_hot(index1, E)                               # [G,S,E]
    if used_token_mask is not None:
        mask1 = mask1 * used_token_mask[..., None]

    # load-balance loss: E * mean_e(importance * load)
    me = jnp.mean(gates, axis=1)                              # [G,E]
    ce = jnp.mean(mask1, axis=1)                              # [G,E]
    l_aux = jnp.mean(jnp.sum(me * ce, axis=-1)) * E

    exp_counts = jnp.sum(mask1, axis=(0, 1))                  # [E]

    if drop_tokens:
        C = _capacity(S, E, capacity_factor, min_capacity)
    else:
        C = S
    # position of each token within its expert's queue
    locations1 = jnp.cumsum(mask1, axis=1) - mask1            # [G,S,E]
    mask1 = mask1 * (locations1 < C)
    loc1 = jnp.sum(locations1 * mask1, axis=-1)               # [G,S]

    gate1 = jnp.sum(gates * mask1, axis=-1)                   # [G,S]

    combine = (gate1[..., None, None] *
               mask1[..., None] *
               _one_hot(loc1.astype(jnp.int32), C)[..., None, :])               # [G,S,E,C]
    dispatch = combine > 0
    return GateOutput(l_aux.astype(jnp.float32), combine, dispatch, exp_counts)


def top2gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               rng: Optional[jax.Array] = None,
               drop_tokens: bool = True) -> GateOutput:
    """Top-2 gating with normalized gate weights (ref: sharded_moe.py:271)."""
    G, S, E = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)

    index1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(index1, E)
    logits_except1 = jnp.where(mask1 > 0, -jnp.inf, logits)
    index2 = jnp.argmax(logits_except1, axis=-1)
    mask2 = _one_hot(index2, E)

    # capacity
    C = _capacity(S, E, 2 * capacity_factor, min_capacity) if drop_tokens else S

    locations1 = jnp.cumsum(mask1, axis=1) - mask1
    # second choices queue after ALL first choices of that expert
    locations2 = jnp.cumsum(mask2, axis=1) - mask2 + \
        jnp.sum(mask1, axis=1, keepdims=True)

    me = jnp.mean(gates, axis=1)
    ce = jnp.mean(mask1, axis=1)
    l_aux = jnp.mean(jnp.sum(me * ce, axis=-1)) * E

    exp_counts = jnp.sum(mask1 + mask2, axis=(0, 1))

    mask1 = mask1 * (locations1 < C)
    mask2 = mask2 * (locations2 < C)
    loc1 = jnp.sum(locations1 * mask1, axis=-1)
    loc2 = jnp.sum(locations2 * mask2, axis=-1)

    gate1 = jnp.sum(gates * mask1, axis=-1)
    gate2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.clip(gate1 + gate2, 1e-9, None)
    gate1 /= denom
    gate2 /= denom

    combine = (gate1[..., None, None] * mask1[..., None] *
               _one_hot(loc1.astype(jnp.int32), C)[..., None, :] +
               gate2[..., None, None] * mask2[..., None] *
               _one_hot(loc2.astype(jnp.int32), C)[..., None, :])
    dispatch = combine > 0
    return GateOutput(l_aux.astype(jnp.float32), combine, dispatch, exp_counts)


class TopKGate:
    """Gate config holder + apply (ref: sharded_moe.py:344 TopKGate)."""

    def __init__(self, k: int = 1, capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0, min_capacity: int = 4,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True):
        assert k in (1, 2), "Only top-1 and top-2 gatings are supported"
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens

    @staticmethod
    def init_params(rng, d_model: int, num_experts: int) -> Dict:
        # fp32 gate weights (the reference keeps the gate in fp32 too)
        w = jax.nn.initializers.normal(0.02)(rng, (d_model, num_experts),
                                             jnp.float32)
        return {"wg": w}

    def __call__(self, params: Dict, x: jnp.ndarray,
                 rng: Optional[jax.Array] = None,
                 train: bool = True) -> GateOutput:
        logits = x.astype(jnp.float32) @ params["wg"]
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(logits, cf, self.min_capacity, rng,
                              self.noisy_gate_policy if train else None,
                              self.drop_tokens)
        return top2gating(logits, cf, self.min_capacity, rng,
                          self.drop_tokens)


def moe_layer_apply(gate: TopKGate,
                    gate_params: Dict,
                    expert_params: PyTree,
                    expert_fn,
                    x: jnp.ndarray,
                    rng: Optional[jax.Array] = None,
                    train: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The MOELayer forward (ref: sharded_moe.py:480-540).

    x: [G, S, d] (G sharded over data axes). expert_params leaves are
    stacked [E, ...] (sharded over the same axes -> all-to-all).
    expert_fn(expert_params, tokens[E, C_total, d]) -> [E, C_total, d],
    vmapped over the expert dim.
    Returns (y [G, S, d], l_aux, exp_counts).
    """
    out = gate(gate_params, x, rng, train)
    dtype = x.dtype
    dispatch = out.dispatch.astype(dtype)                     # [G,S,E,C]
    # dispatch: -> [E, G*C, d]  (the einsum's resharding IS the all-to-all)
    dispatched = jnp.einsum("gsec,gsm->egcm", dispatch, x)
    E, G, C, d = dispatched.shape
    dispatched = dispatched.reshape(E, G * C, d)
    expert_out = expert_fn(expert_params, dispatched)         # [E, G*C, d]
    expert_out = expert_out.reshape(E, G, C, d)
    combined = jnp.einsum("gsec,egcm->gsm",
                          out.combine.astype(dtype), expert_out)
    return combined, out.l_aux, out.exp_counts
