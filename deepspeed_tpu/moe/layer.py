"""MoE facade (ref: deepspeed/moe/layer.py:18 MoE).

Bundles gate + experts + optional residual MLP (PR-MoE, ref layer.py:19
``use_residual``) behind init/apply, plus the partition rules that realize
expert parallelism: expert-stacked leaves sharded over the data axes so the
dispatch einsum emits the all-to-all (the reference's explicit expert
process groups, utils/groups.py:107/160/206, dissolve into this sharding).
"""

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.moe.experts import ffn_expert_fn, init_ffn_experts
from deepspeed_tpu.moe.sharded_moe import TopKGate, moe_layer_apply
from deepspeed_tpu.parallel.sharding import PartitionRule


@dataclass
class MoEConfig:
    num_experts: int = 8
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_residual: bool = False      # PR-MoE
    aux_loss_weight: float = 0.01


class MoE:
    """init/apply MoE block over [G, S, d] activations."""

    def __init__(self, d_model: int, d_ff: int, cfg: MoEConfig):
        self.d_model = d_model
        self.d_ff = d_ff
        self.cfg = cfg
        self.gate = TopKGate(
            k=cfg.k, capacity_factor=cfg.capacity_factor,
            eval_capacity_factor=cfg.eval_capacity_factor,
            min_capacity=cfg.min_capacity,
            noisy_gate_policy=cfg.noisy_gate_policy,
            drop_tokens=cfg.drop_tokens)

    def init_params(self, rng) -> Dict:
        kg, ke, kr, kc = jax.random.split(rng, 4)
        params = {
            "gate": TopKGate.init_params(kg, self.d_model, self.cfg.num_experts),
            "experts": init_ffn_experts(ke, self.cfg.num_experts,
                                        self.d_model, self.d_ff),
        }
        if self.cfg.use_residual:
            init = jax.nn.initializers.normal(0.02)
            params["residual_mlp"] = {
                "wi": {"kernel": init(kr, (self.d_model, self.d_ff), jnp.float32),
                       "bias": jnp.zeros((self.d_ff,), jnp.float32)},
                "wo": {"kernel": init(kc, (self.d_ff, self.d_model), jnp.float32),
                       "bias": jnp.zeros((self.d_model,), jnp.float32)},
            }
            params["coefficient"] = {
                "kernel": jnp.zeros((self.d_model, 2), jnp.float32),
                "bias": jnp.zeros((2,), jnp.float32)}
        return params

    def apply(self, params: Dict, x: jnp.ndarray,
              rng: Optional[jax.Array] = None,
              train: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """x: [G, S, d] -> (y, l_aux, exp_counts)."""
        y, l_aux, exp_counts = moe_layer_apply(
            self.gate, params["gate"], params["experts"], ffn_expert_fn,
            x, rng, train)
        if self.cfg.use_residual:
            # PR-MoE: blend with a dense residual MLP via learned coefficients
            r = params["residual_mlp"]
            h = jax.nn.gelu(x @ r["wi"]["kernel"].astype(x.dtype) +
                            r["wi"]["bias"].astype(x.dtype), approximate=True)
            mlp_out = h @ r["wo"]["kernel"].astype(x.dtype) + \
                r["wo"]["bias"].astype(x.dtype)
            c = params["coefficient"]
            coef = jax.nn.softmax(
                (x @ c["kernel"].astype(x.dtype) + c["bias"].astype(x.dtype)),
                axis=-1)
            y = y * coef[..., 0:1] + mlp_out * coef[..., 1:2]
        return y, l_aux, exp_counts


def moe_partition_rules(prefix: str = "") -> list:
    """Expert-parallel sharding: stacked expert leaves split on dim 0 over
    the data axes (expert-data parallelism). Requires
    num_experts % (data*fsdp) == 0 or falls back to replication via the
    engine's divisibility checks."""
    return [
        PartitionRule(rf"{prefix}experts/(wi|wo)/kernel",
                      P(("data", "fsdp"), None, None)),
        PartitionRule(rf"{prefix}experts/(wi|wo)/bias",
                      P(("data", "fsdp"), None)),
    ]
