// Async file I/O thread pool for NVMe/host offload tiering.
//
// TPU-native equivalent of the reference's libaio module
// (ref: csrc/aio/common/deepspeed_aio_common.cpp,
//  csrc/aio/py_lib/deepspeed_aio_thread.cpp: io_op_desc_t /
//  deepspeed_aio_thread_t, csrc/aio/py_lib/deepspeed_py_aio_handle.cpp:
//  deepspeed_aio_handle_t with _schedule_aio_work/_wait_for_aio_work).
//
// Differences from the reference, by design:
//  - pread/pwrite across a worker-thread pool instead of io_submit: the
//    kernel aio interface needs O_DIRECT alignment of every user buffer;
//    a thread pool with per-thread block-sized chunks achieves comparable
//    NVMe saturation and works on any filesystem. O_DIRECT is attempted
//    and silently downgraded when the fs refuses it.
//  - each request is split into block_size chunks round-robined over the
//    pool (the reference parallelizes identically across its threads,
//    deepspeed_aio_thread.cpp worker loop).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

struct Chunk {
    std::string path;
    char* buf;          // host buffer for this chunk
    int64_t nbytes;
    int64_t file_offset;
    bool is_read;
    int64_t op_id;
};

struct AioHandle {
    int num_threads;
    int queue_depth;   // chunks in flight per thread target (advisory)
    int64_t block_size;
    bool use_direct;

    std::mutex mu;
    std::condition_variable cv_work;
    std::condition_variable cv_done;
    std::deque<Chunk> queue;
    int64_t inflight_chunks = 0;    // queued + executing
    int64_t completed_ops = 0;
    int64_t error_code = 0;         // first errno observed
    int64_t next_op_id = 1;
    bool shutdown = false;
    std::vector<std::thread> workers;

    // per-op remaining chunk counts (op completes when it hits zero)
    std::mutex op_mu;
    std::vector<std::pair<int64_t, int64_t>> op_remaining;
};

int open_file(AioHandle* h, const std::string& path, bool is_read) {
    int flags = is_read ? O_RDONLY : (O_WRONLY | O_CREAT);
    if (h->use_direct) {
        int fd = ::open(path.c_str(), flags | O_DIRECT, 0644);
        if (fd >= 0) return fd;
    }
    return ::open(path.c_str(), flags, 0644);
}

void finish_chunk(AioHandle* h, const Chunk& c) {
    std::lock_guard<std::mutex> lk(h->op_mu);
    for (auto it = h->op_remaining.begin(); it != h->op_remaining.end(); ++it) {
        if (it->first == c.op_id) {
            if (--it->second == 0) {
                h->op_remaining.erase(it);
                h->completed_ops++;
            }
            return;
        }
    }
}

void run_chunk(AioHandle* h, const Chunk& c) {
    int fd = open_file(h, c.path, c.is_read);
    if (fd < 0) {
        std::lock_guard<std::mutex> lk(h->mu);
        if (!h->error_code) h->error_code = -errno;
        return;
    }
    int64_t done = 0;
    bool retried_buffered = false;
    while (done < c.nbytes) {
        ssize_t n = c.is_read
            ? ::pread(fd, c.buf + done, c.nbytes - done, c.file_offset + done)
            : ::pwrite(fd, c.buf + done, c.nbytes - done, c.file_offset + done);
        if (n < 0 && errno == EINVAL && h->use_direct && !retried_buffered) {
            // O_DIRECT alignment refusal: reopen buffered and retry ONCE
            retried_buffered = true;
            ::close(fd);
            fd = ::open(c.path.c_str(),
                        c.is_read ? O_RDONLY : (O_WRONLY | O_CREAT), 0644);
            if (fd < 0) {
                std::lock_guard<std::mutex> lk(h->mu);
                if (!h->error_code) h->error_code = -errno;
                break;
            }
            continue;
        }
        if (n <= 0) {
            std::lock_guard<std::mutex> lk(h->mu);
            if (!h->error_code) h->error_code = n < 0 ? -errno : -EIO;
            break;
        }
        done += n;
    }
    if (fd >= 0) ::close(fd);
}

void worker_loop(AioHandle* h) {
    for (;;) {
        Chunk c;
        {
            std::unique_lock<std::mutex> lk(h->mu);
            h->cv_work.wait(lk, [h] { return h->shutdown || !h->queue.empty(); });
            if (h->shutdown && h->queue.empty()) return;
            c = h->queue.front();
            h->queue.pop_front();
        }
        run_chunk(h, c);
        finish_chunk(h, c);
        {
            std::lock_guard<std::mutex> lk(h->mu);
            h->inflight_chunks--;
        }
        h->cv_done.notify_all();
    }
}

// split [0, nbytes) into block_size chunks and enqueue; returns op id
int64_t submit(AioHandle* h, char* buf, int64_t nbytes, const char* path,
               int64_t file_offset, bool is_read) {
    if (nbytes <= 0) return -EINVAL;
    int64_t n_chunks = (nbytes + h->block_size - 1) / h->block_size;
    int64_t op_id;
    {
        std::lock_guard<std::mutex> lk(h->mu);
        op_id = h->next_op_id++;
    }
    {
        std::lock_guard<std::mutex> lk(h->op_mu);
        h->op_remaining.emplace_back(op_id, n_chunks);
    }
    {
        std::lock_guard<std::mutex> lk(h->mu);
        for (int64_t i = 0; i < n_chunks; i++) {
            int64_t off = i * h->block_size;
            Chunk c{path, buf + off, std::min(h->block_size, nbytes - off),
                    file_offset + off, is_read, op_id};
            h->queue.push_back(c);
            h->inflight_chunks++;
        }
    }
    h->cv_work.notify_all();
    return op_id;
}

int64_t wait_all(AioHandle* h) {
    std::unique_lock<std::mutex> lk(h->mu);
    h->cv_done.wait(lk, [h] { return h->inflight_chunks == 0; });
    if (h->error_code) {
        int64_t e = h->error_code;
        h->error_code = 0;
        return e;
    }
    int64_t n = h->completed_ops;
    h->completed_ops = 0;
    return n;
}

}  // namespace

extern "C" {

void* ds_aio_create(int num_threads, int queue_depth, int64_t block_size,
                    int use_direct) {
    auto* h = new AioHandle();
    h->num_threads = num_threads > 0 ? num_threads : 1;
    h->queue_depth = queue_depth > 0 ? queue_depth : 32;
    h->block_size = block_size > 0 ? block_size : (1 << 20);
    h->use_direct = use_direct != 0;
    for (int i = 0; i < h->num_threads; i++)
        h->workers.emplace_back(worker_loop, h);
    return h;
}

void ds_aio_destroy(void* handle) {
    auto* h = static_cast<AioHandle*>(handle);
    {
        std::lock_guard<std::mutex> lk(h->mu);
        h->shutdown = true;
    }
    h->cv_work.notify_all();
    for (auto& t : h->workers) t.join();
    delete h;
}

// synchronous read/write: submit + wait (ref: deepspeed_py_aio_handle.cpp
// sync_pread/sync_pwrite)
int64_t ds_aio_pread(void* handle, void* buf, int64_t nbytes,
                     const char* path, int64_t file_offset) {
    auto* h = static_cast<AioHandle*>(handle);
    int64_t id = submit(h, static_cast<char*>(buf), nbytes, path,
                        file_offset, true);
    if (id < 0) return id;
    int64_t r = wait_all(h);
    return r < 0 ? r : nbytes;
}

int64_t ds_aio_pwrite(void* handle, void* buf, int64_t nbytes,
                      const char* path, int64_t file_offset) {
    auto* h = static_cast<AioHandle*>(handle);
    int64_t id = submit(h, static_cast<char*>(buf), nbytes, path,
                        file_offset, false);
    if (id < 0) return id;
    int64_t r = wait_all(h);
    return r < 0 ? r : nbytes;
}

// async: enqueue and return op id (ref: _schedule_aio_work)
int64_t ds_aio_submit_read(void* handle, void* buf, int64_t nbytes,
                           const char* path, int64_t file_offset) {
    return submit(static_cast<AioHandle*>(handle), static_cast<char*>(buf),
                  nbytes, path, file_offset, true);
}

int64_t ds_aio_submit_write(void* handle, void* buf, int64_t nbytes,
                            const char* path, int64_t file_offset) {
    return submit(static_cast<AioHandle*>(handle), static_cast<char*>(buf),
                  nbytes, path, file_offset, false);
}

// wait for ALL inflight ops (ref: _wait_for_aio_work); returns #ops
// completed since last wait, or -errno on first error.
int64_t ds_aio_wait(void* handle) {
    return wait_all(static_cast<AioHandle*>(handle));
}

int64_t ds_aio_inflight(void* handle) {
    auto* h = static_cast<AioHandle*>(handle);
    std::lock_guard<std::mutex> lk(h->mu);
    return h->inflight_chunks;
}

// aligned host buffer for O_DIRECT-friendly transfers (the "pinned" pool
// analog; ref: csrc/aio py buffer registration)
void* ds_aligned_alloc(int64_t nbytes, int64_t alignment) {
    void* p = nullptr;
    if (posix_memalign(&p, static_cast<size_t>(alignment),
                       static_cast<size_t>(nbytes)) != 0)
        return nullptr;
    return p;
}

void ds_aligned_free(void* p) { free(p); }

}  // extern "C"
