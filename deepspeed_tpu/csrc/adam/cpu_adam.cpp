// Vectorized host optimizer steps for offloaded optimizer state.
//
// TPU-native equivalent of the reference's CPU Adam/Adagrad kernels
// (ref: csrc/adam/cpu_adam.cpp Adam_Optimizer::Step_* with AVX256/AVX512
//  intrinsics via csrc/includes/simd.h, csrc/adagrad/cpu_adagrad.cpp).
// The reference hand-writes SIMD with _mm256/_mm512 wrappers; here the
// loops are written so g++ -O3 -march=native auto-vectorizes them to the
// same AVX code (verified: single fused loop, no aliasing, omp simd), and
// OpenMP parallelizes across cores exactly like the reference's
// `#pragma omp parallel for` tiling.
//
// bf16 copy-back (ds_adam_update_copy_bf16) mirrors the reference's
// half-precision param copy (cpu_adam.cpp adam_update_copy): the fp32
// master weight is updated and simultaneously rounded to bf16 for the
// device-bound buffer, saving a second pass over memory.

#include <cstdint>
#include <cmath>
#include <cstring>

namespace {

// round-to-nearest-even fp32 -> bf16
inline uint16_t fp32_to_bf16(float f) {
    uint32_t x;
    std::memcpy(&x, &f, 4);
    uint32_t lsb = (x >> 16) & 1;
    x += 0x7fff + lsb;
    return static_cast<uint16_t>(x >> 16);
}

}  // namespace

extern "C" {

// Fused Adam/AdamW step over a flat fp32 partition.
// bias_c1 = 1/(1-beta1^t), bias_c2 = 1/sqrt(1-beta2^t) precomputed by the
// caller (the reference precomputes the same in Adam_Optimizer::Step).
// adamw != 0 -> decoupled weight decay (AdamW); else L2-into-grad Adam.
void ds_adam_update(int64_t n, float* params, const float* grads,
                    float* exp_avg, float* exp_avg_sq,
                    float lr, float beta1, float beta2, float eps,
                    float weight_decay, float bias_c1, float bias_c2,
                    int adamw) {
    const float om_b1 = 1.0f - beta1;
    const float om_b2 = 1.0f - beta2;
    const float step_size = -lr * bias_c1;
    const float decay = adamw ? (1.0f - lr * weight_decay) : 1.0f;
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; i++) {
        float g = grads[i];
        if (!adamw && weight_decay > 0.0f) g += weight_decay * params[i];
        float m = exp_avg[i] * beta1 + g * om_b1;
        float v = exp_avg_sq[i] * beta2 + g * g * om_b2;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        float denom = std::sqrt(v) * bias_c2 + eps;
        params[i] = params[i] * decay + step_size * (m / denom);
    }
}

// Same step + simultaneous bf16 copy-back of updated params.
void ds_adam_update_copy_bf16(int64_t n, float* params, const float* grads,
                              float* exp_avg, float* exp_avg_sq,
                              float lr, float beta1, float beta2, float eps,
                              float weight_decay, float bias_c1,
                              float bias_c2, int adamw,
                              uint16_t* params_bf16_out) {
    const float om_b1 = 1.0f - beta1;
    const float om_b2 = 1.0f - beta2;
    const float step_size = -lr * bias_c1;
    const float decay = adamw ? (1.0f - lr * weight_decay) : 1.0f;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; i++) {
        float g = grads[i];
        if (!adamw && weight_decay > 0.0f) g += weight_decay * params[i];
        float m = exp_avg[i] * beta1 + g * om_b1;
        float v = exp_avg_sq[i] * beta2 + g * g * om_b2;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        float denom = std::sqrt(v) * bias_c2 + eps;
        float p = params[i] * decay + step_size * (m / denom);
        params[i] = p;
        params_bf16_out[i] = fp32_to_bf16(p);
    }
}

// Adagrad step (ref: csrc/adagrad/cpu_adagrad.cpp Adagrad_Optimizer::Step).
void ds_adagrad_update(int64_t n, float* params, const float* grads,
                       float* exp_avg_sq, float lr, float eps,
                       float weight_decay) {
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; i++) {
        float g = grads[i];
        if (weight_decay > 0.0f) g += weight_decay * params[i];
        float v = exp_avg_sq[i] + g * g;
        exp_avg_sq[i] = v;
        params[i] -= lr * g / (std::sqrt(v) + eps);
    }
}

// L2 norms of param and update vectors for the LAMB trust ratio
// (ref: csrc/lamb/fused_lamb_cuda_kernel.cu reduction passes).
// out[0] = ||params||^2, out[1] = ||update||^2
void ds_lamb_norms(int64_t n, const float* params, const float* update,
                   float* out) {
    double p2 = 0.0, u2 = 0.0;
#pragma omp parallel for reduction(+ : p2, u2) schedule(static)
    for (int64_t i = 0; i < n; i++) {
        p2 += static_cast<double>(params[i]) * params[i];
        u2 += static_cast<double>(update[i]) * update[i];
    }
    out[0] = static_cast<float>(p2);
    out[1] = static_cast<float>(u2);
}

}  // extern "C"
