"""AIO performance sweep — NVMe tuning harness.

Capability match for the reference's aio benchmark suite
(ref: csrc/aio/py_test/aio_bench_perf_sweep.py:397 LoC + ds_aio_handle.py,
parse_aio_stats.py): sweep (block_size x queue_depth x thread_count x
read/write) over the C++ aio thread pool, report GB/s per combo and the
best config to paste into the ``aio`` section of the ds_config. The
reference shells out one subprocess per point; in-process is enough
here since the pool is its own threads.

CLI: ``python -m deepspeed_tpu.ops.aio.perf_sweep --nvme-dir /mnt/nvme``
"""

import argparse
import itertools
import json
import os
import time
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

DEFAULT_SWEEP = {
    "block_size": [128 * 1024, 256 * 1024, 1024 * 1024],
    "queue_depth": [4, 16, 32],
    "thread_count": [1, 2, 4],
    "op": ["read", "write"],
}


def _one_point(nvme_dir: str, io_bytes: int, block_size: int,
               queue_depth: int, thread_count: int, op: str,
               use_direct: bool = True) -> float:
    """Returns achieved GB/s for one configuration. ``use_direct``
    (O_DIRECT) bypasses the page cache so the numbers reflect the
    device — without it a freshly-written file reads back from DRAM."""
    from deepspeed_tpu.ops.aio import AlignedBuffer, AsyncIOHandle

    handle = AsyncIOHandle(block_size=block_size, queue_depth=queue_depth,
                           thread_count=thread_count, use_direct=use_direct)
    buf = AlignedBuffer(io_bytes)
    arr = buf.view(io_bytes // 4, np.float32)
    path = os.path.join(nvme_dir, f"_aio_sweep_{os.getpid()}.bin")
    try:
        if op == "write":
            arr[:] = 1.0
            t0 = time.perf_counter()
            handle.sync_pwrite(arr, path)
            dt = time.perf_counter() - t0
        else:
            arr[:] = 1.0
            handle.sync_pwrite(arr, path)
            t0 = time.perf_counter()
            handle.sync_pread(arr, path)
            dt = time.perf_counter() - t0
        return io_bytes / dt / 1e9
    finally:
        if os.path.exists(path):
            os.unlink(path)
        handle.close()
        buf.free()


def sweep(nvme_dir: str, io_mb: int = 64,
          space: Optional[Dict[str, List]] = None,
          use_direct: bool = True) -> List[Dict]:
    """Run the full sweep; returns records grouped by op (reads first),
    best-first within each group. ``use_direct=False`` only for
    filesystems without O_DIRECT (tmpfs) — the numbers then measure the
    page cache, not the device."""
    space = {**DEFAULT_SWEEP, **(space or {})}
    io_bytes = io_mb * 1024 * 1024
    records = []
    keys = list(space.keys())
    for combo in itertools.product(*space.values()):
        cfg = dict(zip(keys, combo))
        try:
            gbps = _one_point(nvme_dir, io_bytes, cfg["block_size"],
                              cfg["queue_depth"], cfg["thread_count"],
                              cfg["op"], use_direct=use_direct)
            records.append({**cfg, "gbps": gbps})
            logger.info(f"{cfg} -> {gbps:.2f} GB/s")
        except Exception as e:
            records.append({**cfg, "gbps": None, "error": str(e)})
            logger.warning(f"{cfg} failed: {e}")
    records.sort(key=lambda r: (r["op"] != "read", -(r["gbps"] or 0.0)))
    return records


def best_aio_config(records: List[Dict]) -> Dict:
    """Best read point → the ``aio`` ds_config section
    (ref: the sweep's optimal-config output)."""
    for r in records:
        if r.get("gbps") and r["op"] == "read":
            return {"block_size": r["block_size"],
                    "queue_depth": r["queue_depth"],
                    "thread_count": r["thread_count"],
                    "single_submit": False, "overlap_events": True}
    return {}


def main(argv=None):
    parser = argparse.ArgumentParser(prog="aio_perf_sweep")
    parser.add_argument("--nvme-dir", required=True,
                        help="directory on the NVMe device to benchmark")
    parser.add_argument("--io-mb", type=int, default=64)
    parser.add_argument("--output", default=None,
                        help="write records json here")
    parser.add_argument("--no-direct", action="store_true",
                        help="skip O_DIRECT (tmpfs etc; measures cache)")
    args = parser.parse_args(argv)
    records = sweep(args.nvme_dir, io_mb=args.io_mb,
                    use_direct=not args.no_direct)
    print(json.dumps({"best_aio_config": best_aio_config(records),
                      "records": records[:10]}, indent=2))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(records, f, indent=2)


if __name__ == "__main__":
    main()
