"""Python handle API over the native async-I/O thread pool.

Parity surface of the reference's ``deepspeed_aio_handle_t``
(ref: csrc/aio/py_lib/deepspeed_py_aio_handle.h:12-65 — sync_pread/
sync_pwrite/async read+write/wait, block_size/queue_depth/thread_count
knobs) driving NVMe offload. Buffers are numpy arrays; ``AlignedBuffer``
allocates page-aligned host memory (O_DIRECT-friendly — the "pinned
buffer" analog on a TPU VM, where host RAM<->HBM DMA needs no cudaHostAlloc).
"""

import ctypes
from typing import Optional

import numpy as np

from deepspeed_tpu.ops.op_builder import AsyncIOBuilder

_DEFAULT_BLOCK = 1 << 20
_ALIGN = 4096


class AlignedBuffer:
    """Page-aligned host buffer exposed as a numpy array."""

    def __init__(self, nbytes: int, dtype=np.float32):
        self._lib = AsyncIOBuilder().load()
        nbytes = max(int(nbytes), _ALIGN)
        # round to alignment so O_DIRECT length checks pass
        nbytes = (nbytes + _ALIGN - 1) // _ALIGN * _ALIGN
        self._ptr = self._lib.ds_aligned_alloc(nbytes, _ALIGN)
        if not self._ptr:
            raise MemoryError(f"aligned alloc of {nbytes} bytes failed")
        self.nbytes = nbytes
        ct = (ctypes.c_byte * nbytes).from_address(self._ptr)
        self.array = np.frombuffer(ct, dtype=np.uint8).view(dtype)

    def view(self, numel: int, dtype=np.float32) -> np.ndarray:
        return self.array.view(dtype)[:numel]

    def data_ptr(self) -> int:
        return self._ptr

    def free(self):
        if self._ptr:
            self._lib.ds_aligned_free(self._ptr)
            self._ptr = None
            self.array = None

    def __del__(self):  # pragma: no cover - gc timing dependent
        try:
            self.free()
        except Exception:  # dslint: disable=DS006 — __del__ must never raise during teardown
            pass


class AsyncIOHandle:
    """Thread-pooled file reader/writer (ref: deepspeed_aio_handle_t)."""

    def __init__(self, block_size: int = _DEFAULT_BLOCK, queue_depth: int = 32,
                 thread_count: int = 4, use_direct: bool = False):
        self._lib = AsyncIOBuilder().load()
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.thread_count = thread_count
        self._h = self._lib.ds_aio_create(thread_count, queue_depth,
                                          block_size, 1 if use_direct else 0)
        if not self._h:
            raise RuntimeError("failed to create aio handle")

    @staticmethod
    def _ptr(arr: np.ndarray) -> ctypes.c_void_p:
        assert arr.flags["C_CONTIGUOUS"], "aio buffers must be contiguous"
        return ctypes.c_void_p(arr.ctypes.data)

    def sync_pread(self, buffer: np.ndarray, filename: str,
                   offset: int = 0) -> int:
        r = self._lib.ds_aio_pread(self._h, self._ptr(buffer), buffer.nbytes,
                                   filename.encode(), offset)
        if r < 0:
            raise OSError(-r, f"aio read of {filename} failed")
        return r

    def sync_pwrite(self, buffer: np.ndarray, filename: str,
                    offset: int = 0) -> int:
        r = self._lib.ds_aio_pwrite(self._h, self._ptr(buffer), buffer.nbytes,
                                    filename.encode(), offset)
        if r < 0:
            raise OSError(-r, f"aio write of {filename} failed")
        return r

    def async_pread(self, buffer: np.ndarray, filename: str,
                    offset: int = 0) -> int:
        return self._lib.ds_aio_submit_read(
            self._h, self._ptr(buffer), buffer.nbytes, filename.encode(),
            offset)

    def async_pwrite(self, buffer: np.ndarray, filename: str,
                     offset: int = 0) -> int:
        return self._lib.ds_aio_submit_write(
            self._h, self._ptr(buffer), buffer.nbytes, filename.encode(),
            offset)

    def wait(self) -> int:
        """Block until every in-flight op completes (ref:
        _wait_for_aio_work). Returns ops completed; raises on I/O error."""
        r = self._lib.ds_aio_wait(self._h)
        if r < 0:
            raise OSError(-r, "async I/O failed")
        return r

    def inflight(self) -> int:
        return self._lib.ds_aio_inflight(self._h)

    def close(self):
        if self._h:
            self._lib.ds_aio_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:  # dslint: disable=DS006 — __del__ must never raise during teardown
            pass
