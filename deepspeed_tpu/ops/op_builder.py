"""JIT builder for native (C++) host ops.

TPU-native analog of the reference's op build system
(ref: op_builder/builder.py:107 OpBuilder.load / :524 CUDAOpBuilder):
the reference JIT-compiles CUDA/C++ pybind11 extensions on first use; here
the native surface is host-only (async file I/O, AVX optimizer steps), so we
compile a plain shared library with ``g++`` and bind it with ``ctypes`` —
no pybind11 in the image, and ctypes avoids a Python ABI dependency.

Build artifacts are cached under ``<repo>/build/`` keyed by a hash of the
sources and flags, so repeat imports are instant.
"""

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import List, Optional

from deepspeed_tpu.utils.logging import logger

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CSRC = os.path.join(_PKG_DIR, "csrc")
_BUILD_DIR = os.environ.get(  # dslint: disable=DS005,DS013 — build-dir path for the op compiler, read once at import on purpose; a path, not a feature flag, so it stays outside the FLAGS registry
    "DS_TPU_BUILD_DIR",
    os.path.join(os.path.dirname(_PKG_DIR), "build"))

_lock = threading.Lock()
_loaded = {}


class OpBuilder:
    """Compile a list of C++ sources into a shared lib, return a CDLL.

    Mirrors the reference builder's contract: ``load()`` either returns the
    cached library or compiles it (ref: op_builder/builder.py:107).
    """

    name: str = ""
    sources: List[str] = []
    extra_flags: List[str] = []

    def __init__(self):
        self._lib: Optional[ctypes.CDLL] = None

    def abs_sources(self) -> List[str]:
        return [os.path.join(_CSRC, s) for s in self.sources]

    def cxx_flags(self) -> List[str]:
        march = [] if os.environ.get("DS_TPU_NO_NATIVE_ARCH") else ["-march=native"]  # dslint: disable=DS005,DS013 — compiler-flag escape hatch for the native build, truthiness on purpose (any value disables)
        return (["-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp",
                 "-Wall"] + march + list(self.extra_flags))

    def _hash(self) -> str:
        h = hashlib.sha256()
        for src in self.abs_sources():
            with open(src, "rb") as f:
                h.update(f.read())
        h.update(" ".join(self.cxx_flags()).encode())
        return h.hexdigest()[:16]

    def lib_path(self) -> str:
        return os.path.join(_BUILD_DIR, f"lib{self.name}_{self._hash()}.so")

    def is_compatible(self) -> bool:
        """Host ops need only a C++ toolchain (cf. ds_report compat matrix)."""
        try:
            subprocess.run(["g++", "--version"], capture_output=True, check=True)
            return True
        except (OSError, subprocess.CalledProcessError):
            return False

    def build(self) -> str:
        path = self.lib_path()
        if os.path.exists(path):
            return path
        os.makedirs(_BUILD_DIR, exist_ok=True)
        # compile to a process-private temp path, then atomically rename so a
        # concurrent process never dlopens a half-written library
        tmp = f"{path}.tmp.{os.getpid()}"
        cmd = ["g++"] + self.cxx_flags() + self.abs_sources() + [
            "-o", tmp, "-lpthread"]
        logger.info("building native op %s: %s", self.name, " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"failed to build op '{self.name}':\n{proc.stderr}")
        os.replace(tmp, path)
        return path

    def load(self) -> ctypes.CDLL:
        with _lock:
            if self.name in _loaded:
                return _loaded[self.name]
            lib = ctypes.CDLL(self.build())
            self._decorate(lib)
            _loaded[self.name] = lib
            return lib

    def _decorate(self, lib: ctypes.CDLL) -> None:
        """Attach argtypes/restype signatures. Override per op."""


class AsyncIOBuilder(OpBuilder):
    """Async file I/O thread pool (ref: op_builder/async_io.py:10,
    csrc/aio/py_lib/deepspeed_aio_thread.cpp)."""

    name = "ds_aio"
    sources = ["aio/ds_aio.cpp"]

    def _decorate(self, lib):
        c = ctypes
        lib.ds_aio_create.argtypes = [c.c_int, c.c_int, c.c_long, c.c_int]
        lib.ds_aio_create.restype = c.c_void_p
        lib.ds_aio_destroy.argtypes = [c.c_void_p]
        for fn in (lib.ds_aio_pread, lib.ds_aio_submit_read):
            fn.argtypes = [c.c_void_p, c.c_void_p, c.c_long, c.c_char_p,
                           c.c_long]
            fn.restype = c.c_long
        for fn in (lib.ds_aio_pwrite, lib.ds_aio_submit_write):
            fn.argtypes = [c.c_void_p, c.c_void_p, c.c_long, c.c_char_p,
                           c.c_long]
            fn.restype = c.c_long
        lib.ds_aio_wait.argtypes = [c.c_void_p]
        lib.ds_aio_wait.restype = c.c_long
        lib.ds_aio_inflight.argtypes = [c.c_void_p]
        lib.ds_aio_inflight.restype = c.c_long
        lib.ds_aligned_alloc.argtypes = [c.c_long, c.c_long]
        lib.ds_aligned_alloc.restype = c.c_void_p
        lib.ds_aligned_free.argtypes = [c.c_void_p]


class CPUAdamBuilder(OpBuilder):
    """Vectorized host Adam/Adagrad/LAMB-trust step for offloaded optimizer
    state (ref: op_builder/cpu_adam.py, csrc/adam/cpu_adam.cpp:284,
    csrc/includes/cpu_adam.h:55 Step_AVX)."""

    name = "ds_cpu_adam"
    sources = ["adam/cpu_adam.cpp"]

    def _decorate(self, lib):
        c = ctypes
        fp = c.POINTER(c.c_float)
        u16 = c.POINTER(c.c_uint16)
        lib.ds_adam_update.argtypes = [
            c.c_long, fp, fp, fp, fp,
            c.c_float, c.c_float, c.c_float, c.c_float, c.c_float,
            c.c_float, c.c_float, c.c_int]
        lib.ds_adam_update_copy_bf16.argtypes = [
            c.c_long, fp, fp, fp, fp,
            c.c_float, c.c_float, c.c_float, c.c_float, c.c_float,
            c.c_float, c.c_float, c.c_int, u16]
        lib.ds_adagrad_update.argtypes = [
            c.c_long, fp, fp, fp, c.c_float, c.c_float, c.c_float]
        lib.ds_lamb_norms.argtypes = [c.c_long, fp, fp, fp]


ALL_OPS = {b.name: b for b in (AsyncIOBuilder(), CPUAdamBuilder())}
