"""Host-side (offloaded) optimizer steps backed by the native AVX kernels.

Parity surface of the reference's ``DeepSpeedCPUAdam``/``DeepSpeedCPUAdagrad``
(ref: deepspeed/ops/adam/cpu_adam.py:13, csrc/adam/cpu_adam.cpp:284) used by
ZeRO-Offload: optimizer state lives in host RAM as fp32 numpy arrays and the
step runs on host cores while the device is busy with the next microbatch.
"""

import ctypes
from typing import Optional

import numpy as np

from deepspeed_tpu.ops.op_builder import CPUAdamBuilder


def _fp(a: np.ndarray):
    assert a.dtype == np.float32 and a.flags["C_CONTIGUOUS"]
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdam:
    """Fused host Adam/AdamW over flat fp32 buffers.

    State (exp_avg, exp_avg_sq) is allocated lazily per param buffer id the
    first time :meth:`step` sees it, mirroring the reference's per-group
    state tensors.
    """

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True):
        self._lib = CPUAdamBuilder().load()
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.state = {}  # id -> dict(step, exp_avg, exp_avg_sq)

    def _get_state(self, key, numel: int):
        st = self.state.get(key)
        if st is None:
            st = {"step": 0,
                  "exp_avg": np.zeros(numel, np.float32),
                  "exp_avg_sq": np.zeros(numel, np.float32)}
            self.state[key] = st
        return st

    def step(self, key, params: np.ndarray, grads: np.ndarray,
             lr: Optional[float] = None,
             params_bf16_out: Optional[np.ndarray] = None) -> int:
        """One Adam step on a flat fp32 partition; optional simultaneous
        bf16 copy-back into the device-bound staging buffer."""
        st = self._get_state(key, params.size)
        st["step"] += 1
        t = st["step"]
        lr = self.lr if lr is None else lr
        bias_c1 = 1.0 / (1.0 - self.beta1 ** t)
        bias_c2 = 1.0 / np.sqrt(1.0 - self.beta2 ** t)
        common = (params.size, _fp(params), _fp(grads), _fp(st["exp_avg"]),
                  _fp(st["exp_avg_sq"]), lr, self.beta1, self.beta2, self.eps,
                  self.weight_decay, bias_c1, bias_c2,
                  1 if self.adamw_mode else 0)
        if params_bf16_out is None:
            self._lib.ds_adam_update(*common)
        else:
            assert params_bf16_out.dtype == np.uint16
            self._lib.ds_adam_update_copy_bf16(
                *common,
                params_bf16_out.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_uint16)))
        return t

    def state_arrays(self, key):
        return self.state[key]

    def load_state(self, key, step: int, exp_avg: np.ndarray,
                   exp_avg_sq: np.ndarray):
        self.state[key] = {"step": int(step),
                           "exp_avg": np.ascontiguousarray(exp_avg, np.float32),
                           "exp_avg_sq": np.ascontiguousarray(exp_avg_sq,
                                                              np.float32)}


class DeepSpeedCPUAdagrad:
    """Host Adagrad (ref: csrc/adagrad/cpu_adagrad.cpp)."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0):
        self._lib = CPUAdamBuilder().load()
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.state = {}

    def step(self, key, params: np.ndarray, grads: np.ndarray,
             lr: Optional[float] = None):
        st = self.state.get(key)
        if st is None:
            st = {"step": 0, "exp_avg_sq": np.zeros(params.size, np.float32)}
            self.state[key] = st
        st["step"] += 1
        self._lib.ds_adagrad_update(
            params.size, _fp(params), _fp(grads), _fp(st["exp_avg_sq"]),
            self.lr if lr is None else lr, self.eps, self.weight_decay)
        return st["step"]

    def state_arrays(self, key):
        st = self.state[key]
        # exp_avg slot kept for checkpoint-format uniformity with Adam
        return {"exp_avg": np.zeros(0, np.float32),
                "exp_avg_sq": st["exp_avg_sq"]}

    def load_state(self, key, step: int, exp_avg: np.ndarray,
                   exp_avg_sq: np.ndarray):
        del exp_avg  # adagrad has no first moment
        self.state[key] = {
            "step": int(step),
            "exp_avg_sq": np.ascontiguousarray(exp_avg_sq, np.float32)}


def lamb_trust_ratio(lib, params: np.ndarray, update: np.ndarray) -> float:
    """||w|| / ||update|| via the native reduction (ref:
    csrc/lamb/fused_lamb_cuda_kernel.cu trust-ratio reductions)."""
    out = np.zeros(2, np.float32)
    lib.ds_lamb_norms(params.size, _fp(params), _fp(update), _fp(out))
    w_norm, u_norm = float(np.sqrt(out[0])), float(np.sqrt(out[1]))
    if w_norm == 0.0 or u_norm == 0.0:
        return 1.0
    return w_norm / u_norm
