"""Group-wise quantization primitives (TPU-native).

Capability match for the reference's CUDA quantization kernels
(ref: csrc/quantization/quantizer.cu, bindings csrc/transformer/inference/
csrc/pt_binding.cpp:62-74 ds_quantize_fp16 / ds_sr_quantize_asym_fp16 / ...)
and the python fallback math in deepspeed/runtime/quantize.py:158-205.

On TPU these are bandwidth-bound elementwise ops: a hand-written kernel
buys nothing because XLA fuses the whole quantize→dequantize chain into
one HBM pass (and into the surrounding matmul when used inline), so the
idiomatic implementation is pure jax under ``jit``. All functions are
functional and differentiable-through via straight-through estimation
where noted.

Conventions
-----------
* ``groups`` splits the *flattened* tensor into equal contiguous groups,
  each with its own scale (same layout as the reference kernels).
* ``bits`` is the target precision; symmetric range is
  ``[-2^(bits-1), 2^(bits-1)-1]``, asymmetric is ``[0, 2^bits-1]``.
* Stochastic rounding draws from ``rng`` (jax PRNG key) — the reference
  uses curand inside the kernel.
"""

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.env import resolve_flag


def _grouped(x: jnp.ndarray, groups: int) -> jnp.ndarray:
    flat = x.reshape(-1)
    n = flat.shape[0]
    if n % groups != 0:
        raise ValueError(f"tensor size {n} not divisible by groups={groups}")
    return flat.reshape(groups, n // groups)


# ----------------------------------------------------------------------
# fake-quantization (quantize→dequantize in one pass) — MoQ training path
# ----------------------------------------------------------------------

@partial(jax.jit, static_argnames=("groups", "bits", "symmetric", "stochastic"))
def quantize_dequantize(x: jnp.ndarray,
                        groups: int = 1,
                        bits: int = 8,
                        symmetric: bool = True,
                        stochastic: bool = False,
                        rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """Fake-quantize ``x`` group-wise at ``bits`` precision.

    Mirrors the reference python fallback (deepspeed/runtime/quantize.py:
    158-205: scale = q_range / (2*absmax), round/clamp, rescale) and the
    sr_quantize path (:88) for stochastic rounding.
    """
    orig_dtype = x.dtype
    g = _grouped(x, groups).astype(jnp.float32)
    q_range = jnp.float32(2 ** bits)

    if symmetric:
        absmax = jnp.max(jnp.abs(g), axis=1, keepdims=True)
        scale = q_range / (2.0 * absmax + 1e-8)
        scaled = g * scale
        if stochastic:
            if rng is None:
                rng = jax.random.PRNGKey(0)
            noise = jax.random.uniform(rng, scaled.shape, dtype=jnp.float32)
            q = jnp.floor(scaled + noise)
        else:
            q = jnp.round(scaled)
        q = jnp.clip(q, -(q_range / 2), q_range / 2 - 1)
        out = q / scale
    else:
        gmin = jnp.min(g, axis=1, keepdims=True)
        gmax = jnp.max(g, axis=1, keepdims=True)
        scale = (gmax - gmin) / q_range + 1e-8
        scaled = (g - gmin) / scale
        if stochastic:
            if rng is None:
                rng = jax.random.PRNGKey(0)
            noise = jax.random.uniform(rng, scaled.shape, dtype=jnp.float32)
            q = jnp.floor(scaled + noise)
        else:
            q = jnp.round(scaled)
        q = jnp.clip(q, 0, q_range - 1)
        out = q * scale + gmin

    return out.reshape(x.shape).astype(orig_dtype)


def quantize_dequantize_ste(x, groups=1, bits=8, symmetric=True):
    """Straight-through-estimator variant: forward fake-quant, identity
    gradient. For quantize-aware training losses."""
    q = quantize_dequantize(x, groups=groups, bits=bits, symmetric=symmetric)
    return x + jax.lax.stop_gradient(q - x)


# ----------------------------------------------------------------------
# real quantization (int8 storage + scales) — inference weight path
# ----------------------------------------------------------------------

@partial(jax.jit, static_argnames=("groups", "bits"))
def quantize(x: jnp.ndarray,
             groups: int = 1,
             bits: int = 8) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric group-wise quantization to int8 storage.

    Returns ``(q, scale)`` with ``q`` int8 of x.shape and ``scale``
    float32 of shape (groups,) such that ``x ≈ q / scale`` (same scale
    convention as the reference: scale multiplies the float to get the
    integer, ref deepspeed/runtime/weight_quantizer.py:14-27).
    """
    if bits > 8:
        raise ValueError(f"int8 storage holds at most 8 bits, got {bits} "
                         "(use quantize_dequantize for wider fake-quant)")
    g = _grouped(x, groups).astype(jnp.float32)
    q_range = jnp.float32(2 ** bits)
    absmax = jnp.max(jnp.abs(g), axis=1, keepdims=True)
    scale = q_range / (2.0 * absmax + 1e-5)
    q = jnp.clip(jnp.round(g * scale), -(q_range / 2), q_range / 2 - 1)
    return q.reshape(x.shape).astype(jnp.int8), scale.reshape(-1)


@partial(jax.jit, static_argnames=("groups", "dtype"))
def dequantize(q: jnp.ndarray,
               scale: jnp.ndarray,
               groups: int = 1,
               dtype=jnp.bfloat16) -> jnp.ndarray:
    """Inverse of :func:`quantize` (ref: csrc .../dequantize.cu)."""
    g = _grouped(q.astype(jnp.float32), groups)
    out = g / scale.reshape(-1, 1)
    return out.reshape(q.shape).astype(dtype)


@partial(jax.jit, static_argnames=("groups", "bits"))
def quantize_asym(x: jnp.ndarray,
                  groups: int = 1,
                  bits: int = 8) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Asymmetric group-wise quantization: returns (q int8 shifted by
    -2^(bits-1), scale, min) with ``x ≈ (q + 2^(bits-1)) * scale + min``."""
    if bits > 8:
        raise ValueError(f"int8 storage holds at most 8 bits, got {bits} "
                         "(use quantize_dequantize for wider fake-quant)")
    g = _grouped(x, groups).astype(jnp.float32)
    q_range = jnp.float32(2 ** bits)
    gmin = jnp.min(g, axis=1, keepdims=True)
    gmax = jnp.max(g, axis=1, keepdims=True)
    scale = (gmax - gmin) / q_range + 1e-8
    q = jnp.clip(jnp.round((g - gmin) / scale), 0, q_range - 1)
    # store shifted to int8 range
    q = (q - q_range / 2).astype(jnp.int8)
    return q.reshape(x.shape), scale.reshape(-1), gmin.reshape(-1)


@partial(jax.jit, static_argnames=("groups", "bits", "dtype"))
def dequantize_asym(q: jnp.ndarray,
                    scale: jnp.ndarray,
                    gmin: jnp.ndarray,
                    groups: int = 1,
                    bits: int = 8,
                    dtype=jnp.bfloat16) -> jnp.ndarray:
    """Inverse of :func:`quantize_asym`; ``bits`` must match."""
    g = _grouped(q.astype(jnp.float32), groups)
    half_range = jnp.float32(2 ** bits) / 2
    out = (g + half_range) * scale.reshape(-1, 1) + gmin.reshape(-1, 1)
    return out.reshape(q.shape).astype(dtype)


# ----------------------------------------------------------------------
# quantized matmul helper (dequantize-on-the-fly, fused by XLA)
# ----------------------------------------------------------------------

def quantized_matmul(x: jnp.ndarray,
                     q_weight: jnp.ndarray,
                     scale: jnp.ndarray,
                     groups: int = 1) -> jnp.ndarray:
    """``x @ dequantize(q_weight)`` with the dequantize fused into the
    HBM→MXU load by XLA. int8 weights halve the HBM traffic of the
    matmul — the same win the reference's int8 inference GEMMs target
    (ref: csrc/transformer/inference qkv_gemm int8 variants)."""
    w = dequantize(q_weight, scale, groups=groups, dtype=x.dtype)
    return x @ w


# ----------------------------------------------------------------------
# paged KV-cache block quantization (int8 storage, per-block×kv-head scales)
# ----------------------------------------------------------------------
#
# Unlike the group helpers above (reference scale convention
# ``x ≈ q / scale``), the KV helpers use the multiply convention of
# ``ops/int8_matmul.py`` / ``engine.quantize_weights_int8``:
#
#     scale = absmax / 127,   q = round(x / scale) in [-127, 127],
#     x ≈ q.astype(f32) * scale
#
# A "block" is one paged-cache block ``[..., block_size, kv_heads,
# head_dim]``; the scale is reduced over the token and head_dim axes so
# each (block, kv_head) pair carries one fp32 scale — the layout the
# paged-attention kernel dequantizes in-register after the block DMA.

KV_QMAX = 127.0


def resolve_kv_quant(mode=None) -> str:
    """Resolve the KV-cache quantization mode: ``"off"`` or ``"int8"``.

    Explicit ``mode`` wins; otherwise the ``DS_KV_QUANT`` env var;
    otherwise off. Booleans map onto the on/off aliases (True → int8).
    Parse/validation live in the shared FLAGS registry
    (:mod:`deepspeed_tpu.utils.env`).
    """
    return resolve_flag("DS_KV_QUANT", mode)


def kv_block_scales(x: jnp.ndarray) -> jnp.ndarray:
    """Symmetric per-(block, kv_head) scales for ``x`` shaped
    ``[..., block_size, kv_heads, head_dim]`` → ``[..., kv_heads]``.

    ``scale = absmax / 127``; an all-zero block yields scale 0 (the
    trash block stays finite: quantize guards the divide, dequantize
    multiplies by 0).
    """
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=(-3, -1))
    return absmax / KV_QMAX


def kv_quantize_blocks(x: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Quantize ``x`` ``[..., bs, Hkv, Dh]`` to int8 with per-(block,
    kv_head) ``scale`` ``[..., Hkv]`` (multiply convention)."""
    safe = jnp.where(scale > 0, scale, 1.0)[..., None, :, None]
    q = jnp.round(x.astype(jnp.float32) / safe)
    return jnp.clip(q, -KV_QMAX, KV_QMAX).astype(jnp.int8)


def kv_requantize_blocks(x: jnp.ndarray,
                         live: Optional[jnp.ndarray] = None
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize blocks ``x`` ``[..., bs, Hkv, Dh]``, zeroing stale token
    rows first (``live`` ``[..., bs]`` bool), so garbage from a block's
    previous owner never inflates the absmax. Returns ``(q, scale)``.
    """
    x = x.astype(jnp.float32)
    if live is not None:
        x = jnp.where(live[..., None, None], x, 0.0)
    scale = kv_block_scales(x)
    return kv_quantize_blocks(x, scale), scale


def kv_dequantize_blocks(q: jnp.ndarray,
                         scale: jnp.ndarray,
                         dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of :func:`kv_quantize_blocks`: ``q * scale`` broadcast
    back over ``[..., bs, Hkv, Dh]``."""
    out = q.astype(jnp.float32) * scale[..., None, :, None]
    return out.astype(dtype)
