"""SparseSelfAttention module + HF-style integration helpers.

Capability equivalent of the reference's module layer
(ref: deepspeed/ops/sparse_attention/sparse_self_attention.py:13
SparseSelfAttention, bert_sparse_self_attention.py:9, and
sparse_attention_utils.py pad/unpad helpers).

Framework convention: tensors are [B, S, H, D] (the reference uses
[B, H, S, D]); masks follow the reference's modes — key_padding_mask is
[B, S] ('add' = additive float, 'mul' = multiplicative 0/1), attn_mask
is [S, S].
"""

from typing import Optional

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.blocksparse import (
    blocksparse_attention, make_lut)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    FixedSparsityConfig, SparsityConfig)


class SparseSelfAttention:
    """Scaled-dot-product attention restricted to a block-sparse layout.

    The layout (and its gather LUT) is built host-side once per sequence
    length and cached; the device only ever runs the sparse kernel.
    """

    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul",
                 max_seq_length: int = 2048):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(
            num_heads=4)
        if key_padding_mask_mode not in ("add", "mul"):
            raise ValueError("key_padding_mask_mode must be 'add' or 'mul'")
        if attn_mask_mode not in ("add", "mul"):
            raise ValueError("attn_mask_mode must be 'add' or 'mul'")
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        self._cache = {}

    def layout_for(self, seq_len: int):
        """(layout, lut, valid) for this sequence length, cached."""
        if seq_len not in self._cache:
            layout = self.sparsity_config.make_layout(seq_len)
            lut, valid = make_lut(layout)
            self._cache[seq_len] = (layout, lut, valid)
        return self._cache[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None):
        B, S, H, D = query.shape
        if H != self.sparsity_config.num_heads:
            raise ValueError(
                f"input has {H} heads, config expects "
                f"{self.sparsity_config.num_heads}")
        if S > self.max_seq_length:
            raise ValueError(
                f"sequence length {S} exceeds max_seq_length "
                f"{self.max_seq_length}")
        layout, lut, valid = self.layout_for(S)
        causal = getattr(self.sparsity_config, "attention",
                         "bidirectional") == "unidirectional"
        return blocksparse_attention(
            query, key, value, layout, causal=causal,
            key_padding_mask=key_padding_mask,
            key_padding_mask_mode=self.key_padding_mask_mode,
            attn_mask=attn_mask, attn_mask_mode=self.attn_mask_mode,
            rpe=rpe, lut_valid=(lut, valid))


class SparseAttentionUtils:
    """Sequence pad/unpad helpers so arbitrary-length inputs can run
    through block-aligned sparse kernels
    (ref: sparse_attention_utils.py:225 pad_to_block_size)."""

    @staticmethod
    def pad_to_block_size(block: int, input_ids=None, attention_mask=None,
                          token_type_ids=None, position_ids=None,
                          inputs_embeds=None, pad_token_id: int = 0):
        """Right-pad sequence-major arrays to a multiple of ``block``.

        Returns (pad_len, input_ids, attention_mask, token_type_ids,
        position_ids, inputs_embeds) — None entries pass through.
        """
        ref = input_ids if input_ids is not None else inputs_embeds
        if ref is None:
            raise ValueError("need input_ids or inputs_embeds")
        S = ref.shape[1]
        pad_len = (-S) % block
        if pad_len == 0:
            return (0, input_ids, attention_mask, token_type_ids,
                    position_ids, inputs_embeds)

        def pad1(x, value=0):
            if x is None:
                return None
            widths = [(0, 0), (0, pad_len)] + [(0, 0)] * (x.ndim - 2)
            return jnp.pad(x, widths, constant_values=value)

        return (pad_len,
                pad1(input_ids, pad_token_id),
                pad1(attention_mask, 0),
                pad1(token_type_ids, 0),
                pad1(position_ids, 0),
                pad1(inputs_embeds, 0))

    @staticmethod
    def unpad_sequence_output(pad_len: int, sequence_output):
        """Strip the padding added by pad_to_block_size."""
        if pad_len == 0:
            return sequence_output
        return sequence_output[:, :-pad_len]


def sparse_density(layout: np.ndarray) -> float:
    """Fraction of active blocks — the advertised compute saving."""
    layout = np.asarray(layout)
    return float(layout.sum()) / layout.size


def build_sparsity_config(sa_cfg, num_heads: int) -> SparsityConfig:
    """Instantiate a SparsityConfig from the engine's ``sparse_attention``
    config section (ref: deepspeed/runtime/config.py get_sparse_attention
    mode dispatch)."""
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
        BigBirdSparsityConfig, BSLongformerSparsityConfig,
        DenseSparsityConfig, VariableSparsityConfig)
    mode = sa_cfg.mode
    common = dict(num_heads=num_heads, block=sa_cfg.block,
                  different_layout_per_head=sa_cfg.different_layout_per_head)
    if mode == "dense":
        return DenseSparsityConfig(**common)
    if mode == "fixed":
        return FixedSparsityConfig(
            num_local_blocks=sa_cfg.num_local_blocks,
            num_global_blocks=sa_cfg.num_global_blocks,
            attention=sa_cfg.attention,
            horizontal_global_attention=sa_cfg.horizontal_global_attention,
            num_different_global_patterns=(
                sa_cfg.num_different_global_patterns),
            **common)
    if mode == "variable":
        return VariableSparsityConfig(
            num_random_blocks=sa_cfg.num_random_blocks,
            local_window_blocks=sa_cfg.local_window_blocks,
            global_block_indices=sa_cfg.global_block_indices,
            global_block_end_indices=sa_cfg.global_block_end_indices,
            attention=sa_cfg.attention,
            horizontal_global_attention=sa_cfg.horizontal_global_attention,
            **common)
    if mode == "bigbird":
        return BigBirdSparsityConfig(
            num_random_blocks=sa_cfg.num_random_blocks,
            num_sliding_window_blocks=sa_cfg.num_sliding_window_blocks,
            num_global_blocks=sa_cfg.num_global_blocks,
            **common)
    if mode == "bslongformer":
        return BSLongformerSparsityConfig(
            num_sliding_window_blocks=sa_cfg.num_sliding_window_blocks,
            global_block_indices=sa_cfg.global_block_indices,
            global_block_end_indices=sa_cfg.global_block_end_indices,
            **common)
    raise ValueError(f"unknown sparse attention mode: {mode}")
