"""Block-sparse attention (ref: deepspeed/ops/sparse_attention/)."""

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    SparsityConfig, DenseSparsityConfig, FixedSparsityConfig,
    VariableSparsityConfig, BigBirdSparsityConfig,
    BSLongformerSparsityConfig)
from deepspeed_tpu.ops.sparse_attention.blocksparse import (
    blocksparse_attention, blocksparse_attention_jnp,
    blocksparse_attention_kernel, blocksparse_reference, make_lut)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseSelfAttention, SparseAttentionUtils, sparse_density,
    build_sparsity_config)

__all__ = [
    "SparsityConfig", "DenseSparsityConfig", "FixedSparsityConfig",
    "VariableSparsityConfig", "BigBirdSparsityConfig",
    "BSLongformerSparsityConfig", "blocksparse_attention",
    "blocksparse_attention_jnp", "blocksparse_attention_kernel",
    "blocksparse_reference", "make_lut", "SparseSelfAttention",
    "SparseAttentionUtils", "sparse_density",
]
