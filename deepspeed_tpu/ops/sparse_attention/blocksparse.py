"""Block-sparse attention for TPU.

Capability equivalent of the reference's Triton block-sparse kernels
(ref: deepspeed/ops/sparse_attention/matmul.py:214 _sparse_matmul /
softmax.py:146 + csrc/sparse_attention/utils.cpp:14 segment_blocks).

TPU-first design: instead of SDD/DSD/DDS matmuls over a CSR-ish layout,
the host compiles the [H, nb, nb] block layout into a gather LUT — for
every (head, query-block-row) the list of active key blocks, padded to
the max row population. Compute is then:

- a Pallas kernel (splash-attention style): grid (B, H, q-block, lut-slot)
  with the LUT scalar-prefetched so the BlockSpec index_map fetches
  exactly the active K/V blocks from HBM; online softmax in VMEM scratch.
  Work is O(S * max_nnz_row * block) — the full sparse speedup.
- a pure-jnp gather path with identical semantics used for grads (the
  Pallas backward recomputes through it) and as the mask-supporting /
  non-TPU fallback. Also O(active blocks), and differentiable.

Both paths never materialize the [S, S] score matrix.
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax<=0.4.x spells it TPUCompilerParams
_CompilerParams = getattr(pltpu, 'CompilerParams', None) \
    or pltpu.TPUCompilerParams

NEG_INF = -1e30
LANES = 128


def make_lut(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Compile a [H, nb, nb] 0/1 layout into (lut, valid).

    lut   : int32 [H, nb, L] — active key-block index per slot (0-padded)
    valid : bool  [H, nb, L] — slot validity

    L = max active blocks in any (head, row). This is the TPU analog of
    the reference's segment_blocks LUT (csrc/sparse_attention/utils.cpp:14).
    """
    layout = np.asarray(layout)
    H, nb, _ = layout.shape
    counts = layout.sum(-1)
    L = max(1, int(counts.max()))
    lut = np.zeros((H, nb, L), dtype=np.int32)
    valid = np.zeros((H, nb, L), dtype=bool)
    for h in range(H):
        for r in range(nb):
            cols = np.nonzero(layout[h, r])[0]
            lut[h, r, :len(cols)] = cols
            valid[h, r, :len(cols)] = True
    return lut, valid


# ---------------------------------------------------------------------------
# pure-jnp gather path (differentiable; supports masks)
# ---------------------------------------------------------------------------

def _gather_blocks(xb: jnp.ndarray, lut: jnp.ndarray) -> jnp.ndarray:
    """xb [B, H, nb, bk, D], lut [H, nq, L] -> [B, H, nq, L, bk, D]."""
    return jax.vmap(lambda xh, luth: xh[:, luth],
                    in_axes=(1, 0), out_axes=1)(xb, lut)


def blocksparse_attention_jnp(q, k, v, lut, valid, block: int,
                              causal: bool = False,
                              scale: Optional[float] = None,
                              key_padding_mask=None,
                              key_padding_mask_mode: str = "add",
                              attn_mask=None,
                              attn_mask_mode: str = "mul",
                              rpe=None):
    """Gather-based block-sparse attention over [B, S, H, D] tensors."""
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    nb = S // block
    L = lut.shape[-1]
    qb = q.transpose(0, 2, 1, 3).reshape(B, H, nb, block, D)
    kb = k.transpose(0, 2, 1, 3).reshape(B, H, nb, block, D)
    vb = v.transpose(0, 2, 1, 3).reshape(B, H, nb, block, D)
    lut = jnp.asarray(lut)
    valid = jnp.asarray(valid)

    kg = _gather_blocks(kb, lut)                    # [B,H,nb,L,bk,D]
    vg = _gather_blocks(vb, lut)

    s = jnp.einsum("bhqid,bhqlkd->bhqilk", qb, kg,
                   preferred_element_type=jnp.float32) * scale
    # global row/col token ids for masking
    row_ids = (jnp.arange(nb)[:, None] * block +
               jnp.arange(block)[None, :])          # [nb, bq]
    col_ids = lut[..., None] * block + jnp.arange(block)  # [H,nb,L,bk]

    keep = jnp.broadcast_to(valid[None, :, :, None, :, None],
                            s.shape)
    if causal:
        cm = (row_ids[None, :, :, None, None] >=
              col_ids[:, :, None, :, :])            # [H,nb,bq,L,bk]
        keep = keep & cm[None]
    if attn_mask is not None:
        am = jnp.asarray(attn_mask)
        amg = am[row_ids[None, :, :, None, None],
                 col_ids[:, :, None, :, :]]         # [H,nb,bq,L,bk]
        if attn_mask_mode == "mul":
            keep = keep & (amg[None] != 0)
        else:
            s = s + amg[None].astype(jnp.float32)
    if rpe is not None:
        # relative-position bias [S, S], always additive
        rp = jnp.asarray(rpe)
        rpg = rp[row_ids[None, :, :, None, None],
                 col_ids[:, :, None, :, :]]
        s = s + rpg[None].astype(jnp.float32)
    if key_padding_mask is not None:
        kp = jnp.asarray(key_padding_mask)          # [B, S]
        kpg = kp[:, col_ids]                        # [B,H,nb,L,bk]
        if key_padding_mask_mode == "mul":
            keep = keep & (kpg[:, :, :, None] != 0)
        else:
            s = s + kpg[:, :, :, None].astype(jnp.float32)

    s = jnp.where(keep, s, NEG_INF)
    sf = s.reshape(B, H, nb, block, L * block)
    keepf = keep.reshape(sf.shape)
    m = jnp.max(sf, axis=-1, keepdims=True)
    # rows with no active key produce all-NEG_INF: emit zeros
    p = jnp.exp(sf - jax.lax.stop_gradient(m)) * keepf
    denom = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.where(denom == 0.0, 1.0, denom)
    p = p.reshape(B, H, nb, block, L, block).astype(q.dtype)
    out = jnp.einsum("bhqilk,bhqlkd->bhqid", p, vg)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# Pallas forward kernel (LUT scalar-prefetched)
# ---------------------------------------------------------------------------

def _bs_fwd_kernel(lut_ref, nnz_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scratch, l_scratch, acc_scratch,
                   *, causal: bool, scale: float, block: int, num_l: int):
    h = pl.program_id(1)
    qi = pl.program_id(2)
    li = pl.program_id(3)

    @pl.when(li == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    ki = lut_ref[h, qi, li]
    active = li < nnz_ref[h, qi]

    @pl.when(active)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + qi * block
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + ki * block
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_scratch[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # fully-masked rows: s - m_new would be 0 everywhere; zero them so
        # the kernel matches the jnp path's "no active key -> zeros" output
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scratch[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(li == num_l - 1)
    def _finish():
        l = l_scratch[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l_safe).astype(o_ref.dtype)


def _bs_pallas_fwd(q, k, v, lut, nnz, block, causal, scale):
    """q/k/v [B, H, S, D] (kernel layout); lut [H, nb, L], nnz [H, nb]."""
    B, H, S, D = q.shape
    nb = S // block
    L = lut.shape[-1]

    def qmap(b, h, qi, li, lut_ref, nnz_ref):
        return (b, h, qi, 0)

    def kvmap(b, h, qi, li, lut_ref, nnz_ref):
        return (b, h, lut_ref[h, qi, li], 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nb, L),
        in_specs=[
            pl.BlockSpec((1, 1, block, D), qmap),
            pl.BlockSpec((1, 1, block, D), kvmap),
            pl.BlockSpec((1, 1, block, D), kvmap),
        ],
        out_specs=pl.BlockSpec((1, 1, block, D), qmap),
        scratch_shapes=[
            pltpu.VMEM((block, LANES), jnp.float32),
            pltpu.VMEM((block, LANES), jnp.float32),
            pltpu.VMEM((block, D), jnp.float32),
        ],
    )
    kernel = functools.partial(_bs_fwd_kernel, causal=causal, scale=scale,
                               block=block, num_l=L)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
    )(jnp.asarray(lut), jnp.asarray(nnz), q, k, v)


def _ceil_to(x, m):
    return (x + m - 1) // m * m


# one custom_vjp function per (layout, block, causal, scale, D) — cached so
# repeated eager calls reuse the same traced/compiled function object
_KERNEL_CACHE = {}


def _get_kernel_fn(lut, valid, block, causal, scale, D):
    key = (lut.tobytes(), lut.shape, block, causal, float(scale), D)
    if key in _KERNEL_CACHE:
        return _KERNEL_CACHE[key]
    nnz = np.asarray(valid).sum(-1).astype(np.int32)
    Dp = _ceil_to(D, LANES)

    @jax.custom_vjp
    def f(q, k, v):
        qt = q.transpose(0, 2, 1, 3)
        kt = k.transpose(0, 2, 1, 3)
        vt = v.transpose(0, 2, 1, 3)
        if Dp != D:
            pad = [(0, 0), (0, 0), (0, 0), (0, Dp - D)]
            qt, kt, vt = jnp.pad(qt, pad), jnp.pad(kt, pad), jnp.pad(vt, pad)
        o = _bs_pallas_fwd(qt, kt, vt, lut, nnz, block, causal, scale)
        return o[..., :D].transpose(0, 2, 1, 3)

    def fwd(q, k, v):
        return f(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda a, b, c: blocksparse_attention_jnp(
                a, b, c, lut, valid, block, causal=causal, scale=scale),
            q, k, v)
        return vjp(g)

    f.defvjp(fwd, bwd)
    _KERNEL_CACHE[key] = f
    return f


def blocksparse_attention_kernel(q, k, v, lut, valid, block: int,
                                 causal: bool = False,
                                 scale: Optional[float] = None):
    """Pallas block-sparse attention over [B, S, H, D]; grads recompute
    through the jnp gather path (same math, exact VJP)."""
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    lut = np.asarray(lut, dtype=np.int32)
    valid = np.asarray(valid)
    return _get_kernel_fn(lut, valid, block, causal, scale, D)(q, k, v)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def blocksparse_attention(q, k, v, layout, causal: bool = False,
                          scale: Optional[float] = None,
                          key_padding_mask=None,
                          key_padding_mask_mode: str = "add",
                          attn_mask=None, attn_mask_mode: str = "mul",
                          rpe=None,
                          use_kernel: Optional[bool] = None,
                          lut_valid: Optional[Tuple] = None):
    """Block-sparse attention over [B, S, H, D] with a [H, nb, nb] layout.

    The Pallas kernel path is used on TPU when no element-wise masks are
    given; otherwise the jnp gather path (same complexity) runs.
    ``lut_valid`` lets callers pass a pre-compiled ``make_lut`` result.
    """
    B, S, H, D = q.shape
    layout = np.asarray(layout)
    nb = layout.shape[1]
    if S % nb != 0:
        raise ValueError(f"seq len {S} not divisible by layout blocks {nb}")
    block = S // nb
    lut, valid = lut_valid if lut_valid is not None else make_lut(layout)
    if use_kernel is None:
        use_kernel = (jax.default_backend() == "tpu"
                      and key_padding_mask is None and attn_mask is None
                      and rpe is None and block % 8 == 0)
    if use_kernel:
        return blocksparse_attention_kernel(q, k, v, lut, valid, block,
                                            causal=causal, scale=scale)
    return blocksparse_attention_jnp(
        q, k, v, lut, valid, block, causal=causal, scale=scale,
        key_padding_mask=key_padding_mask,
        key_padding_mask_mode=key_padding_mask_mode,
        attn_mask=attn_mask, attn_mask_mode=attn_mask_mode, rpe=rpe)


def blocksparse_reference(q, k, v, layout, causal: bool = False,
                          scale: Optional[float] = None,
                          key_padding_mask=None,
                          key_padding_mask_mode: str = "add",
                          attn_mask=None, attn_mask_mode: str = "mul",
                          rpe=None):
    """Dense O(S^2) reference with the layout expanded to an element mask
    (parity oracle, analog of ref tests/unit/test_sparse_attention.py)."""
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    nb = layout.shape[1]
    block = S // nb
    mask = np.kron(np.asarray(layout), np.ones((block, block)))  # [H,S,S]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    keep = jnp.asarray(mask != 0)[None]
    if causal:
        keep = keep & jnp.tril(jnp.ones((S, S), bool))[None, None]
    if attn_mask is not None:
        am = jnp.asarray(attn_mask)
        if attn_mask_mode == "mul":
            keep = keep & (am != 0)[None, None]
        else:
            logits = logits + am[None, None].astype(jnp.float32)
    if key_padding_mask is not None:
        kp = jnp.asarray(key_padding_mask)
        if key_padding_mask_mode == "mul":
            keep = keep & (kp != 0)[:, None, None, :]
        else:
            logits = logits + kp[:, None, None, :].astype(jnp.float32)
    if rpe is not None:
        logits = logits + jnp.asarray(rpe)[None, None].astype(jnp.float32)
    logits = jnp.where(keep, logits, NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    p = (p / jnp.where(denom == 0.0, 1.0, denom)).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
