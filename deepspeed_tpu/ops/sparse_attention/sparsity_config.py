"""Block-sparse attention sparsity layouts.

Capability equivalent of the reference's sparsity pattern registry
(ref: deepspeed/ops/sparse_attention/sparsity_config.py:9 SparsityConfig,
:63 Dense, :94 Fixed, :243 Variable, :421 BigBird, :544 BSLongformer).

A layout is a numpy array of shape [num_heads, num_blocks, num_blocks]
with 1 where a query block attends to a key block. The reference builds
these with per-element python loops for Triton; here they are vectorized
numpy since on TPU the layout is host-side metadata compiled into a
block-gather LUT (see blocksparse.py) — the device never sees it.
"""

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base class holding properties shared by all block-sparse patterns."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence length {seq_len} must be divisible by block size "
                f"{self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks),
                        dtype=np.int64)

    def propagate_first_head(self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks active — for comparison/debug (ref :63)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


def _check_attention(attention: str, horizontal_global_attention: bool):
    if attention not in ("unidirectional", "bidirectional"):
        raise NotImplementedError(
            "only uni/bi-directional attention is supported")
    if attention != "bidirectional" and horizontal_global_attention:
        raise ValueError(
            "horizontal global attention requires bidirectional attention")


class FixedSparsityConfig(SparsityConfig):
    """'Fixed' pattern from Sparse Transformers (Child et al. 2019):
    local windows plus fixed global representative blocks (ref :94).
    """

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks ({num_local_blocks}) must be divisible by "
                f"num_global_blocks ({num_global_blocks})")
        _check_attention(attention, horizontal_global_attention)
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "multiple global patterns require different_layout_per_head")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                "num_different_global_patterns cannot exceed "
                "num_local_blocks // num_global_blocks")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        L, G = self.num_local_blocks, self.num_global_blocks
        rows = np.arange(nb)[:, None]
        cols = np.arange(nb)[None, :]
        # local windows: same window, and col<=row if unidirectional
        local = (rows // L) == (cols // L)
        if self.attention == "unidirectional":
            local &= cols <= rows
        for h in range(self.num_layout_heads):
            layout[h][local] = 1
            # global representative blocks: last G blocks of each window,
            # shifted back by the head's pattern index
            first = L - (1 + h % self.num_different_global_patterns) * G
            end = nb - nb % L
            starts = list(range(first, end, L))
            if end < nb:  # short trailing window
                starts.append(min(end + first, nb - G))
            for i in starts:
                first_row = 0 if self.attention == "bidirectional" else i
                layout[h, first_row:, i:i + G] = 1
                if self.horizontal_global_attention:
                    layout[h, i:i + G, :] = 1
        return self.propagate_first_head(layout)


class VariableSparsityConfig(SparsityConfig):
    """Fixed-pattern generalization: random blocks + per-window sizes +
    user-chosen global block indices/ranges (ref :243)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        local_window_blocks = local_window_blocks or [4]
        global_block_indices = (global_block_indices
                                if global_block_indices is not None else [0])
        if global_block_end_indices is not None:
            if len(global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global block start/end index lists must be same length")
            for s, e in zip(global_block_indices, global_block_end_indices):
                if s >= e:
                    raise ValueError(
                        f"global block start {s} must be < end {e}")
        _check_attention(attention, horizontal_global_attention)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks
        self.global_block_indices = global_block_indices
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def _set_random(self, h: int, layout: np.ndarray, rng) -> None:
        nb = layout.shape[1]
        if nb < self.num_random_blocks:
            raise ValueError(
                f"num_random_blocks ({self.num_random_blocks}) must be <= "
                f"number of block rows ({nb})")
        for row in range(nb):
            cols = rng.choice(nb, size=self.num_random_blocks, replace=False)
            layout[h, row, cols] = 1

    def _set_local(self, h: int, layout: np.ndarray) -> None:
        nb = layout.shape[1]
        # explicit windows first, then repeat the last size for the remainder
        start, idx = 0, 0
        while start < nb:
            size = self.local_window_blocks[
                min(idx, len(self.local_window_blocks) - 1)]
            idx += 1
            if size <= 0:
                raise ValueError("local window sizes must be positive")
            end = min(start + size, nb)
            blk = layout[h, start:end, start:end]
            if self.attention == "unidirectional":
                blk |= np.tril(np.ones_like(blk))
            else:
                blk[:] = 1
            start += size

    def _set_global(self, h: int, layout: np.ndarray) -> None:
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for s, e in spans:
            if s >= nb:
                continue
            e = min(e, nb)
            first_row = 0 if self.attention == "bidirectional" else s
            layout[h, first_row:, s:e] = 1
            if self.horizontal_global_attention:
                layout[h, s:e, :] = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        rng = np.random.default_rng(self.seed)
        for h in range(self.num_layout_heads):
            self._set_random(h, layout, rng)
            self._set_local(h, layout)
            self._set_global(h, layout)
        return self.propagate_first_head(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird (Zaheer et al. 2020): random + sliding window + global
    first blocks (ITC mode) (ref :421)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        if nb < self.num_random_blocks:
            raise ValueError("num_random_blocks must be <= block rows")
        if nb < self.num_sliding_window_blocks:
            raise ValueError("num_sliding_window_blocks must be <= block rows")
        if nb < self.num_global_blocks:
            raise ValueError("num_global_blocks must be <= block rows")
        rng = np.random.default_rng(self.seed)
        rows = np.arange(nb)[:, None]
        cols = np.arange(nb)[None, :]
        w = self.num_sliding_window_blocks // 2
        sliding = np.abs(rows - cols) <= w
        for h in range(self.num_layout_heads):
            for row in range(nb):
                rnd = rng.choice(nb, size=self.num_random_blocks,
                                 replace=False)
                layout[h, row, rnd] = 1
            layout[h][sliding] = 1
            g = self.num_global_blocks
            layout[h, :g, :] = 1
            layout[h, :, :g] = 1
        return self.propagate_first_head(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer (Beltagy et al. 2020): sliding window +
    global blocks at chosen indices (ref :544)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None):
        super().__init__(num_heads, block, different_layout_per_head)
        global_block_indices = (global_block_indices
                                if global_block_indices is not None else [0])
        if global_block_end_indices is not None:
            if len(global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    "global block start/end index lists must be same length")
            for s, e in zip(global_block_indices, global_block_end_indices):
                if s >= e:
                    raise ValueError(
                        f"global block start {s} must be < end {e}")
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices
        self.global_block_end_indices = global_block_end_indices

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError("num_sliding_window_blocks must be <= block rows")
        rows = np.arange(nb)[:, None]
        cols = np.arange(nb)[None, :]
        w = self.num_sliding_window_blocks // 2
        sliding = np.abs(rows - cols) <= w
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for h in range(self.num_layout_heads):
            layout[h][sliding] = 1
            for s, e in spans:
                if s >= nb:
                    continue
                e = min(e, nb)
                layout[h, s:e, :] = 1
                layout[h, :, s:e] = 1
        return self.propagate_first_head(layout)
