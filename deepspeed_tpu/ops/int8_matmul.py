"""Fused int8 weight-dequant matmul (Pallas).

The reference ships dedicated int8 GEMM + dequant inference kernels
(ref: csrc/transformer/inference/csrc/pt_binding.cpp:866 qkv_gemm/
mlp_gemm int8 variants, csrc/transformer/inference/csrc/dequantize.cu).
Here weight-only int8 serving normally leans on XLA to fuse
``q.astype(bf16) * scale`` into the consuming matmul
(models/gpt.py _kernel_of) — bandwidth-bound and usually fused. This
kernel is the guaranteed-fused fallback (VERDICT r4 weak #6): the int8
weight is the ONLY weight HBM traffic (1 byte/param), dequantized in
VMEM tiles on the way into the MXU, fp32 accumulation over K tiles,
per-output-channel scale applied once at the end.

Enable in serving with DS_INT8_FUSED=1 (inference/engine.py wires it
through gpt._dense); ``tools/infer_bench.py`` measures fused vs
XLA-dequant so the flag only ships where it wins.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax<=0.4.x spells it TPUCompilerParams
_CompilerParams = getattr(pltpu, 'CompilerParams', None) \
    or pltpu.TPUCompilerParams


def _dq_matmul_kernel(x_ref, q_ref, s_ref, o_ref, acc, *, num_k: int):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    x = x_ref[...]                                   # [bm, bk] compute dtype
    w = q_ref[...].astype(x.dtype)                   # [bk, bn] int8 -> bf16
    acc[:] += jax.lax.dot(x, w, preferred_element_type=jnp.float32)

    @pl.when(ki == num_k - 1)
    def _done():
        o_ref[:] = (acc[:] * s_ref[...].astype(jnp.float32)) \
            .astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_m", "block_n",
                                             "block_k", "interpret"))
def int8_matmul(x: jnp.ndarray, q: jnp.ndarray, scale: jnp.ndarray,
                block_m: int = 256, block_n: int = 512,
                block_k: int = 512, interpret: bool = False) -> jnp.ndarray:
    """``x [M, K] @ dequant(q [K, N], scale [1, N]) -> [M, N]`` with the
    weight read from HBM as int8. M is padded up to a tile internally;
    K and N must divide by their blocks (model dims are 128-multiples).
    """
    M, K = x.shape
    Kq, N = q.shape
    assert K == Kq, (x.shape, q.shape)
    scale = scale.reshape(1, N)
    block_m = min(block_m, max(8, M))
    block_k = min(block_k, K)
    block_n = min(block_n, N)
    assert K % block_k == 0 and N % block_n == 0, (K, N, block_k, block_n)
    Mp = -(-M // block_m) * block_m
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))
    grid = (Mp // block_m, N // block_n, K // block_k)
    out = pl.pallas_call(
        functools.partial(_dq_matmul_kernel, num_k=grid[2]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda mi, ni, ki: (mi, ki)),
            pl.BlockSpec((block_k, block_n), lambda mi, ni, ki: (ki, ni)),
            pl.BlockSpec((1, block_n), lambda mi, ni, ki: (0, ni)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda mi, ni, ki: (mi, ni)),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((Mp, N), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, q, scale)
    return out[:M] if Mp != M else out


def int8_matmul_reference(x: jnp.ndarray, q: jnp.ndarray,
                          scale: jnp.ndarray) -> jnp.ndarray:
    """The XLA-fusion path this kernel replaces (gpt._kernel_of)."""
    return x @ (q.astype(x.dtype) * scale.astype(x.dtype))


def fit_blocks(K: int, N: int, want_k: int = 512, want_n: int = 512,
               align: int = 128):
    """Largest lane-aligned tile sizes dividing (K, N), capped at the
    requested sizes — or None when a dim is not even ``align``-divisible
    (e.g. a raw-vocab lm_head), in which case callers fall back to the
    XLA dequant path instead of crashing mid-trace (model dims like
    llama-7b's d_ff=11008 are 128-multiples but NOT 512-multiples)."""
    def fit(dim, want):
        if dim % align:
            return None
        units = dim // align
        for u in range(min(want // align, units), 0, -1):
            if units % u == 0:
                return u * align
        return None

    bk, bn = fit(K, want_k), fit(N, want_n)
    return None if bk is None or bn is None else (bk, bn)
