"""LAMB optimizer.

Capability equivalent of the reference's fused LAMB CUDA kernel
(ref: csrc/lamb/fused_lamb_cuda_kernel.cu, deepspeed/ops/lamb/fused_lamb.py:12).
The per-tensor trust-ratio reductions that the CUDA kernel computes with a
two-pass block reduction are plain jnp reductions here; XLA fuses the whole
update into one pass per tensor, matching the fused kernel's purpose.
"""

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.ops.adam import ScaleByAdamState, _scale_by_learning_rate


def scale_by_lamb_trust_ratio(b1: float = 0.9, b2: float = 0.999,
                              eps: float = 1e-6, weight_decay: float = 0.0,
                              max_coeff: float = 10.0,
                              min_coeff: float = 0.01) -> optax.GradientTransformation:
    """Adam moments + per-tensor trust ratio (LAMB), with the reference's
    max/min coefficient clamps (ref: fused_lamb.py:16 max_coeff/min_coeff)."""

    def init_fn(params):
        mu = jax.tree_util.tree_map(jnp.zeros_like, params)
        nu = jax.tree_util.tree_map(jnp.zeros_like, params)
        return ScaleByAdamState(count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update_fn(updates, state, params):
        assert params is not None, "LAMB requires params for the trust ratio"
        count = state.count + 1
        mu = jax.tree_util.tree_map(
            lambda g, m: b1 * m + (1 - b1) * g.astype(jnp.float32), updates, state.mu)
        nu = jax.tree_util.tree_map(
            lambda g, v: b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32)),
            updates, state.nu)
        c = count.astype(jnp.float32)
        bc1 = 1 - b1 ** c
        bc2 = 1 - b2 ** c

        def lamb_update(m, v, p):
            update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay > 0.0:
                update = update + weight_decay * p.astype(jnp.float32)
            w_norm = jnp.linalg.norm(p.astype(jnp.float32))
            u_norm = jnp.linalg.norm(update)
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, min_coeff, max_coeff),
                1.0)
            return trust * update

        new_updates = jax.tree_util.tree_map(lamb_update, mu, nu, params)
        return new_updates, ScaleByAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


ScheduleOrFloat = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


def fused_lamb(learning_rate: ScheduleOrFloat, b1: float = 0.9, b2: float = 0.999,
               eps: float = 1e-6, weight_decay: float = 0.0,
               max_coeff: float = 10.0,
               min_coeff: float = 0.01) -> optax.GradientTransformation:
    return optax.chain(
        scale_by_lamb_trust_ratio(b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
                                  max_coeff=max_coeff, min_coeff=min_coeff),
        _scale_by_learning_rate(learning_rate),
    )
