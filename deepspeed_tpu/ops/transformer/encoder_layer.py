"""Fused BERT-style transformer encoder layer.

Capability match for the reference's transformer training kernel
(ref: csrc/transformer/ds_transformer_cuda.cpp + the python module
deepspeed/ops/transformer/transformer.py:460 DeepSpeedTransformerLayer,
config :22 DeepSpeedTransformerConfig). The reference hand-fuses QKV
GEMM, softmax, dropout, layernorm and GELU into CUDA kernels; on TPU
the layer is written as straight jax — XLA fuses the elementwise chain
into the GEMMs — with the attention core dispatched to the Pallas flash
kernel when no padding mask is present (the kernel computes full
attention; masked batches take the jnp softmax path, whose masking
fuses too).

Supports both residual placements the reference ships parity models for
(post-LN `tests/unit/modeling.py`, pre-LN `modelingpreln.py`) via
``pre_layer_norm``.
"""

from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp


@dataclass
class DeepSpeedTransformerConfig:
    """(ref: ops/transformer/transformer.py:22) the knobs that affect
    math; kernel-scheduling knobs of the CUDA version (stochastic_mode,
    attn_dropout_checkpoint, ...) dissolve under XLA."""
    batch_size: int = -1          # unused: shapes are traced (API parity)
    hidden_size: int = 256
    intermediate_size: int = -1   # defaults to 4*hidden
    heads: int = 4
    attn_dropout_ratio: float = 0.1
    hidden_dropout_ratio: float = 0.1
    num_hidden_layers: int = -1
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = True
    fp16: bool = False            # API parity; dtype follows inputs

    def __post_init__(self):
        if self.intermediate_size <= 0:
            self.intermediate_size = 4 * self.hidden_size
        assert self.hidden_size % self.heads == 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.heads


def init_layer_params(rng: jax.Array, cfg: DeepSpeedTransformerConfig,
                      dtype=jnp.float32) -> Dict:
    h, ff = cfg.hidden_size, cfg.intermediate_size
    k = jax.random.split(rng, 4)
    s = 0.02
    return {
        "qkv": {"kernel": jax.random.normal(k[0], (h, 3 * h), dtype) * s,
                "bias": jnp.zeros((3 * h,), dtype)},
        "attn_out": {"kernel": jax.random.normal(k[1], (h, h), dtype) * s,
                     "bias": jnp.zeros((h,), dtype)},
        "mlp_in": {"kernel": jax.random.normal(k[2], (h, ff), dtype) * s,
                   "bias": jnp.zeros((ff,), dtype)},
        "mlp_out": {"kernel": jax.random.normal(k[3], (ff, h), dtype) * s,
                    "bias": jnp.zeros((h,), dtype)},
        "ln1": {"scale": jnp.ones((h,), dtype), "bias": jnp.zeros((h,), dtype)},
        "ln2": {"scale": jnp.ones((h,), dtype), "bias": jnp.zeros((h,), dtype)},
    }


def _layernorm(x, scale, bias, eps):
    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.var(x, axis=-1, keepdims=True)
    return ((x - m) * jax.lax.rsqrt(v + eps)) * scale + bias


def _dropout(x, rate, rng):
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def _attention_core(q, k, v, attn_mask, cfg, dropout_rng, deterministic,
                    allow_flash=True):
    """[B,S,H,D] attention; flash kernel when unmasked + deterministic,
    masked jnp softmax otherwise."""
    B, S, H, D = q.shape
    use_flash = (allow_flash
                 and (deterministic or cfg.attn_dropout_ratio == 0.0)
                 and S >= 128 and D % 8 == 0)
    if use_flash:
        try:
            from deepspeed_tpu.ops.attention.flash import flash_attention
            return flash_attention(q, k, v, causal=False,
                                   kv_mask=attn_mask)
        except Exception:  # dslint: disable=DS006 — flash is an optimization; fall back to the reference einsum path
            pass
    scale = 1.0 / jnp.sqrt(jnp.float32(D))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if attn_mask is not None:
        # attn_mask [B, S]: 1 = attend, 0 = padding
        bias = jnp.where(attn_mask[:, None, None, :] > 0, 0.0, -1e9)
        logits = logits + bias
    probs = jax.nn.softmax(logits, axis=-1)
    if not deterministic and cfg.attn_dropout_ratio > 0:
        probs = _dropout(probs, cfg.attn_dropout_ratio, dropout_rng)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)


def layer_forward(params: Dict, x: jnp.ndarray,
                  cfg: DeepSpeedTransformerConfig,
                  attn_mask: Optional[jnp.ndarray] = None,
                  rng: Optional[jax.Array] = None,
                  deterministic: bool = True,
                  allow_flash: bool = True) -> jnp.ndarray:
    """One encoder block. x: [B, S, H]; attn_mask: [B, S] (1=token).

    Pre-LN:  x + Attn(LN(x));  x + MLP(LN(x))
    Post-LN: LN(x + Attn(x));  LN(x + MLP(x))
    (ref: ops/transformer/transformer.py forward, pre_layer_norm branch)
    """
    B, S, h = x.shape
    H, D = cfg.heads, cfg.head_dim
    if rng is not None:
        r_attn, r_probs, r_mlp = jax.random.split(rng, 3)
    else:
        r_attn = r_probs = r_mlp = None
        deterministic = True

    def attn_block(inp):
        from jax.ad_checkpoint import checkpoint_name
        qkv = inp @ params["qkv"]["kernel"].astype(inp.dtype) + \
            params["qkv"]["bias"].astype(inp.dtype)
        qkv = checkpoint_name(qkv, "qkv")
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, H, D)
        k = k.reshape(B, S, H, D)
        v = v.reshape(B, S, H, D)
        ctx = _attention_core(q, k, v, attn_mask, cfg, r_probs,
                              deterministic,
                              allow_flash=allow_flash).reshape(B, S, h)
        ctx = checkpoint_name(ctx, "attn")
        out = ctx @ params["attn_out"]["kernel"].astype(inp.dtype) + \
            params["attn_out"]["bias"].astype(inp.dtype)
        if not deterministic and cfg.hidden_dropout_ratio > 0:
            out = _dropout(out, cfg.hidden_dropout_ratio, r_attn)
        return out

    def mlp_block(inp):
        from jax.ad_checkpoint import checkpoint_name
        mid = inp @ params["mlp_in"]["kernel"].astype(inp.dtype) + \
            params["mlp_in"]["bias"].astype(inp.dtype)
        mid = checkpoint_name(mid, "mlp_pre")
        mid = jax.nn.gelu(mid, approximate=True)
        out = mid @ params["mlp_out"]["kernel"].astype(inp.dtype) + \
            params["mlp_out"]["bias"].astype(inp.dtype)
        if not deterministic and cfg.hidden_dropout_ratio > 0:
            out = _dropout(out, cfg.hidden_dropout_ratio, r_mlp)
        return out

    eps = cfg.layer_norm_eps
    dt = x.dtype
    ln1_s = params["ln1"]["scale"].astype(dt)
    ln1_b = params["ln1"]["bias"].astype(dt)
    ln2_s = params["ln2"]["scale"].astype(dt)
    ln2_b = params["ln2"]["bias"].astype(dt)
    if cfg.pre_layer_norm:
        x = x + attn_block(_layernorm(x, ln1_s, ln1_b, eps))
        x = x + mlp_block(_layernorm(x, ln2_s, ln2_b, eps))
    else:
        x = _layernorm(x + attn_block(x), ln1_s, ln1_b, eps)
        x = _layernorm(x + mlp_block(x), ln2_s, ln2_b, eps)
    return x.astype(dt)


def layer_forward_reference(params, x, cfg, attn_mask=None):
    """Naive fp32 reference of the same math, for kernel-parity tests
    (analog of tests/unit/modeling.py vs the fused CUDA layer). Forces
    the jnp softmax path so it stays an independent oracle for the
    flash kernel."""
    p32 = jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), params)
    return layer_forward(p32, x.astype(jnp.float32), cfg,
                         attn_mask=attn_mask, deterministic=True,
                         allow_flash=False)
