from deepspeed_tpu.ops.transformer.encoder_layer import (
    DeepSpeedTransformerConfig, init_layer_params, layer_forward,
    layer_forward_reference)

__all__ = ["DeepSpeedTransformerConfig", "init_layer_params",
           "layer_forward", "layer_forward_reference"]
