"""Adam / AdamW optimizer transforms.

Capability equivalent of the reference's fused GPU Adam
(ref: csrc/adam/multi_tensor_adam.cu, deepspeed/ops/adam/fused_adam.py:16)
and the AVX CPU Adam (ref: csrc/adam/cpu_adam.cpp, ops/adam/cpu_adam.py:13).

On TPU a hand-fused Adam kernel is unnecessary for the device path: the
whole optimizer update is a handful of elementwise ops that XLA fuses into
one pass over HBM — exactly what multi_tensor_adam.cu buys on CUDA. What we
keep from the reference design:
  * bit-accurate Adam/AdamW semantics (bias correction, eps placement,
    adam_w_mode toggle — fused_adam.py:73)
  * a host (CPU) Adam path for offloaded optimizer state
    (deepspeed_tpu.runtime.zero.offload) mirroring cpu_adam's role.

Implemented as optax-style GradientTransformations so they compose with the
engine's clipping/accumulation pipeline.
"""

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax


class ScaleByAdamState(NamedTuple):
    count: jnp.ndarray
    mu: Any
    nu: Any


def scale_by_adam(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                  eps_root: float = 0.0,
                  mu_dtype: Optional[jnp.dtype] = None) -> optax.GradientTransformation:
    """Adam scaling with the reference's bias-correction form."""

    def init_fn(params):
        mu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params)
        nu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p), params)
        return ScaleByAdamState(count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        mu = jax.tree_util.tree_map(
            lambda g, m: b1 * m.astype(jnp.float32) + (1 - b1) * g.astype(jnp.float32),
            updates, state.mu)
        nu = jax.tree_util.tree_map(
            lambda g, v: b2 * v.astype(jnp.float32) + (1 - b2) * jnp.square(g.astype(jnp.float32)),
            updates, state.nu)
        c = count.astype(jnp.float32)
        bc1 = 1 - b1 ** c
        bc2 = 1 - b2 ** c
        new_updates = jax.tree_util.tree_map(
            lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2 + eps_root) + eps),
            mu, nu)
        mu = jax.tree_util.tree_map(
            lambda m, t: m.astype(mu_dtype or t.dtype), mu, state.mu)
        return new_updates, ScaleByAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


ScheduleOrFloat = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


def fused_adam(learning_rate: ScheduleOrFloat, b1: float = 0.9, b2: float = 0.999,
               eps: float = 1e-8, weight_decay: float = 0.0,
               adam_w_mode: bool = True,
               mask: Optional[Any] = None) -> optax.GradientTransformation:
    """FusedAdam equivalent (ref: ops/adam/fused_adam.py:16).

    adam_w_mode=True  -> decoupled weight decay (AdamW; ref :73 "adam_w_mode")
    adam_w_mode=False -> L2-style decay added to the gradient.
    """
    chain = []
    if not adam_w_mode and weight_decay > 0.0:
        wd = optax.add_decayed_weights(weight_decay, mask=mask)
        chain.append(wd)
    chain.append(scale_by_adam(b1=b1, b2=b2, eps=eps))
    if adam_w_mode and weight_decay > 0.0:
        chain.append(optax.add_decayed_weights(weight_decay, mask=mask))
    chain.append(_scale_by_learning_rate(learning_rate))
    return optax.chain(*chain)


def _scale_by_learning_rate(learning_rate: ScheduleOrFloat):
    if callable(learning_rate):
        return optax.scale_by_schedule(lambda count: -learning_rate(count))
    return optax.scale(-learning_rate)


def adagrad(learning_rate: ScheduleOrFloat, eps: float = 1e-8,
            weight_decay: float = 0.0) -> optax.GradientTransformation:
    """CPU-Adagrad capability equivalent (ref: csrc/adagrad/cpu_adagrad.cpp)."""
    chain = []
    if weight_decay > 0.0:
        chain.append(optax.add_decayed_weights(weight_decay))
    chain.append(optax.scale_by_rss(initial_accumulator_value=0.0, eps=eps))
    chain.append(_scale_by_learning_rate(learning_rate))
    return optax.chain(*chain)
