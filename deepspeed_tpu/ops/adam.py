"""Adam / AdamW optimizer transforms.

Capability equivalent of the reference's fused GPU Adam
(ref: csrc/adam/multi_tensor_adam.cu, deepspeed/ops/adam/fused_adam.py:16)
and the AVX CPU Adam (ref: csrc/adam/cpu_adam.cpp, ops/adam/cpu_adam.py:13).

On TPU a hand-fused Adam kernel is unnecessary for the device path: the
whole optimizer update is a handful of elementwise ops that XLA fuses into
one pass over HBM — exactly what multi_tensor_adam.cu buys on CUDA. What we
keep from the reference design:
  * bit-accurate Adam/AdamW semantics (bias correction, eps placement,
    adam_w_mode toggle — fused_adam.py:73)
  * a host (CPU) Adam path for offloaded optimizer state
    (deepspeed_tpu.runtime.zero.offload) mirroring cpu_adam's role.

Implemented as optax-style GradientTransformations so they compose with the
engine's clipping/accumulation pipeline.
"""

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax


class ScaleByAdamState(NamedTuple):
    count: jnp.ndarray
    mu: Any
    nu: Any


def scale_by_adam(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                  eps_root: float = 0.0,
                  mu_dtype: Optional[jnp.dtype] = None,
                  nu_dtype: Optional[jnp.dtype] = None) -> optax.GradientTransformation:
    """Adam scaling with the reference's bias-correction form.

    mu_dtype/nu_dtype: storage dtype for the moments (arithmetic is always
    fp32). Setting both to bfloat16 is the memory-efficient mode — 2 bytes
    per moment instead of 4, the capability that lets GPT-1.5B-class models
    keep full optimizer state in one chip's HBM.
    """

    def init_fn(params):
        mu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params)
        nu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=nu_dtype or p.dtype), params)
        return ScaleByAdamState(count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        mu = jax.tree_util.tree_map(
            lambda g, m: b1 * m.astype(jnp.float32) + (1 - b1) * g.astype(jnp.float32),
            updates, state.mu)
        nu = jax.tree_util.tree_map(
            lambda g, v: b2 * v.astype(jnp.float32) + (1 - b2) * jnp.square(g.astype(jnp.float32)),
            updates, state.nu)
        c = count.astype(jnp.float32)
        bc1 = 1 - b1 ** c
        bc2 = 1 - b2 ** c
        new_updates = jax.tree_util.tree_map(
            lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2 + eps_root) + eps),
            mu, nu)
        mu = jax.tree_util.tree_map(
            lambda m, t: m.astype(mu_dtype or t.dtype), mu, state.mu)
        nu = jax.tree_util.tree_map(
            lambda v, t: v.astype(nu_dtype or t.dtype), nu, state.nu)
        return new_updates, ScaleByAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def stochastic_round_bf16(x: jnp.ndarray, rng: jax.Array) -> jnp.ndarray:
    """Stochastically round fp32 -> bf16: add 16 uniform random low bits
    and truncate. Unbiased in expectation, which is what keeps bf16 master
    weights training (an update smaller than one bf16 ulp still lands with
    probability update/ulp — the standard TPU recipe for master-less bf16
    training; same role as the reference's fp32 masters,
    ref runtime/bf16_optimizer.py:75, met with 6x less state memory)."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    noise = jax.random.bits(rng, x.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    return jax.lax.bitcast_convert_type(
        ((bits + noise) >> 16).astype(jnp.uint16), jnp.bfloat16)


def sr_apply_updates(params, updates, rng: jax.Array):
    """optax.apply_updates with stochastic rounding into bf16 leaves;
    non-bf16 leaves get the plain fp32 add."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    ulist = jax.tree_util.tree_leaves(updates)
    outs = []
    for i, (p, u) in enumerate(zip(leaves, ulist)):
        s = p.astype(jnp.float32) + u.astype(jnp.float32)
        if p.dtype == jnp.bfloat16:
            outs.append(stochastic_round_bf16(s, jax.random.fold_in(rng, i)))
        else:
            outs.append(s.astype(p.dtype))
    return jax.tree_util.tree_unflatten(treedef, outs)


ScheduleOrFloat = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


def fused_adam(learning_rate: ScheduleOrFloat, b1: float = 0.9, b2: float = 0.999,
               eps: float = 1e-8, weight_decay: float = 0.0,
               adam_w_mode: bool = True,
               mask: Optional[Any] = None,
               state_dtype: Optional[jnp.dtype] = None) -> optax.GradientTransformation:
    """FusedAdam equivalent (ref: ops/adam/fused_adam.py:16).

    adam_w_mode=True  -> decoupled weight decay (AdamW; ref :73 "adam_w_mode")
    adam_w_mode=False -> L2-style decay added to the gradient.
    state_dtype=bfloat16 -> memory-efficient moments (see scale_by_adam).
    """
    chain = []
    if not adam_w_mode and weight_decay > 0.0:
        wd = optax.add_decayed_weights(weight_decay, mask=mask)
        chain.append(wd)
    chain.append(scale_by_adam(b1=b1, b2=b2, eps=eps,
                               mu_dtype=state_dtype, nu_dtype=state_dtype))
    if adam_w_mode and weight_decay > 0.0:
        chain.append(optax.add_decayed_weights(weight_decay, mask=mask))
    chain.append(_scale_by_learning_rate(learning_rate))
    return optax.chain(*chain)


def _scale_by_learning_rate(learning_rate: ScheduleOrFloat):
    if callable(learning_rate):
        return optax.scale_by_schedule(lambda count: -learning_rate(count))
    return optax.scale(-learning_rate)


def adagrad(learning_rate: ScheduleOrFloat, eps: float = 1e-8,
            weight_decay: float = 0.0) -> optax.GradientTransformation:
    """CPU-Adagrad capability equivalent (ref: csrc/adagrad/cpu_adagrad.cpp)."""
    chain = []
    if weight_decay > 0.0:
        chain.append(optax.add_decayed_weights(weight_decay))
    chain.append(optax.scale_by_rss(initial_accumulator_value=0.0, eps=eps))
    chain.append(_scale_by_learning_rate(learning_rate))
    return optax.chain(*chain)
