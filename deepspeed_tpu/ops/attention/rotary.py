"""Rotary position embeddings (GPT-J / GPT-NeoX convention).

Capability analog of the reference's rotary inference kernel
(ref: csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu, driven from
ops/transformer/inference/transformer_inference.py). TPU-native: a few
fused elementwise ops — XLA folds them into the surrounding attention
matmuls, so no custom kernel is warranted (bandwidth-bound, zero reuse).

GPT-J uses the interleaved ("rotate every two") layout on the first
``rotary_dim`` channels of each head; remaining channels pass through.
"""

from typing import Optional, Tuple

import jax.numpy as jnp


def _rotate_every_two(x: jnp.ndarray) -> jnp.ndarray:
    x1 = x[..., ::2]
    x2 = x[..., 1::2]
    return jnp.stack((-x2, x1), axis=-1).reshape(x.shape)


def rotary_sin_cos(positions: jnp.ndarray, rotary_dim: int,
                   base: float = 10000.0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """positions [S] or [B, S] -> (sin, cos), each
    ``positions.shape + (rotary_dim,)`` (interleaved pairs)."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, rotary_dim, 2,
                                          dtype=jnp.float32) / rotary_dim))
    ang = positions.astype(jnp.float32)[..., None] * inv_freq
    sin = jnp.repeat(jnp.sin(ang), 2, axis=-1)
    cos = jnp.repeat(jnp.cos(ang), 2, axis=-1)
    return sin, cos


def apply_rotary(q: jnp.ndarray, k: jnp.ndarray,
                 positions: jnp.ndarray,
                 rotary_dim: Optional[int] = None,
                 base: float = 10000.0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Rotate q, k ([B, S, H, D]) by position; positions is [S] absolute,
    or [B, S] for per-row positions (left-padded / packed batches)."""
    D = q.shape[-1]
    rd = D if rotary_dim is None else rotary_dim
    sin, cos = rotary_sin_cos(positions, rd, base)
    if positions.ndim == 1:            # [S, rd] -> [1, S, 1, rd]
        sin, cos = sin[None], cos[None]
    sin = sin[:, :, None, :].astype(q.dtype)
    cos = cos[:, :, None, :].astype(q.dtype)

    def rot(t):
        t_rot = t[..., :rd] * cos + _rotate_every_two(t[..., :rd]) * sin
        if rd == D:
            return t_rot
        return jnp.concatenate([t_rot, t[..., rd:]], axis=-1)

    return rot(q), rot(k)
