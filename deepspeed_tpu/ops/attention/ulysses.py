"""Ulysses-style all-to-all sequence parallelism.

The reference version has NO sequence parallelism (SURVEY §2.2 — absent at
v0.6.4; DeepSpeed-Ulysses is the lineage's later answer). This is the
TPU-native equivalent: where ring attention (ops/attention/ring.py)
rotates K/V blocks around the ICI ring, Ulysses re-shards with two
all-to-alls so every device runs a FULL-sequence attention over a slice
of the heads:

- activations arrive sharded on the sequence dim: [B, S/sp, H, D];
- all-to-all #1 swaps the shard dim: seq -> heads, giving every device
  the whole sequence for H/sp heads;
- local attention (the Pallas flash kernel when eligible — full sequence
  locally means the fused kernel applies unchanged);
- all-to-all #2 swaps back: heads -> seq.

Trade-off vs ring: 2 all-to-alls of activation size per attention call
(O(B·S·d/sp) bytes each, constant in sp) instead of sp ppermute hops of
K/V; attention compute is perfectly balanced even for causal masks
(ring's lower-triangle causes stage imbalance), and the unmodified
single-device kernel runs inside. Requires the sp degree to divide the
head count — for GQA, BOTH head counts (the local kernel keeps the
global q/kv group ratio).
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from deepspeed_tpu.utils.jax_compat import axis_size, shard_map


def _ulysses_local(q, k, v, segs, mask, *, axis: str, causal: bool,
                   scale: float, use_flash: bool, block_q: int,
                   block_kv: int, window: Optional[int],
                   bwd_block_q: Optional[int], bwd_block_kv: Optional[int],
                   window_impl: Optional[str] = None):
    """Inside shard_map: q local [B, S_loc, H, D]; k/v may carry Hkv < H
    heads (GQA) -> out [B, S_loc, H, D]. segs/mask: [B, S_loc] or None."""
    sp = axis_size(axis)
    B, S_loc, H, D = q.shape
    Hkv = k.shape[2]
    assert H % sp == 0, f"n_heads {H} not divisible by sp degree {sp}"
    assert Hkv % sp == 0, \
        f"kv heads {Hkv} not divisible by sp degree {sp} (GQA + Ulysses " \
        "needs both head counts divisible)"

    # seq-sharded -> head-sharded: [B, S_loc, H, D] -> [B, S, H/sp, D]
    def seq2head(x):
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    def head2seq(x):
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
    # per-token metadata (packed segment ids, kv validity) must cover the
    # FULL sequence the local kernel now sees — an all-gather of [B, S]
    # ints is noise next to the qkv all-to-alls (reference capability
    # analog: block-sparse long-seq, ref ops/sparse_attention/matmul.py)
    full_segs = (None if segs is None else
                 jax.lax.all_gather(segs, axis, axis=1, tiled=True))
    full_mask = (None if mask is None else
                 jax.lax.all_gather(mask, axis, axis=1, tiled=True))

    if use_flash:
        from deepspeed_tpu.ops.attention.flash import flash_attention
        out = flash_attention(qh, kh, vh, causal=causal, scale=scale,
                              block_q=block_q, block_kv=block_kv,
                              segment_ids=full_segs, kv_mask=full_mask,
                              window=window, window_impl=window_impl,
                              bwd_block_q=bwd_block_q,
                              bwd_block_kv=bwd_block_kv)
    else:
        from deepspeed_tpu.ops.attention.flash import mha_reference
        out = mha_reference(qh, kh, vh, causal=causal, scale=scale,
                            segment_ids=full_segs, kv_mask=full_mask,
                            window=window)

    return head2seq(out)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Mesh, *, causal: bool = True,
                      scale: Optional[float] = None,
                      axis: str = "sequence",
                      use_flash: bool = False,
                      block_q: int = 512,
                      block_kv: int = 512,
                      segment_ids: Optional[jnp.ndarray] = None,
                      kv_mask: Optional[jnp.ndarray] = None,
                      window: Optional[int] = None,
                      bwd_block_q: Optional[int] = None,
                      bwd_block_kv: Optional[int] = None,
                      window_impl: Optional[str] = None) -> jnp.ndarray:
    """Exact (causal) attention with the sequence dim sharded over ``axis``
    via head<->sequence all-to-alls. q,k,v: [B, S, H, D] global arrays.

    Packed sequences (segment_ids), key-validity masks (kv_mask) and
    sliding windows compose with the sequence sharding: heads stay whole
    per rank, so after the seq->head all-to-all the local flash kernel
    sees full rows and applies the masks exactly as in the unsharded
    case. (Ring SP composes with the same features by a different route
    — per-token metadata rotates with its K/V block; see
    ops/attention/ring.py. Trade-off: Ulysses is perfectly
    load-balanced under causal masks and needs sp | heads; the ring has
    no head-divisibility constraint, rotates only the small grouped K/V
    under GQA, and stops early under sliding windows.)
    """
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    inner = partial(_ulysses_local, axis=axis, causal=causal, scale=scale,
                    use_flash=use_flash, block_q=block_q, block_kv=block_kv,
                    window=window, bwd_block_q=bwd_block_q,
                    bwd_block_kv=bwd_block_kv, window_impl=window_impl)
    spec = P(None, axis, None, None)
    tok_spec = P(None, axis)
    args = [q, k, v]
    in_specs = [spec, spec, spec]
    for extra in (segment_ids, kv_mask):
        args.append(extra)
        in_specs.append(None if extra is None else tok_spec)
    mapped = shard_map(
        inner, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=spec,
        axis_names={axis},
        check_vma=False)
    # same eager-canonicalization workaround as ring_attention
    return jax.jit(mapped)(*args)
