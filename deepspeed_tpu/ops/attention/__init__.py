from deepspeed_tpu.ops.attention.paged import (  # noqa: F401
    paged_decode_attention,
    paged_decode_reference,
    resolve_decode_impl,
)
