"""Flash attention — Pallas TPU kernel with custom VJP.

Capability equivalent of the reference's fused attention path inside the
transformer training kernel (ref: csrc/transformer/softmax_kernels.cu +
strided-batch GEMM attention, csrc/includes/strided_batch_gemm.h) and the
long-sequence story of block-sparse attention (SURVEY §2.5/§5): an O(S)
memory attention that never materializes the [S, S] score matrix.

Algorithm: FlashAttention-2 style online softmax.
Forward: grid (B, H, Q-blocks, KV-blocks), KV innermost ("arbitrary"
dimension) with running max / sum / accumulator in VMEM scratch that
persists across the sequential KV iterations.
Backward: recompute-based FA2 — one kernel accumulating (dk, dv) over Q
blocks, one accumulating dq over KV blocks, using the saved logsumexp and
the precomputed per-row delta = rowsum(dO * O).

All matmuls hit the MXU in the input dtype with fp32 accumulation
(preferred_element_type); softmax statistics in fp32.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax<=0.4.x spells it TPUCompilerParams
_CompilerParams = getattr(pltpu, 'CompilerParams', None) \
    or pltpu.TPUCompilerParams

NEG_INF = -1e30


def _norm_window(window):
    """Decode the static ``window`` argument into
    ``(mask_window, band_window)``.

    ``window`` is either an int — the BANDED implementation: DMA-eliding
    index-map clamps + band-aware grid skipping + in-body mask — or the
    tagged tuple ``("masked", int)`` — the FALLBACK that expresses the
    sliding window purely as an in-body mask over the plain causal
    geometry. The fallback exists because the banded index-map clamp is
    the prime suspect in the round-4 on-chip Mosaic compile hang
    (STATUS.md "Rig situation"; bisect: tools/flash_window_bisect.py):
    it uses ONLY constructs already proven through real Mosaic (the
    causal clamp/skip and the causal-mask `where` pattern from the
    'plain' smoke case). Cost: O(S^2) HBM reads/compute like plain
    causal instead of O(S*W) — correctness is identical because fully
    out-of-band blocks wash out of the online softmax exactly like
    fully-masked kv_mask blocks (see flash_attention docstring)."""
    if window is None:
        return None, None
    if isinstance(window, tuple):
        impl, w = window
        assert impl == "masked", f"unknown window impl {impl!r}"
        return int(w), None
    return int(window), int(window)


def resolve_window_impl(window, window_impl=None):
    """Tag ``window`` for the masked fallback when requested (explicit
    arg wins, else DS_FLASH_WINDOW_IMPL, default banded). Shared by
    every window entry point (flash_attention, ring, ulysses) so the
    PARITY.md quarantine advice works uniformly."""
    if window is None or isinstance(window, tuple):
        return window
    from deepspeed_tpu.utils.env import resolve_flag
    impl = window_impl or resolve_flag("DS_FLASH_WINDOW_IMPL")
    if impl not in ("banded", "masked"):
        # ValueError, not assert: this validates user input (env var /
        # config) and must survive python -O
        raise ValueError(f"unknown window impl {impl!r}: "
                         f"expected 'banded' or 'masked'")
    return ("masked", int(window)) if impl == "masked" else int(window)
LANES = 128
STATS = 8   # lane width for per-row softmax stats (lse/delta) — sublane-aligned


def _ceil_to(x, m):
    return (x + m - 1) // m * m


def _causal_kv_index_map(block_q, block_kv, num_kv, window=None, q_off=0):
    """Block index map for KV-blocked inputs when the grid is
    (b, h, q-block, kv-block) and causal skipping applies: skipped
    above-diagonal steps re-map to the last valid KV block, so the index
    equals the previous step's and Mosaic elides the DMA (the compute is
    already skipped by pl.when). Clamped into range for Skv != S callers.

    With a sliding ``window``, blocks fully BELOW the band (ki too small)
    clamp up to the first in-band block — their fetches elide the same
    way, making windowed attention O(S*W) in HBM reads as well.

    ``q_off`` is a STATIC global q-position offset: ring attention calls
    the kernel with q rows that globally sit ``q_off`` tokens after the
    held K/V block's first key (the ring-step distance is static once
    the ring loop is unrolled), so all causal/window geometry shifts by
    it."""

    window = _norm_window(window)[1]     # banded geometry only

    def kvmap(b, h, qi, ki):
        limit = jnp.minimum((qi * block_q + block_q - 1 + q_off) // block_kv,
                            num_kv - 1)
        ki = jnp.minimum(ki, limit)
        if window is not None:
            lo = jnp.clip((qi * block_q + q_off - window + 1) // block_kv,
                          0, num_kv - 1)
            ki = jnp.maximum(ki, lo)
        return (b, h, ki, 0)

    return kvmap


def _band_run(qi, ki, block_q, block_kv, causal, window, q_off=0):
    """Whether grid step (qi, ki) intersects the attention band."""
    window = _norm_window(window)[1]     # banded geometry only
    run = True
    if causal:
        run = qi * block_q + block_q - 1 + q_off >= ki * block_kv
    if window is not None:
        # lowest q row of the block must still reach the block's last col
        run = jnp.logical_and(
            run,
            ki * block_kv + block_kv - 1 >= qi * block_q + q_off - window + 1)
    return run


def _window_mask(s, rows, cols, window):
    """cols within (rows - window, rows]: Mistral-style local attention."""
    return jnp.where(rows - cols < window, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, *rest,
                causal: bool, has_mask: bool, has_segs: bool, scale: float,
                block_q: int, block_kv: int, num_kv: int, window=None,
                q_off: int = 0):
    rest = list(rest)
    mask_ref = rest.pop(0) if has_mask else None
    qseg_ref = rest.pop(0) if has_segs else None
    kseg_ref = rest.pop(0) if has_segs else None
    o_ref, lse_ref, m_scratch, l_scratch, acc_scratch = rest
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    run = _band_run(qi, ki, block_q, block_kv, causal, window, q_off)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]                  # [block_q, d]
        k = k_ref[0, 0]                  # [block_kv, d]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bkv]

        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) \
                + qi * block_q + q_off
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + ki * block_kv
            s = jnp.where(rows >= cols, s, NEG_INF)
            if window is not None:
                s = _window_mask(s, rows, cols, _norm_window(window)[0])
        if has_mask:
            s = jnp.where(mask_ref[0, 0][None, :] > 0, s, NEG_INF)
        if has_segs:
            s = jnp.where(qseg_ref[0, 0][:, None] == kseg_ref[0, 0][None, :],
                          s, NEG_INF)

        m_prev = m_scratch[:, :1]                        # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                           # [bq, bkv] f32
        alpha = jnp.exp(m_prev - m_new)                  # [bq, 1]
        l_new = alpha * l_scratch[:, :1] + jnp.sum(p, axis=-1, keepdims=True)

        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(ki == num_kv - 1)
    def _finish():
        l = l_scratch[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l_safe).astype(o_ref.dtype)
        lse = m_scratch[:, :1] + jnp.log(l_safe)
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:]).astype(jnp.float32)


def _mask_spec(block_kv, kvmap):
    """Block spec for the optional key-validity mask, following the
    (possibly clamped) kv block index map. The [B, Skv] metadata is fed
    to the kernel as [B, 1, Skv]: Mosaic requires the LAST TWO dims of a
    block to be (8, 128)-tile-divisible or equal to the array dims, and
    a (1, block) slice of [B, Skv] violates that whenever B > 1 (caught
    by the on-chip smoke; interpret mode does not check tiling)."""
    def mmap(b, h, qi, ki):
        _, _, kblk, _ = kvmap(b, h, qi, ki)
        return (b, 0, kblk)

    return pl.BlockSpec((1, 1, block_kv), mmap)


def _qseg_spec(block_q, qmap):
    """Block spec for the q-side segment ids ([B, S] fed as [B, 1, S] —
    see _mask_spec), following qmap."""
    def smap(*ids):
        _, _, qblk, _ = qmap(*ids)
        return (ids[0], 0, qblk)

    return pl.BlockSpec((1, 1, block_q), smap)


def _group_head(map_fn, group: int):
    """Wrap a (b, h, i, j) block index map so the head index addresses a
    GROUPED kv array (GQA: kv head = q head // group)."""
    if group == 1:
        return map_fn

    def wrapped(b, h, i, j):
        bb, _, blk, z = map_fn(b, h, i, j)
        return (bb, h // group, blk, z)

    return wrapped


def _flash_fwd(q, k, v, mask, qsegs, ksegs, causal, scale, block_q, block_kv,
               window=None, q_off=0):
    # arrays are [B, H, S, D] inside the op (wrapper transposes)
    B, H, S, D = q.shape
    Skv = k.shape[2]
    group = H // k.shape[1]          # GQA: q heads per kv head
    block_q = min(block_q, S)
    block_kv = min(block_kv, Skv)
    assert S % block_q == 0 and Skv % block_kv == 0, (S, Skv, block_q, block_kv)
    num_q = S // block_q
    num_kv = Skv // block_kv

    def qmap(b, h, qi, ki):
        return (b, h, qi, 0)

    if causal:
        kvmap = _causal_kv_index_map(block_q, block_kv, num_kv, window, q_off)
    else:
        def kvmap(b, h, qi, ki):
            return (b, h, ki, 0)
    kvmap_h = _group_head(kvmap, group)

    grid = (B, H, num_q, num_kv)
    has_mask = mask is not None
    has_segs = qsegs is not None
    assert (qsegs is None) == (ksegs is None)
    kernel = functools.partial(
        _fwd_kernel, causal=causal, has_mask=has_mask, has_segs=has_segs,
        scale=scale, block_q=block_q, block_kv=block_kv, num_kv=num_kv,
        window=window, q_off=q_off)

    in_specs = [
        pl.BlockSpec((1, 1, block_q, D), qmap),
        pl.BlockSpec((1, 1, block_kv, D), kvmap_h),
        pl.BlockSpec((1, 1, block_kv, D), kvmap_h),
    ]
    operands = [q, k, v]
    if has_mask:
        in_specs.append(_mask_spec(block_kv, kvmap))
        operands.append(mask[:, None])
    if has_segs:
        in_specs.append(_qseg_spec(block_q, qmap))
        in_specs.append(_mask_spec(block_kv, kvmap))   # kv-side segments
        operands.extend([qsegs[:, None], ksegs[:, None]])

    out_shape = [
        jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        jax.ShapeDtypeStruct((B, H, S, STATS), jnp.float32),
    ]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), qmap),
            pl.BlockSpec((1, 1, block_q, STATS), qmap),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        out_shape=out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")),
    )(*operands)
    return o, lse[..., 0]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *rest, causal: bool, has_mask: bool, has_segs: bool,
                    scale: float, block_q: int, block_kv: int, num_q: int,
                    window=None, q_off: int = 0):
    rest = list(rest)
    mask_ref = rest.pop(0) if has_mask else None
    qseg_ref = rest.pop(0) if has_segs else None
    kseg_ref = rest.pop(0) if has_segs else None
    dk_ref, dv_ref, dk_scratch, dv_scratch = rest
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    run = _band_run(qi, ki, block_q, block_kv, causal, window, q_off)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]                # [bq, d]
        k = k_ref[0, 0]                # [bkv, d]
        v = v_ref[0, 0]
        do = do_ref[0, 0]              # [bq, d]
        lse = lse_ref[0, 0][:, :1]     # [bq, 1]
        delta = delta_ref[0, 0][:, :1]  # [bq, 1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) \
                + qi * block_q + q_off
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + ki * block_kv
            s = jnp.where(rows >= cols, s, NEG_INF)
            if window is not None:
                s = _window_mask(s, rows, cols, _norm_window(window)[0])
        if has_mask:
            s = jnp.where(mask_ref[0, 0][None, :] > 0, s, NEG_INF)
        if has_segs:
            s = jnp.where(qseg_ref[0, 0][:, None] == kseg_ref[0, 0][None, :],
                          s, NEG_INF)
        p = jnp.exp(s - lse)                               # [bq, bkv]

        # dv += p^T @ do
        dv_scratch[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = do @ v^T ; ds = p * (dp - delta) * scale
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                      # [bq, bkv]
        # dk += ds^T @ q
        dk_scratch[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q - 1)
    def _finish():
        dk_ref[0, 0] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scratch[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   *rest, causal: bool, has_mask: bool, has_segs: bool,
                   scale: float, block_q: int, block_kv: int, num_kv: int,
                   window=None, q_off: int = 0):
    rest = list(rest)
    mask_ref = rest.pop(0) if has_mask else None
    qseg_ref = rest.pop(0) if has_segs else None
    kseg_ref = rest.pop(0) if has_segs else None
    dq_ref, dq_scratch = rest
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scratch[:] = jnp.zeros_like(dq_scratch)

    run = _band_run(qi, ki, block_q, block_kv, causal, window, q_off)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) \
                + qi * block_q + q_off
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + ki * block_kv
            s = jnp.where(rows >= cols, s, NEG_INF)
            if window is not None:
                s = _window_mask(s, rows, cols, _norm_window(window)[0])
        if has_mask:
            s = jnp.where(mask_ref[0, 0][None, :] > 0, s, NEG_INF)
        if has_segs:
            s = jnp.where(qseg_ref[0, 0][:, None] == kseg_ref[0, 0][None, :],
                          s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scratch[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_kv - 1)
    def _finish():
        dq_ref[0, 0] = dq_scratch[:].astype(dq_ref.dtype)


def _flash_bwd(causal, scale, block_q, block_kv, window, res, g, q_off=0,
               delta=None, out_fp32=False):
    """out_fp32: emit fp32 grads (ring accumulates per-step contributions
    across hops — rounding each to the input dtype first would compound
    quantization noise; the custom-vjp path keeps input-dtype cotangents
    as jax requires). res's ``o`` may be None when ``delta`` is given."""
    q, k, v, mask, qsegs, ksegs, o, lse = res
    do = g
    B, H, S, D = q.shape
    Skv = k.shape[2]
    group = H // k.shape[1]          # GQA: q heads per kv head
    block_q = min(block_q, S)
    block_kv = min(block_kv, Skv)
    assert S % block_q == 0 and Skv % block_kv == 0, \
        (S, Skv, block_q, block_kv)
    num_q = S // block_q
    num_kv = Skv // block_kv
    has_mask = mask is not None
    has_segs = qsegs is not None
    assert (qsegs is None) == (ksegs is None)

    if delta is None:
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1)                              # [B,H,S]
    lse_b = jnp.broadcast_to(lse[..., None], (B, H, S, STATS))
    delta_b = jnp.broadcast_to(delta[..., None], (B, H, S, STATS))

    def qmap(b, h, i, j):
        return (b, h, i, 0)

    if causal:
        kvmap_q_outer = _causal_kv_index_map(block_q, block_kv, num_kv,
                                             window, q_off)
    else:
        def kvmap_q_outer(b, h, i, j):
            return (b, h, j, 0)

    # ---- dq ----
    kvmap_q_outer_h = _group_head(kvmap_q_outer, group)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, D), qmap),
        pl.BlockSpec((1, 1, block_kv, D), kvmap_q_outer_h),
        pl.BlockSpec((1, 1, block_kv, D), kvmap_q_outer_h),
        pl.BlockSpec((1, 1, block_q, D), qmap),
        pl.BlockSpec((1, 1, block_q, STATS), qmap),
        pl.BlockSpec((1, 1, block_q, STATS), qmap),
    ]
    operands = [q, k, v, do, lse_b, delta_b]
    if has_mask:
        in_specs.append(_mask_spec(block_kv, kvmap_q_outer))
        operands.append(mask[:, None])
    if has_segs:
        in_specs.append(_qseg_spec(block_q, qmap))
        in_specs.append(_mask_spec(block_kv, kvmap_q_outer))
        operands.extend([qsegs[:, None], ksegs[:, None]])
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, has_mask=has_mask,
                          has_segs=has_segs,
                          scale=scale, block_q=block_q, block_kv=block_kv,
                          num_kv=num_kv, window=window, q_off=q_off),
        grid=(B, H, num_q, num_kv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, D), qmap),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct(
            (B, H, S, D), jnp.float32 if out_fp32 else q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")),
    )(*operands)

    # ---- dk, dv ---- (kv outer, q inner)
    def kvmap(b, h, ki, qi):
        return (b, h, ki, 0)

    if causal:
        # early q blocks are above the diagonal for this kv block: clamp
        # to the first valid q block so the skipped steps' fetches elide
        # (min'd into range for Skv > S callers, where no q block may be
        # valid for the last kv blocks). With a sliding window the LAST
        # valid q block is bounded too — late steps clamp down the same
        # way.
        band_w = _norm_window(window)[1]   # banded geometry only

        def qmap_kv_outer(b, h, ki, qi):
            first = jnp.clip((ki * block_kv - q_off) // block_q,
                             0, num_q - 1)
            qi = jnp.maximum(qi, first)
            if band_w is not None:
                last = jnp.clip(
                    (ki * block_kv + block_kv - 1 + band_w - 1 - q_off)
                    // block_q,
                    0, num_q - 1)
                qi = jnp.minimum(qi, last)
            return (b, h, qi, 0)
    else:
        def qmap_kv_outer(b, h, ki, qi):
            return (b, h, qi, 0)

    kvmap_in_h = _group_head(kvmap, group)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, D), qmap_kv_outer),
        pl.BlockSpec((1, 1, block_kv, D), kvmap_in_h),
        pl.BlockSpec((1, 1, block_kv, D), kvmap_in_h),
        pl.BlockSpec((1, 1, block_q, D), qmap_kv_outer),
        pl.BlockSpec((1, 1, block_q, STATS), qmap_kv_outer),
        pl.BlockSpec((1, 1, block_q, STATS), qmap_kv_outer),
    ]
    operands = [q, k, v, do, lse_b, delta_b]
    if has_mask:
        # kv blocks are on the OUTER grid dim here; _mask_spec follows
        # this call's kvmap, which resolves to (b, ki)
        in_specs.append(_mask_spec(block_kv, kvmap))
        operands.append(mask[:, None])
    if has_segs:
        in_specs.append(_qseg_spec(block_q, qmap_kv_outer))
        in_specs.append(_mask_spec(block_kv, kvmap))
        operands.extend([qsegs[:, None], ksegs[:, None]])
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, has_mask=has_mask,
                          has_segs=has_segs,
                          scale=scale, block_q=block_q, block_kv=block_kv,
                          num_q=num_q, window=window, q_off=q_off),
        grid=(B, H, num_kv, num_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_kv, D), kvmap),
            pl.BlockSpec((1, 1, block_kv, D), kvmap),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, D), jnp.float32),
            pltpu.VMEM((block_kv, D), jnp.float32),
        ],
        out_shape=[
            # GQA partials stay fp32 so the cross-head reduction below
            # accumulates at full precision (cast once after the sum)
            jax.ShapeDtypeStruct(
                (B, H, Skv, D),
                jnp.float32 if (group > 1 or out_fp32) else k.dtype),
            jax.ShapeDtypeStruct(
                (B, H, Skv, D),
                jnp.float32 if (group > 1 or out_fp32) else v.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")),
    )(*operands)

    if group > 1:
        # per-q-head partials -> per-kv-head grads (GQA): accumulation
        # across q heads can't happen inside the kernel (h is a parallel
        # grid dim), so reduce the group outside
        Hkv = H // group
        kd = jnp.float32 if out_fp32 else k.dtype
        vd = jnp.float32 if out_fp32 else v.dtype
        dk = dk.reshape(B, Hkv, group, Skv, D).sum(2).astype(kd)
        dv = dv.reshape(B, Hkv, group, Skv, D).sum(2).astype(vd)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11, 12))
def _flash(q, k, v, mask, qsegs, ksegs, causal, scale, block_q, block_kv,
           window=None, bwd_block_q=None, bwd_block_kv=None):
    o, _ = _flash_fwd(q, k, v, mask, qsegs, ksegs, causal, scale, block_q,
                      block_kv, window)
    return o


def _flash_vjp_fwd(q, k, v, mask, qsegs, ksegs, causal, scale, block_q,
                   block_kv, window=None, bwd_block_q=None,
                   bwd_block_kv=None):
    o, lse = _flash_fwd(q, k, v, mask, qsegs, ksegs, causal, scale, block_q,
                        block_kv, window)
    # named so a selective remat policy can keep the residuals — without
    # these, jax.checkpoint re-runs the whole forward kernel in the backward
    # pass just to regenerate o/lse. The o residual is stored with (H, D)
    # merged into one 128-aligned trailing axis: saving it in the kernel's
    # [B, H, S, D] layout would tile D=64 up to 128 lanes — 2x the HBM for
    # every checkpointed layer.
    B, H, S, D = o.shape
    o_res = o.transpose(0, 2, 1, 3).reshape(B, S, H * D)
    o_res = checkpoint_name(o_res, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, mask, qsegs, ksegs, o_res, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_kv, window, bwd_block_q,
                   bwd_block_kv, res, g):
    q, k, v, mask, qsegs, ksegs, o_res, lse = res
    B, H, S, D = q.shape
    o = o_res.reshape(B, S, H, D).transpose(0, 2, 1, 3)
    # the dq/dkv kernels have different reuse patterns than the forward
    # (both stream the FULL opposite operand per block) — let callers tune
    # their tiles independently of the fwd blocks
    dq, dk, dv = _flash_bwd(causal, scale, bwd_block_q or block_q,
                            bwd_block_kv or block_kv, window,
                            (q, k, v, mask, qsegs, ksegs, o, lse), g)
    return dq, dk, dv, None, None, None


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 512, block_kv: int = 512,
                    kv_mask: Optional[jnp.ndarray] = None,
                    segment_ids: Optional[jnp.ndarray] = None,
                    window: Optional[int] = None,
                    bwd_block_q: Optional[int] = None,
                    bwd_block_kv: Optional[int] = None,
                    window_impl: Optional[str] = None) -> jnp.ndarray:
    """Flash attention over [B, S, H, D] tensors.

    Head dims that are sublane-aligned (multiple of 8) run unpadded: Mosaic
    masks the lane remainder, so QK^T streams only D real contraction lanes
    through the MXU and HBM moves only real bytes. Padding D=64 up to 128
    (the previous behavior) doubled both the attention matmul cycles and the
    q/k/v/o HBM traffic. Odd head dims still pad to the next sublane
    multiple. Fallback is the caller's job (models gate via _flash_eligible).

    kv_mask: optional [B, Skv] key-validity mask (1 = attend, 0 = padding)
    — the encoder attention-mask path. Padded QUERY rows produce
    normalized-over-valid-keys outputs like the dense path; rows with NO
    valid key degenerate to a uniform average of v (identical to the
    dense softmax-over-NEG_INF behavior) — garbage-by-contract, and
    their gradients are zero as long as the loss masks them, which every
    masked loss here does.

    segment_ids: optional [B, S] int ids for PACKED sequences (requires
    S == Skv): token i attends token j only when segment_ids match (and
    causality holds) — block-diagonal attention, so several short
    documents share one row with zero cross-contamination.

    Grouped-query attention: k/v may carry FEWER heads than q
    (``H % Hkv == 0``); each group of ``H // Hkv`` query heads shares one
    kv head, shrinking the KV cache by the group factor.

    window: optional sliding-window size (requires causal): token i
    attends tokens (i-window, i] only — O(S*window) compute AND HBM
    reads (out-of-band blocks' fetches are elided via index-map clamps).

    window_impl: "banded" (default; also via DS_FLASH_WINDOW_IMPL) keeps
    the O(S*W) index-map clamps; "masked" is the fallback that expresses
    the window purely as an in-body mask over plain causal geometry —
    O(S^2) reads, but built ONLY from constructs proven through real
    Mosaic (see _norm_window; the banded clamp is the r4 compile-hang
    suspect, quarantined until tools/flash_window_bisect.py clears it).
    """
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    assert H % Hkv == 0, f"q heads {H} not a multiple of kv heads {Hkv}"
    assert v.shape[2] == Hkv, \
        f"k has {Hkv} heads but v has {v.shape[2]} — kv head counts must match"
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    if segment_ids is not None:
        assert k.shape[1] == S, "segment_ids requires self-attention (Skv == S)"
    if window is not None:
        assert causal, "sliding window attention requires causal=True"
        assert isinstance(window, tuple) or window >= 1
        window = resolve_window_impl(window, window_impl)
    q, k, v, D, Dp = _pad_heads(q, k, v)
    # kernel-internal layout is [B, H, S, D]
    q = q.transpose(0, 2, 1, 3)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    if kv_mask is not None:
        kv_mask = kv_mask.astype(jnp.float32)
    if segment_ids is not None:
        segment_ids = segment_ids.astype(jnp.int32)
    out = _flash(q, k, v, kv_mask, segment_ids, segment_ids, causal, scale,
                 block_q, block_kv, window, bwd_block_q, bwd_block_kv)
    out = out.transpose(0, 2, 1, 3)
    if Dp != D:
        out = out[..., :D]
    return out


# ---------------------------------------------------------------------------
# block-level entry points (ring attention building blocks)
# ---------------------------------------------------------------------------

def flash_block_fwd_t(q, k, v, kv_mask=None, q_segs=None, kv_segs=None, *,
                      causal=True, scale, block_q=512, block_kv=512,
                      window=None, q_off=0):
    """Kernel-layout ([B, H, S, D], D sublane-aligned) variant of
    :func:`flash_block_fwd` — no per-call pad/transpose, so a ring loop
    can hoist the layout change out of its steps. Returns (o [B,H,S,D],
    lse [B,H,S]). Not differentiable (ring owns the VJP)."""
    return _flash_fwd(q, k, v, kv_mask, q_segs, kv_segs, causal, scale,
                      block_q, block_kv, window, q_off)


def flash_block_bwd_t(q, k, v, do, lse, kv_mask=None, q_segs=None,
                      kv_segs=None, *, causal=True, scale, block_q=512,
                      block_kv=512, window=None, q_off=0, delta, o=None):
    """Kernel-layout backward companion of :func:`flash_block_fwd_t`;
    ``delta`` (= rowsum(do*o), [B,H,S]) is precomputed ONCE per ring
    backward, so ``o`` is not needed (pass it only if delta were ever
    recomputed here). Returns fp32 (dq, dk, dv) in [B,H,S,D] — the ring
    sums per-step contributions across hops and must not round each to
    the input dtype first."""
    return _flash_bwd(causal, scale, block_q, block_kv, window,
                      (q, k, v, kv_mask, q_segs, kv_segs, o, lse),
                      do, q_off, delta, out_fp32=True)


def _pad_heads(q, k, v):
    D = q.shape[-1]
    Dp = D if D % 8 == 0 else _ceil_to(D, 8)
    if Dp != D:
        pad = [(0, 0), (0, 0), (0, 0), (0, Dp - D)]
        q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
    return q, k, v, D, Dp


def flash_block_fwd(q, k, v, kv_mask=None, q_segs=None, kv_segs=None, *,
                    causal=True, scale=None, block_q=512, block_kv=512,
                    window=None, q_off=0):
    """One flash forward over [B, S, H, D] tensors, returning BOTH the
    normalized output and the per-row logsumexp: ``(o [B,S,H,D],
    lse [B,H,S])``.

    NOT differentiable — ring attention (ops/attention/ring.py) calls
    this per held K/V block inside its own custom VJP and combines the
    per-block (o, lse) pairs with an online softmax across ring steps.
    ``q_off`` is the static global position of q row 0 relative to key 0
    of this block (the ring-step distance x S_local); q-side and kv-side
    segment ids are separate because the kv metadata rotates with its
    block."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    q, k, v, D, Dp = _pad_heads(q, k, v)
    q = q.transpose(0, 2, 1, 3)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    if kv_mask is not None:
        kv_mask = kv_mask.astype(jnp.float32)
    if q_segs is not None:
        q_segs = q_segs.astype(jnp.int32)
        kv_segs = kv_segs.astype(jnp.int32)
    o, lse = _flash_fwd(q, k, v, kv_mask, q_segs, kv_segs, causal, scale,
                        block_q, block_kv, window, q_off)
    o = o.transpose(0, 2, 1, 3)
    if Dp != D:
        o = o[..., :D]
    return o, lse


def flash_block_bwd(q, k, v, do, o, lse, kv_mask=None, q_segs=None,
                    kv_segs=None, *, causal=True, scale=None, block_q=512,
                    block_kv=512, window=None, q_off=0):
    """Backward companion of :func:`flash_block_fwd`: given the global
    ``lse`` (combined across ring steps) and the global output ``o``,
    returns this block's additive contribution ``(dq, dk, dv)`` in
    [B, S, H, D] layout. Per-block contributions with a shared lse/delta
    sum to the exact softmax gradient (FA2 recompute form)."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    q, k, v, D, Dp = _pad_heads(q, k, v)
    # pad do/o the same way (zero lanes contribute nothing to delta)
    if Dp != D:
        pad = [(0, 0), (0, 0), (0, 0), (0, Dp - D)]
        do = jnp.pad(do, pad)
        o = jnp.pad(o, pad)
    q = q.transpose(0, 2, 1, 3)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    do = do.transpose(0, 2, 1, 3)
    o = o.transpose(0, 2, 1, 3)
    if kv_mask is not None:
        kv_mask = kv_mask.astype(jnp.float32)
    if q_segs is not None:
        q_segs = q_segs.astype(jnp.int32)
        kv_segs = kv_segs.astype(jnp.int32)
    dq, dk, dv = _flash_bwd(causal, scale, block_q, block_kv, window,
                            (q, k, v, kv_mask, q_segs, kv_segs, o, lse),
                            do, q_off)
    dq = dq.transpose(0, 2, 1, 3)
    dk = dk.transpose(0, 2, 1, 3)
    dv = dv.transpose(0, 2, 1, 3)
    if Dp != D:
        dq, dk, dv = dq[..., :D], dk[..., :D], dv[..., :D]
    return dq, dk, dv


def mha_reference(q, k, v, causal=True, scale=None, kv_mask=None,
                  segment_ids=None, window=None):
    """Pure-jnp reference for parity tests (analog of the python BERT
    baselines in ref tests/unit/test_cuda_forward.py)."""
    B, S, H, D = q.shape
    if k.shape[2] != H:              # GQA: repeat kv heads per group
        k = jnp.repeat(k, H // k.shape[2], axis=2)
        v = jnp.repeat(v, H // v.shape[2], axis=2)
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, k.shape[1]), bool))
        if window is not None:
            mask = mask & ~jnp.tril(jnp.ones((S, k.shape[1]), bool),
                                    -window)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    if kv_mask is not None:
        logits = jnp.where(kv_mask[:, None, None, :] > 0, logits, NEG_INF)
    if segment_ids is not None:
        same = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(same[:, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
