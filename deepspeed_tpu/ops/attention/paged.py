"""Paged-attention decode kernel — Pallas TPU flash-decode through the
block table.

The serving engine's gather path (`inference/engine.py _gather_blocks`)
materializes the WHOLE virtual cache ``[B, NB*block, Hkv, Dh]`` out of
the block pool every layer, every decoded token, then masks everything
past ``lengths``: per token that is O(S_max) HBM reads plus an
equal-size HBM write of the transient gathered copy, x2 (K, V) xL
layers — decode is gather-bound and the paged cache's memory win is
undone by a dense copy that exists only to feed two einsums.

This kernel attends THROUGH the block table instead (vLLM's
PagedAttention, Kwon et al. 2023, with FlashAttention-2's online
softmax, Dao 2023):

- block tables and per-slot lengths ride in as scalar-prefetch operands
  (``pltpu.PrefetchScalarGridSpec``), so the K/V BlockSpec index_map
  dereferences ``tables[b, j]`` BEFORE the grid step runs and each step
  DMAs exactly one pool block ``[block, Hkv, Dh]`` from HBM — no dense
  gather copy ever exists;
- grid ``(B, NB)`` with the KV (block) dimension innermost; fp32
  running max / sum / accumulator live in VMEM scratch across the
  sequential block iterations (the FA2 online softmax);
- ``pl.when`` skips blocks entirely past ``lengths[b]`` — and, with a
  sliding ``window``, blocks entirely below the band start — while the
  index_map CLAMPS skipped steps to the nearest in-band block so their
  index equals a neighbor step's and Mosaic elides the DMA (the same
  causal-clamp trick as ops/attention/flash.py): per-token HBM traffic
  is O(actual length), not O(S_max);
- GQA: the kv-head loop is unrolled IN the kernel body (Hkv is static
  and small), packing the ``group = H // Hkv`` query heads that share a
  kv head into one MXU matmul per head. Folding the head loop into the
  body — rather than a (B, Hkv, NB) grid — means one pool block fetch
  serves ALL kv heads (the pool's native layout is
  ``[N, block, Hkv, Dh]``, so a per-head grid would re-DMA each block
  Hkv times or force a full-pool relayout);
- the final partial block is masked by position exactly like the gather
  path, so the two implementations are numerically interchangeable (the
  gather path stays the bit-reference, see docs/PARITY.md).

The gather path remains the reference implementation and the non-TPU
default; tests drive this kernel in interpret mode under
``JAX_PLATFORMS=cpu`` (tests/test_paged_attention.py).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax<=0.4.x spells it TPUCompilerParams
_CompilerParams = getattr(pltpu, 'CompilerParams', None) \
    or pltpu.TPUCompilerParams

NEG_INF = -1e30
LANES = 128


def resolve_decode_impl(impl: Optional[str] = None) -> str:
    """Resolve the paged-decode implementation switch.

    Explicit argument wins, else the ``DS_PAGED_DECODE_IMPL`` env var,
    else the platform default: ``"pallas"`` on TPU, ``"gather"``
    elsewhere (the gather path is the reference implementation and the
    portable fallback). Shared by InferenceEngine and ServingEngine so
    env overrides work uniformly."""
    if impl is None:
        from deepspeed_tpu.utils.env import resolve_flag
        impl = resolve_flag("DS_PAGED_DECODE_IMPL")
    if impl is None:
        from deepspeed_tpu.utils import on_tpu
        impl = "pallas" if on_tpu() else "gather"
    if impl not in ("pallas", "gather"):
        # ValueError, not assert: validates user input (env var / config)
        # and must survive python -O
        raise ValueError(f"unknown paged decode impl {impl!r}: "
                         f"expected 'pallas' or 'gather'")
    return impl


def paged_hbm_bytes_per_token(cfg, num_slots: int, mean_len: float,
                              max_len: int, dtype=jnp.bfloat16,
                              impl: str = "pallas",
                              block_size: Optional[int] = None,
                              scale_bytes_per_block: int = 0) -> int:
    """Analytic HBM bytes the attention cache path moves per decoded
    token (all layers, K+V) — the PERF.md comparison unit.

    gather: reads the whole ``[B, NB*block, ...]`` virtual cache out of
    the pool AND writes the transient gathered copy, then the einsums
    read the copy again — 3 passes over ``num_slots * max_len`` tokens.
    pallas: reads only the occupied blocks of each live slot, once.

    ``dtype`` must be the ACTUAL pool dtype (int8 under DS_KV_QUANT,
    bf16/f32 otherwise — the bench passes ``cache.pool_dtype``);
    ``scale_bytes_per_block`` + ``block_size`` fold the quantized pools'
    per-block fp32 scale overhead into the per-token cost."""
    per_tok = 2.0 * cfg.n_layers * cfg.kv_heads * cfg.head_dim \
        * jnp.dtype(dtype).itemsize
    if scale_bytes_per_block and block_size:
        # the scale pools are read alongside every block DMA
        per_tok += scale_bytes_per_block / float(block_size)
    if impl == "gather":
        return int(3 * num_slots * int(max_len) * per_tok)
    return int(int(num_slots * mean_len) * per_tok)


def _kv_index_map(bs: int, nb: int, window: Optional[int], q_len: int = 1,
                  rank: int = 4):
    """Block index map for the K/V pools when the grid is (b, j) and the
    pools are scalar-prefetch-addressed: step (b, j) fetches pool block
    ``tables[b, clamp(j)]``. Steps past the slot's last occupied block
    clamp DOWN to it, steps below the sliding-window band clamp UP to
    the band's first block — either way the skipped step's index equals
    a run step's (or its neighbor's), so Mosaic elides the DMA exactly
    like the causal clamp in ops/attention/flash.py. With a verify
    chunk (``q_len > 1``) the last query sits at ``lengths + q_len - 1``,
    so the high clamp covers that block too.

    ``rank=4`` addresses the K/V pools ``[N, block, Hkv, Dh]``;
    ``rank=2`` addresses the int8 mode's scale pools ``[N, Hkv]`` with
    the SAME table indirection, so each grid step's scale rides the
    same prefetch discipline as its block."""
    def imap(b, j, tables_ref, lengths_ref):
        pos = lengths_ref[b]
        hi = jnp.minimum((pos + (q_len - 1)) // bs, nb - 1)
        jj = jnp.minimum(j, hi)
        if window is not None:
            lo = jnp.clip((pos - window + 1) // bs, 0, nb - 1)
            jj = jnp.maximum(jj, lo)
        return (tables_ref[b, jj],) + (0,) * (rank - 1)

    return imap


def _paged_decode_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref,
                         *rest, bs: int, n_kv: int, group: int, q_len: int,
                         scale: float, window: Optional[int], nb: int,
                         quant: bool = False):
    """One (slot, pool-block) grid step of flash-decode.

    q_ref: [1, H*q_len, Dh] (H = n_kv * group; rows ordered (kv head,
    group member, chunk offset) so each kv head's queries are one
    contiguous MXU matmul); k_ref / v_ref: [1, bs, Hkv, Dh] — ONE pool
    block, already table-indirected by the index_map; scratch: running
    max / sum / fp32 accumulator per query row, persistent across the j
    (block) iterations of slot b. q_len == 1 is plain decode; q_len > 1
    is the speculative verify chunk — query row with chunk offset g is
    causal at position ``lengths[b] + g`` (within-chunk causality falls
    out of the same position mask, since the chunk's K/V are already
    scattered into the pool).

    ``quant=True``: k_ref/v_ref hold int8 and two extra refs
    ks_ref/vs_ref ([1, Hkv] fp32 per-block scales, same table
    indirection) precede the output — the block is dequantized
    IN-REGISTER right after its DMA (the ops/int8_matmul.py idiom), so
    HBM traffic stays the int8 payload + one scale vector per block."""
    if quant:
        ks_ref, vs_ref, o_ref, m_scratch, l_scratch, acc_scratch = rest
    else:
        o_ref, m_scratch, l_scratch, acc_scratch = rest
    b = pl.program_id(0)
    j = pl.program_id(1)
    pos = lengths_ref[b]
    # last block any query in the chunk may touch
    hi = jnp.minimum((pos + (q_len - 1)) // bs, nb - 1)

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    run = j <= hi
    if window is not None:
        # band start of the FIRST query; later queries' bands begin
        # higher and are enforced per element below
        lo = jnp.clip((pos - window + 1) // bs, 0, nb - 1)
        run = jnp.logical_and(run, j >= lo)

    R = group * q_len                         # query rows per kv head

    @pl.when(run)
    def _body():
        q = q_ref[0]                          # [H*q_len, Dh]
        k = k_ref[0]                          # [bs, Hkv, Dh]
        v = v_ref[0]
        # positions of this block's slots in the slot's virtual cache;
        # the final partial block masks by position exactly like the
        # gather path (idx <= pos + chunk offset, window band below it)
        cols = jax.lax.broadcasted_iota(jnp.int32, (R, bs), 1) + j * bs
        qpos = pos
        if q_len > 1:
            # row r of a kv-head slice is (group member r // q_len,
            # chunk offset r % q_len): each chunk query is causal at
            # its own position
            qpos = pos + jax.lax.broadcasted_iota(
                jnp.int32, (R, bs), 0) % q_len
        valid = cols <= qpos
        if window is not None:
            valid = jnp.logical_and(valid, cols > qpos - window)

        for h in range(n_kv):                 # static unroll: Hkv is small
            rows = slice(h * R, (h + 1) * R)
            qh = q[rows, :]                   # [R, Dh] — one MXU matmul
            kh = k[:, h, :]                   # [bs, Dh]     covers the whole
            vh = v[:, h, :]                   # GQA group of this kv head
            if quant:
                # in-register dequantize: int8 block × its fp32 scale
                qh = qh.astype(jnp.float32)
                kh = kh.astype(jnp.float32) * ks_ref[0, h]
                vh = vh.astype(jnp.float32) * vs_ref[0, h]
            s = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # [R, bs]
            s = jnp.where(valid, s, NEG_INF)

            m_prev = m_scratch[rows, :1]                     # [R, 1]
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.exp(s - m_new)                           # [R, bs]
            alpha = jnp.exp(m_prev - m_new)
            l_new = alpha * l_scratch[rows, :1] \
                + jnp.sum(p, axis=-1, keepdims=True)
            acc_scratch[rows, :] = acc_scratch[rows, :] * alpha \
                + jax.lax.dot_general(
                    p.astype(vh.dtype), vh, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            m_scratch[rows, :] = jnp.broadcast_to(
                m_new, (R, m_scratch.shape[1]))
            l_scratch[rows, :] = jnp.broadcast_to(
                l_new, (R, l_scratch.shape[1]))

    @pl.when(j == hi)
    def _finish():
        l = l_scratch[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scratch[:] / l_safe).astype(o_ref.dtype)


def paged_decode_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                           v_pool: jnp.ndarray, tables: jnp.ndarray,
                           lengths: jnp.ndarray, *, scale: float,
                           window: Optional[int] = None,
                           interpret: Optional[bool] = None,
                           k_scale=None, v_scale=None) -> jnp.ndarray:
    """Flash-decode one new token per serving slot THROUGH the block
    table — no dense cache materialization.

    q: [B, Hkv, group, Dh] post-rotary queries (grouped per shared kv
    head); k_pool / v_pool: [N, block, Hkv, Dh] pools (the new token's
    K/V must already be scattered in at position ``lengths[b]``);
    tables: [B, NB] int32 block tables (trash-block-0 convention for
    unused entries); lengths: [B] int32 per-slot cache positions (slot b
    attends positions <= lengths[b], banded by ``window`` when set).
    ``k_scale``/``v_scale`` ([N, Hkv] fp32): int8 pools, dequantized
    in-register after each block DMA (DS_KV_QUANT=int8).

    Returns [B, Hkv, group, Dh] in q's dtype. ``interpret`` defaults to
    True off-TPU so the same call tests on CPU (interpret mode) and
    compiles through Mosaic on chip."""
    B, n_kv, group, Dh = q.shape
    return _paged_attention_call(
        q.reshape(B, n_kv * group, Dh), k_pool, v_pool, tables, lengths,
        n_kv=n_kv, group=group, q_len=1, scale=scale, window=window,
        interpret=interpret, k_scale=k_scale,
        v_scale=v_scale).reshape(B, n_kv, group, Dh)


def paged_verify_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                           v_pool: jnp.ndarray, tables: jnp.ndarray,
                           lengths: jnp.ndarray, *, scale: float,
                           window: Optional[int] = None,
                           interpret: Optional[bool] = None,
                           k_scale=None, v_scale=None) -> jnp.ndarray:
    """Flash-verify a G-token speculative chunk per slot THROUGH the
    block table — the ``q_len > 1`` generalization of
    :func:`paged_decode_attention` for draft/verify serving.

    q: [B, G, Hkv, group, Dh] post-rotary chunk queries; the chunk's
    K/V must already be scattered into the pools at positions
    ``lengths[b] .. lengths[b] + G - 1`` (writes-before-attention, so
    within-chunk causality is just the position mask: chunk query i of
    slot b attends cache positions <= lengths[b] + i). Same grid and
    per-block DMA economics as decode — the chunk only widens the MXU
    matmul per fetched block, which is exactly why verify is nearly
    free on TPU. Returns [B, G, Hkv, group, Dh] in q's dtype."""
    B, G, n_kv, group, Dh = q.shape
    # head-major row packing (kv head, group member, chunk offset):
    # each kv head's group*G query rows stay one contiguous matmul
    q_rows = q.transpose(0, 2, 3, 1, 4).reshape(B, n_kv * group * G, Dh)
    out = _paged_attention_call(
        q_rows, k_pool, v_pool, tables, lengths, n_kv=n_kv, group=group,
        q_len=G, scale=scale, window=window, interpret=interpret,
        k_scale=k_scale, v_scale=v_scale)
    return out.reshape(B, n_kv, group, G, Dh).transpose(0, 3, 1, 2, 4)


def _paged_attention_call(q_rows, k_pool, v_pool, tables, lengths, *,
                          n_kv: int, group: int, q_len: int, scale: float,
                          window: Optional[int],
                          interpret: Optional[bool],
                          k_scale=None, v_scale=None) -> jnp.ndarray:
    """Shared pallas_call plumbing for decode (q_len=1) and verify
    (q_len=G). q_rows: [B, n_kv*group*q_len, Dh], head-major rows.
    ``k_scale``/``v_scale`` ([N, Hkv] fp32) switch the int8 dequantize-
    in-kernel mode on (pools must then be int8)."""
    B, rows, Dh = q_rows.shape
    N, bs, Hkv, Dh_p = k_pool.shape
    assert (n_kv, Dh, rows) == (Hkv, Dh_p, n_kv * group * q_len), \
        (q_rows.shape, k_pool.shape, (n_kv, group, q_len))
    assert v_pool.shape == k_pool.shape, (v_pool.shape, k_pool.shape)
    quant = k_scale is not None
    nb = tables.shape[1]
    if interpret is None:
        from deepspeed_tpu.utils import on_tpu
        interpret = not on_tpu()

    kvmap = _kv_index_map(bs, nb, window, q_len)

    def qmap(b, j, tables_ref, lengths_ref):
        return (b, 0, 0)

    in_specs = [
        pl.BlockSpec((1, rows, Dh), qmap),
        pl.BlockSpec((1, bs, Hkv, Dh), kvmap),
        pl.BlockSpec((1, bs, Hkv, Dh), kvmap),
    ]
    operands = [q_rows, k_pool, v_pool]
    if quant:
        smap = _kv_index_map(bs, nb, window, q_len, rank=2)
        in_specs += [pl.BlockSpec((1, Hkv), smap),
                     pl.BlockSpec((1, Hkv), smap)]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, rows, Dh), qmap),
        scratch_shapes=[
            pltpu.VMEM((rows, LANES), jnp.float32),
            pltpu.VMEM((rows, LANES), jnp.float32),
            pltpu.VMEM((rows, Dh), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_decode_kernel, bs=bs, n_kv=n_kv, group=group, q_len=q_len,
        scale=float(scale), window=window, nb=nb, quant=quant)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, rows, Dh), q_rows.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(tables, jnp.int32), jnp.asarray(lengths, jnp.int32),
      *operands)


def _gather_dequant(pool, scale_pool, tables, dtype):
    """Gather pool blocks through the tables and dequantize with the
    per-(block, kv_head) scales — the quantized twin of the engine's
    ``_gather_blocks``, shared by both bit-reference paths."""
    from deepspeed_tpu.ops import quantizer
    g = quantizer.kv_dequantize_blocks(pool[tables], scale_pool[tables],
                                       dtype=dtype)
    B, nb, bs = g.shape[0], g.shape[1], g.shape[2]
    return g.reshape(B, nb * bs, g.shape[3], g.shape[4])


def paged_decode_reference(q, k_pool, v_pool, tables, lengths, *, scale,
                           window=None, k_scale=None, v_scale=None):
    """Dense gather reference of :func:`paged_decode_attention` for the
    parity tests — the same math as the engine's gather path
    (inference/engine.py _block_decode_paged), minus the model around
    it. With ``k_scale``/``v_scale`` the pools are int8 and the gather
    dequantizes through the ops/quantizer KV helpers."""
    B, n_kv, group, Dh = q.shape
    bs = k_pool.shape[1]
    nb = tables.shape[1]
    if k_scale is None:
        kc = k_pool[tables].reshape(B, nb * bs, n_kv, Dh)
        vc = v_pool[tables].reshape(B, nb * bs, n_kv, Dh)
    else:
        kc = _gather_dequant(k_pool, k_scale, tables, q.dtype)
        vc = _gather_dequant(v_pool, v_scale, tables, q.dtype)
    s = jnp.einsum("bkgd,bskd->bkgs", q, kc).astype(jnp.float32) * scale
    idx = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, nb * bs), 3)
    pos = lengths[:, None, None, None]
    s = jnp.where(idx <= pos, s, NEG_INF)
    if window is not None:
        s = jnp.where(idx > pos - window, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bkgs,bskd->bkgd", p, vc)


def paged_verify_reference(q, k_pool, v_pool, tables, lengths, *, scale,
                           window=None, k_scale=None, v_scale=None):
    """Dense gather reference of :func:`paged_verify_attention` — the
    same math as the engine's gather-path verify block
    (inference/engine.py _block_verify_paged), minus the model.
    q: [B, G, Hkv, group, Dh]."""
    B, G, n_kv, group, Dh = q.shape
    bs = k_pool.shape[1]
    nb = tables.shape[1]
    if k_scale is None:
        kc = k_pool[tables].reshape(B, nb * bs, n_kv, Dh)
        vc = v_pool[tables].reshape(B, nb * bs, n_kv, Dh)
    else:
        kc = _gather_dequant(k_pool, k_scale, tables, q.dtype)
        vc = _gather_dequant(v_pool, v_scale, tables, q.dtype)
    s = jnp.einsum("bqkgd,bskd->bkgqs", q, kc).astype(jnp.float32) * scale
    idx = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, 1, nb * bs), 4)
    qpos = lengths[:, None, None, None, None] + jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, 1, G, 1), 3)
    s = jnp.where(idx <= qpos, s, NEG_INF)
    if window is not None:
        s = jnp.where(idx > qpos - window, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bkgqs,bskd->bqkgd", p, vc)
