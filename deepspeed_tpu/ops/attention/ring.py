"""Ring attention — sequence/context parallelism over ICI.

The reference version has NO sequence parallelism (SURVEY §2.2: absent at
v0.6.4; its long-sequence story is block-sparse attention). This module is
the modern TPU-native equivalent capability called for by BASELINE.md's
north star: exact attention over sequences sharded across chips.

Design (Ring Attention / blockwise attention):
- the sequence dim of Q, K, V is sharded over the 'sequence' mesh axis;
- each device computes attention of its local Q block against the K/V
  block it currently holds, maintaining online-softmax running stats
  (max, sum, accumulator) exactly like flash attention;
- K/V blocks rotate around the ring via `lax.ppermute` each step, so after
  n_seq steps every Q block has seen every K/V block; peak memory is
  O(S/n) per chip and the rotation overlaps with compute via XLA's
  latency-hiding scheduler;
- causal masking uses global token positions, so the result is exactly
  standard causal attention.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def _ring_attention_local(q, k, v, segs, kvm, *, axis: str, causal: bool,
                          scale: float, window: Optional[int]):
    """Inside shard_map: q local [B, S_loc, H, D]; k/v may carry Hkv < H
    heads (GQA) — the SMALL grouped k/v rotate around the ring (the
    ICI-traffic win scales with the group factor) and are repeated
    locally per step for the einsum. segs/kvm: [B, S_loc] per-token
    metadata (packed segment ids / key-validity) that ROTATES with its
    K/V block — each step masks scores against the metadata of the block
    currently held, so packing and padding masks are exact under the
    ring. Returns [B, S_loc, H, D]."""
    n = jax.lax.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    B, S_loc, H, D = q.shape
    Hkv = k.shape[2]
    assert H % Hkv == 0, f"q heads {H} not a multiple of kv heads {Hkv}"
    group = H // Hkv
    qf = q.astype(jnp.float32)

    q_pos = idx * S_loc + jax.lax.broadcasted_iota(
        jnp.int32, (S_loc, S_loc), 0)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        k_cur, v_cur, segs_cur, kvm_cur, m, l, acc = carry
        # the block currently held originated at ring position (idx - i) % n
        src = (idx - i) % n
        # repeat LOCALLY for the einsum; the carry (and the ppermute
        # below) stays at the small grouped width
        k_use = jnp.repeat(k_cur, group, axis=2) if group > 1 else k_cur
        v_use = jnp.repeat(v_cur, group, axis=2) if group > 1 else v_cur
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_use.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * S_loc + jax.lax.broadcasted_iota(
                jnp.int32, (S_loc, S_loc), 1)
            mask = q_pos[None, None] >= k_pos[None, None]
            if window is not None:
                mask = jnp.logical_and(
                    mask, q_pos[None, None] - k_pos[None, None] < window)
            s = jnp.where(mask, s, -1e30)
        if segs_cur is not None:
            same = segs[:, None, :, None] == segs_cur[:, None, None, :]
            s = jnp.where(same, s, -1e30)
        if kvm_cur is not None:
            s = jnp.where(kvm_cur[:, None, None, :] > 0, s, -1e30)
        m_cur = jnp.max(s, axis=-1, keepdims=True)            # [B,H,Sq,1]
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, v_use.astype(jnp.float32))
        acc_new = acc * alpha.transpose(0, 1, 2, 3) + pv
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        segs_nxt = (None if segs_cur is None
                    else jax.lax.ppermute(segs_cur, axis, perm))
        kvm_nxt = (None if kvm_cur is None
                   else jax.lax.ppermute(kvm_cur, axis, perm))
        return (k_nxt, v_nxt, segs_nxt, kvm_nxt, m_new, l_new,
                acc_new), None

    m0 = jnp.full((B, H, S_loc, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, H, S_loc, 1), jnp.float32)
    acc0 = jnp.zeros((B, H, S_loc, D), jnp.float32)
    (_, _, _, _, m, l, acc), _ = jax.lax.scan(
        step, (k, v, segs, kvm, m0, l0, acc0), jnp.arange(n))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe).transpose(0, 2, 1, 3)                # [B,S_loc,H,D]
    return out.astype(q.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, *, causal: bool = True,
                   scale: Optional[float] = None,
                   axis: str = "sequence",
                   segment_ids: Optional[jnp.ndarray] = None,
                   kv_mask: Optional[jnp.ndarray] = None,
                   window: Optional[int] = None) -> jnp.ndarray:
    """Exact (causal) attention with the sequence dim sharded over ``axis``.

    q,k,v: [B, S, H, D] global arrays whose S dim is (or will be) sharded
    over the 'sequence' mesh axis. Batch/head dims stay auto-sharded.

    segment_ids/kv_mask: [B, S] packed-sequence ids / key-validity —
    sharded like the tokens; each shard's slice rotates around the ring
    with its K/V block, so packing/padding masks are exact. window:
    sliding-window causal attention (mask-exact; out-of-band ring steps
    still rotate — the flash kernel's DMA elision is the single-chip
    perf path, the ring's win is capacity).
    """
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if window is not None:
        assert causal, "sliding window requires causal attention"
    if segment_ids is not None:
        segment_ids = segment_ids.astype(jnp.int32)
    if kv_mask is not None:
        kv_mask = kv_mask.astype(jnp.float32)
    inner = partial(_ring_attention_local, axis=axis, causal=causal,
                    scale=scale, window=window)
    spec = P(None, axis, None, None)
    tok_spec = P(None, axis)
    args = [q, k, v, segment_ids, kv_mask]
    in_specs = [spec, spec, spec,
                None if segment_ids is None else tok_spec,
                None if kv_mask is None else tok_spec]
    mapped = jax.shard_map(
        inner, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=spec,
        axis_names={axis},
        check_vma=False)
    # partial-manual shard_map mis-canonicalizes out_specs when traced
    # eagerly in this jax version; under jit it is correct — force it.
    return jax.jit(mapped)(*args)
