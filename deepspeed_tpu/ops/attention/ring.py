"""Ring attention — sequence/context parallelism over ICI, flash-grade.

The reference version has NO sequence parallelism (SURVEY §2.2: absent at
v0.6.4; its long-sequence story is block-sparse attention). This module is
the modern TPU-native equivalent capability called for by BASELINE.md's
north star: exact attention over sequences sharded across chips.

Design (Ring Attention / blockwise attention):
- the sequence dim of Q, K, V is sharded over the 'sequence' mesh axis;
- each device computes attention of its local Q block against the K/V
  block it currently holds; per-block results carry their logsumexp and
  are combined across ring steps with an online softmax — exactly the
  flash-attention recurrence lifted one level up;
- K/V blocks rotate around the ring via `lax.ppermute` each step, so after
  n_seq steps every Q block has seen every K/V block; the rotation
  overlaps with compute via XLA's latency-hiding scheduler;
- the LOCAL block computation is the Pallas flash kernel
  (ops/attention/flash.py `flash_block_fwd_t/bwd_t`, kernel layout held
  across the whole loop so q/do/o are padded+transposed once, not per
  step) on TPU, and a chunked online-softmax in plain jnp elsewhere —
  peak local memory is O(S_loc · block), never the O(S_loc²) dense score
  matrix;
- the ring loop is UNROLLED (the ring size is static), so each step's
  mask geometry is static too: step 0 is ordinary causal attention,
  step i ≥ 1 sees a K/V block exactly i·S_loc tokens behind its queries
  — causality is automatic there, and a sliding window becomes a band
  at a static offset the kernel's index maps can elide DMAs for.
  Steps whose band is statically empty are dropped entirely, so causal
  sliding-window ring attention does ceil((w+S_loc-1)/S_loc) hops, not
  n_seq;
- a module-level `jax.custom_vjp` replays the rotation schedule in the
  backward pass (dk/dv accumulators travel WITH their K/V block and are
  delivered home over whichever direction is fewer hops), so reverse-mode
  never materializes per-step dense residuals from scan transposition;
- causal masking uses global token positions, so the result is exactly
  standard causal attention; per-token metadata (packed segment ids /
  key-validity) ROTATES with its K/V block, so packing and padding masks
  are exact under the ring.

Contract for degenerate rows: a row with NO valid visible key anywhere
returns exact 0 (the dense single-chip path returns a uniform average of
v instead — both are garbage-by-contract; any masked loss zeroes their
gradient).

Zigzag layout (``layout="zigzag"``): causal ring attention on a
contiguous layout is imbalanced — device d's queries can see d+1 of the
n K/V blocks, so the last device does ~2x the work of the average and
sets the wall clock. The zigzag layout splits the sequence into 2n
chunks and gives device d chunks (d, 2n-1-d) — every device then holds
exactly one "early" and one "late" chunk and does the SAME work at
every ring step:
- step 0 (self): the local shard [lo, hi] is globally monotone and its
  chunk boundaries align, so a plain LOCAL causal mask is exactly the
  global causal mask restricted to this block;
- step i>0 against the block from device src=(idx-i) mod n: if
  src < idx both local chunks see src's LOW chunk fully (its high chunk
  is entirely in their future); if src > idx the local HIGH chunk sees
  both of src's chunks fully (the low chunk sees neither). Either way
  the step computes exactly half the full-block work, mask-free.
Tokens must be pre-permuted with :func:`zigzag_perm` (and positions /
targets / segment metadata with them) — the model's per-token compute
is permutation-invariant, so only the data layout changes.
Sliding windows are not supported under zigzag (the band geometry is no
longer a static per-step offset); use the contiguous layout there.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.ops.attention.flash import (
    NEG_INF, _norm_window, _pad_heads, flash_block_bwd_t,
    flash_block_fwd_t, resolve_window_impl)
from deepspeed_tpu.utils.jax_compat import axis_size, shard_map


def _largest_divisor(n: int, cap: int) -> int:
    """Largest divisor of ``n`` that is <= cap (cap >= 1)."""
    for c in range(min(cap, n), 0, -1):
        if n % c == 0:
            return c
    return 1


def zigzag_perm(S: int, n: int) -> np.ndarray:
    """Token permutation for the zigzag ring layout: split the sequence
    into 2n chunks; device d's shard is [chunk d, chunk 2n-1-d]. Apply to
    tokens/targets/positions/segment metadata on the HOST (``x[:, p]``)
    before sharding the sequence dim contiguously over the ring axis."""
    assert S % (2 * n) == 0, (S, n)
    C = S // (2 * n)
    out = np.empty(S, np.int64)
    for d in range(n):
        base = d * 2 * C
        out[base:base + C] = np.arange(d * C, (d + 1) * C)
        out[base + C:base + 2 * C] = np.arange((2 * n - 1 - d) * C,
                                               (2 * n - d) * C)
    return out


def zigzag_unperm(S: int, n: int) -> np.ndarray:
    """Inverse of :func:`zigzag_perm` (restore global order)."""
    return np.argsort(zigzag_perm(S, n))


def _seq_slice(x, a, b, axis):
    """x[..., a:b, ...] along ``axis`` (static bounds)."""
    return None if x is None else jax.lax.slice_in_dim(x, a, b, axis=axis)


def _num_steps(n: int, S_loc: int, causal: bool, window) -> int:
    """Ring hops that can ever intersect the attention band. For causal
    sliding-window attention, block i's closest key is i*S_loc - (S_loc-1)
    tokens behind the query — once that is >= window the step is dead for
    EVERY device and the rotation chain stops early. (Host arithmetic:
    the early stop applies to the masked impl too — a dead step is dead
    regardless of how in-band blocks mask.)"""
    win = _norm_window(window)[0]
    if causal and win is not None:
        return min(n, -(-(win + S_loc - 1) // S_loc))
    return n


def _step_cfg(i: int, S_loc: int, causal: bool, window):
    """Static mask geometry of ring step i: (causal, q_off, window) for
    the local block call. Step 0 is self-attention; step i >= 1 sees keys
    exactly i*S_loc tokens behind every query, so causality is automatic
    (mask-free) unless a sliding window cuts a band through the block.
    ``window`` may be the tagged ("masked", W) form — geometry uses the
    int, but the RETURNED window keeps the tag so the flash block leafs
    pick the requested impl (flash._norm_window)."""
    win = _norm_window(window)[0]
    if not causal:
        return False, 0, None
    if i == 0:
        return True, 0, window
    off = i * S_loc
    if win is None or off + S_loc - 1 < win:
        return False, 0, None       # fully in band: no masking at all
    return True, off, window


# ---------------------------------------------------------------------------
# local block compute (jnp fallback: chunked online softmax)
# ---------------------------------------------------------------------------

def _mask_scores(s, rows, cols, blk_causal, window, qsegs, ksegs, kvm):
    """Apply causal/window/segment/validity masks to [B, H, Sq, c]."""
    window = _norm_window(window)[0]     # mask arithmetic needs the int
    if blk_causal:
        m = rows[None, None, :, None] >= cols[None, None, None, :]
        if window is not None:
            m = jnp.logical_and(
                m, rows[None, None, :, None] - cols[None, None, None, :]
                < window)
        s = jnp.where(m, s, NEG_INF)
    if qsegs is not None:
        same = qsegs[:, None, :, None] == ksegs[:, None, None, :]
        s = jnp.where(same, s, NEG_INF)
    if kvm is not None:
        s = jnp.where(kvm[:, None, None, :] > 0, s, NEG_INF)
    return s


def _chunk_scores(qf, k, v, qsegs, ksegs, kvm, j, c, *, rows, group,
                  blk_causal, window, scale):
    """Shared fwd/bwd chunk prologue: slice chunk j of the held K/V block
    (+ its rotated metadata), repeat GQA groups, compute masked scores.
    The q-position offset is already baked into ``rows`` by the caller.
    Returns (s [B,H,Sq,c] fp32, kj, vj [B,c,H,D])."""
    kj = jax.lax.dynamic_slice_in_dim(k, j * c, c, axis=1)
    vj = jax.lax.dynamic_slice_in_dim(v, j * c, c, axis=1)
    if group > 1:
        kj = jnp.repeat(kj, group, axis=2)
        vj = jnp.repeat(vj, group, axis=2)
    ksj = (None if ksegs is None else
           jax.lax.dynamic_slice_in_dim(ksegs, j * c, c, axis=1))
    kvj = (None if kvm is None else
           jax.lax.dynamic_slice_in_dim(kvm, j * c, c, axis=1))
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kj.astype(jnp.float32)) * scale
    cols = j * c + jnp.arange(c, dtype=jnp.int32)
    s = _mask_scores(s, rows, cols, blk_causal, window, qsegs, ksj, kvj)
    return s, kj, vj


def _jnp_block_fwd(q, k, v, qsegs, ksegs, kvm, *, blk_causal, window,
                   q_off, scale, chunk):
    """Chunked online-softmax attention of local q [B,S,H,D] against one
    K/V block. Peak memory O(B·H·S·chunk) instead of the dense
    O(B·H·S·S_kv). Returns (o [B,H,S,D] in q.dtype, lse [B,H,S] fp32)."""
    B, S, H, D = q.shape
    Skv, Hkv = k.shape[1], k.shape[2]
    group = H // Hkv
    c = _largest_divisor(Skv, chunk)
    nc = Skv // c
    qf = q.astype(jnp.float32)
    rows = q_off + jnp.arange(S, dtype=jnp.int32)
    prolog = functools.partial(
        _chunk_scores, qf, k, v, qsegs, ksegs, kvm, c=c, rows=rows,
        group=group, blk_causal=blk_causal, window=window, scale=scale)

    def step(carry, j):
        m, l, acc = carry
        s, _, vj = prolog(j=j)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vj.astype(jnp.float32))
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((B, H, S), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    acc0 = jnp.zeros((B, H, S, D), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(step, (m0, l0, acc0),
                                  jnp.arange(nc, dtype=jnp.int32))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = (acc / l_safe[..., None]).astype(q.dtype)          # [B,H,S,D]
    lse = m + jnp.log(l_safe)
    return o, lse


def _jnp_block_bwd(q, k, v, do, lse, delta, qsegs, ksegs, kvm, *,
                   blk_causal, window, q_off, scale, chunk):
    """This block's additive (dq, dk, dv) contribution given the GLOBAL
    lse [B,H,S] and delta [B,H,S] (= rowsum(do*o)). Chunked like the
    forward. Returns fp32 (dq [B,H,S,D], dk/dv [B,Hkv,Skv,D])."""
    B, S, H, D = q.shape
    Skv, Hkv = k.shape[1], k.shape[2]
    group = H // Hkv
    c = _largest_divisor(Skv, chunk)
    nc = Skv // c
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    rows = q_off + jnp.arange(S, dtype=jnp.int32)
    prolog = functools.partial(
        _chunk_scores, qf, k, v, qsegs, ksegs, kvm, c=c, rows=rows,
        group=group, blk_causal=blk_causal, window=window, scale=scale)

    def step(dq_acc, j):
        s, kj, vj = prolog(j=j)
        p = jnp.exp(s - lse[..., None])                    # [B,H,S,c]
        dv_j = jnp.einsum("bhqk,bqhd->bhkd", p, dof)       # [B,H,c,D]
        dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vj.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bhqd", ds,
                                     kj.astype(jnp.float32))
        dk_j = jnp.einsum("bhqk,bqhd->bhkd", ds, qf)       # [B,H,c,D]
        if group > 1:
            dk_j = dk_j.reshape(B, Hkv, group, c, D).sum(2)
            dv_j = dv_j.reshape(B, Hkv, group, c, D).sum(2)
        return dq_acc, (dk_j, dv_j)

    dq0 = jnp.zeros((B, H, S, D), jnp.float32)
    dq, (dks, dvs) = jax.lax.scan(step, dq0,
                                  jnp.arange(nc, dtype=jnp.int32))
    dk = jnp.moveaxis(dks, 0, 2).reshape(B, Hkv, Skv, D)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(B, Hkv, Skv, D)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# ring core (inside shard_map) with custom VJP
# ---------------------------------------------------------------------------

def _rotate(xs, axis, perm):
    return [None if x is None else jax.lax.ppermute(x, axis, perm)
            for x in xs]


def _ring_fwd_inner(q, k, v, segs, kvm, axis, causal, scale, window,
                    use_flash, block_q, block_kv, chunk, layout):
    n = axis_size(axis)
    idx = jax.lax.axis_index(axis)
    B, S_loc, H, D = q.shape
    zig = layout == "zigzag"
    steps = n if zig else _num_steps(n, S_loc, causal, window)
    C = S_loc // 2                           # zigzag half-block
    perm = [(j, (j + 1) % n) for j in range(n)]

    if use_flash:
        # kernel layout once for the whole loop: [B, H, S, Dp] with the
        # head dim sublane-padded — the K/V carry rotates transposed too
        qp, kp, vp, D0, Dp = _pad_heads(q, k, v)
        q_use = qp.transpose(0, 2, 1, 3)
        k_cur = kp.transpose(0, 2, 1, 3)
        v_cur = vp.transpose(0, 2, 1, 3)
        seq_ax = 2                           # seq axis of q/k/v operands
    else:
        q_use, k_cur, v_cur, D0, Dp = q, k, v, D, D
        seq_ax = 1
    segs_cur, kvm_cur = segs, kvm

    def fwd_block(q_c, k_c, v_c, qsg, sg, km, bc, off, w):
        """One local attention block in the current operand layout.
        Returns (o [B,H,Sq,Dp], lse [B,H,Sq])."""
        if use_flash:
            return flash_block_fwd_t(
                q_c, k_c, v_c, kv_mask=km, q_segs=qsg, kv_segs=sg,
                causal=bc, scale=scale, block_q=block_q,
                block_kv=block_kv, window=w, q_off=off)
        return _jnp_block_fwd(q_c, k_c, v_c, qsg, sg, km,
                              blk_causal=bc, window=w, q_off=off,
                              scale=scale, chunk=chunk)

    m = jnp.full((B, H, S_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, S_loc), jnp.float32)
    acc = jnp.zeros((B, H, S_loc, Dp), jnp.float32)

    for i in range(steps):
        if zig and i > 0:
            # balanced zigzag step: src's block is either entirely
            # visible to-the-low-chunk-level (src < idx: its low chunk
            # is past for BOTH local chunks, its high chunk future for
            # both) or visible only to the local high chunk (src > idx:
            # both its chunks are past for the high chunk, future for
            # the low). Both branches are mask-free half-block work.
            def br_lo(k_c=k_cur, v_c=v_cur, sg=segs_cur, km=kvm_cur):
                o, lse = fwd_block(
                    q_use, _seq_slice(k_c, 0, C, seq_ax),
                    _seq_slice(v_c, 0, C, seq_ax), segs,
                    _seq_slice(sg, 0, C, 1), _seq_slice(km, 0, C, 1),
                    False, 0, None)
                return o, lse

            def br_hi(k_c=k_cur, v_c=v_cur, sg=segs_cur, km=kvm_cur):
                o_hi, lse_hi = fwd_block(
                    _seq_slice(q_use, C, S_loc, 2 if use_flash else 1),
                    k_c, v_c, _seq_slice(segs, C, S_loc, 1), sg, km,
                    False, 0, None)
                pad_o = jnp.zeros((B, H, C, Dp), o_hi.dtype)
                pad_l = jnp.full((B, H, C), NEG_INF, jnp.float32)
                return (jnp.concatenate([pad_o, o_hi], axis=2),
                        jnp.concatenate([pad_l, lse_hi], axis=2))

            src = jax.lax.rem(idx - i + n, n)
            o_i, lse_i = jax.lax.cond(src < idx, br_lo, br_hi)
            o_i = o_i.astype(q.dtype)
        else:
            blk_causal, q_off, blk_window = (
                (causal, 0, window) if zig
                else _step_cfg(i, S_loc, causal, window))

            def compute(k_c=k_cur, v_c=v_cur, sg=segs_cur, km=kvm_cur,
                        bc=blk_causal, off=q_off, w=blk_window):
                return fwd_block(q_use, k_c, v_c, segs, sg, km, bc, off,
                                 w)

            if causal and i > 0:
                # contiguous layout: devices "above" this step's source
                # never see it (the block is entirely in their future) —
                # skip the compute, not just the result. No collectives
                # inside, so a device-varying branch is fine under
                # shard_map.
                o_i, lse_i = jax.lax.cond(
                    idx >= i, compute,
                    lambda: (jnp.zeros((B, H, S_loc, Dp), q.dtype),
                             jnp.full((B, H, S_loc), NEG_INF,
                                      jnp.float32)))
            else:
                o_i, lse_i = compute()

        m_new = jnp.maximum(m, lse_i)
        alpha = jnp.exp(m - m_new)
        # a block where a row has NO valid key reports lse == NEG_INF and
        # a garbage o (uniform over its local keys, the dense-softmax
        # degenerate form) — gate its mass to zero so rows with no valid
        # visible key anywhere come out as exact 0 (see module contract)
        coef = jnp.where(lse_i > NEG_INF / 2, jnp.exp(lse_i - m_new), 0.0)
        l = l * alpha + coef
        acc = acc * alpha[..., None] + coef[..., None] * \
            o_i.astype(jnp.float32)
        m = m_new

        if i < steps - 1:
            k_cur, v_cur, segs_cur, kvm_cur = _rotate(
                [k_cur, v_cur, segs_cur, kvm_cur], axis, perm)

    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe[..., None]).transpose(0, 2, 1, 3)[..., :D0]
    lse = m + jnp.log(l_safe)
    return out.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10,
                                                    11, 12, 13))
def _ring_core(q, k, v, segs, kvm, axis, causal, scale, window, use_flash,
               block_q, block_kv, chunk, layout):
    out, _ = _ring_fwd_inner(q, k, v, segs, kvm, axis, causal, scale,
                             window, use_flash, block_q, block_kv, chunk,
                             layout)
    return out


def _ring_core_fwd(q, k, v, segs, kvm, axis, causal, scale, window,
                   use_flash, block_q, block_kv, chunk, layout):
    out, lse = _ring_fwd_inner(q, k, v, segs, kvm, axis, causal, scale,
                               window, use_flash, block_q, block_kv,
                               chunk, layout)
    return out, (q, k, v, segs, kvm, out, lse)


def _ring_core_bwd(axis, causal, scale, window, use_flash, block_q,
                   block_kv, chunk, layout, res, g):
    q, k, v, segs, kvm, o, lse = res
    do = g
    n = axis_size(axis)
    idx = jax.lax.axis_index(axis)
    B, S_loc, H, D = q.shape
    Hkv = k.shape[2]
    zig = layout == "zigzag"
    steps = n if zig else _num_steps(n, S_loc, causal, window)
    C = S_loc // 2
    perm = [(j, (j + 1) % n) for j in range(n)]

    # global per-row delta = rowsum(do * o) — shared by every block's
    # recompute (FA2 backward identity); computed ONCE, like the layout
    # change below (both are step-invariant)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).transpose(0, 2, 1)            # [B, H, S_loc]

    if use_flash:
        qp, kp, vp, D0, Dp = _pad_heads(q, k, v)
        dop = _pad_heads(do, do, do)[0]
        q_use = qp.transpose(0, 2, 1, 3)
        k_cur = kp.transpose(0, 2, 1, 3)
        v_cur = vp.transpose(0, 2, 1, 3)
        do_use = dop.transpose(0, 2, 1, 3)
        seq_ax = 2
    else:
        q_use, k_cur, v_cur, do_use = q, k, v, do
        D0, Dp = D, D
        seq_ax = 1
    segs_cur, kvm_cur = segs, kvm

    def bwd_block(q_c, do_c, lse_c, delta_c, k_c, v_c, qsg, sg, km, bc,
                  off, w):
        """One local backward block in the current operand layout.
        Returns fp32 (dq [B,H,Sq,Dp], dk/dv [B,Hkv,Skv,Dp])."""
        if use_flash:
            dq_i, dk_i, dv_i = flash_block_bwd_t(
                q_c, k_c, v_c, do_c, lse_c, kv_mask=km, q_segs=qsg,
                kv_segs=sg, causal=bc, scale=scale, block_q=block_q,
                block_kv=block_kv, window=w, q_off=off, delta=delta_c)
        else:
            dq_i, dk_i, dv_i = _jnp_block_bwd(
                q_c, k_c, v_c, do_c, lse_c, delta_c, qsg, sg, km,
                blk_causal=bc, window=w, q_off=off, scale=scale,
                chunk=chunk)
        return (dq_i.astype(jnp.float32), dk_i.astype(jnp.float32),
                dv_i.astype(jnp.float32))

    dq = jnp.zeros((B, H, S_loc, Dp), jnp.float32)
    dk_acc = jnp.zeros((B, Hkv, S_loc, Dp), jnp.float32)
    dv_acc = jnp.zeros((B, Hkv, S_loc, Dp), jnp.float32)

    for i in range(steps):
        if zig and i > 0:
            # mirror of the forward's balanced branches (see
            # _ring_fwd_inner): src < idx -> all q rows vs src's low
            # chunk (grads land in the accumulator's low half);
            # src > idx -> local high q rows vs src's full block.
            def br_lo(k_c=k_cur, v_c=v_cur, sg=segs_cur, km=kvm_cur):
                dq_i, dk_lo, dv_lo = bwd_block(
                    q_use, do_use, lse, delta,
                    _seq_slice(k_c, 0, C, seq_ax),
                    _seq_slice(v_c, 0, C, seq_ax), segs,
                    _seq_slice(sg, 0, C, 1), _seq_slice(km, 0, C, 1),
                    False, 0, None)
                pad = jnp.zeros((B, Hkv, C, Dp), jnp.float32)
                return (dq_i, jnp.concatenate([dk_lo, pad], axis=2),
                        jnp.concatenate([dv_lo, pad], axis=2))

            def br_hi(k_c=k_cur, v_c=v_cur, sg=segs_cur, km=kvm_cur):
                dq_hi, dk_i, dv_i = bwd_block(
                    _seq_slice(q_use, C, S_loc, seq_ax),
                    _seq_slice(do_use, C, S_loc, seq_ax),
                    _seq_slice(lse, C, S_loc, 2),
                    _seq_slice(delta, C, S_loc, 2),
                    k_c, v_c, _seq_slice(segs, C, S_loc, 1), sg, km,
                    False, 0, None)
                pad = jnp.zeros((B, H, C, Dp), jnp.float32)
                return (jnp.concatenate([pad, dq_hi], axis=2), dk_i,
                        dv_i)

            src = jax.lax.rem(idx - i + n, n)
            dq_i, dk_i, dv_i = jax.lax.cond(src < idx, br_lo, br_hi)
        else:
            blk_causal, q_off, blk_window = (
                (causal, 0, window) if zig
                else _step_cfg(i, S_loc, causal, window))

            def compute(k_c=k_cur, v_c=v_cur, sg=segs_cur, km=kvm_cur,
                        bc=blk_causal, off=q_off, w=blk_window):
                return bwd_block(q_use, do_use, lse, delta, k_c, v_c,
                                 segs, sg, km, bc, off, w)

            if causal and i > 0:
                dq_i, dk_i, dv_i = jax.lax.cond(
                    idx >= i, compute,
                    lambda: (jnp.zeros((B, H, S_loc, Dp), jnp.float32),
                             jnp.zeros((B, Hkv, S_loc, Dp), jnp.float32),
                             jnp.zeros((B, Hkv, S_loc, Dp),
                                       jnp.float32)))
            else:
                dq_i, dk_i, dv_i = compute()

        dq = dq + dq_i
        dk_acc = dk_acc + dk_i
        dv_acc = dv_acc + dv_i

        if i < steps - 1:
            k_cur, v_cur, segs_cur, kvm_cur, dk_acc, dv_acc = _rotate(
                [k_cur, v_cur, segs_cur, kvm_cur, dk_acc, dv_acc],
                axis, perm)

    # deliver each K/V block's grad accumulator back to its origin: block
    # b sits at device (b + steps - 1) % n now — go forward the rest of
    # the way around, or retrace backwards, whichever is fewer hops
    fwd_hops = (n - steps + 1) % n
    bwd_hops = steps - 1
    if fwd_hops <= bwd_hops:
        for _ in range(fwd_hops):
            dk_acc, dv_acc = _rotate([dk_acc, dv_acc], axis, perm)
    else:
        inv = [(j, (j - 1) % n) for j in range(n)]
        for _ in range(bwd_hops):
            dk_acc, dv_acc = _rotate([dk_acc, dv_acc], axis, inv)

    dq_out = dq.transpose(0, 2, 1, 3)[..., :D0].astype(q.dtype)
    dk_out = dk_acc.transpose(0, 2, 1, 3)[..., :D0].astype(k.dtype)
    dv_out = dv_acc.transpose(0, 2, 1, 3)[..., :D0].astype(v.dtype)
    return dq_out, dk_out, dv_out, None, None


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, *, causal: bool = True,
                   scale: Optional[float] = None,
                   axis: str = "sequence",
                   segment_ids: Optional[jnp.ndarray] = None,
                   kv_mask: Optional[jnp.ndarray] = None,
                   window: Optional[int] = None,
                   use_flash: Optional[bool] = None,
                   block_q: int = 512, block_kv: int = 512,
                   chunk: int = 1024,
                   layout: str = "contiguous",
                   window_impl: Optional[str] = None) -> jnp.ndarray:
    """Exact (causal) attention with the sequence dim sharded over ``axis``.

    q,k,v: [B, S, H, D] global arrays whose S dim is (or will be) sharded
    over the 'sequence' mesh axis. Batch/head dims stay auto-sharded.
    k/v may carry fewer heads (GQA) — the SMALL grouped k/v rotate around
    the ring (the ICI-traffic win scales with the group factor).

    segment_ids/kv_mask: [B, S] packed-sequence ids / key-validity —
    sharded like the tokens; each shard's slice rotates around the ring
    with its K/V block, so packing/padding masks are exact. window:
    sliding-window causal attention — ring steps whose band is
    statically empty are dropped, so the rotation does
    ceil((window + S_loc - 1)/S_loc) hops instead of n_seq.

    The local block runs the Pallas flash kernel on TPU (``use_flash``
    defaults to auto-detect; ``block_q``/``block_kv`` are clamped to
    divisors of the local shard) and a chunked online-softmax in plain
    jnp elsewhere (``chunk`` keys at a time) — peak local memory is
    O(S_loc · block), not O(S_loc²). Backward runs through a ring-level
    custom VJP that replays the rotation (no dense per-step residuals).

    layout: "contiguous" (default) shards the sequence in order;
    "zigzag" expects tokens pre-permuted with :func:`zigzag_perm` and
    balances the causal triangle so every device does equal work at
    every ring step (~2x faster at large ring sizes; see module
    docstring). Causal-only, no sliding window.
    """
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if window is not None:
        assert causal, "sliding window requires causal attention"
        # tag for the masked fallback (PARITY.md window quarantine); the
        # tag rides the nondiff window arg into the flash block leafs
        window = resolve_window_impl(window, window_impl)
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring layout {layout!r}")
    if layout == "zigzag":
        if not causal or window is not None:
            raise ValueError(
                "zigzag layout balances the CAUSAL triangle; use the "
                "contiguous layout for non-causal or windowed attention")
    H, Hkv = q.shape[2], k.shape[2]
    assert H % Hkv == 0, f"q heads {H} not a multiple of kv heads {Hkv}"
    assert v.shape[2] == Hkv, \
        f"k has {Hkv} heads but v has {v.shape[2]} — kv head counts must match"
    n_seq = mesh.shape[axis]
    S = q.shape[1]
    assert S % n_seq == 0, (S, n_seq)
    S_loc = S // n_seq
    if layout == "zigzag":
        assert S_loc % 2 == 0, \
            f"zigzag needs an even local shard, got S_loc={S_loc}"
    if use_flash is None:
        from deepspeed_tpu.utils import on_tpu
        use_flash = on_tpu() and S_loc >= 128
    # zigzag steps run on half blocks — tiles must divide C as well
    blk_unit = S_loc // 2 if layout == "zigzag" else S_loc
    block_q = _largest_divisor(blk_unit, min(block_q, blk_unit))
    block_kv = _largest_divisor(blk_unit, min(block_kv, blk_unit))
    if segment_ids is not None:
        segment_ids = segment_ids.astype(jnp.int32)
    if kv_mask is not None:
        kv_mask = kv_mask.astype(jnp.float32)

    def inner(q, k, v, segs, kvm):
        return _ring_core(q, k, v, segs, kvm, axis, causal, scale, window,
                          use_flash, block_q, block_kv, chunk, layout)

    spec = P(None, axis, None, None)
    tok_spec = P(None, axis)
    args = [q, k, v, segment_ids, kv_mask]
    in_specs = [spec, spec, spec,
                None if segment_ids is None else tok_spec,
                None if kv_mask is None else tok_spec]
    mapped = shard_map(
        inner, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=spec,
        axis_names={axis},
        check_vma=False)
    # partial-manual shard_map mis-canonicalizes out_specs when traced
    # eagerly in this jax version; under jit it is correct — force it.
    return jax.jit(mapped)(*args)
