"""Chunked softmax cross-entropy — the vocabulary-projection + loss fused op.

Capability analog of the reference's fused logit/loss CUDA path (ref:
csrc/transformer/softmax_kernels.cu — fused scaled-masked softmax; the
reference never ships a vocab-parallel loss because Megatron owns it there,
ref tests/model/Megatron_GPT2 harness delegates to Megatron's
vocab_parallel_cross_entropy). TPU-first design:

At GPT-2 scale the logits tensor dominates loss-path memory: B=16, S=1024,
V=50k is a 3.3GB fp32 array, and the standard ``log_softmax`` path
materializes it (plus the log-prob tensor, plus a residual for the backward)
— several × 3.3GB of HBM for bytes that are consumed immediately. This op
scans over token chunks and computes, per chunk, only the row logsumexp and
the gold-token logit, so peak extra memory is O(chunk × V) instead of
O(N × V). The backward recomputes each chunk's logits (one extra logit
matmul — ~2% of a training step's FLOPs) and accumulates the vocab-weight
gradient in an fp32 scan carry.

The matmuls contract in the input dtype (bf16 on TPU) with fp32
accumulation on the MXU; softmax statistics and the dW accumulator are
fp32. dlogits is cast to the weight dtype for the two backward matmuls —
the same precision trade every other layer's gradients make.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["softmax_xent_ll", "chunked_softmax_xent"]


def _chunk_logits(xc, w, b):
    """[C, H] @ [V, H]^T (+ b) -> fp32 [C, V] with fp32 MXU accumulation."""
    logits = jax.lax.dot_general(
        xc, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if b is not None:
        logits = logits + b.astype(jnp.float32)
    return logits


def _fwd_scan(x, w, b, t, chunk):
    N, H = x.shape
    nc = N // chunk
    xs = x.reshape(nc, chunk, H)
    ts = t.reshape(nc, chunk)

    def body(_, xt):
        xc, tc = xt
        logits = _chunk_logits(xc, w, b)
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
        gold = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        return None, (gold - lse, lse)

    _, (ll, lse) = jax.lax.scan(body, None, (xs, ts))
    return ll.reshape(N), lse.reshape(N)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _xent_ll(x, w, b, t, chunk):
    ll, _ = _fwd_scan(x, w, b, t, chunk)
    return ll


def _xent_ll_fwd(x, w, b, t, chunk):
    ll, lse = _fwd_scan(x, w, b, t, chunk)
    return ll, (x, w, b, t, lse)


def _xent_ll_bwd(chunk, res, g):
    x, w, b, t, lse = res
    N, H = x.shape
    V = w.shape[0]
    nc = N // chunk
    xs = x.reshape(nc, chunk, H)
    ts = t.reshape(nc, chunk)
    gs = g.reshape(nc, chunk).astype(jnp.float32)
    ls = lse.reshape(nc, chunk)

    def body(carry, xtgl):
        dw, db = carry
        xc, tc, gc, lc = xtgl
        logits = _chunk_logits(xc, w, b)
        p = jnp.exp(logits - lc[:, None])                  # softmax, fp32
        cols = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
        onehot = (cols == tc[:, None]).astype(jnp.float32)
        dlog = gc[:, None] * (onehot - p)                  # d loss / d logits
        dlb = dlog.astype(w.dtype)
        dxc = jax.lax.dot_general(                         # [C,V] @ [V,H]
            dlb, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)
        dw = dw + jax.lax.dot_general(                     # [V,C] @ [C,H]
            dlb, xc, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if db is not None:
            db = db + jnp.sum(dlog, axis=0)
        return (dw, db), dxc

    dw0 = jnp.zeros((V, H), jnp.float32)
    db0 = None if b is None else jnp.zeros((V,), jnp.float32)
    (dw, db), dx = jax.lax.scan(body, (dw0, db0), (xs, ts, gs, ls))
    return (dx.reshape(N, H), dw.astype(w.dtype),
            None if b is None else db.astype(b.dtype), None)


_xent_ll.defvjp(_xent_ll_fwd, _xent_ll_bwd)


def softmax_xent_ll(x: jnp.ndarray, w: jnp.ndarray, targets: jnp.ndarray,
                    bias: Optional[jnp.ndarray] = None,
                    chunk: int = 2048) -> jnp.ndarray:
    """Per-token log-likelihood without materializing the logits matrix.

    ``ll[i] = logits[i, targets[i]] - logsumexp(logits[i])`` where
    ``logits = x @ w.T (+ bias)``.

    Args:
      x: ``[..., H]`` activations (compute dtype; leading dims flattened).
      w: ``[V, H]`` vocabulary projection (``wte`` layout — for an
        ``[H, V]`` lm-head kernel pass ``kernel.T``; XLA folds the
        transpose into the matmul).
      targets: ``[...]`` int32 gold token ids, same leading shape as x.
      bias: optional ``[V]`` logit bias (e.g. GPT-J lm_head).
      chunk: tokens per scan step. Peak extra memory is ~``chunk × V``
        fp32; 2048×50k ≈ 412MB. N is zero-padded up to a chunk multiple
        (padded rows get zero cotangent — they never contribute grads).

    Returns fp32 ``ll`` with the leading shape of ``targets``.
    """
    lead = targets.shape
    H = x.shape[-1]
    x2 = x.reshape(-1, H)
    t2 = targets.reshape(-1).astype(jnp.int32)
    N = x2.shape[0]
    c = int(min(chunk, N))
    # prefer an exact divisor of N near the requested chunk (same adaptive-
    # divisor approach as the flash block fallback) — a padded final chunk
    # wastes a full chunk of logit matmul when N is just over a multiple
    div = next((d for d in range(c, 0, -1) if N % d == 0), 1)
    if div >= c // 2:
        c = div
    pad = (-N) % c
    if pad:
        x2 = jnp.concatenate([x2, jnp.zeros((pad, H), x2.dtype)])
        t2 = jnp.concatenate([t2, jnp.zeros((pad,), t2.dtype)])
    ll = _xent_ll(x2, w, bias, t2, c)
    return ll[:N].reshape(lead)


def chunked_softmax_xent(x, w, targets, bias=None, chunk: int = 2048,
                         loss_mask=None) -> jnp.ndarray:
    """Masked-mean negative log-likelihood over ``targets`` (scalar fp32)."""
    ll = softmax_xent_ll(x, w, targets, bias=bias, chunk=chunk)
    if loss_mask is not None:
        return -(ll * loss_mask).sum() / jnp.maximum(loss_mask.sum(), 1.0)
    return -ll.mean()
