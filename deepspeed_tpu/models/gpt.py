"""GPT-family causal transformer — the framework's flagship training model.

Capability analog of the reference's Megatron-GPT2 workloads
(ref: tests/model/Megatron_GPT2 perf harness, tests/unit/megatron_model.py)
and of the fused transformer training kernel
(ref: csrc/transformer/ds_transformer_cuda.cpp — QKV GEMM, softmax, dropout,
layernorm, gelu). TPU-first design decisions:

- **Stacked layers + lax.scan**: all L layers' weights are stacked on a
  leading axis and the block runs under ``lax.scan`` — one compiled layer
  body regardless of depth (fast compiles, natural pipeline partitioning,
  and per-layer remat).
- **bf16 matmuls on the MXU**, fp32 layernorm/softmax accumulations.
- **TP via partition rules** on the stacked weights (see
  ``gpt_partition_rules``): column-parallel QKV/MLP-in, row-parallel
  attn-out/MLP-out — XLA inserts the two allreduces per layer that
  Megatron does by hand.
- Attention dispatches to the Pallas flash kernel on TPU when enabled
  (deepspeed_tpu.ops.attention.flash), else a fused-softmax jnp path.
"""

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.sharding import PartitionRule


@dataclass
class GPTConfig:
    vocab_size: int = 50304           # padded to 128-multiple for the MXU
    n_layers: int = 12
    n_heads: int = 12
    d_model: int = 768
    d_ff: Optional[int] = None        # default 4*d_model
    max_seq_len: int = 1024
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    remat: bool = True                # activation checkpointing per layer
    # 'full': recompute everything (nothing_saveable — min memory);
    # 'selective': save matmul/attention outputs, recompute layernorm/gelu/
    # elementwise only (~25% less recompute for ~8*d bytes/token/layer);
    # 'flash_only': save just the flash residuals; 'offload_flash': flash
    # residuals stream to pinned host memory — full-remat HBM footprint
    # without the flash-fwd recompute (cpu_checkpointing analog)
    remat_policy: str = "selective"
    use_flash_attention: bool = True
    # 1024-blocks measured fastest at seq>=1024 on v5e (PERF.md); the
    # kernel clamps to the sequence length for shorter inputs
    flash_block_q: int = 1024
    flash_block_kv: int = 1024
    # backward-kernel tiles (None = same as forward). The dq/dkv kernels
    # stream the full opposite operand per block, so their best tile can
    # differ from the forward's
    flash_block_bwd_q: Optional[int] = None
    flash_block_bwd_kv: Optional[int] = None
    tie_embeddings: bool = True
    # tokens per chunk for the fused chunked cross-entropy (0 = off, use
    # the dense log_softmax path). At large vocab×batch×seq the dense path
    # materializes multi-GB logits; chunking caps loss-path memory at
    # ~chunk×V fp32 (ops/cross_entropy.py)
    loss_chunk: int = 0
    # sequence/context parallelism: shard the token dim over the 'sequence'
    # mesh axis (set mesh too). sp_impl: 'ring' rotates K/V over ICI
    # (ops/attention/ring.py), 'ulysses' re-shards seq<->heads with two
    # all-to-alls and runs the full flash kernel locally
    # (ops/attention/ulysses.py).
    sequence_parallel: bool = False
    sp_impl: str = "ring"
    # ring data layout: "contiguous" shards the sequence in order;
    # "zigzag" balances the causal triangle across the ring (~2x at
    # large ring sizes) and expects tokens/targets/positions/segment
    # metadata pre-permuted with ops.attention.ring.zigzag_perm (the
    # rest of the model is per-token, so only attention cares)
    sp_layout: str = "contiguous"
    mesh: Any = None
    # --- architecture variants for foreign-checkpoint injection --------
    # (ref: module_inject/replace_policy.py — GPT-Neo :112 uses unscaled
    #  attention; GPT-J :157 uses rotary + parallel attn/MLP residual and
    #  no learned positions)
    attn_scale: Optional[float] = None     # None -> 1/sqrt(head_dim)
    rotary_dim: Optional[int] = None       # GPT-J rotary channels (0/None=off)
    parallel_residual: bool = False        # x + attn(h) + mlp(h), h=ln1(x)
    use_wpe: bool = True                   # learned absolute positions
    # grouped-query attention: fewer kv heads than q heads (None = MHA).
    # Shrinks the inference KV cache by n_heads/n_kv_heads; the flash
    # kernel groups kv blocks natively
    n_kv_heads: Optional[int] = None
    # sliding-window (local) attention: token i attends (i-window, i]
    # only — O(S*window) compute and HBM reads in the flash kernel
    attn_window: Optional[int] = None
    # "banded" (O(S*W) index-map clamps) or "masked" (in-body mask over
    # plain causal geometry — the Mosaic-proven fallback while the
    # banded clamp is under the r4 compile-hang quarantine); None
    # resolves from DS_FLASH_WINDOW_IMPL (default banded)
    attn_window_impl: Optional[str] = None
    # --- llama-family architecture knobs -------------------------------
    # norm: 'layernorm' (GPT-2) or 'rmsnorm' (llama — scale only, no
    # mean subtraction); activation: 'gelu' or 'swiglu' (gated MLP with
    # a SEPARATE gate kernel so column-parallel TP shards gate/up
    # consistently); use_bias=False drops every projection bias
    norm: str = "layernorm"
    norm_eps: float = 1e-5                 # llama checkpoints use 1e-6
    activation: str = "gelu"
    use_bias: bool = True
    rope_theta: float = 10000.0            # rotary base (llama-3: 5e5)

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        h = self.n_kv_heads or self.n_heads
        assert self.n_heads % h == 0, (self.n_heads, h)
        return h

    @property
    def qkv_dim(self) -> int:
        """Fused qkv projection width: H*Dh + 2*Hkv*Dh."""
        return (self.n_heads + 2 * self.kv_heads) * self.head_dim

    @property
    def ffn_dim(self) -> int:
        return self.d_ff if self.d_ff is not None else 4 * self.d_model


# canonical model-size presets (GPT-2 family; 1.5B mirrors the reference
# perf harness config: 48 layers / 1600 hidden / seq 1024,
# ref: tests/model/Megatron_GPT2/run_perf_baseline.py:17)
PRESETS = {
    "gpt2-small": dict(n_layers=12, n_heads=12, d_model=768),
    "gpt2-medium": dict(n_layers=24, n_heads=16, d_model=1024),
    "gpt2-large": dict(n_layers=36, n_heads=20, d_model=1280),
    "gpt2-xl": dict(n_layers=48, n_heads=25, d_model=1600),
    "gpt2-1.5b": dict(n_layers=48, n_heads=25, d_model=1600),
    "gpt2-4b": dict(n_layers=64, n_heads=32, d_model=2304),
    "gpt2-8b": dict(n_layers=72, n_heads=32, d_model=3072),
}

# llama-family architecture: rmsnorm + swiglu + rotary + no biases,
# untied head, no learned positions (ref capability analog: the policy
# registry's per-architecture variants, module_inject/replace_policy.py)
_LLAMA_ARCH = dict(norm="rmsnorm", activation="swiglu", use_bias=False,
                   use_wpe=False, tie_embeddings=False,
                   parallel_residual=False, norm_eps=1e-6)
PRESETS.update({
    "llama-tiny": dict(n_layers=4, n_heads=8, n_kv_heads=4, d_model=256,
                       d_ff=688, rotary_dim=32, vocab_size=512,
                       max_seq_len=256, **_LLAMA_ARCH),
    "llama-7b": dict(n_layers=32, n_heads=32, d_model=4096, d_ff=11008,
                     rotary_dim=128, vocab_size=32000, max_seq_len=2048,
                     **_LLAMA_ARCH),
    "llama-13b": dict(n_layers=40, n_heads=40, d_model=5120, d_ff=13824,
                      rotary_dim=128, vocab_size=32000, max_seq_len=2048,
                      **_LLAMA_ARCH),
})


def preset(name: str, **overrides) -> GPTConfig:
    cfg = dict(PRESETS[name])
    cfg.update(overrides)
    return GPTConfig(**cfg)


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: GPTConfig) -> Dict:
    """fp32 master parameters; layer weights stacked on axis 0."""
    k_embed, k_pos, k_layers, k_head = jax.random.split(rng, 4)
    d, L, ff = cfg.d_model, cfg.n_layers, cfg.ffn_dim
    init = jax.nn.initializers.normal(stddev=0.02)
    # residual-branch projections scaled per GPT-2 (1/sqrt(2L))
    resid_init = jax.nn.initializers.normal(stddev=0.02 / np.sqrt(2.0 * L))

    def stacked(key, shape, initializer=init):
        return initializer(key, (L,) + shape, jnp.float32)

    ks = jax.random.split(k_layers, 6)

    def norm_p():
        if cfg.norm == "rmsnorm":
            return {"scale": jnp.ones((L, d))}
        return {"scale": jnp.ones((L, d)), "bias": jnp.zeros((L, d))}

    def maybe_bias(entry, width):
        if cfg.use_bias:
            entry["bias"] = jnp.zeros((L, width))
        return entry

    params = {
        "wte": {"embedding": init(k_embed, (cfg.vocab_size, d), jnp.float32)},
        "wpe": {"embedding": init(k_pos, (cfg.max_seq_len, d), jnp.float32)},
        "block": {
            "ln1": norm_p(),
            "qkv": maybe_bias(
                {"kernel": stacked(ks[0], (d, cfg.qkv_dim))}, cfg.qkv_dim),
            "attn_out": maybe_bias(
                {"kernel": stacked(ks[1], (d, d), resid_init)}, d),
            "ln2": norm_p(),
            "mlp_in": maybe_bias(
                {"kernel": stacked(ks[2], (d, ff))}, ff),
            "mlp_out": maybe_bias(
                {"kernel": stacked(ks[3], (ff, d), resid_init)}, d),
        },
        "ln_f": ({"scale": jnp.ones((d,))} if cfg.norm == "rmsnorm"
                 else {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))}),
    }
    if cfg.activation == "swiglu":
        params["block"]["mlp_gate"] = maybe_bias(
            {"kernel": stacked(ks[4], (d, ff))}, ff)
    if not cfg.use_wpe:
        del params["wpe"]
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": init(k_head, (d, cfg.vocab_size), jnp.float32)}
    return params


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def remat_policy(name: str, flash: bool = False):
    """Checkpoint policy for the per-layer remat (analog of the reference's
    activation-checkpointing variants, ref:
    runtime/activation_checkpointing/checkpointing.py).

    'selective' saves the tagged matmul/attention outputs so the backward
    pass only recomputes layernorms, gelu and elementwise ops — the
    standard save-dots/recompute-elementwise trade. When the flash kernel
    is active its packed out residual ("flash_out") IS the attention
    output, so the "attn" tag is dropped to avoid saving the same bytes
    twice. 'flash_only' keeps just the flash residuals (~d bytes/token
    per layer) and recomputes the cheap matmuls — the memory-lean setting
    that fits 1.5B-class training on a 16GB chip. 'full' recomputes
    everything.
    """
    if name == "selective":
        names = ["qkv", "mlp_pre", "flash_out", "flash_lse"]
        if not flash:
            names.append("attn")
        return jax.checkpoint_policies.save_only_these_names(*names)
    if name == "flash_only":
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse")
    if name == "offload_flash":
        # flash residuals move to PINNED HOST memory instead of either
        # living in HBM (flash_only) or being recomputed (full): HBM cost
        # ~0 like 'full', backward skips the flash-fwd recompute like
        # 'flash_only'. The d2h/h2d rides the same async DMA path XLA
        # schedules around compute. TPU-native analog of the reference's
        # cpu_checkpointing (ref: runtime/activation_checkpointing/
        # checkpointing.py:28 PartitionedActivations/cpu_checkpointing).
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=["flash_out", "flash_lse"],
            offload_src="device", offload_dst="pinned_host")
    if name == "full":
        return jax.checkpoint_policies.nothing_saveable
    raise ValueError(f"unknown remat_policy {name!r} (expected "
                     "'selective', 'flash_only', 'offload_flash' or "
                     "'full')")


def _norm(x, p, cfg):
    """Config-dispatched normalization: GPT-2 layernorm or llama rmsnorm
    (scale-only, no mean subtraction). eps comes from cfg.norm_eps —
    llama-family checkpoints are trained with 1e-6."""
    eps = cfg.norm_eps
    if cfg.norm == "rmsnorm":
        xf = x.astype(jnp.float32)
        y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1,
                                        keepdims=True) + eps)
        return (y * p["scale"].astype(jnp.float32)).astype(x.dtype)
    return _layernorm(x, p["scale"], p["bias"], eps=eps)


def _kernel_of(p, dtype):
    """The (possibly int8-quantized) weight of a dense entry, in compute
    dtype. Weight-only int8 entries carry {"q": int8, "scale": fp32
    per-output-channel} instead of {"kernel"} (inference/engine.py
    quantize_weights_int8); dequantization fuses into the matmul."""
    if "q" in p:
        return p["q"].astype(dtype) * p["scale"].astype(dtype)
    return p["kernel"].astype(dtype)


def _int8_fused_enabled() -> bool:
    """DS_INT8_FUSED=1 routes int8 dense entries through the Pallas
    fused dequant-matmul (ops/int8_matmul.py) instead of trusting XLA
    to fuse _kernel_of's dequant — the fallback the reference covers
    with dedicated int8 GEMM kernels (ref: csrc/transformer/inference
    pt_binding.cpp:866). TPU-only: the kernel needs Mosaic."""
    from deepspeed_tpu.utils import on_tpu
    from deepspeed_tpu.utils.env import resolve_flag
    return resolve_flag("DS_INT8_FUSED") and on_tpu()


def _dense(h, p, lora=None):
    """h @ kernel (+ bias when the config kept biases). A LoRA-adapted
    entry (runtime/lora.py) adds the low-rank path h @ A @ B * scale —
    the dense delta is never materialized.

    ``lora`` is the serving-time multi-tenant hook (inference/
    adapters.py): a pair of per-slot gathered rank-block factors
    ``(a_blk [B, NBa, in, rb], b_blk [B, NBa, rb, out])`` applied as
    batched low-rank matmuls summed over the rank-block axis. Scale is
    pre-folded into b_blk; base-only slots gather the pool's all-zeros
    trash block, so their contribution is exactly +0.0."""
    blocks = None
    if "q" in p and p["q"].ndim == 2 and _int8_fused_enabled():
        from deepspeed_tpu.ops.int8_matmul import fit_blocks, int8_matmul
        blocks = fit_blocks(*p["q"].shape)
    if blocks is not None:
        lead, K = h.shape[:-1], h.shape[-1]
        y = int8_matmul(h.reshape(-1, K), p["q"],
                        p["scale"].reshape(1, -1),
                        block_k=blocks[0], block_n=blocks[1])
        y = y.reshape(*lead, y.shape[-1])
    else:
        y = h @ _kernel_of(p, h.dtype)
    if "lora_a" in p:
        y = y + ((h @ p["lora_a"].astype(h.dtype))
                 @ p["lora_b"].astype(h.dtype))             * p["lora_scale"].astype(h.dtype)
    if lora is not None:
        a_blk, b_blk = lora
        u = jnp.einsum("bsi,bnir->bnsr", h, a_blk.astype(h.dtype))
        y = y + jnp.einsum("bnsr,bnro->bso", u, b_blk.astype(h.dtype))
    b = p.get("bias")
    return y if b is None else y + b.astype(h.dtype)


def _qkv_split_rotary(qkv, cfg, positions, B, S):
    """Split a fused qkv projection into per-head q/k/v and apply rotary
    — the ONE copy of the attention prologue shared by the dense block,
    the MoE block, and inference prefill (divergent copies previously
    left rotary dead in the MoE block)."""
    H, Dh, Hkv = cfg.n_heads, cfg.head_dim, cfg.kv_heads
    q, k, v = jnp.split(qkv, [H * Dh, (H + Hkv) * Dh], axis=-1)
    q = q.reshape(B, S, H, Dh)
    k = k.reshape(B, S, Hkv, Dh)
    v = v.reshape(B, S, Hkv, Dh)
    if cfg.rotary_dim:
        from deepspeed_tpu.ops.attention.rotary import apply_rotary
        q, k = apply_rotary(
            q, k, positions if positions is not None else jnp.arange(S),
            cfg.rotary_dim, base=cfg.rope_theta)
    return q, k, v


def _layernorm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _effective_block(pref: int, seq_len: int) -> Optional[int]:
    """Largest block <= pref (>=128) that divides seq_len — keeps the
    flash kernel active when the preferred size doesn't tile the
    sequence (e.g. 1024-blocks with S=1536 fall back to 512)."""
    b = min(pref, seq_len)
    while b >= 128 and seq_len % b != 0:
        b //= 2
    return b if b >= 128 and seq_len % b == 0 else None


def _flash_blocks(cfg: GPTConfig, seq_len: int):
    """(block_q, block_kv) for this sequence, or None if ineligible.
    Explicit gate (no blanket except — Mosaic failures surface at
    jit-compile time, outside any trace-time try)."""
    if not cfg.use_flash_attention or seq_len < 128:
        return None
    bq = _effective_block(cfg.flash_block_q, seq_len)
    bkv = _effective_block(cfg.flash_block_kv, seq_len)
    if bq is None or bkv is None:
        return None
    from deepspeed_tpu.utils import on_tpu
    return (bq, bkv) if on_tpu() else None


def _flash_eligible(cfg: GPTConfig, seq_len: int) -> bool:
    return _flash_blocks(cfg, seq_len) is not None


def _attention(q, k, v, cfg: GPTConfig, segment_ids=None, kv_mask=None):
    """Causal multi-head attention. q,k,v: [B, S, H, Dh].

    segment_ids: optional [B, S] packed-sequence ids — attention stays
    inside each segment (block-diagonal x causal).
    kv_mask: optional [B, S] key-validity mask (left-padded prompts)."""
    scale = cfg.attn_scale  # None -> kernels default to 1/sqrt(Dh)
    if cfg.sequence_parallel and cfg.mesh is not None:
        # GQA works under both SP impls: ring rotates the small grouped
        # k/v; Ulysses needs the sp degree to divide both head counts
        if cfg.sp_impl == "ulysses":
            if cfg.sp_layout == "zigzag":
                # a contiguous causal mask applied to zigzag-permuted
                # tokens is silently wrong attention — refuse loudly
                raise ValueError(
                    "sp_layout='zigzag' is a RING layout (balances the "
                    "causal ring schedule); ulysses keeps the natural "
                    "order — use sp_layout='contiguous' with it")
            from deepspeed_tpu.ops.attention.ulysses import ulysses_attention
            S = q.shape[1]
            blocks = _flash_blocks(cfg, S)
            return ulysses_attention(
                q, k, v, cfg.mesh, causal=True, scale=scale,
                use_flash=blocks is not None,
                block_q=blocks[0] if blocks else cfg.flash_block_q,
                block_kv=blocks[1] if blocks else cfg.flash_block_kv,
                segment_ids=segment_ids, kv_mask=kv_mask,
                window=cfg.attn_window,
                window_impl=cfg.attn_window_impl,
                bwd_block_q=(_effective_block(cfg.flash_block_bwd_q, S)
                             if cfg.flash_block_bwd_q else None),
                bwd_block_kv=(_effective_block(cfg.flash_block_bwd_kv, S)
                              if cfg.flash_block_bwd_kv else None))
        if cfg.sp_impl != "ring":
            raise ValueError(f"unknown sp_impl {cfg.sp_impl!r} "
                             "(expected 'ring' or 'ulysses')")
        from deepspeed_tpu.ops.attention.ring import ring_attention
        # packing/padding metadata rotates with the K/V blocks; the local
        # block runs the Pallas flash kernel when eligible (gated on the
        # LOCAL shard length — that is what the kernel sees per step)
        S_loc = q.shape[1] // cfg.mesh.shape["sequence"]
        blocks = _flash_blocks(cfg, S_loc)
        return ring_attention(
            q, k, v, cfg.mesh, causal=True, scale=scale,
            segment_ids=segment_ids, kv_mask=kv_mask,
            window=cfg.attn_window, use_flash=blocks is not None,
            block_q=blocks[0] if blocks else 512,
            block_kv=blocks[1] if blocks else 512,
            layout=cfg.sp_layout,
            window_impl=cfg.attn_window_impl)
    blocks = _flash_blocks(cfg, q.shape[1])
    if blocks is not None:
        from deepspeed_tpu.ops.attention.flash import flash_attention
        # bwd overrides pass through the same divisibility normalization
        # as the fwd blocks (a non-dividing block would truncate the
        # backward grid); fall back to the fwd block when none divides
        S = q.shape[1]
        bwd_q = (_effective_block(cfg.flash_block_bwd_q, S)
                 if cfg.flash_block_bwd_q else None)
        bwd_kv = (_effective_block(cfg.flash_block_bwd_kv, S)
                  if cfg.flash_block_bwd_kv else None)
        return flash_attention(q, k, v, causal=True, scale=scale,
                               block_q=blocks[0], block_kv=blocks[1],
                               segment_ids=segment_ids, kv_mask=kv_mask,
                               window=cfg.attn_window,
                               window_impl=cfg.attn_window_impl,
                               bwd_block_q=bwd_q, bwd_block_kv=bwd_kv)
    from deepspeed_tpu.ops.attention.flash import mha_reference
    return mha_reference(q, k, v, causal=True, scale=scale,
                         segment_ids=segment_ids, kv_mask=kv_mask,
                         window=cfg.attn_window)


def _block(x, layer_params, cfg: GPTConfig, dropout_rng=None,
           deterministic=True, segment_ids=None, positions=None):
    """One transformer block. x: [B, S, D]. positions: optional [B, S]
    per-row rotary positions (packed batches restart per document)."""
    B, S, D = x.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    p = layer_params

    if dropout_rng is not None:
        dr_attn, dr_mlp = jax.random.split(dropout_rng)
    else:
        dr_attn = dr_mlp = None

    h = _norm(x, p["ln1"], cfg)
    qkv = _dense(h, p["qkv"])
    qkv = checkpoint_name(qkv, "qkv")
    q, k, v = _qkv_split_rotary(qkv, cfg, positions, B, S)
    attn = _attention(q, k, v, cfg, segment_ids=segment_ids).reshape(B, S, D)
    attn = checkpoint_name(attn, "attn")
    attn = _dense(attn, p["attn_out"])
    if not deterministic and cfg.dropout > 0:
        attn = _dropout(attn, cfg.dropout, dr_attn)

    # GPT-J style parallel residual: MLP reads the SAME ln1 output and
    # both branches add to x (ref: HFGPTJLayerPolicy, replace_policy.py:157)
    mlp_src = h if cfg.parallel_residual else None
    if not cfg.parallel_residual:
        x = x + attn
        mlp_src = _norm(x, p["ln2"], cfg)

    m = _dense(mlp_src, p["mlp_in"])
    m = checkpoint_name(m, "mlp_pre")
    if cfg.activation == "swiglu":
        # gated MLP: silu(x @ gate) * (x @ up) — separate kernels so
        # column-parallel TP keeps gate/up halves aligned per shard
        m = jax.nn.silu(_dense(mlp_src, p["mlp_gate"])) * m
    else:
        m = jax.nn.gelu(m, approximate=True)
    m = _dense(m, p["mlp_out"])
    if not deterministic and cfg.dropout > 0:
        m = _dropout(m, cfg.dropout, dr_mlp)
    if cfg.parallel_residual:
        return x + attn + m
    return x + m


def _dropout(x, rate, rng):
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def forward(params: Dict, tokens: jnp.ndarray, cfg: GPTConfig,
            rng: Optional[jax.Array] = None,
            deterministic: bool = True,
            pld_theta: Optional[jnp.ndarray] = None,
            hidden_only: bool = False,
            segment_ids: Optional[jnp.ndarray] = None,
            positions: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """tokens [B, S] int32 -> logits [B, S, V] (compute dtype).

    pld_theta: optional progressive-layer-drop keep-base (traced scalar;
    ref: deepspeed/runtime/progressive_layer_drop.py + arXiv:2010.13369):
    layer l survives with prob 1 - (l/L)*(1-theta), deeper layers dropped
    more often. Training-only (pass None for eval).

    segment_ids/positions: packed-sequence support — [B, S] ids keep
    attention block-diagonal per document, [B, S] positions restart the
    learned positional embedding at each document start."""
    B, S = tokens.shape
    dtype = cfg.dtype
    if (cfg.sequence_parallel and cfg.sp_layout == "zigzag"
            and positions is None):
        raise ValueError(
            "sp_layout='zigzag' permutes the token order — pass "
            "positions (the zigzag_perm itself for unpacked batches) so "
            "positional encodings follow the tokens")
    wte = params["wte"]["embedding"].astype(dtype)
    x = wte[tokens]
    if cfg.use_wpe:
        wpe = params["wpe"]["embedding"].astype(dtype)
        x = x + (wpe[positions] if positions is not None
                 else wpe[:S][None])

    block = params["block"]
    L = cfg.n_layers

    # pin the scan carry's layout: without this, XLA's sharding
    # propagation may pick conflicting activation shardings between the
    # forward and transpose scan bodies under fsdp x tp (an "involuntary
    # full rematerialization" reshard per layer); batch stays over the dp
    # axes, token/feature dims replicated
    # under sequence parallelism the token dim stays sharded over
    # 'sequence' — pinning it replicated would allgather the full
    # residual stream every layer and erase SP's memory win
    carry_spec = P(("data", "fsdp"),
                   "sequence" if cfg.sequence_parallel else None, None)

    def _pin(t):
        try:
            from jax.sharding import AxisType, get_abstract_mesh
        except ImportError:
            # jax<=0.4.x has no AxisType/abstract-mesh introspection:
            # apply the constraint and fall back where the trace context
            # rejects it (no mesh in scope, or a shard_map manual region
            # — both raise at trace time on those versions)
            try:
                return jax.lax.with_sharding_constraint(t, carry_spec)
            except Exception:
                return t
        m = get_abstract_mesh()
        if m is None or m.empty or not {"data", "fsdp"} <= set(m.axis_names):
            return t  # no engine mesh in context (e.g. raw single-device)
        # inside a shard_map region (e.g. the compressed-collective wire
        # path maps the loss over 'data') manual axes are already local —
        # a constraint naming them is both meaningless and rejected
        manual = {n for n, ty in zip(m.axis_names, m.axis_types)
                  if ty == AxisType.Manual}
        if not manual:
            return jax.lax.with_sharding_constraint(t, carry_spec)

        def keep(entry):
            if entry is None:
                return None
            names = entry if isinstance(entry, tuple) else (entry,)
            left = tuple(n for n in names if n not in manual)
            return left if left else None
        spec = P(*(keep(e) for e in carry_spec))
        if all(e is None for e in spec):
            return t
        return jax.lax.with_sharding_constraint(t, spec)

    def body(carry, scanned):
        layer, lidx = scanned
        x, r = carry
        x = _pin(x)
        r, dr = jax.random.split(r) if r is not None else (None, None)
        y = _block(x, layer, cfg, dropout_rng=dr, deterministic=deterministic,
                   segment_ids=segment_ids, positions=positions)
        if pld_theta is not None and not deterministic:
            kr = jax.random.fold_in(dr, jnp.int32(7))
            keep_p = 1.0 - (lidx.astype(jnp.float32) / L) * \
                (1.0 - pld_theta.astype(jnp.float32))
            keep = jax.random.bernoulli(kr, keep_p)
            y = jnp.where(keep, y, x)
        return (_pin(y), r), None

    if cfg.remat:
        # the policy must match the attention path actually taken: when
        # flash is requested but ineligible for this S, the jnp path tags
        # "attn" and produces no flash residuals
        body = jax.checkpoint(
            body, policy=remat_policy(cfg.remat_policy,
                                      flash=_flash_eligible(cfg, S)))

    rng = rng if rng is not None else jax.random.PRNGKey(0)
    (x, _), _ = jax.lax.scan(body, (x, rng), (block, jnp.arange(L)))

    x = _norm(x, params["ln_f"], cfg)
    if hidden_only:
        return x
    if cfg.tie_embeddings:
        logits = x @ wte.T
    else:
        head = params["lm_head"]
        logits = x @ head["kernel"].astype(dtype)
        if "bias" in head:   # e.g. GPT-J ships an lm_head bias
            logits = logits + head["bias"].astype(dtype)
    return logits


def _head_nll(other: Dict, y: jnp.ndarray, targets: jnp.ndarray,
              cfg: GPTConfig, loss_mask=None) -> jnp.ndarray:
    """Mean next-token NLL from post-ln_f hidden states (pipeline / layered
    heads). Honors cfg.loss_chunk (fused chunked CE, ops/cross_entropy.py)
    and an optional [.., S] loss mask (packed batches)."""
    w, b = _vocab_proj(other, cfg)
    if cfg.loss_chunk:
        from deepspeed_tpu.ops.cross_entropy import chunked_softmax_xent
        return chunked_softmax_xent(y, w, targets, bias=b,
                                    chunk=cfg.loss_chunk,
                                    loss_mask=loss_mask)
    logits = jax.lax.dot_general(
        y, w, (((y.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if b is not None:
        logits = logits + b.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1).squeeze(-1)
    if loss_mask is not None:
        return -(ll * loss_mask).sum() / jnp.maximum(loss_mask.sum(), 1.0)
    return -ll.mean()


def _vocab_proj(params: Dict, cfg: GPTConfig):
    """(w [V, H], bias [V] | None) for the chunked-loss path."""
    if cfg.tie_embeddings:
        return params["wte"]["embedding"].astype(cfg.dtype), None
    head = params["lm_head"]
    b = head.get("bias")
    return (head["kernel"].astype(cfg.dtype).T,
            None if b is None else b.astype(cfg.dtype))


def loss_fn(params: Dict, batch: Dict, rng: jax.Array, cfg: GPTConfig,
            deterministic: bool = False) -> jnp.ndarray:
    """Causal LM cross-entropy. batch: {"tokens": [B, S]} (next-token) or
    {"tokens", "targets"}. fp32 log-softmax for stability.

    Packed batches add "segment_ids"/"positions" [B, S]; pair them with a
    "loss_mask" zeroing each segment's last token (whose next-token
    target crosses into the following document)."""
    tokens = batch["tokens"]
    targets = batch.get("targets")
    segs = batch.get("segment_ids")
    poss = batch.get("positions")
    if targets is None:
        targets = tokens[:, 1:]
        tokens = tokens[:, :-1]
        segs = None if segs is None else segs[:, :-1]
        poss = None if poss is None else poss[:, :-1]
    mask = batch.get("loss_mask")
    if mask is not None and mask.shape[-1] != targets.shape[-1]:
        # pack_documents emits a (S-1)-wide mask aligned with the
        # implicit-targets slice above; pairing it with an explicit
        # seq-wide "targets" key would silently misalign mask/segments
        raise ValueError(
            f"loss_mask width {mask.shape[-1]} != target width "
            f"{targets.shape[-1]} — a pack_documents batch must either "
            f"keep implicit targets (no 'targets' key; loss_fn slices "
            f"next-token pairs) or be rewritten as a whole by "
            f"dataloader.zigzag_batch, which derives targets BEFORE "
            f"permuting so every per-token array stays aligned")
    if cfg.loss_chunk:
        # fused vocab-projection + loss: never materializes [B, S, V]
        # (ops/cross_entropy.py — frees ~3GB+ at GPT-2-1.5B scale)
        from deepspeed_tpu.ops.cross_entropy import chunked_softmax_xent
        x = forward(params, tokens, cfg, rng, deterministic=deterministic,
                    pld_theta=batch.get("pld_theta"), hidden_only=True,
                    segment_ids=segs, positions=poss)
        w, b = _vocab_proj(params, cfg)
        return chunked_softmax_xent(x, w, targets, bias=b,
                                    chunk=cfg.loss_chunk, loss_mask=mask)
    logits = forward(params, tokens, cfg, rng, deterministic=deterministic,
                     pld_theta=batch.get("pld_theta"),
                     segment_ids=segs, positions=poss)
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1).squeeze(-1)
    if mask is not None:
        return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return -ll.mean()


def make_loss_fn(cfg: GPTConfig):
    """Engine-contract loss: (params, batch, rng) -> loss."""
    def _loss(params, batch, rng):
        return loss_fn(params, batch, rng, cfg)
    return _loss


# ---------------------------------------------------------------------------
# sharding
# ---------------------------------------------------------------------------

def gpt_partition_rules() -> list:
    """TP rules for the stacked-layer layout (dim 0 = layer).

    Megatron mapping (delegated to client mpu in the reference, SURVEY §2.2;
    owned here): qkv & mlp_in column-parallel, attn_out & mlp_out
    row-parallel, vocab-parallel embedding.
    """
    return [
        PartitionRule(r"block/qkv/kernel", P(None, None, "model")),
        PartitionRule(r"block/qkv/bias", P(None, "model")),
        PartitionRule(r"block/attn_out/kernel", P(None, "model", None)),
        PartitionRule(r"block/mlp_in/kernel", P(None, None, "model")),
        PartitionRule(r"block/mlp_in/bias", P(None, "model")),
        PartitionRule(r"block/mlp_gate/kernel", P(None, None, "model")),
        PartitionRule(r"block/mlp_gate/bias", P(None, "model")),
        PartitionRule(r"block/mlp_out/kernel", P(None, "model", None)),
        # NOTE: embeddings deliberately NOT model-sharded: a vocab-sharded
        # table makes XLA fully rematerialize the gather (SPMD warning) —
        # proper masked vocab-parallel lookup is a follow-up; fsdp sharding
        # still applies under ZeRO-3.
    ]


# ---------------------------------------------------------------------------
# pipeline parallelism integration
# ---------------------------------------------------------------------------

def gpt_pipeline_partition_rules(tp: bool = False) -> list:
    """Partition rules for pipeline mode: the stacked layer dim is sharded
    over 'pipe' (each stage owns n_layers/pp layers), optionally composed
    with Megatron TP on the inner dims."""
    model = "model" if tp else None
    return [
        PartitionRule(r"block/(ln1|ln2)/(scale|bias)", P("pipe", None)),
        PartitionRule(r"block/qkv/kernel", P("pipe", None, model)),
        PartitionRule(r"block/qkv/bias", P("pipe", model)),
        PartitionRule(r"block/attn_out/kernel", P("pipe", model, None)),
        PartitionRule(r"block/attn_out/bias", P("pipe", None)),
        PartitionRule(r"block/mlp_in/kernel", P("pipe", None, model)),
        PartitionRule(r"block/mlp_in/bias", P("pipe", model)),
        PartitionRule(r"block/mlp_gate/kernel", P("pipe", None, model)),
        PartitionRule(r"block/mlp_gate/bias", P("pipe", model)),
        PartitionRule(r"block/mlp_out/kernel", P("pipe", model, None)),
        PartitionRule(r"block/mlp_out/bias", P("pipe", None)),
    ]


def make_pipeline_loss_fn(cfg: GPTConfig, mesh, num_stages: int,
                          num_micro: int, schedule: str = "1f1b",
                          virtual_chunks: int = 1):
    """Engine-contract loss running the transformer stack as a shard_map
    pipeline over the 'pipe' mesh axis (1 stage = n_layers/pp layers).
    Embedding + LM head run replicated over pipe (tied-weight grads are
    psum'd across stages by shard_map's transpose — the ReduceTiedGrads
    capability, ref pipe/engine.py:240)."""
    from deepspeed_tpu.runtime.pipe.engine import make_pipelined_loss_fn

    assert cfg.n_layers % num_stages == 0, (cfg.n_layers, num_stages)

    def split_params(params):
        other = {k: v for k, v in params.items() if k != "block"}
        return params["block"], other

    def embed_fn(other, batch):
        tokens = batch["tokens"]
        targets = batch.get("targets")
        if targets is None:
            targets = tokens[:, 1:]
            tokens = tokens[:, :-1]
        S = tokens.shape[1]
        x = other["wte"]["embedding"].astype(cfg.dtype)[tokens]
        if cfg.use_wpe:
            x = x + other["wpe"]["embedding"].astype(cfg.dtype)[:S][None]
        return x, targets

    def stage_fn(block_local, x):
        def body(carry, layer):
            return _block(carry, layer, cfg, deterministic=True), None
        y, _ = jax.lax.scan(body, x, block_local)
        return y

    def head_loss_fn(other, y, targets):
        y = _norm(y, other["ln_f"], cfg)
        return _head_nll(other, y, targets, cfg)

    # block leaves: rank 2 -> P('pipe'), rank 3 -> P('pipe')
    def spec_of(leaf):
        return P(*(["pipe"] + [None] * (leaf.ndim - 1)))

    import dataclasses
    dummy = init_params(jax.random.PRNGKey(0), dataclasses.replace(
        cfg, vocab_size=8, n_layers=num_stages, n_heads=1,
        n_kv_heads=None, d_model=8, d_ff=None, max_seq_len=8,
        rotary_dim=None, mesh=None))
    specs = jax.tree_util.tree_map(spec_of, dummy["block"])

    if schedule == "interleaved":
        assert cfg.n_layers % (num_stages * virtual_chunks) == 0, \
            (cfg.n_layers, num_stages, virtual_chunks)
    return make_pipelined_loss_fn(
        embed_fn, stage_fn, head_loss_fn, split_params,
        num_stages, num_micro, mesh, specs, remat_stage=cfg.remat,
        schedule=schedule, virtual_chunks=virtual_chunks)


# ---------------------------------------------------------------------------
# ZeRO-Infinity parameter streaming integration
# ---------------------------------------------------------------------------

def layered_model(cfg: GPTConfig):
    """LayeredModel contract for the parameter-streaming engine
    (runtime/zero/param_offload.py) — trains GPTs larger than device HBM
    (ref capability: 13B params on one 32GB GPU, docs/_pages/features.md:116;
    ref machinery: runtime/swap_tensor/partitioned_param_swapper.py:37)."""
    from deepspeed_tpu.runtime.zero.param_offload import LayeredModel

    def split_params(params):
        other = {k: v for k, v in params.items() if k != "block"}
        return params["block"], other

    def embed_fn(other, batch):
        tokens = batch["tokens"]
        targets = batch.get("targets")
        if targets is None:
            targets = tokens[:, 1:]
            tokens = tokens[:, :-1]
        S = tokens.shape[1]
        x = other["wte"]["embedding"].astype(cfg.dtype)[tokens]
        if cfg.use_wpe:
            x = x + other["wpe"]["embedding"].astype(cfg.dtype)[:S][None]
        return x, targets

    def layer_fn(lp, x):
        return _block(x, lp, cfg, deterministic=True)

    def head_fn(other, y, targets):
        y = _norm(y, other["ln_f"], cfg)
        return _head_nll(other, y, targets, cfg)

    return LayeredModel(split_params=split_params, embed_fn=embed_fn,
                        layer_fn=layer_fn, head_fn=head_fn,
                        n_layers=cfg.n_layers,
                        layer_remat_policy=(remat_policy(cfg.remat_policy,
                                                         flash=cfg.use_flash_attention)
                                            if cfg.remat else None))


def host_param_factory(seed: int, cfg: GPTConfig):
    """Host-RAM parameter factory for models too large to materialize as
    one stacked tree: factory(i) -> layer i's fp32 numpy pytree (unstacked),
    factory("other") -> embeddings/final-norm tree. Feeds
    InfinityParamEngine without ever holding more than one layer twice."""
    d, ff = cfg.d_model, cfg.ffn_dim

    def norm_p():
        if cfg.norm == "rmsnorm":
            return {"scale": np.ones((d,), np.float32)}
        return {"scale": np.ones((d,), np.float32),
                "bias": np.zeros((d,), np.float32)}

    def dense_p(r, shape, std):
        entry = {"kernel": (r.standard_normal(shape, np.float32) * std)}
        if cfg.use_bias:
            entry["bias"] = np.zeros((shape[-1],), np.float32)
        return entry

    def factory(which):
        if which == "other":
            r = np.random.default_rng(seed)
            other = {
                "wte": {"embedding": (r.standard_normal(
                    (cfg.vocab_size, d), np.float32) * 0.02)},
                "ln_f": norm_p(),
            }
            if cfg.use_wpe:
                other["wpe"] = {"embedding": (r.standard_normal(
                    (cfg.max_seq_len, d), np.float32) * 0.02)}
            if not cfg.tie_embeddings:
                other["lm_head"] = {"kernel": (r.standard_normal(
                    (d, cfg.vocab_size), np.float32) * 0.02)}
            return other
        i = int(which)
        r = np.random.default_rng(seed + 1 + i)
        resid = 0.02 / np.sqrt(2.0 * cfg.n_layers)
        layer = {
            "ln1": norm_p(),
            "qkv": dense_p(r, (d, cfg.qkv_dim), 0.02),
            "attn_out": dense_p(r, (d, d), resid),
            "ln2": norm_p(),
            "mlp_in": dense_p(r, (d, ff), 0.02),
            "mlp_out": dense_p(r, (ff, d), resid),
        }
        if cfg.activation == "swiglu":
            layer["mlp_gate"] = dense_p(r, (d, ff), 0.02)
        return layer

    return factory


def kv_bytes_per_token(cfg: GPTConfig, dtype=jnp.bfloat16) -> int:
    """Bytes of K+V cache ONE token occupies across all layers — the
    paged-cache allocator's budget unit (inference/paged_cache.py). The
    static engine pays this for `max_batch x S_max` slots up front; the
    paged cache pays it per token actually in flight."""
    return int(2 * cfg.n_layers * cfg.kv_heads * cfg.head_dim
               * jnp.dtype(dtype).itemsize)


def decode_geometry(cfg: GPTConfig, block_size: int,
                    max_seq_len: Optional[int] = None) -> Tuple[int, int]:
    """(blocks_per_slot, tokens_per_slot) for a block-paged KV cache over
    this config: the per-request block table is sized to cover the model's
    maximum sequence, rounded up to whole blocks. Shared by the paged
    cache, the serving scheduler and the engine's slot programs so all
    three agree on the gathered cache's virtual length."""
    assert block_size >= 1
    s = max_seq_len or cfg.max_seq_len
    nb = -(-s // block_size)
    return nb, nb * block_size


def num_params(cfg: GPTConfig) -> int:
    d, L, ff, V = cfg.d_model, cfg.n_layers, cfg.ffn_dim, cfg.vocab_size
    qkv = cfg.qkv_dim                  # (H + 2*Hkv) * Dh — GQA-aware
    nb = 1 if cfg.use_bias else 0
    per_layer = (d * qkv + nb * qkv + d * d + nb * d
                 + 2 * d * ff + nb * (ff + d)
                 + (2 if cfg.norm == "layernorm" else 1) * 2 * d)
    if cfg.activation == "swiglu":
        per_layer += d * ff + nb * ff  # separate gate kernel
    n = V * d + L * per_layer + (2 if cfg.norm == "layernorm" else 1) * d
    if cfg.use_wpe:
        n += cfg.max_seq_len * d
    if not cfg.tie_embeddings:
        n += d * V
    return n


def train_flops_per_token(cfg: GPTConfig, seq_len: int,
                          include_head: bool = True) -> float:
    """Model flops per token, fwd+bwd — Megatron-LM-style accounting
    (the reference's own lineage): 6*N_matmul + attention, where N_matmul
    counts every matmul parameter including the logit projection (for tied
    embeddings the d*V head matmul is real compute even though the weight
    is shared with wte)."""
    N = num_params(cfg) - cfg.vocab_size * cfg.d_model  # drop wte lookup
    if cfg.tie_embeddings and include_head:
        N += cfg.d_model * cfg.vocab_size  # the tied logit matmul
    attn = 12 * cfg.n_layers * cfg.d_model * seq_len
    return 6.0 * N + attn
