"""Compact ResNet for CIFAR-class vision workloads.

Workload analog of the reference's first example config
(ref: BASELINE.json config #1 — DeepSpeedExamples/cifar trains a small
conv net under ZeRO stage 1; the reference tutorial at
docs/_tutorials/cifar-10.md drives it through deepspeed.initialize).

TPU-first design decisions:
- **NHWC layout + HWIO kernels**: the native TPU convolution layout —
  XLA maps these convs straight onto the MXU without transposes
  (torch's NCHW would insert layout conversions around every conv).
- **GroupNorm instead of BatchNorm**: BatchNorm's running stats are
  mutable state (breaks the stateless loss_fn contract) and its batch
  statistics need a cross-device sync under data parallelism (the
  reference leans on NCCL SyncBN). GroupNorm is per-sample: zero
  cross-device traffic, identical semantics at any dp degree, and jits
  into the surrounding program. fp32 statistics, bf16 everything else.
- **Stacked residual blocks under lax.scan** per stage (same compile-
  once-per-depth trick as the GPT stack) — constant compile time in
  depth, with per-block remat available through jax.checkpoint.
"""

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any


@dataclass
class ResNetConfig:
    num_classes: int = 10
    # CIFAR-style stem (3x3, no max-pool); stage widths double
    widths: Tuple[int, ...] = (64, 128, 256)
    # residual blocks per stage (2, 2, 2) ~ ResNet-20-class capacity
    depths: Tuple[int, ...] = (2, 2, 2)
    groups: int = 8                    # GroupNorm groups
    dtype: Any = jnp.bfloat16
    remat: bool = False
    image_size: int = 32
    in_channels: int = 3


def _conv_init(key, h, w, cin, cout):
    fan_in = h * w * cin
    return (jax.random.normal(key, (h, w, cin, cout), jnp.float32)
            * np.sqrt(2.0 / fan_in))


def init_params(key: jax.Array, cfg: ResNetConfig) -> PyTree:
    keys = iter(jax.random.split(key, 4 + 4 * sum(cfg.depths)))
    params: Dict[str, Any] = {
        "stem": {"kernel": _conv_init(next(keys), 3, 3, cfg.in_channels,
                                      cfg.widths[0]),
                 "gn_scale": jnp.ones((cfg.widths[0],), jnp.float32),
                 "gn_bias": jnp.zeros((cfg.widths[0],), jnp.float32)},
        "head": {"kernel": jax.random.normal(
            next(keys), (cfg.widths[-1], cfg.num_classes), jnp.float32)
            / np.sqrt(cfg.widths[-1]),
            "bias": jnp.zeros((cfg.num_classes,), jnp.float32)},
    }
    for si, (w, d) in enumerate(zip(cfg.widths, cfg.depths)):
        cin = cfg.widths[max(si - 1, 0)]
        # stage entry: strided projection when width/resolution changes
        stage: Dict[str, Any] = {}
        if si > 0:
            stage["proj"] = {"kernel": _conv_init(next(keys), 1, 1, cin, w)}
        # stacked block weights: leading axis = block index (lax.scan)
        stage["conv1"] = jnp.stack([_conv_init(next(keys), 3, 3, w, w)
                                    for _ in range(d)])
        stage["conv2"] = jnp.stack([_conv_init(next(keys), 3, 3, w, w)
                                    for _ in range(d)])
        stage["gn1_scale"] = jnp.ones((d, w), jnp.float32)
        stage["gn1_bias"] = jnp.zeros((d, w), jnp.float32)
        stage["gn2_scale"] = jnp.ones((d, w), jnp.float32)
        stage["gn2_bias"] = jnp.zeros((d, w), jnp.float32)
        params[f"stage{si}"] = stage
    return params


def _conv(x, kernel, stride=1, dtype=jnp.bfloat16):
    # no preferred_element_type: a widened output dtype breaks the conv
    # transpose rule under AD (fp32 cotangent vs bf16 operands), and the
    # MXU accumulates bf16 convs in fp32 internally regardless
    return jax.lax.conv_general_dilated(
        x.astype(dtype), kernel.astype(dtype),
        window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _groupnorm(x, scale, bias, groups):
    """Per-sample GroupNorm over NHWC; fp32 statistics."""
    B, H, W, C = x.shape
    g = min(groups, C)
    xf = x.astype(jnp.float32).reshape(B, H, W, g, C // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xn = ((xf - mean) * jax.lax.rsqrt(var + 1e-5)).reshape(B, H, W, C)
    return (xn * scale + bias).astype(x.dtype)


def forward(params: PyTree, images: jnp.ndarray,
            cfg: ResNetConfig) -> jnp.ndarray:
    """images: [B, H, W, C] float (any range; caller normalizes) ->
    logits [B, num_classes] (fp32)."""
    x = images.astype(cfg.dtype)
    stem = params["stem"]
    x = _conv(x, stem["kernel"], dtype=cfg.dtype)
    x = _groupnorm(x, stem["gn_scale"], stem["gn_bias"], cfg.groups)
    x = jax.nn.relu(x)

    for si in range(len(cfg.widths)):
        stage = params[f"stage{si}"]
        if si > 0:
            # downsample: strided 1x1 projection into the wider stage
            x = _conv(x, stage["proj"]["kernel"], stride=2, dtype=cfg.dtype)

        def block(h, wts):
            c1, c2, s1, b1, s2, b2 = wts
            y = _groupnorm(_conv(h, c1, dtype=cfg.dtype), s1, b1, cfg.groups)
            y = jax.nn.relu(y)
            y = _groupnorm(_conv(y, c2, dtype=cfg.dtype), s2, b2, cfg.groups)
            return jax.nn.relu(h + y), None

        body = block
        if cfg.remat:
            body = jax.checkpoint(block)
        x, _ = jax.lax.scan(
            body, x, (stage["conv1"], stage["conv2"],
                      stage["gn1_scale"], stage["gn1_bias"],
                      stage["gn2_scale"], stage["gn2_bias"]))

    x = x.astype(jnp.float32).mean(axis=(1, 2))        # global avg pool
    head = params["head"]
    return x @ head["kernel"] + head["bias"]


def loss_fn(params: PyTree, batch: Dict[str, jnp.ndarray], rng: jax.Array,
            cfg: ResNetConfig) -> jnp.ndarray:
    """batch: {"images": [B,H,W,C], "labels": [B]} -> mean CE loss."""
    del rng
    logits = forward(params, batch["images"], cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, batch["labels"][:, None],
                               axis=-1).squeeze(-1)
    return nll.mean()


def make_loss_fn(cfg: ResNetConfig):
    return partial(loss_fn, cfg=cfg)


def accuracy(params: PyTree, batch: Dict[str, jnp.ndarray],
             cfg: ResNetConfig) -> jnp.ndarray:
    logits = forward(params, batch["images"], cfg)
    return (jnp.argmax(logits, -1) == batch["labels"]).mean()


def num_params(cfg: ResNetConfig) -> int:
    k = jax.random.PRNGKey(0)
    return sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(init_params(k, cfg)))
