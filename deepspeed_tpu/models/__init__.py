"""Model families (loss-function + param-pytree contract for the engine)."""

from deepspeed_tpu.models import bert, gpt, moe_gpt, resnet  # noqa: F401

__all__ = ["bert", "gpt", "moe_gpt", "resnet"]
