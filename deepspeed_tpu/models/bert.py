"""BERT model family — MLM (+NSP) pretraining, TPU-native.

Capability match for the reference's BERT stack: the fused transformer
layer it showcases (ref: deepspeed/ops/transformer/transformer.py:460,
tutorial docs/_tutorials/bert-pretraining.md) and the full BERT parity
models its kernel tests train (ref: tests/unit/modeling.py 1,597 LoC
post-LN, modelingpreln.py pre-LN). Layers are stacked on a leading axis
and run under ``lax.scan`` (one compiled block, L iterations — the XLA
analog of the reference reusing one CUDA layer object per depth);
blocks live under the ``"block"`` pytree key so MoQ/eigenvalue's
stacked-layer machinery applies unchanged.
"""

from dataclasses import dataclass, field
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.transformer.encoder_layer import (
    DeepSpeedTransformerConfig, _layernorm, init_layer_params, layer_forward)


@dataclass
class BertConfig:
    vocab_size: int = 30522
    n_layers: int = 12
    n_heads: int = 12
    d_model: int = 768
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = True
    dtype: jnp.dtype = jnp.bfloat16
    # per-layer activation checkpointing; off by default (small-model
    # fine-tuning fits HBM) — pretraining batch sizes need it (the
    # bert_bench/pretrain call sites enable it)
    remat: bool = False
    remat_policy: str = "selective"   # see models.gpt.remat_policy
    # fused chunked MLM cross-entropy (0 = dense log_softmax). At
    # seq512 x batch32 the dense path materializes a 2GB fp32 [B,S,V]
    # logits tensor; chunking caps it at ~chunk x V (ops/cross_entropy.py)
    loss_chunk: int = 0

    @property
    def layer_config(self) -> DeepSpeedTransformerConfig:
        return DeepSpeedTransformerConfig(
            hidden_size=self.d_model, heads=self.n_heads,
            attn_dropout_ratio=self.dropout,
            hidden_dropout_ratio=self.dropout,
            num_hidden_layers=self.n_layers,
            layer_norm_eps=self.layer_norm_eps,
            pre_layer_norm=self.pre_layer_norm)


PRESETS = {
    "bert-base": dict(n_layers=12, n_heads=12, d_model=768),
    "bert-large": dict(n_layers=24, n_heads=16, d_model=1024),
    "bert-tiny": dict(n_layers=2, n_heads=2, d_model=128),
}


def preset(name: str, **overrides) -> BertConfig:
    return BertConfig(**{**PRESETS[name], **overrides})


def init_params(rng: jax.Array, cfg: BertConfig) -> Dict:
    ks = jax.random.split(rng, 8)
    s = 0.02
    d = cfg.d_model

    layer_keys = jax.random.split(ks[0], cfg.n_layers)
    per_layer = [init_layer_params(k, cfg.layer_config) for k in layer_keys]
    block = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_layer)

    return {
        "embeddings": {
            "word": jax.random.normal(ks[1], (cfg.vocab_size, d)) * s,
            "position": jax.random.normal(ks[2], (cfg.max_seq_len, d)) * s,
            "token_type": jax.random.normal(ks[3], (cfg.type_vocab_size, d)) * s,
            "ln": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
        },
        "block": block,
        "pooler": {"kernel": jax.random.normal(ks[4], (d, d)) * s,
                   "bias": jnp.zeros((d,))},
        "mlm": {  # transform + tied-embedding decoder bias
            "kernel": jax.random.normal(ks[5], (d, d)) * s,
            "bias": jnp.zeros((d,)),
            "ln": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
            "decoder_bias": jnp.zeros((cfg.vocab_size,)),
        },
        "nsp": {"kernel": jax.random.normal(ks[6], (d, 2)) * s,
                "bias": jnp.zeros((2,))},
    }


def encode(params: Dict, tokens: jnp.ndarray, cfg: BertConfig,
           token_type_ids: Optional[jnp.ndarray] = None,
           attention_mask: Optional[jnp.ndarray] = None,
           rng: Optional[jax.Array] = None,
           deterministic: bool = True) -> jnp.ndarray:
    """tokens [B, S] -> hidden states [B, S, D] (compute dtype)."""
    B, S = tokens.shape
    dtype = cfg.dtype
    emb = params["embeddings"]
    x = emb["word"].astype(dtype)[tokens] + \
        emb["position"].astype(dtype)[:S][None]
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(tokens)
    x = x + emb["token_type"].astype(dtype)[token_type_ids]
    x = _layernorm(x, emb["ln"]["scale"].astype(dtype),
                   emb["ln"]["bias"].astype(dtype), cfg.layer_norm_eps)

    lcfg = cfg.layer_config
    assert deterministic or rng is not None, \
        "training mode (deterministic=False) needs an rng for dropout"
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def body(carry, layer):
        h, r = carry
        r, lr = jax.random.split(r)
        y = layer_forward(layer, h, lcfg, attn_mask=attention_mask,
                          rng=None if deterministic else lr,
                          deterministic=deterministic)
        return (y, r), None

    if cfg.remat:
        # per-layer activation checkpointing: without it the scan keeps
        # every layer's attention/MLP intermediates for the backward —
        # BERT-large at pretraining batch sizes does not fit HBM
        # (ref capability: activation_checkpointing/checkpointing.py).
        # Policy shared with the GPT family (encoder_layer tags
        # qkv/attn/mlp_pre and the flash kernel its packed residuals).
        # The flash flag must mirror _attention_core's gate so the
        # selective policy never saves the attention output twice
        # (packed flash_out + 'attn').
        from deepspeed_tpu.models.gpt import remat_policy
        head_dim = cfg.d_model // cfg.n_heads
        try:
            d0 = jax.devices()[0]
            on_tpu = "tpu" in (d0.platform + d0.device_kind).lower()
        except Exception:
            on_tpu = False
        # masked batches take the flash path too (kv_mask support); the
        # gate must still mirror _attention_core's dropout condition
        flash_used = (S >= 128 and head_dim % 8 == 0 and on_tpu
                      and (deterministic or cfg.dropout == 0.0))
        body = jax.checkpoint(
            body, policy=remat_policy(cfg.remat_policy, flash=flash_used))

    (x, _), _ = jax.lax.scan(body, (x, rng), params["block"])
    return x


def _mlm_hidden(params: Dict, x: jnp.ndarray, cfg: BertConfig):
    """MLM head transform: encoder states -> pre-decode hidden [B,S,d]."""
    dtype = x.dtype
    h = x @ params["mlm"]["kernel"].astype(dtype) + \
        params["mlm"]["bias"].astype(dtype)
    h = jax.nn.gelu(h, approximate=True)
    return _layernorm(h, params["mlm"]["ln"]["scale"].astype(dtype),
                      params["mlm"]["ln"]["bias"].astype(dtype),
                      cfg.layer_norm_eps)


def _nsp_logits(params: Dict, x: jnp.ndarray):
    dtype = x.dtype
    pooled = jnp.tanh(x[:, 0] @ params["pooler"]["kernel"].astype(dtype) +
                      params["pooler"]["bias"].astype(dtype))
    return pooled @ params["nsp"]["kernel"].astype(dtype) + \
        params["nsp"]["bias"].astype(dtype)


def forward(params: Dict, tokens: jnp.ndarray, cfg: BertConfig,
            token_type_ids=None, attention_mask=None,
            rng: Optional[jax.Array] = None,
            deterministic: bool = True):
    """Returns (mlm_logits [B,S,V], nsp_logits [B,2])."""
    x = encode(params, tokens, cfg, token_type_ids, attention_mask,
               rng, deterministic)
    dtype = x.dtype
    # MLM head: transform -> LN -> tied-embedding decode
    h = _mlm_hidden(params, x, cfg)
    mlm_logits = h @ params["embeddings"]["word"].astype(dtype).T + \
        params["mlm"]["decoder_bias"].astype(dtype)
    return mlm_logits, _nsp_logits(params, x)


def loss_fn(params: Dict, batch: Dict, rng: jax.Array, cfg: BertConfig,
            deterministic: bool = False) -> jnp.ndarray:
    """MLM (+optional NSP) loss. batch:
    tokens [B,S]; mlm_labels [B,S] with -1 = not masked;
    optional token_type_ids, attention_mask, nsp_labels [B]."""
    labels = batch["mlm_labels"]
    mask = (labels >= 0).astype(jnp.float32)
    if cfg.loss_chunk:
        from deepspeed_tpu.ops.cross_entropy import chunked_softmax_xent
        x = encode(params, batch["tokens"], cfg,
                   batch.get("token_type_ids"), batch.get("attention_mask"),
                   rng, deterministic)
        h = _mlm_hidden(params, x, cfg)
        loss = chunked_softmax_xent(
            h, params["embeddings"]["word"].astype(h.dtype),
            jnp.maximum(labels, 0),
            bias=params["mlm"]["decoder_bias"].astype(h.dtype),
            chunk=cfg.loss_chunk, loss_mask=mask)
        nsp_logits = _nsp_logits(params, x)
    else:
        mlm_logits, nsp_logits = forward(
            params, batch["tokens"], cfg,
            token_type_ids=batch.get("token_type_ids"),
            attention_mask=batch.get("attention_mask"),
            rng=rng, deterministic=deterministic)
        logp = jax.nn.log_softmax(mlm_logits.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(
            logp, jnp.maximum(labels, 0)[..., None], axis=-1).squeeze(-1)
        loss = -(picked * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    if "nsp_labels" in batch:
        nsp_logp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32), -1)
        loss = loss - jnp.mean(jnp.take_along_axis(
            nsp_logp, batch["nsp_labels"][:, None], axis=-1))
    return loss


def make_loss_fn(cfg: BertConfig):
    """Engine-contract loss: (params, batch, rng) -> loss."""
    def _loss(params, batch, rng):
        return loss_fn(params, batch, rng, cfg)
    return _loss


# ---------------------------------------------------------------------------
# SQuAD fine-tuning head (the BingBertSquad workload,
# ref: tests/model/BingBertSquad + DeepSpeedExamples' nvidia/modeling
# BertForQuestionAnswering — a start/end span classifier on the encoder)
# ---------------------------------------------------------------------------

def init_squad_head(rng: jax.Array, cfg: BertConfig) -> Dict:
    """Span-prediction head params: add under params["qa"]."""
    return {"kernel": jax.random.normal(rng, (cfg.d_model, 2)) * 0.02,
            "bias": jnp.zeros((2,))}


def squad_logits(params: Dict, tokens: jnp.ndarray, cfg: BertConfig,
                 token_type_ids=None, attention_mask=None,
                 rng: Optional[jax.Array] = None,
                 deterministic: bool = True):
    """-> (start_logits [B, S], end_logits [B, S]) fp32."""
    x = encode(params, tokens, cfg, token_type_ids, attention_mask,
               rng, deterministic)
    qa = params["qa"]
    logits = x @ qa["kernel"].astype(x.dtype) + qa["bias"].astype(x.dtype)
    s, e = jnp.split(logits.astype(jnp.float32), 2, axis=-1)
    return s[..., 0], e[..., 0]


def squad_loss_fn(params: Dict, batch: Dict, rng: jax.Array,
                  cfg: BertConfig, deterministic: bool = False):
    """Mean of start/end-position cross-entropies. batch: tokens [B,S],
    start_positions [B], end_positions [B], optional token_type_ids /
    attention_mask."""
    s_logits, e_logits = squad_logits(
        params, batch["tokens"], cfg, batch.get("token_type_ids"),
        batch.get("attention_mask"), rng, deterministic)
    S = s_logits.shape[1]

    def xent(logits, pos):
        # out-of-range positions (e.g. unanswerable examples marked with
        # seq_len, the reference's ignored_index convention, or -1) are
        # excluded from the loss
        valid = ((pos >= 0) & (pos < S)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        picked = jnp.take_along_axis(
            logp, jnp.clip(pos, 0, S - 1)[:, None], axis=-1)[:, 0]
        return -(picked * valid).sum() / jnp.maximum(valid.sum(), 1.0)

    return 0.5 * (xent(s_logits, batch["start_positions"]) +
                  xent(e_logits, batch["end_positions"]))


def make_squad_loss_fn(cfg: BertConfig):
    def _loss(params, batch, rng):
        return squad_loss_fn(params, batch, rng, cfg)
    return _loss


def bert_partition_rules(vocab_parallel: bool = False):
    """TP rules: column-parallel qkv/mlp_in, row-parallel
    attn_out/mlp_out — the Megatron recipe the reference delegates to
    the client mpu (SURVEY.md §2.2 TP row). ``vocab_parallel`` also
    row-shards the word embedding (requires vocab_size % tp == 0)."""
    from deepspeed_tpu.parallel.sharding import PartitionRule
    from jax.sharding import PartitionSpec as P
    rules = [
        PartitionRule(r"block/qkv/kernel", P(None, None, "model")),
        PartitionRule(r"block/qkv/bias", P(None, "model")),
        PartitionRule(r"block/attn_out/kernel", P(None, "model", None)),
        PartitionRule(r"block/mlp_in/kernel", P(None, None, "model")),
        PartitionRule(r"block/mlp_in/bias", P(None, "model")),
        PartitionRule(r"block/mlp_out/kernel", P(None, "model", None)),
    ]
    if vocab_parallel:
        rules.append(PartitionRule(r"embeddings/word", P("model", None)))
    return rules


def num_params(cfg: BertConfig) -> int:
    d, L, V = cfg.d_model, cfg.n_layers, cfg.vocab_size
    per_layer = 12 * d * d + 13 * d
    emb = (V + cfg.max_seq_len + cfg.type_vocab_size) * d + 2 * d
    heads = 2 * d * d + 6 * d + V + 2  # pooler + mlm transform/ln + nsp
    return L * per_layer + emb + heads
