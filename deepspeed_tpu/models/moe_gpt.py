"""GPT with Mixture-of-Experts FFNs — the GPT-MoE workload
(ref: BASELINE.json config #5 "GPT-MoE NLG"; reference wiring in
deepspeed/moe/layer.py applied to every-other FFN in Megatron-MoE).

Same stacked-layer lax.scan design as models/gpt.py; every layer's MLP is
a GShard MoE (top-1/top-2, capacity, load-balance aux loss). Expert
weights are stacked [L, E, ...] and sharded over the data axes on the E
dim (expert-data parallelism); the dispatch einsum inside the scan emits
the per-layer all-to-all.
"""

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import gpt as gpt_lib
from deepspeed_tpu.models.gpt import (GPTConfig, _attention,
                                      _dense, _norm,
                                      _qkv_split_rotary)
from deepspeed_tpu.moe.experts import ffn_expert_fn
from deepspeed_tpu.moe.layer import MoEConfig
from deepspeed_tpu.moe.sharded_moe import TopKGate, moe_layer_apply
from deepspeed_tpu.parallel.sharding import PartitionRule


@dataclass
class MoEGPTConfig(GPTConfig):
    num_experts: int = 8
    moe_k: int = 1
    capacity_factor: float = 1.25
    # eval capacity (None = same as training): the gate picks this when
    # train=False — leaving it at the gate's own 1.0 default silently
    # dropped tokens in validation (same defect class as the inference
    # _ffn bug caught by the Mixtral parity test)
    eval_capacity_factor: Optional[float] = None
    # combine-weight convention: "gshard" (top-1 weighs by the raw
    # softmax prob — the reference's top1gating) or "topk_softmax"
    # (softmax over the selected k, i.e. 1.0 at k=1 — Mixtral). The two
    # agree at k=2. Serving must match what the checkpoint trained with.
    gate_weighting: str = "gshard"
    min_capacity: int = 4
    aux_loss_weight: float = 0.01
    noisy_gate_policy: Optional[str] = None


def init_params(rng: jax.Array, cfg: MoEGPTConfig) -> Dict:
    base = gpt_lib.init_params(rng, cfg)
    L, E, d, ff = cfg.n_layers, cfg.num_experts, cfg.d_model, cfg.ffn_dim
    ks = jax.random.split(jax.random.fold_in(rng, 99), 3)
    init = jax.nn.initializers.normal(0.02)
    # replace dense MLP with per-layer expert stacks + gate
    block = base["block"]
    del block["mlp_in"], block["mlp_out"]
    block.pop("mlp_gate", None)        # swiglu dense gate -> expert wg
    def expert_p(key, shape):
        entry = {"kernel": init(key, shape, jnp.float32)}
        if cfg.use_bias:
            entry["bias"] = jnp.zeros(shape[:2] + shape[-1:], jnp.float32)
        return entry

    experts = {
        "wi": expert_p(ks[1], (L, E, d, ff)),
        "wo": expert_p(ks[2], (L, E, ff, d)),
    }
    if cfg.activation == "swiglu":
        # llama/mixtral expert dialect: a separate silu gate stack
        # (ffn_expert_fn dispatches on the "wg" key)
        experts["wg"] = expert_p(jax.random.fold_in(ks[1], 7),
                                 (L, E, d, ff))
    block["moe"] = {
        "gate": {"wg": init(ks[0], (L, d, E), jnp.float32)},
        "experts": experts,
    }
    return base


def num_params(cfg: MoEGPTConfig) -> int:
    """Dense-GPT count with every layer's MLP swapped for the E-expert
    stack + gate (init_params above is the shape source of truth)."""
    d, L, ff, E = cfg.d_model, cfg.n_layers, cfg.ffn_dim, cfg.num_experts
    nb = 1 if cfg.use_bias else 0
    n_proj = 3 if cfg.activation == "swiglu" else 2
    dense_mlp = n_proj * d * ff + nb * ((n_proj - 1) * ff + d)
    moe_mlp = E * (n_proj * d * ff + nb * ((n_proj - 1) * ff + d)) + d * E
    return gpt_lib.num_params(cfg) + L * (moe_mlp - dense_mlp)


def _moe_block(x, layer_params, cfg: MoEGPTConfig, rng, train: bool,
               positions=None, segment_ids=None):
    """One transformer block with MoE FFN. x: [B, S, D]. positions /
    segment_ids: optional [B, S] packed-batch metadata (rotary restarts
    + block-diagonal attention per document)."""
    B, S, D = x.shape
    p = layer_params

    h = _norm(x, p["ln1"], cfg)
    qkv = _dense(h, p["qkv"])
    q, k, v = _qkv_split_rotary(qkv, cfg, positions, B, S)
    attn = _attention(q, k, v, cfg,
                      segment_ids=segment_ids).reshape(B, S, D)
    attn = _dense(attn, p["attn_out"])
    x = x + attn

    h = _norm(x, p["ln2"], cfg)
    gate = TopKGate(k=cfg.moe_k, capacity_factor=cfg.capacity_factor,
                    eval_capacity_factor=(cfg.eval_capacity_factor
                                          if cfg.eval_capacity_factor
                                          is not None
                                          else cfg.capacity_factor),
                    min_capacity=cfg.min_capacity,
                    noisy_gate_policy=cfg.noisy_gate_policy)
    y, l_aux, _counts = moe_layer_apply(
        gate, p["moe"]["gate"], p["moe"]["experts"], ffn_expert_fn,
        h, rng, train)
    return x + y, l_aux


def forward(params: Dict, tokens: jnp.ndarray, cfg: MoEGPTConfig,
            rng: Optional[jax.Array] = None,
            train: bool = True,
            hidden_only: bool = False,
            positions: Optional[jnp.ndarray] = None,
            segment_ids: Optional[jnp.ndarray] = None,
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """-> (logits [B,S,V] — or post-ln_f hidden states —, total_l_aux)."""
    B, S = tokens.shape
    dtype = cfg.dtype
    wte = params["wte"]["embedding"].astype(dtype)
    x = wte[tokens]
    if cfg.use_wpe:
        x = x + params["wpe"]["embedding"].astype(dtype)[:S][None]
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def body(carry, layer):
        x, aux, r = carry
        r, lr = jax.random.split(r)
        y, l_aux = _moe_block(x, layer, cfg, lr, train,
                              positions=positions,
                              segment_ids=segment_ids)
        return (y, aux + l_aux, r), None

    body_fn = body
    if cfg.remat:
        body_fn = jax.checkpoint(body, policy=jax.checkpoint_policies.nothing_saveable)
    (x, aux, _), _ = jax.lax.scan(
        body_fn, (x, jnp.zeros([], jnp.float32), rng), params["block"])

    x = _norm(x, params["ln_f"], cfg)
    if hidden_only:
        return x, aux / cfg.n_layers
    logits = x @ wte.T if cfg.tie_embeddings else \
        x @ params["lm_head"]["kernel"].astype(dtype)
    return logits, aux / cfg.n_layers


def loss_fn(params, batch, rng, cfg: MoEGPTConfig, train: bool = True):
    tokens = batch["tokens"]
    targets = batch.get("targets")
    if targets is None:
        targets = tokens[:, 1:]
        tokens = tokens[:, :-1]
    # _head_nll owns the CE math for both paths (dense log_softmax, or
    # the fused chunked CE when cfg.loss_chunk is set)
    from deepspeed_tpu.models.gpt import _head_nll
    implicit = batch.get("targets") is None
    poss = batch.get("positions")
    segs = batch.get("segment_ids")
    mask = batch.get("loss_mask")
    if implicit:
        poss = None if poss is None else poss[:, :-1]
        segs = None if segs is None else segs[:, :-1]
    x, l_aux = forward(params, tokens, cfg, rng, train, hidden_only=True,
                       positions=poss, segment_ids=segs)
    return (_head_nll(params, x, targets, cfg, loss_mask=mask)
            + cfg.aux_loss_weight * l_aux)


def make_loss_fn(cfg: MoEGPTConfig):
    def _loss(params, batch, rng):
        return loss_fn(params, batch, rng, cfg)
    return _loss


def moe_gpt_partition_rules(tp: bool = False) -> list:
    """Expert-parallel rules for the [L, E, ...] stacks: shard E (dim 1)
    over the data axes; attention follows the dense GPT TP rules."""
    model = "model" if tp else None
    rules = [
        PartitionRule(r"block/moe/experts/(wi|wg|wo)/kernel",
                      P(None, ("data", "fsdp"), None, None)),
        PartitionRule(r"block/moe/experts/(wi|wg|wo)/bias",
                      P(None, ("data", "fsdp"), None)),
    ]
    if tp:
        rules += [
            PartitionRule(r"block/qkv/kernel", P(None, None, model)),
            PartitionRule(r"block/qkv/bias", P(None, model)),
            PartitionRule(r"block/attn_out/kernel", P(None, model, None)),
        ]
    return rules
